"""Control-flow meta-ops: while, conditional_block, tensor arrays, and the
LoD/rank-table plumbing of the reference's dynamic-RNN machinery.

Reference: ``paddle/fluid/operators/while_op.cc``, ``conditional_block_op.cc``,
``tensor_array_read_write_op.cc``, ``lod_rank_table_op.cc``,
``lod_tensor_to_array_op.cc``, ``shrink_rnn_memory_op.cc``.

TPU re-design (static shapes, functional control flow):
  * ``while`` lowers its sub-block into ONE ``lax.while_loop`` whose carry is
    the set of loop-written variables (including tensor arrays) — the
    reference instead re-interprets the sub-block per iteration against a
    StepScope (``while_op.cc`` Run loop).
  * Tensor arrays are fixed-capacity dense buffers + a dynamic length
    (``TensorArray`` pytree) — writes are ``lax.dynamic_update_slice`` so
    they trace into scan/while bodies.
  * The batch-shrinking dynamic-RNN machinery (LoDRankTable /
    lod_tensor_to_array / shrink_rnn_memory) keeps the FULL padded batch on
    every step and masks finished sequences instead of shrinking — dynamic
    shapes don't exist under XLA; masking trades FLOPs for compilability
    (the MXU is idle-tolerant, reshapes are not).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.registry import (
    register_op, LowerContext, ShapeInferenceSkip, infer_shape_unary)

DEFAULT_ARRAY_CAPACITY = 128


# ---------------------------------------------------------------------------
# TensorArray runtime value
# ---------------------------------------------------------------------------

@jax.tree_util.register_pytree_node_class
class TensorArray:
    """Fixed-capacity stacked buffer with a dynamic logical length.

    Replaces the reference's ``LoDTensorArray`` (a growable
    ``vector<LoDTensor>``): growth is not traceable, so capacity is fixed at
    creation and ``length`` tracks the high-water mark.
    """

    def __init__(self, data, length):
        self.data = data          # [capacity, *elem_shape]
        self.length = length      # int32 scalar (possibly traced)

    @property
    def capacity(self):
        return self.data.shape[0]

    def write(self, index, value, keep=None):
        """Functional write; ``keep`` (a traced bool) makes the write a
        row-level no-op when False — the bounded-scan while lowering
        gates post-termination iterations this way so its done-mask
        never has to select over the WHOLE buffer (a [T, B, V] output
        array otherwise costs 3 full passes per step; measured 137
        ms/batch on the seq2seq decoder before this gate)."""
        index = jnp.asarray(index, jnp.int32).reshape(())
        value = jnp.asarray(value)
        if keep is not None:
            if value.dtype != self.data.dtype:
                raise TypeError(
                    f"TensorArray.write: value dtype {value.dtype} != "
                    f"buffer dtype {self.data.dtype}")
            old_row = jax.lax.dynamic_index_in_dim(self.data, index,
                                                   axis=0, keepdims=False)
            value = jnp.where(keep, value, old_row)
        start = (index,) + (0,) * value.ndim
        # no dtype coercion on either path: a mismatched write is a
        # loud trace-time error (TypeError above when gated, the
        # dynamic_update_slice dtype check here when not)
        data = jax.lax.dynamic_update_slice(self.data, value[None], start)
        length = jnp.maximum(self.length, index + 1)
        if keep is not None:
            length = jnp.where(keep, length, self.length)
        return TensorArray(data, length)

    def read(self, index):
        index = jnp.asarray(index, jnp.int32).reshape(())
        return jax.lax.dynamic_index_in_dim(self.data, index, axis=0,
                                            keepdims=False)

    @staticmethod
    def empty(elem_shape, dtype, capacity=DEFAULT_ARRAY_CAPACITY):
        data = jnp.zeros((capacity,) + tuple(elem_shape), dtype=dtype)
        return TensorArray(data, jnp.asarray(0, jnp.int32))

    def tree_flatten(self):
        return (self.data, self.length), None

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)


# ---------------------------------------------------------------------------
# array read/write/length  (tensor_array_read_write_op.cc)
# ---------------------------------------------------------------------------

def _infer_skip(op, block):
    raise ShapeInferenceSkip()


@register_op("write_to_array", infer_shape=infer_shape_unary("X"),
             no_grad_inputs=("I",))
def write_to_array_lower(ctx: LowerContext):
    x = ctx.input("X")
    i = ctx.input("I")
    out_name = ctx.op.output("Out")[0]
    arr = ctx.env.get(out_name)
    if not isinstance(arr, TensorArray):
        cap = ctx.attr("capacity", DEFAULT_ARRAY_CAPACITY)
        arr = TensorArray.empty(x.shape, x.dtype, cap)
    # inside a bounded-scan while body, post-termination iterations run
    # with a frozen carry; the keep gate turns their writes into
    # row-level no-ops (see TensorArray.write / while_lower)
    ctx.outputs[out_name] = arr.write(i, x,
                                      keep=ctx.aux.get("loop_keep"))


@register_op("read_from_array", infer_shape=infer_shape_unary("X"),
             no_grad_inputs=("I",))
def read_from_array_lower(ctx: LowerContext):
    arr = ctx.input("X")
    if not isinstance(arr, TensorArray):
        raise TypeError("read_from_array input is not a TensorArray")
    ctx.set_output("Out", arr.read(ctx.input("I")))


@register_op("lod_array_length", infer_shape=_infer_skip, no_gradient=True)
def lod_array_length_lower(ctx: LowerContext):
    arr = ctx.input("X")
    ctx.set_output("Out", arr.length.reshape(1))


# ---------------------------------------------------------------------------
# while  (while_op.cc)
# ---------------------------------------------------------------------------

def _block_has_host_ops(block):
    from paddle_tpu.executor import _has_host_ops
    return _has_host_ops(block)


def _collect_written(block):
    names = []
    for op in block.ops:
        for n in op.output_arg_names:
            if n and n not in names:
                names.append(n)
        for a in op.attrs.values():
            if hasattr(a, "ops"):  # nested sub-block
                for n in _collect_written(a):
                    if n not in names:
                        names.append(n)
    return names


@register_op("while", infer_shape=_infer_skip,
             no_grad_inputs=("Condition",))
def while_lower(ctx: LowerContext):
    """One functional loop over the sub-block.

    Carry = condition + every sub-block-written var already present in the
    outer env (loop state must be initialized before the loop, as in the
    reference).  Pure temporaries recompute inside the body each iteration.

    Differentiability: when a static trip bound is known (``max_iters``
    attr, or the capacity of a TensorArray read in the body — exact for
    DynamicRNN, whose arrays come from lod_tensor_to_array), the loop
    lowers to a **bounded lax.scan with a done-mask**, which jax.vjp can
    differentiate (the reference instead emits a while_grad op,
    while_op.cc).  Otherwise it lowers to lax.while_loop (forward-only).
    """
    sub_block = ctx.attr("sub_block")
    cond_name = ctx.op.input("Condition")[0]
    written = _collect_written(sub_block)

    # CSP/host ops in the body (go/select/channel_*) cannot trace into a
    # lax loop; in interpret mode run a plain Python while over the eager
    # sub-block instead (the reference's per-iteration re-interpretation,
    # while_op.cc)
    if ctx.aux.get("interpret") and _block_has_host_ops(sub_block):
        env = ctx.env
        lb = ctx.aux["lower_block"]
        import numpy as _np
        while bool(_np.asarray(env[cond_name]).reshape(-1)[0]):
            lb(sub_block, env, ctx._rng_key, ctx.training, ctx.aux)
        for n in written:
            if n in env:
                ctx.outputs[n] = env[n]
        return

    outer_env = dict(ctx.env)
    # snapshot for the grad op: loop carries overwrite their own names in
    # the env, so while_grad must re-run the forward from PRE-loop values
    ctx.aux.setdefault("env_snapshots", {}).setdefault(
        id(sub_block), dict(ctx.env))
    rng_key = ctx._rng_key
    training = ctx.training
    aux = ctx.aux
    lower_block = aux["lower_block"]

    # Tensor arrays first written INSIDE the loop (e.g. DynamicRNN output
    # arrays) must be loop-carried: discover their shapes with one abstract
    # body evaluation and seed empty arrays.
    missing_arrays = [n for n in _array_outs(sub_block)
                      if n not in outer_env]
    if missing_arrays:
        def probe(_):
            env = dict(outer_env)
            lower_block(sub_block, env, rng_key, training, dict(aux))
            return tuple(env[n] for n in missing_arrays)

        shapes = jax.eval_shape(probe, 0)
        for n, s in zip(missing_arrays, shapes):
            outer_env[n] = TensorArray(
                jnp.zeros(s.data.shape, s.data.dtype),
                jnp.asarray(0, jnp.int32))

    carry_names = [cond_name] + [n for n in written
                                 if n in outer_env and n != cond_name]

    def cond_fun(carry):
        return jnp.asarray(carry[0]).reshape(()).astype(bool)

    def body_fun(carry, keep=None):
        env = dict(outer_env)
        env.update({n: v for n, v in zip(carry_names, carry)})
        body_aux = dict(aux)
        if keep is not None:
            body_aux["loop_keep"] = keep
        lower_block(sub_block, env, rng_key, training, body_aux)
        return tuple(env[n] for n in carry_names)

    init = tuple(outer_env[n] for n in carry_names)

    bound = ctx.attr("max_iters", None)
    if bound is None:
        bound = _static_trip_bound(sub_block, outer_env)

    if bound is not None:
        def scan_body(carry, _):
            keep = cond_fun(carry)
            # nested bounded loops: a frozen OUTER carry re-derives a
            # True inner condition, so the inner writes must stay gated
            # by the inherited outer mask
            outer_keep = aux.get("loop_keep")
            if outer_keep is not None:
                keep = jnp.logical_and(keep, outer_keep)
            new_carry = body_fun(carry, keep=keep)
            # done-mask merge.  TensorArray leaves are merged ROW-WISE
            # inside their writes (keep gate above): post-done body
            # iterations see a frozen carry, so every write re-produces
            # its own old row — a whole-buffer where() here would read
            # both generations and select (3 full passes over e.g. a
            # [T, B, vocab] decoder output array, per step).
            def merge(new, old):
                if isinstance(new, TensorArray):
                    return new
                return jnp.where(keep, new, old)

            merged = jax.tree_util.tree_map(
                merge, new_carry, carry,
                is_leaf=lambda x: isinstance(x, TensorArray))
            return merged, None

        final, _ = jax.lax.scan(scan_body, init, None, length=int(bound))

        # the bound is a *claim* that the loop terminates within `bound`
        # trips; a still-true condition after the scan means the loop was
        # silently truncated — fail loudly instead (ADVICE r1).  Some PJRT
        # backends cannot run host callbacks; there the check degrades to a
        # one-time warning at lowering time.
        still_true = cond_fun(final)
        # a FROZEN outer carry (this loop nested in a post-termination
        # outer iteration) keeps the inner condition True by design —
        # that is not exhaustion
        outer_keep = ctx.aux.get("loop_keep")
        if outer_keep is not None:
            still_true = jnp.logical_and(still_true, outer_keep)
        if _host_callbacks_supported():
            def _check_exhausted(still_true, bound=int(bound)):
                if bool(still_true):
                    raise RuntimeError(
                        f"while loop did not terminate within its static "
                        f"trip bound of {bound} iterations (inferred from "
                        f"TensorArray capacity or the 'max_iters' attr); "
                        f"raise 'max_iters' on the while op")
            jax.debug.callback(_check_exhausted, still_true)
        else:
            _warn_no_exhaustion_check(int(bound))
    else:
        final = jax.lax.while_loop(cond_fun, body_fun, init)
    for n, v in zip(carry_names, final):
        ctx.outputs[n] = v


_HOST_CALLBACK_OK = None


def _host_callbacks_supported():
    """Whether the active backend can run jax.debug.callback (feature-
    detected once: some PJRT plugins reject host send/recv)."""
    global _HOST_CALLBACK_OK
    if _HOST_CALLBACK_OK is None:
        try:
            def probe(x):
                jax.debug.callback(lambda v: None, x)
                return x
            # ensure_compile_time_eval: the probe must really EXECUTE here,
            # even when this runs inside an outer jit trace (otherwise the
            # inner jit is inlined and the callback pollutes the outer
            # computation).
            with jax.ensure_compile_time_eval():
                jax.jit(probe)(jnp.zeros(())).block_until_ready()
            _HOST_CALLBACK_OK = True
        except Exception:
            _HOST_CALLBACK_OK = False
    return _HOST_CALLBACK_OK


_WARNED_NO_CHECK = set()


def _warn_no_exhaustion_check(bound):
    if bound not in _WARNED_NO_CHECK:
        _WARNED_NO_CHECK.add(bound)
        import warnings
        warnings.warn(
            f"backend cannot run host callbacks; a while loop lowered with "
            f"static trip bound {bound} will be silently truncated if it "
            f"needs more iterations", RuntimeWarning)


def _static_trip_bound(block, env):
    """Max capacity over TensorArrays read in the loop body, if any."""
    bound = None
    for op in block.ops:
        if op.type == "read_from_array":
            arr = env.get(op.input("X")[0])
            if isinstance(arr, TensorArray):
                cap = int(arr.capacity)
                bound = cap if bound is None else max(bound, cap)
        for a in op.attrs.values():
            if hasattr(a, "ops"):
                sub = _static_trip_bound(a, env)
                if sub is not None:
                    bound = sub if bound is None else max(bound, sub)
    return bound


def _array_outs(block):
    """Out names of write_to_array ops in ``block`` (recursively)."""
    names = []
    for op in block.ops:
        if op.type == "write_to_array":
            for n in op.output("Out"):
                if n and n not in names:
                    names.append(n)
        for a in op.attrs.values():
            if hasattr(a, "ops"):
                for n in _array_outs(a):
                    if n not in names:
                        names.append(n)
    return names


# ---------------------------------------------------------------------------
# conditional_block  (conditional_block_op.cc)
# ---------------------------------------------------------------------------

@register_op("conditional_block", infer_shape=_infer_skip, no_gradient=True)
def conditional_block_lower(ctx: LowerContext):
    """Scalar-condition branch via lax.cond.

    Output vars must pre-exist in the env (their value is kept when the
    condition is false) or they default to zeros of the true-branch shape.
    """
    sub_block = ctx.attr("sub_block")
    conds = ctx.inputs("Cond") if ctx.op.input("Cond") else ctx.inputs("X")
    pred = jnp.all(jnp.stack([jnp.asarray(c).reshape(-1).all()
                              for c in conds]))
    out_names = [n for n in ctx.op.output("Out") if n]
    outer_env = dict(ctx.env)
    aux = ctx.aux
    lower_block = aux["lower_block"]
    rng_key, training = ctx._rng_key, ctx.training

    def run_branch(_):
        env = dict(outer_env)
        lower_block(sub_block, env, rng_key, training, dict(aux))
        return tuple(env[n] for n in out_names)

    def skip_branch(_):
        outs = []
        true_shapes = jax.eval_shape(run_branch, 0)
        for n, sd in zip(out_names, true_shapes):
            if n in outer_env:
                outs.append(outer_env[n])
            else:
                outs.append(jnp.zeros(sd.shape, sd.dtype))
        return tuple(outs)

    results = jax.lax.cond(pred.astype(bool), run_branch, skip_branch, 0)
    for n, v in zip(out_names, results):
        ctx.outputs[n] = v


# ---------------------------------------------------------------------------
# split/merge_lod_tensor  (IfElse batch routing, split_lod_tensor_op.cc)
# ---------------------------------------------------------------------------
# TPU re-design: both "branches" see the FULL batch; merge selects per row
# by the mask.  No dynamic shapes, work is masked not skipped.

@register_op("split_lod_tensor", infer_shape=_infer_skip, no_gradient=True)
def split_lod_tensor_lower(ctx: LowerContext):
    x = ctx.input("X")
    ctx.set_output("OutTrue", x)
    ctx.set_output("OutFalse", x)


@register_op("merge_lod_tensor", infer_shape=infer_shape_unary("InTrue"),
             no_grad_inputs=("Mask",))
def merge_lod_tensor_lower(ctx: LowerContext):
    mask = ctx.input("Mask")
    in_true = ctx.input("InTrue")
    in_false = ctx.input("InFalse")
    m = jnp.asarray(mask).reshape((-1,) + (1,) * (in_true.ndim - 1))
    ctx.set_output("Out", jnp.where(m.astype(bool), in_true, in_false))


# ---------------------------------------------------------------------------
# LoD rank table machinery (lod_rank_table_op.cc, lod_tensor_to_array_op.cc)
# ---------------------------------------------------------------------------

class RankTable:
    """Sequence (index, length) pairs sorted by decreasing length — static
    metadata (reference ``LoDRankTable``, lod_rank_table.h)."""

    def __init__(self, items):
        self.items = list(items)  # [(orig_index, length)] sorted desc

    @property
    def lengths(self):
        return [l for _, l in self.items]

    @property
    def indices(self):
        return [i for i, _ in self.items]


def _lod_to_lengths(lod, level=0):
    splits = lod[level]
    return [splits[i + 1] - splits[i] for i in range(len(splits) - 1)]


class DynRankTable:
    """Rank table over RUNTIME lengths (bucketed dynamic-LoD mode).

    The static RankTable sorts rows by decreasing length so active rows
    form a prefix; sorting a traced quantity is impossible AND
    unnecessary here — the TPU lowerings keep the full batch and mask
    per-row by length anyway, so the dyn table keeps ORIGINAL order
    (identity indices) and carries the traced splits plus static bounds.
    """

    def __init__(self, splits, num_seqs, cap, n_rows):
        self.splits = splits            # [B+1] traced int32
        self.num_seqs = int(num_seqs)   # static batch size
        self.cap = int(cap)             # static maxlen bucket
        self.n_rows = int(n_rows)       # static padded row bucket of X

    @property
    def lengths_arr(self):
        return self.splits[1:] - self.splits[:-1]


def _is_dyn_lod(lod):
    from paddle_tpu.lod import DynLoD
    return isinstance(lod, DynLoD)


@register_op("lod_rank_table", infer_shape=_infer_skip, no_gradient=True)
def lod_rank_table_lower(ctx: LowerContext):
    lod = ctx.input_lod("X")
    x = ctx.input("X")
    level = ctx.attr("level", 0)
    out_name = ctx.op.output("Out")[0]
    if _is_dyn_lod(lod):
        if level != 0:
            raise NotImplementedError(
                "lod_rank_table over a non-zero lod level is not "
                "supported in bucketed dynamic-LoD mode — the bucketed "
                "feed carries a single (deepest) level of row splits")
        ctx.outputs[out_name] = DynRankTable(
            lod.splits(ctx.env).astype(jnp.int32), lod.num_seqs,
            lod.maxlen_bucket, x.shape[0])
        return
    if lod is None:
        # dense [B, T, ...] input: every row has length T
        lengths = [x.shape[1] if x.ndim > 1 else 1] * x.shape[0]
    else:
        lengths = _lod_to_lengths(lod, level)
    items = sorted(enumerate(lengths), key=lambda p: -p[1])
    table = RankTable(items)
    ctx.outputs[out_name] = table


@register_op("max_sequence_len", infer_shape=_infer_skip, no_gradient=True)
def max_sequence_len_lower(ctx: LowerContext):
    table = ctx.input("RankTable")
    if isinstance(table, DynRankTable):
        ctx.set_output("Out", jnp.max(table.lengths_arr)
                       .astype(jnp.int32).reshape(1))
        return
    ctx.set_output("Out", jnp.asarray([max(table.lengths)], jnp.int32))


@register_op("lod_tensor_to_array", infer_shape=infer_shape_unary("X"),
             no_grad_inputs=("RankTable",))
def lod_tensor_to_array_lower(ctx: LowerContext):
    """Ragged [sum(T_i), D] + rank table -> TensorArray of time-major
    padded steps [t] -> [B, D] (full batch, zero-padded for finished rows).

    The reference shrinks the batch at each step (sequence2batch);
    here every step keeps the full sorted batch and finished rows are
    zero rows — downstream ``shrink_rnn_memory`` turns into a mask.
    """
    x = ctx.input("X")
    table = ctx.input("RankTable")
    lod = ctx.input_lod("X")
    if isinstance(table, DynRankTable):
        # bucketed mode: traced splits, static T bound = the lod bucket;
        # ONE batched gather [cap, B] (an unrolled per-step loop would
        # emit O(cap) HLO ops — exactly wrong for long-sequence buckets)
        starts = table.splits[:-1]
        lengths = table.lengths_arr
        ts = jnp.arange(table.cap)
        idx = jnp.clip(starts[None, :] + ts[:, None], 0,
                       x.shape[0] - 1)                      # [cap, B]
        mask = (ts[:, None] < lengths[None, :]).astype(x.dtype)
        data = x[idx] * mask.reshape(mask.shape + (1,) * (x.ndim - 1))
        out_name = ctx.op.output("Out")[0]
        ctx.outputs[out_name] = TensorArray(
            data, jnp.max(lengths).astype(jnp.int32))
        return
    lengths = table.lengths
    indices = table.indices
    max_len = max(lengths) if lengths else 0
    batch = len(lengths)
    feat_shape = x.shape[1:]

    if lod is None:
        # dense [B, T, ...]: reorder rows by rank table
        steps = [x[jnp.asarray(indices), t] for t in range(max_len)]
    else:
        splits = lod[0]
        rows = []
        for t in range(max_len):
            idxs = []
            valid = []
            for b, orig in enumerate(indices):
                if t < lengths[b]:
                    idxs.append(splits[orig] + t)
                    valid.append(True)
                else:
                    idxs.append(0)
                    valid.append(False)
            step = x[jnp.asarray(idxs)]
            mask = jnp.asarray(valid, x.dtype).reshape(
                (batch,) + (1,) * (len(feat_shape)))
            rows.append(step * mask)
        steps = rows

    data = jnp.stack(steps) if steps else jnp.zeros((0, batch) + feat_shape,
                                                    x.dtype)
    out_name = ctx.op.output("Out")[0]
    ctx.outputs[out_name] = TensorArray(
        data, jnp.asarray(max_len, jnp.int32))


@register_op("array_to_lod_tensor", infer_shape=infer_shape_unary("X"),
             no_grad_inputs=("RankTable",))
def array_to_lod_tensor_lower(ctx: LowerContext):
    """Inverse of lod_tensor_to_array: stacked [T, B, D] steps -> ragged
    [sum(T_i), D] rows in original order (emitted LoD is the sorted-restored
    one)."""
    arr = ctx.input("X")
    table = ctx.input("RankTable")
    if isinstance(table, DynRankTable):
        # restore padded-ragged rows [n_rows, ...] with the SAME runtime
        # splits (identity order — the dyn table never sorted)
        from paddle_tpu.lod import DynLoD, SPLITS_SUFFIX
        data = arr.data                       # [cap, B, ...]
        splits = table.splits
        r = jnp.arange(table.n_rows)
        seg = jnp.clip(jnp.searchsorted(splits[1:], r, side="right")
                       .astype(jnp.int32), 0, table.num_seqs - 1)
        t = jnp.clip(r - splits[seg], 0, data.shape[0] - 1)
        gathered = data[t, seg]
        valid = (r < splits[-1]).reshape(
            (-1,) + (1,) * (gathered.ndim - 1))
        out_name = ctx.op.output("Out")[0]
        ctx.set_output("Out", jnp.where(valid, gathered, 0))
        name = out_name + SPLITS_SUFFIX
        ctx.outputs[name] = splits
        ctx.set_output_lod("Out", DynLoD(name, table.num_seqs,
                                         table.cap))
        return
    lengths = table.lengths
    indices = table.indices
    data = arr.data  # [cap, B, ...]
    rows = []
    for b, orig in sorted(zip(range(len(indices)), indices),
                          key=lambda p: p[1]):
        rows.append(data[:lengths[b], b])
    out = jnp.concatenate(rows, axis=0) if rows else data[:0, 0]
    ctx.set_output("Out", out)
    restored = [0] * len(indices)
    for b, orig in enumerate(indices):
        restored[orig] = lengths[b]
    splits = [0]
    for L in restored:
        splits.append(splits[-1] + L)
    ctx.set_output_lod("Out", [splits])


@register_op("shrink_rnn_memory", infer_shape=infer_shape_unary("X"),
             no_grad_inputs=("RankTable", "I"))
def shrink_rnn_memory_lower(ctx: LowerContext):
    """Reference shrinks memory to the still-active prefix of the sorted
    batch; TPU version keeps the full batch and zero-masks finished rows
    (rank table is sorted by decreasing length, so active rows are a
    prefix)."""
    x = ctx.input("X")
    table = ctx.input("RankTable")
    i = ctx.input("I")
    lengths = table.lengths_arr if isinstance(table, DynRankTable) \
        else jnp.asarray(table.lengths, jnp.int32)
    step = jnp.asarray(i).reshape(()).astype(jnp.int32)
    active = (lengths > step).astype(x.dtype)
    mask = active.reshape((x.shape[0],) + (1,) * (x.ndim - 1))
    ctx.set_output("Out", x * mask)


@register_op("reorder_lod_tensor_by_rank", infer_shape=infer_shape_unary("X"),
             no_grad_inputs=("RankTable",))
def reorder_lod_tensor_by_rank_lower(ctx: LowerContext):
    x = ctx.input("X")
    table = ctx.input("RankTable")
    if isinstance(table, DynRankTable):
        # dyn tables keep original order — reorder is the identity
        ctx.set_output("Out", x)
        return
    lod = ctx.input_lod("X")
    if lod is None:
        # dense [B, ...]: one row per sequence
        ctx.set_output("Out", x[jnp.asarray(table.indices)])
        return
    # ragged input: reorder whole SUB-SEQUENCES into rank-table order
    # (indexing rows by sequence ids would interleave unrelated rows)
    if len(lod) > 1:
        raise NotImplementedError(
            "reorder_lod_tensor_by_rank over a nested (multi-level) LoD "
            "tensor: level-0 splits index level-1 entries, not rows — "
            "flatten the nesting (sequence_reshape / sub_nested_seq) "
            "before reordering")
    splits = np.asarray(lod[0])
    rows = []
    new_splits = [0]
    for orig in table.indices:
        rows.extend(range(int(splits[orig]), int(splits[orig + 1])))
        new_splits.append(len(rows))
    ctx.set_output("Out", x[jnp.asarray(np.asarray(rows, np.int32))])
    ctx.set_output_lod("Out", [new_splits])


# ---------------------------------------------------------------------------
# recurrent (StaticRNN) — lax.scan over the sub-block
# ---------------------------------------------------------------------------

@register_op("recurrent", infer_shape=_infer_skip)
def recurrent_lower(ctx: LowerContext):
    """StaticRNN (reference ``recurrent_op.cc:222``): scan the sub-block
    over the time axis.

    attrs: sub_block, step_inputs (outer [B,T,D] var -> step var name),
    memories [{pre, mem, init}], step_outputs (step var -> stacked outer
    var).  Time axis is 1 (batch-major outer, scan internally time-major).
    """
    sub_block = ctx.attr("sub_block")
    step_inputs = ctx.attr("step_inputs")      # {outer_name: step_name}
    memories = ctx.attr("memories")            # [{pre, mem, init}]
    step_outputs = ctx.attr("step_outputs")    # {step_name: outer_name}

    xs = {sn: jnp.moveaxis(ctx.env[on], 1, 0)
          for on, sn in step_inputs.items()}   # [T, B, D]
    init_carry = tuple(ctx.env[m["init"]] for m in memories)

    outer_env = dict(ctx.env)
    aux = ctx.aux
    lower_block = aux["lower_block"]
    rng_key, training = ctx._rng_key, ctx.training
    out_step_names = list(step_outputs)

    def body(carry, x_t):
        env = dict(outer_env)
        for m, c in zip(memories, carry):
            env[m["pre"]] = c
        env.update(x_t)
        lower_block(sub_block, env, rng_key, training, dict(aux))
        new_carry = tuple(env[m["mem"]] for m in memories)
        outs = tuple(env[n] for n in out_step_names)
        return new_carry, outs

    final_carry, stacked = jax.lax.scan(body, init_carry, xs)
    for sn, outer in step_outputs.items():
        idx = out_step_names.index(sn)
        ctx.outputs[outer] = jnp.moveaxis(stacked[idx], 0, 1)  # [B,T,D]
    for m, c in zip(memories, final_carry):
        ctx.outputs[m["mem"] + "@FINAL"] = c
