"""Linear-algebra / elementwise / reduction ops.

Reference op set: ``paddle/fluid/operators/{mul,matmul,elementwise_*,scale,
sum,mean,reduce_op,cumsum,...}``.  Each lowering is a pure jax.numpy
function; XLA maps matmuls onto the MXU and fuses the elementwise ops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.registry import (
    register_op, register_grad_lower, infer_shape_unary, ShapeInferenceSkip)


# ---------------------------------------------------------------------------
# mul / matmul  (reference: mul_op.cc, matmul_op.cc, math/matmul.h)
# ---------------------------------------------------------------------------

def _flatten_to_2d(x, num_col_dims):
    lead = int(np.prod(x.shape[:num_col_dims])) if num_col_dims > 0 else 1
    return x.reshape(lead, -1)


def _infer_mul(op, block):
    x = block.var(op.input("X")[0])
    y = block.var(op.input("Y")[0])
    if x.shape is None or y.shape is None:
        raise ShapeInferenceSkip()
    xn = op.attr("x_num_col_dims", 1)
    yn = op.attr("y_num_col_dims", 1)
    out = block.var(op.output("Out")[0])
    out.shape = tuple(x.shape[:xn]) + tuple(y.shape[yn:])
    out.dtype = x.dtype
    out.lod_level = x.lod_level


@register_op("mul", infer_shape=_infer_mul, amp_cast=("X", "Y"))
def mul_lower(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    xn = ctx.attr("x_num_col_dims", 1)
    yn = ctx.attr("y_num_col_dims", 1)
    x2 = _flatten_to_2d(x, xn)
    y2 = y.reshape(int(np.prod(y.shape[:yn])), -1)
    out = jnp.matmul(x2, y2)
    out = out.reshape(tuple(x.shape[:xn]) + tuple(y.shape[yn:]))
    ctx.set_output("Out", out)


def _infer_matmul(op, block):
    x = block.var(op.input("X")[0])
    y = block.var(op.input("Y")[0])
    if x.shape is None or y.shape is None:
        raise ShapeInferenceSkip()
    tx, ty = op.attr("transpose_X", False), op.attr("transpose_Y", False)
    xs = list(x.shape)
    ys = list(y.shape)
    if len(xs) >= 2 and tx:
        xs[-1], xs[-2] = xs[-2], xs[-1]
    if len(ys) >= 2 and ty:
        ys[-1], ys[-2] = ys[-2], ys[-1]
    if len(xs) == 1 and len(ys) == 1:
        shape = (1,)
    elif len(xs) == 1:
        shape = tuple(ys[:-2]) + (ys[-1],)
    elif len(ys) == 1:
        shape = tuple(xs[:-1])
    else:
        batch = xs[:-2] if len(xs) > len(ys) else ys[:-2]
        shape = tuple(batch) + (xs[-2], ys[-1])
    out = block.var(op.output("Out")[0])
    out.shape = shape
    out.dtype = x.dtype


@register_op("matmul", infer_shape=_infer_matmul, amp_cast=("X", "Y"))
def matmul_lower(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    if ctx.attr("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2) if x.ndim >= 2 else x
    if ctx.attr("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2) if y.ndim >= 2 else y
    out = jnp.matmul(x, y)
    alpha = ctx.attr("alpha", 1.0)
    if alpha != 1.0:
        out = out * alpha
    if out.ndim == 0:
        out = out.reshape(1)
    ctx.set_output("Out", out)


# ---------------------------------------------------------------------------
# elementwise family  (reference: elementwise_op_function.h broadcast engine)
# ---------------------------------------------------------------------------

def _elementwise_broadcast(x, y, axis):
    """Paddle broadcast: Y's shape aligns to X starting at ``axis``."""
    if y.ndim == x.ndim:
        return y
    if axis == -1:
        axis = x.ndim - y.ndim
    shape = [1] * x.ndim
    for i, d in enumerate(y.shape):
        shape[axis + i] = d
    return y.reshape(shape)


def _infer_ew(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    out.shape = x.shape
    out.dtype = x.dtype
    out.lod_level = x.lod_level


def _make_elementwise(name, fn):
    @register_op("elementwise_" + name, infer_shape=_infer_ew)
    def lower(ctx):
        x, y = ctx.input("X"), ctx.input("Y")
        yb = _elementwise_broadcast(x, y, ctx.attr("axis", -1))
        ctx.set_output("Out", fn(x, yb))
    lower.__name__ = f"elementwise_{name}_lower"
    return lower


_make_elementwise("add", jnp.add)
_make_elementwise("sub", jnp.subtract)
_make_elementwise("mul", jnp.multiply)
_make_elementwise("div", jnp.divide)
_make_elementwise("max", jnp.maximum)
_make_elementwise("min", jnp.minimum)
_make_elementwise("pow", jnp.power)
_make_elementwise("mod", jnp.mod)
_make_elementwise("floordiv", jnp.floor_divide)


# ---------------------------------------------------------------------------
# scale / sum / mean / minus / sign / clip
# ---------------------------------------------------------------------------

@register_op("scale", infer_shape=infer_shape_unary())
def scale_lower(ctx):
    x = ctx.input("X")
    scale = ctx.attr("scale", 1.0)
    bias = ctx.attr("bias", 0.0)
    bias_after = ctx.attr("bias_after_scale", True)
    if bias_after:
        ctx.set_output("Out", x * scale + bias)
    else:
        ctx.set_output("Out", (x + bias) * scale)


@register_op("sum", infer_shape=infer_shape_unary(),
             selected_rows_inputs=("X",))
def sum_lower(ctx):
    """Reference sum_op.cc: sums LoDTensors and/or SelectedRows.  All-sparse
    inputs concatenate into one SelectedRows (duplicate rows are fine —
    consumers scatter-add or merge); mixed inputs densify."""
    from paddle_tpu.selected_rows import SelectedRows, is_selected_rows
    xs = ctx.inputs("X")
    if any(is_selected_rows(x) for x in xs):
        if all(is_selected_rows(x) for x in xs):
            rows = jnp.concatenate([x.rows for x in xs])
            vals = jnp.concatenate([x.value for x in xs])
            ctx.set_output("Out", SelectedRows(rows, vals, xs[0].height))
            return
        xs = [x.to_dense() if is_selected_rows(x) else x for x in xs]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    ctx.set_output("Out", out)


def _infer_mean(op, block):
    out = block.var(op.output("Out")[0])
    out.shape = (1,)
    out.dtype = block.var(op.input("X")[0]).dtype


@register_op("mean", infer_shape=_infer_mean)
def mean_lower(ctx):
    x = ctx.input("X")
    lod = ctx.input_lod("X")
    from paddle_tpu.lod import DynLoD
    if isinstance(lod, DynLoD):
        # bucketed dynamic-LoD rows: average over the REAL rows only —
        # rows past splits[-1] are zero padding (their values, e.g. the
        # clamped cross-entropy of an all-zero softmax row, are noise)
        splits = lod.splits(ctx.env)
        n_real = splits[-1]
        r = jnp.arange(x.shape[0])
        mask = (r < n_real).astype(x.dtype).reshape(
            (-1,) + (1,) * (x.ndim - 1))
        per_row = int(np.prod(x.shape[1:])) if x.ndim > 1 else 1
        total = jnp.sum(x * mask)
        count = jnp.maximum(n_real.astype(x.dtype) * per_row, 1)
        ctx.set_output("Out", (total / count).reshape(1))
        return
    ctx.set_output("Out", jnp.mean(x).reshape(1))


@register_op("minus", infer_shape=infer_shape_unary())
def minus_lower(ctx):
    ctx.set_output("Out", ctx.input("X") - ctx.input("Y"))


@register_op("sign", infer_shape=infer_shape_unary())
def sign_lower(ctx):
    ctx.set_output("Out", jnp.sign(ctx.input("X")))


@register_op("clip", infer_shape=infer_shape_unary())
def clip_lower(ctx):
    ctx.set_output("Out", jnp.clip(ctx.input("X"), ctx.attr("min"),
                                   ctx.attr("max")))


@register_op("clip_by_norm", infer_shape=infer_shape_unary())
def clip_by_norm_lower(ctx):
    x = ctx.input("X")
    max_norm = ctx.attr("max_norm")
    norm = jnp.sqrt(jnp.sum(x * x))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12),
                      1.0).astype(x.dtype)
    ctx.set_output("Out", x * scale)


# ---------------------------------------------------------------------------
# reductions  (reference: reduce_op.cc functor family, cum_op.h)
# ---------------------------------------------------------------------------

def _infer_reduce(op, block):
    x = block.var(op.input("X")[0])
    if x.shape is None:
        raise ShapeInferenceSkip()
    dim = op.attr("dim", [0])
    if isinstance(dim, int):
        dim = [dim]
    keep = op.attr("keep_dim", False)
    reduce_all = op.attr("reduce_all", False)
    out = block.var(op.output("Out")[0])
    if reduce_all:
        out.shape = tuple([1] * len(x.shape)) if keep else (1,)
    else:
        dims = [d % len(x.shape) for d in dim]
        if keep:
            out.shape = tuple(1 if i in dims else d
                              for i, d in enumerate(x.shape))
        else:
            shape = tuple(d for i, d in enumerate(x.shape) if i not in dims)
            out.shape = shape if shape else (1,)
    out.dtype = x.dtype


def _make_reduce(name, fn):
    @register_op("reduce_" + name, infer_shape=_infer_reduce)
    def lower(ctx):
        x = ctx.input("X")
        dim = ctx.attr("dim", [0])
        if isinstance(dim, int):
            dim = [dim]
        keep = ctx.attr("keep_dim", False)
        if ctx.attr("reduce_all", False):
            out = fn(x, axis=None, keepdims=keep)
            if not keep:
                out = out.reshape(1)
        else:
            axes = tuple(d % x.ndim for d in dim)
            out = fn(x, axis=axes, keepdims=keep)
            if out.ndim == 0:
                out = out.reshape(1)
        ctx.set_output("Out", out)
    lower.__name__ = f"reduce_{name}_lower"
    return lower


_make_reduce("sum", jnp.sum)
_make_reduce("mean", jnp.mean)
_make_reduce("max", jnp.max)
_make_reduce("min", jnp.min)
_make_reduce("prod", jnp.prod)


@register_op("cumsum", infer_shape=infer_shape_unary())
def cumsum_lower(ctx):
    x = ctx.input("X")
    axis = ctx.attr("axis", -1)
    exclusive = ctx.attr("exclusive", False)
    reverse = ctx.attr("reverse", False)
    if reverse:
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if exclusive:
        out = out - (jnp.flip(ctx.input("X"), axis) if reverse
                     else ctx.input("X"))
    if reverse:
        out = jnp.flip(out, axis)
    ctx.set_output("Out", out)


# ---------------------------------------------------------------------------
# norms / similarity
# ---------------------------------------------------------------------------

def _infer_scalar_out(op, block):
    out = block.var(op.output("Out")[0])
    out.shape = (1,)
    out.dtype = block.var(op.input("X")[0]).dtype


@register_op("squared_l2_norm", infer_shape=_infer_scalar_out)
def squared_l2_norm_lower(ctx):
    x = ctx.input("X")
    ctx.set_output("Out", jnp.sum(x * x).reshape(1))


@register_op("l1_norm", infer_shape=_infer_scalar_out)
def l1_norm_lower(ctx):
    ctx.set_output("Out", jnp.sum(jnp.abs(ctx.input("X"))).reshape(1))


def _infer_norm(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    out.shape = x.shape
    out.dtype = x.dtype


@register_op("norm", infer_shape=_infer_norm)
def norm_lower(ctx):
    x = ctx.input("X")
    axis = ctx.attr("axis", 1)
    eps = ctx.attr("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True) + eps)
    ctx.set_output("Out", x / norm)
    ctx.set_output("Norm", norm)


def _infer_cos_sim(op, block):
    x = block.var(op.input("X")[0])
    if x.shape is None:
        raise ShapeInferenceSkip()
    out = block.var(op.output("Out")[0])
    out.shape = (x.shape[0], 1)
    out.dtype = x.dtype


@register_op("cos_sim", infer_shape=_infer_cos_sim)
def cos_sim_lower(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    xn = jnp.sqrt(jnp.sum(x * x, axis=1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, axis=1, keepdims=True))
    dot = jnp.sum(x * y, axis=1, keepdims=True)
    ctx.set_output("Out", dot / (xn * yn))
    ctx.set_output("XNorm", xn)
    ctx.set_output("YNorm", yn)


# ---------------------------------------------------------------------------
# dot / outer helpers used by layers
# ---------------------------------------------------------------------------

def _infer_bilinear(op, block):
    x = block.var(op.input("X")[0])
    w = block.var(op.input("Weight")[0])
    if x.shape is None or w.shape is None:
        raise ShapeInferenceSkip()
    out = block.var(op.output("Out")[0])
    out.shape = (x.shape[0], w.shape[0])
    out.dtype = x.dtype


@register_op("bilinear_tensor_product", infer_shape=_infer_bilinear)
def bilinear_tensor_product_lower(ctx):
    x, y, w = ctx.input("X"), ctx.input("Y"), ctx.input("Weight")
    # x: (B, M), y: (B, N), w: (S, M, N) -> out (B, S)
    out = jnp.einsum("bm,smn,bn->bs", x, w, y)
    b = ctx.input("Bias")
    if b is not None:
        out = out + b
    ctx.set_output("Out", out)
