"""Recurrent compute ops: lstm / gru (ragged "dynamic" form) + unit cells.

Reference: ``paddle/fluid/operators/lstm_op.cc``, ``gru_op.cc``,
``lstm_unit_op.cc``, ``gru_unit_op.cc``, fused cell kernels under
``operators/math/detail/``.

TPU re-design: the reference reorders ragged batches into
length-descending "batch" form and launches one fused CUDA kernel per time
step (``math/sequence2batch.h``).  Here the ragged input is padded to
[B, T, G] with a STATIC gather table (LoD is trace-time metadata), one
``lax.scan`` runs the whole sequence inside the compiled block, and
finished rows are masked.  Gradients come from jax.vjp through the scan.

Gate layouts follow the reference:
  lstm Weight [H, 4H] with gate order (c, i, f, o)  — lstm_op.cc docs
  gru  Weight [H, 3H] = [W_u | W_r | W_c]           — gru_op.cc docs
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.registry import (
    register_op, LowerContext, ShapeInferenceSkip)

_ACTS = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "identity": lambda x: x,
    "linear": lambda x: x,
}


def _dyn(lod):
    from paddle_tpu.lod import DynLoD
    return isinstance(lod, DynLoD)


def _infer_skip(op, block):
    raise ShapeInferenceSkip()


def _infer_rnn(op, block):
    w = block.var(op.input("Weight")[0])
    H = w.shape[0]
    for slot in ("Hidden", "Cell"):
        names = op.output(slot)
        if names:
            v = block.var(names[0])
            v.shape = (-1, H)
            v.dtype = w.dtype
            x = block.var(op.input("Input")[0])
            v.lod_level = x.lod_level


def _infer_unit(op, block):
    prev = block.var(op.input("C_prev" if op.input("C_prev")
                              else "HiddenPrev")[0])
    for slot in ("C", "H", "Hidden"):
        names = op.output(slot)
        if names:
            v = block.var(names[0])
            v.shape = prev.shape
            v.dtype = prev.dtype


def _lod_pad_tables(lod, is_reverse=False, ctx=None, n_rows=None):
    """(gather [B,T], scatter [N], lengths [B], B, T) index tables between
    flat ragged [N, ...] and padded [B, T, ...] layouts.

    Static lod: trace-time numpy tables (exact shapes per lod).
    DynLoD (bucketed mode, lod.py): traced jnp tables with static
    (B, T_bucket) — one executable per bucket; ``n_rows`` is the bucketed
    row count and rows past splits[-1] scatter back as zeros."""
    from paddle_tpu.lod import DynLoD
    if isinstance(lod, DynLoD):
        splits = lod.splits(ctx.env).astype(jnp.int32)   # [B+1]
        B, T = lod.num_seqs, lod.maxlen_bucket
        N = n_rows
        lengths = splits[1:] - splits[:-1]               # [B]
        t_idx = jnp.arange(T)[None, :]                   # [1, T]
        valid = t_idx < lengths[:, None]                 # [B, T]
        off = (lengths[:, None] - 1 - t_idx) if is_reverse else t_idx
        src = splits[:-1, None] + off
        gather = jnp.where(valid, src, N).astype(jnp.int32)
        # scatter: flat row -> padded slot; padding rows -> B*T (OOB =
        # zero row appended by _to_flat's pad mode)
        flat_slot = (jnp.arange(B)[:, None] * T + t_idx)
        scatter = jnp.full((N,), B * T, jnp.int32).at[
            gather.reshape(-1)].set(
                flat_slot.reshape(-1).astype(jnp.int32))
        return gather, scatter, lengths, B, T
    splits = np.asarray(lod[-1])
    lengths = (splits[1:] - splits[:-1]).astype(np.int64)
    B, T = len(lengths), int(lengths.max()) if len(lengths) else 0
    N = int(splits[-1])
    gather = np.full((B, max(T, 1)), N, dtype=np.int32)  # N = zero-pad row
    scatter = np.zeros(N, dtype=np.int32)
    for b in range(B):
        for t in range(lengths[b]):
            src = splits[b] + t
            slot = (lengths[b] - 1 - t) if is_reverse else t
            gather[b, slot] = src
            scatter[src] = b * max(T, 1) + slot
    return gather, scatter, lengths, B, max(T, 1)


def _to_padded(x, gather):
    padded_src = jnp.concatenate(
        [x, jnp.zeros((1,) + x.shape[1:], x.dtype)], axis=0)
    return padded_src[jnp.asarray(gather)]          # [B, T, ...]


def _to_flat(padded, scatter, B, T, pad=False):
    flat = padded.reshape((B * T,) + padded.shape[2:])
    if pad:
        # one extra zero row: dynamic-mode padding rows index B*T (static
        # scatters never reach B*T — skip the copy on the hot path)
        flat = jnp.concatenate(
            [flat, jnp.zeros((1,) + flat.shape[1:], flat.dtype)], axis=0)
    return flat[jnp.asarray(scatter)]


# ---------------------------------------------------------------------------
# lstm (layer: dynamic_lstm)
# ---------------------------------------------------------------------------

@register_op("lstm", infer_shape=_infer_rnn)
def lstm_lower(ctx: LowerContext):
    x = ctx.input("Input")          # [N, 4H] pre-projected
    weight = ctx.input("Weight")    # [H, 4H]
    bias = ctx.input("Bias")        # [1, 4H] (+3H peephole)
    lod = ctx.input_lod("Input")
    if lod is None:
        raise ValueError("lstm op requires LoD on Input")
    H = weight.shape[0]
    use_peepholes = ctx.attr("use_peepholes", False)
    is_reverse = ctx.attr("is_reverse", False)
    act_gate = _ACTS[ctx.attr("gate_activation", "sigmoid")]
    act_cell = _ACTS[ctx.attr("cell_activation", "tanh")]
    act_cand = _ACTS[ctx.attr("candidate_activation", "tanh")]

    gather, scatter, lengths, B, T = _lod_pad_tables(
        lod, is_reverse, ctx=ctx, n_rows=x.shape[0])
    xp = _to_padded(x, gather)                      # [B, T, 4H]
    xp = jnp.moveaxis(xp, 1, 0)                     # [T, B, 4H]
    len_arr = jnp.asarray(lengths)

    gate_bias = bias[:, :4 * H] if bias is not None else 0.0
    if use_peepholes:
        w_ic = bias[:, 4 * H:5 * H]
        w_fc = bias[:, 5 * H:6 * H]
        w_oc = bias[:, 6 * H:7 * H]

    h0 = ctx.input("H0")
    c0 = ctx.input("C0")
    h_init = h0 if h0 is not None else jnp.zeros((B, H), x.dtype)
    c_init = c0 if c0 is not None else jnp.zeros((B, H), x.dtype)

    def step(carry, inp):
        h_prev, c_prev, t = carry
        x_t = inp
        gates = x_t + h_prev @ weight + gate_bias
        g_c, g_i, g_f, g_o = jnp.split(gates, 4, axis=-1)
        if use_peepholes:
            g_i = g_i + c_prev * w_ic
            g_f = g_f + c_prev * w_fc
        i = act_gate(g_i)
        f = act_gate(g_f)
        cand = act_cand(g_c)
        c = f * c_prev + i * cand
        if use_peepholes:
            g_o = g_o + c * w_oc
        o = act_gate(g_o)
        h = o * act_cell(c)
        mask = (t < len_arr).astype(x.dtype)[:, None]
        h = mask * h + (1 - mask) * h_prev
        c = mask * c + (1 - mask) * c_prev
        return (h, c, t + 1), (h, c)

    (_, _, _), (hs, cs) = jax.lax.scan(
        step, (h_init, c_init, jnp.asarray(0, jnp.int32)), xp)
    hs = jnp.moveaxis(hs, 0, 1)                     # [B, T, H]
    cs = jnp.moveaxis(cs, 0, 1)
    ctx.set_output("Hidden", _to_flat(hs, scatter, B, T, pad=_dyn(lod)))
    ctx.set_output("Cell", _to_flat(cs, scatter, B, T, pad=_dyn(lod)))
    out_lod = lod if _dyn(lod) else [list(l) for l in lod]
    ctx.set_output_lod("Hidden", out_lod)
    ctx.set_output_lod("Cell", out_lod)


# ---------------------------------------------------------------------------
# gru (layer: dynamic_gru)
# ---------------------------------------------------------------------------

@register_op("gru", infer_shape=_infer_rnn)
def gru_lower(ctx: LowerContext):
    x = ctx.input("Input")          # [N, 3H]
    weight = ctx.input("Weight")    # [H, 3H] = [W_u | W_r | W_c]
    bias = ctx.input("Bias")        # [1, 3H]
    lod = ctx.input_lod("Input")
    if lod is None:
        raise ValueError("gru op requires LoD on Input")
    H = weight.shape[0]
    is_reverse = ctx.attr("is_reverse", False)
    act_gate = _ACTS[ctx.attr("gate_activation", "sigmoid")]
    act_cand = _ACTS[ctx.attr("activation", "tanh")]

    w_ur = weight[:, :2 * H]
    w_c = weight[:, 2 * H:]
    gather, scatter, lengths, B, T = _lod_pad_tables(
        lod, is_reverse, ctx=ctx, n_rows=x.shape[0])
    xp = jnp.moveaxis(_to_padded(x, gather), 1, 0)  # [T, B, 3H]
    len_arr = jnp.asarray(lengths)

    if bias is not None:
        xp = xp + bias

    h0 = ctx.input("H0")
    h_init = h0 if h0 is not None else jnp.zeros((B, H), x.dtype)

    def step(carry, inp):
        h_prev, t = carry
        x_t = inp
        g_ur = x_t[:, :2 * H] + h_prev @ w_ur
        u = act_gate(g_ur[:, :H])
        r = act_gate(g_ur[:, H:])
        cand = act_cand(x_t[:, 2 * H:] + (r * h_prev) @ w_c)
        # reference math/detail/gru_kernel.h: h = prev + u * (cand - prev)
        h = h_prev + u * (cand - h_prev)
        mask = (t < len_arr).astype(x.dtype)[:, None]
        h = mask * h + (1 - mask) * h_prev
        return (h, t + 1), h

    (_, _), hs = jax.lax.scan(step, (h_init, jnp.asarray(0, jnp.int32)), xp)
    hs = jnp.moveaxis(hs, 0, 1)
    ctx.set_output("Hidden", _to_flat(hs, scatter, B, T, pad=_dyn(lod)))
    ctx.set_output_lod("Hidden",
                       lod if _dyn(lod) else [list(l) for l in lod])


# ---------------------------------------------------------------------------
# single-step cells
# ---------------------------------------------------------------------------

@register_op("lstm_unit", infer_shape=_infer_unit)
def lstm_unit_lower(ctx: LowerContext):
    """One LSTM step (reference lstm_unit_op.cc): X [B,4H] pre-projected,
    C_prev [B,H] -> C, H.  Gate order (i, f, o, g) per the reference
    lstm_unit_op.h:63-66 / .cu:51-54 kernels."""
    x = ctx.input("X")
    c_prev = ctx.input("C_prev")
    forget_bias = ctx.attr("forget_bias", 0.0)
    H = c_prev.shape[-1]
    i, f, o, g = (x[:, :H], x[:, H:2 * H], x[:, 2 * H:3 * H], x[:, 3 * H:])
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f + forget_bias)
    o = jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c = f * c_prev + i * g
    h = o * jnp.tanh(c)
    ctx.set_output("C", c)
    ctx.set_output("H", h)


@register_op("gru_unit", infer_shape=_infer_unit)
def gru_unit_lower(ctx: LowerContext):
    """One GRU step (reference gru_unit_op.cc)."""
    x = ctx.input("Input")          # [B, 3H]
    h_prev = ctx.input("HiddenPrev")
    weight = ctx.input("Weight")    # [H, 3H]
    bias = ctx.input("Bias")
    act_gate = _ACTS[{1: "sigmoid", 2: "tanh", 0: "identity",
                      3: "relu"}.get(ctx.attr("gate_activation", 1),
                                     "sigmoid")] \
        if isinstance(ctx.attr("gate_activation", 1), int) \
        else _ACTS[ctx.attr("gate_activation", "sigmoid")]
    act_cand = _ACTS[{1: "sigmoid", 2: "tanh", 0: "identity",
                      3: "relu"}.get(ctx.attr("activation", 2), "tanh")] \
        if isinstance(ctx.attr("activation", 2), int) \
        else _ACTS[ctx.attr("activation", "tanh")]
    H = h_prev.shape[-1]
    if bias is not None:
        x = x + bias
    w_ur = weight[:, :2 * H]
    w_c = weight[:, 2 * H:]
    g_ur = x[:, :2 * H] + h_prev @ w_ur
    u = act_gate(g_ur[:, :H])
    r = act_gate(g_ur[:, H:])
    reset_h = r * h_prev
    cand = act_cand(x[:, 2 * H:] + reset_h @ w_c)
    # reference gru_unit_op.h: h = prev + u * (cand - prev)
    h = h_prev + u * (cand - h_prev)
    ctx.set_output("Gate", jnp.concatenate([u, r, cand], axis=-1))
    ctx.set_output("ResetHiddenPrev", reset_h)
    ctx.set_output("Hidden", h)


# ---------------------------------------------------------------------------
# lstmp (layer: dynamic_lstmp) — LSTM with recurrent projection
# (reference ``lstmp_op.h``: recurrence runs over r_t = proj_act(h_t P))
# ---------------------------------------------------------------------------

def _infer_lstmp(op, block):
    x = block.var(op.input("Input")[0])
    pw = block.var(op.input("ProjWeight")[0])
    if x.shape is None or pw.shape is None:
        raise ShapeInferenceSkip()
    h = pw.shape[0]
    p = pw.shape[1]
    proj = block.var(op.output("Projection")[0])
    proj.shape = (x.shape[0], p)
    proj.dtype = x.dtype
    proj.lod_level = x.lod_level
    cell = block.var(op.output("Cell")[0])
    cell.shape = (x.shape[0], h)
    cell.dtype = x.dtype
    cell.lod_level = x.lod_level


@register_op("lstmp", infer_shape=_infer_lstmp)
def lstmp_lower(ctx: LowerContext):
    x = ctx.input("Input")              # [N, 4H] pre-projected
    weight = ctx.input("Weight")        # [P, 4H] recurrent weight over r
    proj_weight = ctx.input("ProjWeight")  # [H, P]
    bias = ctx.input("Bias")            # [1, 4H] (+3H peephole)
    lod = ctx.input_lod("Input")
    if lod is None:
        raise ValueError("lstmp op requires LoD on Input")
    H, P = proj_weight.shape
    use_peepholes = ctx.attr("use_peepholes", False)
    is_reverse = ctx.attr("is_reverse", False)
    act_gate = _ACTS[ctx.attr("gate_activation", "sigmoid")]
    act_cell = _ACTS[ctx.attr("cell_activation", "tanh")]
    act_cand = _ACTS[ctx.attr("candidate_activation", "tanh")]
    act_proj = _ACTS[ctx.attr("proj_activation", "tanh")]

    gather, scatter, lengths, B, T = _lod_pad_tables(
        lod, is_reverse, ctx=ctx, n_rows=x.shape[0])
    xp = jnp.moveaxis(_to_padded(x, gather), 1, 0)   # [T, B, 4H]
    len_arr = jnp.asarray(lengths)

    gate_bias = bias[:, :4 * H] if bias is not None else 0.0
    if use_peepholes:
        w_ic = bias[:, 4 * H:5 * H]
        w_fc = bias[:, 5 * H:6 * H]
        w_oc = bias[:, 6 * H:7 * H]

    r_init = jnp.zeros((B, P), x.dtype)
    c_init = jnp.zeros((B, H), x.dtype)

    def step(carry, x_t):
        r_prev, c_prev, t = carry
        gates = x_t + r_prev @ weight + gate_bias
        g_c, g_i, g_f, g_o = jnp.split(gates, 4, axis=-1)
        if use_peepholes:
            g_i = g_i + c_prev * w_ic
            g_f = g_f + c_prev * w_fc
        i = act_gate(g_i)
        f = act_gate(g_f)
        cand = act_cand(g_c)
        c = f * c_prev + i * cand
        if use_peepholes:
            g_o = g_o + c * w_oc
        o = act_gate(g_o)
        h = o * act_cell(c)
        r = act_proj(h @ proj_weight)
        mask = (t < len_arr).astype(x.dtype)[:, None]
        r = mask * r + (1 - mask) * r_prev
        c = mask * c + (1 - mask) * c_prev
        return (r, c, t + 1), (r, c)

    (_, _, _), (rs, cs) = jax.lax.scan(
        step, (r_init, c_init, jnp.asarray(0, jnp.int32)), xp)
    rs = jnp.moveaxis(rs, 0, 1)
    cs = jnp.moveaxis(cs, 0, 1)
    ctx.set_output("Projection", _to_flat(rs, scatter, B, T, pad=_dyn(lod)))
    ctx.set_output("Cell", _to_flat(cs, scatter, B, T, pad=_dyn(lod)))
    out_lod = lod if _dyn(lod) else [list(l) for l in lod]
    ctx.set_output_lod("Projection", out_lod)
    ctx.set_output_lod("Cell", out_lod)
