"""Metric ops — reference ``accuracy_op.cc``, ``auc_op.cc``,
``precision_recall_op.cc``, ``edit_distance_op.cc``."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.ops.registry import register_op, ShapeInferenceSkip


def _infer_accuracy(op, block):
    for slot in ("Accuracy", "Correct", "Total"):
        names = op.output(slot)
        if names:
            v = block.var(names[0])
            v.shape = (1,)
            v.dtype = "float32" if slot == "Accuracy" else "int64"


@register_op("accuracy", infer_shape=_infer_accuracy, no_gradient=True)
def accuracy_lower(ctx):
    # Out: top-k indices from top_k op (N, k); Label: (N, 1)
    indices = ctx.input("Indices")
    label = ctx.input("Label")
    if label.ndim == 2:
        label = label.reshape(-1)
    correct = jnp.any(indices == label[:, None], axis=1)
    num_correct = jnp.sum(correct.astype(jnp.int64))
    total = jnp.asarray(indices.shape[0], dtype=jnp.int64)
    ctx.set_output("Accuracy",
                   (num_correct.astype(jnp.float32) / total).reshape(1))
    ctx.set_output("Correct", num_correct.reshape(1))
    ctx.set_output("Total", total.reshape(1))


@register_op("auc", no_gradient=True)
def auc_lower(ctx):
    """Streaming AUC using histogram buckets (reference auc_op.cc)."""
    predict = ctx.input("Predict")  # (N, 2) softmax probs or (N,1)
    label = ctx.input("Label").reshape(-1)
    pos_score = predict[:, -1]
    num_buckets = ctx.attr("num_thresholds", 200) + 1
    bucket = jnp.clip((pos_score * (num_buckets - 1)).astype(jnp.int32),
                      0, num_buckets - 1)
    is_pos = (label > 0).astype(jnp.int64)
    tp_hist = jnp.zeros(num_buckets, jnp.int64).at[bucket].add(is_pos)
    fp_hist = jnp.zeros(num_buckets, jnp.int64).at[bucket].add(1 - is_pos)
    stat_pos = ctx.input("StatPos")
    stat_neg = ctx.input("StatNeg")
    if stat_pos is not None:
        tp_hist = tp_hist + stat_pos.astype(jnp.int64)
        fp_hist = fp_hist + stat_neg.astype(jnp.int64)
    # AUC by trapezoid over descending-threshold cumulative counts
    tp_cum = jnp.cumsum(tp_hist[::-1])
    fp_cum = jnp.cumsum(fp_hist[::-1])
    tot_pos = tp_cum[-1]
    tot_neg = fp_cum[-1]
    tp_prev = jnp.concatenate([jnp.zeros(1, jnp.int64), tp_cum[:-1]])
    fp_prev = jnp.concatenate([jnp.zeros(1, jnp.int64), fp_cum[:-1]])
    area = jnp.sum((fp_cum - fp_prev) * (tp_cum + tp_prev) / 2.0)
    denom = (tot_pos * tot_neg).astype(jnp.float64).astype(jnp.float32)
    auc = jnp.where(denom > 0, area.astype(jnp.float32) / jnp.maximum(denom, 1.0), 0.0)
    ctx.set_output("AUC", auc.reshape(1))
    ctx.set_output("StatPosOut", tp_hist)
    ctx.set_output("StatNegOut", fp_hist)


@register_op("precision_recall", no_gradient=True)
def precision_recall_lower(ctx):
    """Multi-class precision/recall (macro + micro averaged)."""
    max_probs = ctx.input("MaxProbs")
    indices = ctx.input("Indices").reshape(-1)
    labels = ctx.input("Labels").reshape(-1)
    cls = ctx.attr("class_number")
    weights = ctx.input("Weights")
    w = weights.reshape(-1) if weights is not None else \
        jnp.ones_like(labels, dtype=jnp.float32)
    pred = indices
    tp = jnp.zeros(cls, jnp.float32).at[labels].add(
        w * (pred == labels).astype(jnp.float32))
    fp = jnp.zeros(cls, jnp.float32).at[pred].add(
        w * (pred != labels).astype(jnp.float32))
    fn = jnp.zeros(cls, jnp.float32).at[labels].add(
        w * (pred != labels).astype(jnp.float32))
    states = ctx.input("StatesInfo")
    if states is not None:  # (cls, 4): tp, fp, tn, fn accumulated
        tp = tp + states[:, 0]
        fp = fp + states[:, 1]
        fn = fn + states[:, 3]
    eps = 1e-6
    prec = tp / jnp.maximum(tp + fp, eps)
    rec = tp / jnp.maximum(tp + fn, eps)
    f1 = 2 * prec * rec / jnp.maximum(prec + rec, eps)
    macro = jnp.stack([jnp.mean(prec), jnp.mean(rec), jnp.mean(f1)])
    mtp, mfp, mfn = jnp.sum(tp), jnp.sum(fp), jnp.sum(fn)
    mprec = mtp / jnp.maximum(mtp + mfp, eps)
    mrec = mtp / jnp.maximum(mtp + mfn, eps)
    micro = jnp.stack([mprec, mrec,
                       2 * mprec * mrec / jnp.maximum(mprec + mrec, eps)])
    ctx.set_output("BatchMetrics", jnp.concatenate([macro, micro]))
    ctx.set_output("AccumMetrics", jnp.concatenate([macro, micro]))
    zeros = jnp.zeros(cls, jnp.float32)
    ctx.set_output("AccumStatesInfo", jnp.stack([tp, fp, zeros, fn], axis=1))


# ---------------------------------------------------------------------------
# chunk_eval — reference ``chunk_eval_op.h`` (NER chunk F1 under
# IOB/IOE/IOBES/plain schemes).  Host op: LoD-ragged segment parsing.
# ---------------------------------------------------------------------------

_CHUNK_SCHEMES = {
    # scheme: (num_tag_types, tag_begin, tag_inside, tag_end, tag_single)
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, 0),
}


def _chunk_segments(labels, num_chunk_types, scheme):
    """Parse (begin, end, type) segments from one tag sequence —
    reference GetSegments/ChunkBegin/ChunkEnd."""
    num_tag, tag_begin, tag_inside, tag_end, tag_single = \
        _CHUNK_SCHEMES[scheme]
    other = num_chunk_types

    def chunk_end(ptag, ptype, tag, type_):
        if ptype == other:
            return False
        if type_ == other or type_ != ptype:
            return True
        if ptag in (tag_begin, tag_inside):
            return tag in (tag_begin, tag_single)
        return ptag in (tag_end, tag_single)

    def chunk_begin(ptag, ptype, tag, type_):
        if ptype == other:
            return type_ != other
        if type_ == other:
            return False
        if type_ != ptype:
            return True
        if tag == tag_begin or tag == tag_single:
            return True
        if tag in (tag_inside, tag_end):
            return ptag in (tag_end, tag_single)
        return False

    segments = []
    in_chunk = False
    start = 0
    tag, type_ = -1, other
    for i, lab in enumerate(labels):
        ptag, ptype = tag, type_
        tag = int(lab) % num_tag
        type_ = int(lab) // num_tag
        if in_chunk and chunk_end(ptag, ptype, tag, type_):
            segments.append((start, i - 1, ptype))
            in_chunk = False
        if chunk_begin(ptag, ptype, tag, type_):
            start = i
            in_chunk = True
    if in_chunk:
        segments.append((start, len(labels) - 1, type_))
    return segments


@register_op("chunk_eval", no_gradient=True, host=True)
def chunk_eval_lower(ctx):
    import numpy as np
    inference = np.asarray(ctx.input("Inference")).reshape(-1)
    label = np.asarray(ctx.input("Label")).reshape(-1)
    lod = ctx.input_lod("Inference") or ctx.input_lod("Label")
    splits = lod[0] if lod is not None else [0, len(label)]
    num_chunk_types = int(ctx.attr("num_chunk_types"))
    scheme = ctx.attr("chunk_scheme", "IOB")
    excluded = set(ctx.attr("excluded_chunk_types", []) or [])

    num_infer = num_label = num_correct = 0
    for i in range(len(splits) - 1):
        lo, hi = int(splits[i]), int(splits[i + 1])
        inf_seg = [s for s in _chunk_segments(inference[lo:hi],
                                              num_chunk_types, scheme)
                   if s[2] not in excluded]
        lab_seg = [s for s in _chunk_segments(label[lo:hi],
                                              num_chunk_types, scheme)
                   if s[2] not in excluded]
        num_infer += len(inf_seg)
        num_label += len(lab_seg)
        num_correct += len(set(inf_seg) & set(lab_seg))

    precision = num_correct / num_infer if num_infer else 0.0
    recall = num_correct / num_label if num_label else 0.0
    f1 = (2 * precision * recall / (precision + recall)
          if num_correct else 0.0)
    ctx.set_output("Precision", jnp.asarray([precision], jnp.float32))
    ctx.set_output("Recall", jnp.asarray([recall], jnp.float32))
    ctx.set_output("F1-Score", jnp.asarray([f1], jnp.float32))
    ctx.set_output("NumInferChunks", jnp.asarray([num_infer], jnp.int64))
    ctx.set_output("NumLabelChunks", jnp.asarray([num_label], jnp.int64))
    ctx.set_output("NumCorrectChunks", jnp.asarray([num_correct], jnp.int64))


# ---------------------------------------------------------------------------
# positive_negative_pair — reference ``positive_negative_pair_op.h``:
# per-query ranking pair statistics (LTR models).
# ---------------------------------------------------------------------------

@register_op("positive_negative_pair", no_gradient=True, host=True)
def positive_negative_pair_lower(ctx):
    import numpy as np
    score = np.asarray(ctx.input("Score")).reshape(-1)
    label = np.asarray(ctx.input("Label")).reshape(-1)
    qid = np.asarray(ctx.input("QueryID")).reshape(-1)
    weight = ctx.input("Weight")
    w = np.asarray(weight).reshape(-1) if weight is not None else None
    pos = neg = neu = 0.0
    for q in np.unique(qid):
        idx = np.where(qid == q)[0]
        for a in range(len(idx)):
            for b in range(a + 1, len(idx)):
                i, j = idx[a], idx[b]
                if label[i] == label[j]:
                    continue
                pair_w = 1.0 if w is None else (w[i] + w[j]) / 2.0
                hi, lo = (i, j) if label[i] > label[j] else (j, i)
                if score[hi] > score[lo]:
                    pos += pair_w
                elif score[hi] == score[lo]:
                    neu += pair_w
                else:
                    neg += pair_w
    # accumulate previous state if wired
    for slot, add in (("AccumulatePositivePair", pos),
                      ("AccumulateNegativePair", neg),
                      ("AccumulateNeutralPair", neu)):
        prev = ctx.input(slot)
        if prev is not None:
            if slot.endswith("PositivePair"):
                pos = add + float(np.asarray(prev).reshape(-1)[0])
            elif slot.endswith("NegativePair"):
                neg = add + float(np.asarray(prev).reshape(-1)[0])
            else:
                neu = add + float(np.asarray(prev).reshape(-1)[0])
    ctx.set_output("PositivePair", jnp.asarray([pos], jnp.float32))
    ctx.set_output("NegativePair", jnp.asarray([neg], jnp.float32))
    ctx.set_output("NeutralPair", jnp.asarray([neu], jnp.float32))
