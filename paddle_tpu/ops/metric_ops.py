"""Metric ops — reference ``accuracy_op.cc``, ``auc_op.cc``,
``precision_recall_op.cc``, ``edit_distance_op.cc``."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.ops.registry import register_op, ShapeInferenceSkip


def _infer_accuracy(op, block):
    for slot in ("Accuracy", "Correct", "Total"):
        names = op.output(slot)
        if names:
            v = block.var(names[0])
            v.shape = (1,)
            v.dtype = "float32" if slot == "Accuracy" else "int64"


@register_op("accuracy", infer_shape=_infer_accuracy, no_gradient=True)
def accuracy_lower(ctx):
    # Out: top-k indices from top_k op (N, k); Label: (N, 1)
    indices = ctx.input("Indices")
    label = ctx.input("Label")
    if label.ndim == 2:
        label = label.reshape(-1)
    correct = jnp.any(indices == label[:, None], axis=1)
    num_correct = jnp.sum(correct.astype(jnp.int64))
    total = jnp.asarray(indices.shape[0], dtype=jnp.int64)
    ctx.set_output("Accuracy",
                   (num_correct.astype(jnp.float32) / total).reshape(1))
    ctx.set_output("Correct", num_correct.reshape(1))
    ctx.set_output("Total", total.reshape(1))


@register_op("auc", no_gradient=True)
def auc_lower(ctx):
    """Streaming AUC using histogram buckets (reference auc_op.cc)."""
    predict = ctx.input("Predict")  # (N, 2) softmax probs or (N,1)
    label = ctx.input("Label").reshape(-1)
    pos_score = predict[:, -1]
    num_buckets = ctx.attr("num_thresholds", 200) + 1
    bucket = jnp.clip((pos_score * (num_buckets - 1)).astype(jnp.int32),
                      0, num_buckets - 1)
    is_pos = (label > 0).astype(jnp.int64)
    tp_hist = jnp.zeros(num_buckets, jnp.int64).at[bucket].add(is_pos)
    fp_hist = jnp.zeros(num_buckets, jnp.int64).at[bucket].add(1 - is_pos)
    stat_pos = ctx.input("StatPos")
    stat_neg = ctx.input("StatNeg")
    if stat_pos is not None:
        tp_hist = tp_hist + stat_pos.astype(jnp.int64)
        fp_hist = fp_hist + stat_neg.astype(jnp.int64)
    # AUC by trapezoid over descending-threshold cumulative counts
    tp_cum = jnp.cumsum(tp_hist[::-1])
    fp_cum = jnp.cumsum(fp_hist[::-1])
    tot_pos = tp_cum[-1]
    tot_neg = fp_cum[-1]
    tp_prev = jnp.concatenate([jnp.zeros(1, jnp.int64), tp_cum[:-1]])
    fp_prev = jnp.concatenate([jnp.zeros(1, jnp.int64), fp_cum[:-1]])
    area = jnp.sum((fp_cum - fp_prev) * (tp_cum + tp_prev) / 2.0)
    denom = (tot_pos * tot_neg).astype(jnp.float64).astype(jnp.float32)
    auc = jnp.where(denom > 0, area.astype(jnp.float32) / jnp.maximum(denom, 1.0), 0.0)
    ctx.set_output("AUC", auc.reshape(1))
    ctx.set_output("StatPosOut", tp_hist)
    ctx.set_output("StatNegOut", fp_hist)


@register_op("precision_recall", no_gradient=True)
def precision_recall_lower(ctx):
    """Multi-class precision/recall (macro + micro averaged)."""
    max_probs = ctx.input("MaxProbs")
    indices = ctx.input("Indices").reshape(-1)
    labels = ctx.input("Labels").reshape(-1)
    cls = ctx.attr("class_number")
    weights = ctx.input("Weights")
    w = weights.reshape(-1) if weights is not None else \
        jnp.ones_like(labels, dtype=jnp.float32)
    pred = indices
    tp = jnp.zeros(cls, jnp.float32).at[labels].add(
        w * (pred == labels).astype(jnp.float32))
    fp = jnp.zeros(cls, jnp.float32).at[pred].add(
        w * (pred != labels).astype(jnp.float32))
    fn = jnp.zeros(cls, jnp.float32).at[labels].add(
        w * (pred != labels).astype(jnp.float32))
    states = ctx.input("StatesInfo")
    if states is not None:  # (cls, 4): tp, fp, tn, fn accumulated
        tp = tp + states[:, 0]
        fp = fp + states[:, 1]
        fn = fn + states[:, 3]
    eps = 1e-6
    prec = tp / jnp.maximum(tp + fp, eps)
    rec = tp / jnp.maximum(tp + fn, eps)
    f1 = 2 * prec * rec / jnp.maximum(prec + rec, eps)
    macro = jnp.stack([jnp.mean(prec), jnp.mean(rec), jnp.mean(f1)])
    mtp, mfp, mfn = jnp.sum(tp), jnp.sum(fp), jnp.sum(fn)
    mprec = mtp / jnp.maximum(mtp + mfp, eps)
    mrec = mtp / jnp.maximum(mtp + mfn, eps)
    micro = jnp.stack([mprec, mrec,
                       2 * mprec * mrec / jnp.maximum(mprec + mrec, eps)])
    ctx.set_output("BatchMetrics", jnp.concatenate([macro, micro]))
    ctx.set_output("AccumMetrics", jnp.concatenate([macro, micro]))
    zeros = jnp.zeros(cls, jnp.float32)
    ctx.set_output("AccumStatesInfo", jnp.stack([tp, fp, zeros, fn], axis=1))
