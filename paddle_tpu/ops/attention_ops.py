"""Fused scaled-dot-product attention with a Pallas TPU kernel.

The reference composes attention from mul/softmax/matmul graph ops
(``python/paddle/fluid/nets.py`` scaled_dot_product_attention;
``test_parallel_executor.py`` transformer).  On TPU the [B,H,S,S] score
tensor is the HBM-bandwidth hot spot, so the forward fuses
QK^T -> mask -> softmax -> AV in ONE Pallas kernel per (batch, head,
q-block): scores live only in VMEM.  Backward recomputes through the XLA
reference path (flash backward kernel is a later optimization).

Masking model (matches the transformer workloads):
  * ``k_mask`` [B, S_k] with 1 = attend / 0 = padding, optional;
  * ``causal`` flag for decoder self-attention.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.registry import (
    register_op, LowerContext, ShapeInferenceSkip)

NEG_INF = -1e9


def _reference_attention(q, k, v, k_mask, causal, scale):
    """Plain-XLA attention; also the vjp path for the Pallas forward.

    Dtype-stable: scores/softmax in f32, output in ``q.dtype`` — so the
    fallback path and the Pallas kernel (out dtype = q.dtype) agree, and
    vjp cotangents always match the forward output dtype (bf16 under AMP).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if k_mask is not None:
        s = s + (1.0 - k_mask[:, None, None, :].astype(jnp.float32)) \
            * NEG_INF
    if causal:
        S_q, S_k = q.shape[2], k.shape[2]
        row = jax.lax.broadcasted_iota(jnp.int32, (S_q, S_k), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (S_q, S_k), 1)
        s = s + jnp.where(col > row, NEG_INF, 0.0)[None, None]
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def _flash_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *, causal, scale,
                  block_q):
    q = q_ref[0, 0]                     # [Bq, D]
    k = k_ref[0, 0]                     # [S, D]
    v = v_ref[0, 0]                     # [S, D]
    s = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale   # [Bq, S]
    mask = mask_ref[0, 0].astype(jnp.float32)  # [S] (mask arrives [B, 1, S])
    s = s + (1.0 - mask)[None, :] * NEG_INF
    if causal:
        i = pl.program_id(2)
        S = k.shape[0]
        row = jax.lax.broadcasted_iota(jnp.int32, (block_q, S), 0) \
            + i * block_q
        col = jax.lax.broadcasted_iota(jnp.int32, (block_q, S), 1)
        s = s + jnp.where(col > row, NEG_INF, 0.0)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    denom = jnp.sum(p, axis=-1, keepdims=True)
    # second MXU pass in the kv dtype (bf16 under mixed precision)
    o = jax.lax.dot_general(
        p.astype(v.dtype), v, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) / denom
    o_ref[0, 0] = o.astype(o_ref.dtype)


try:  # pallas is TPU/GPU-oriented; import lazily-safe
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def _pick_block_q(s_q):
    """Pallas TPU needs the second-to-last block dim divisible by 8 or
    equal to the array dim; None = use the reference path instead."""
    for cand in (128, 64, 32, 16, 8):
        if s_q % cand == 0:
            return cand
    return s_q if s_q <= 512 else None  # full-array block as last resort


def _pallas_attention(q, k, v, k_mask, causal, scale, interpret=False):
    B, H, S_q, D_k = q.shape
    S_k = k.shape[2]
    D_v = v.shape[3]
    block_q = _pick_block_q(S_q)
    if block_q is None:
        return _reference_attention(q, k, v, k_mask, causal, scale)
    grid = (B, H, S_q // block_q)
    kernel = functools.partial(_flash_kernel, causal=causal, scale=scale,
                               block_q=block_q)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D_k),
                         lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S_k, D_k), lambda b, h, i: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S_k, D_v), lambda b, h, i: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S_k), lambda b, h, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D_v),
                               lambda b, h, i: (b, h, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, H, S_q, D_v), q.dtype),
        interpret=interpret,
    )(q, k, v, k_mask[:, None, :])


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def fused_attention(q, k, v, k_mask, causal, scale, use_pallas):
    if use_pallas and _HAS_PALLAS:
        on_tpu = any(d.platform == "tpu" for d in jax.devices())
        return _pallas_attention(q, k, v, k_mask, causal, scale,
                                 interpret=not on_tpu)
    return _reference_attention(q, k, v, k_mask, causal, scale)


def _fused_fwd(q, k, v, k_mask, causal, scale, use_pallas):
    out = fused_attention(q, k, v, k_mask, causal, scale, use_pallas)
    return out, (q, k, v, k_mask)


def _fused_bwd(causal, scale, use_pallas, res, g):
    q, k, v, k_mask = res
    _, vjp_fn = jax.vjp(
        lambda q_, k_, v_: _reference_attention(q_, k_, v_, k_mask,
                                                causal, scale),
        q, k, v)
    dq, dk, dv = vjp_fn(g)
    return dq, dk, dv, None


fused_attention.defvjp(_fused_fwd, _fused_bwd)


# ---------------------------------------------------------------------------
# IR op
# ---------------------------------------------------------------------------

def _infer_attn(op, block):
    q = block.var(op.input("Q")[0])
    v = block.var(op.input("V")[0])
    out = block.var(op.output("Out")[0])
    if q.shape is None or v.shape is None:
        raise ShapeInferenceSkip()
    out.shape = tuple(q.shape[:3]) + (v.shape[3],)
    out.dtype = q.dtype


def _attn_grad_lower(ctx: LowerContext):
    qe = ctx.env[ctx.op.input("Q")[0]]
    ke = ctx.env[ctx.op.input("K")[0]]
    ve = ctx.env[ctx.op.input("V")[0]]
    mask_names = ctx.op.input("KMask")
    k_mask = ctx.env[mask_names[0]] if mask_names else None
    if k_mask is None:
        k_mask = jnp.ones((qe.shape[0], ke.shape[2]), qe.dtype)
    causal = ctx.attr("causal", False)
    scale = ctx.attr("scale", 1.0)
    g = ctx.env[ctx.op.input("Out@GRAD")[0]]
    # mirror the forward's AMP cast so the vjp's output dtype matches the
    # cotangent coming back from (possibly bf16) downstream consumers;
    # emitted grads are cast back to the primal env dtypes
    amp = bool(ctx.aux.get("amp"))

    def cast_in(x):
        return x.astype(jnp.bfloat16) \
            if amp and x.dtype == jnp.float32 else x

    q, k, v = cast_in(qe), cast_in(ke), cast_in(ve)
    _, vjp_fn = jax.vjp(
        lambda q_, k_, v_: _reference_attention(q_, k_, v_, k_mask,
                                                causal, scale), q, k, v)
    dq, dk, dv = vjp_fn(g.astype(q.dtype))
    for slot, val, prim in (("Q@GRAD", dq, qe), ("K@GRAD", dk, ke),
                            ("V@GRAD", dv, ve)):
        names = ctx.op.output(slot)
        if names and names[0]:
            ctx.outputs[names[0]] = val.astype(prim.dtype)


@register_op("scaled_dot_product_attention", infer_shape=_infer_attn,
             grad_lower=_attn_grad_lower, no_grad_inputs=("KMask",),
             amp_cast=("Q", "K", "V"))
def sdpa_lower(ctx: LowerContext):
    """Q,K,V: [B, H, S, D]; KMask: [B, S_k] (1=attend); Out: [B, H, Sq, D].

    attrs: causal (bool), scale (float), use_flash (bool, default True).
    """
    q = ctx.input("Q")
    k = ctx.input("K")
    v = ctx.input("V")
    k_mask = ctx.input("KMask")
    if k_mask is None:
        k_mask = jnp.ones((q.shape[0], k.shape[2]), q.dtype)
    causal = ctx.attr("causal", False)
    scale = ctx.attr("scale", 1.0)
    use_flash = ctx.attr("use_flash", True)
    # flash path has no attention-weight dropout; the graph builder falls
    # back to the composed path when dropout is requested in training
    ctx.set_output("Out", fused_attention(q, k, v, k_mask, causal,
                                          float(scale), bool(use_flash)))
