"""Fused scaled-dot-product attention with a Pallas TPU kernel.

The reference composes attention from mul/softmax/matmul graph ops
(``python/paddle/fluid/nets.py`` scaled_dot_product_attention;
``test_parallel_executor.py`` transformer).  On TPU the [B,H,S,S] score
tensor is the HBM-bandwidth hot spot, so the forward fuses
QK^T -> mask -> softmax -> AV in ONE Pallas kernel per (batch, head,
q-block): scores live only in VMEM.  K/V stream through VMEM one block at
a time with an online softmax (VMEM use independent of sequence length),
and the backward runs as two flash kernels (dq; dk+dv) from the saved
log-sum-exp residual, with fully-masked causal blocks skipped.  Measured
crossover (``bench_attention.py`` -> checked-in ``BENCH_ATTENTION.md``,
v5e fwd+bwd causal bf16, 64k tokens, 1024-blocks): S=512 flash 1.13x of
XLA, S=1024 1.47x, S=2048 1.94x, S=4096 XLA OOMs ([B,H,S,S] f32 scores)
while flash runs.  Below the PADDLE_TPU_FLASH_MIN_S crossover (default
512, from that artifact) the composed XLA path wins and is used
instead.

Masking model (matches the transformer workloads):
  * ``k_mask`` [B, S_k] with 1 = attend / 0 = padding, optional;
  * ``causal`` flag for decoder self-attention.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from paddle_tpu.ops.registry import (
    register_op, LowerContext, ShapeInferenceSkip)

NEG_INF = -1e9


def _reference_attention(q, k, v, k_mask, causal, scale):
    """Plain-XLA attention; also the vjp path for the Pallas forward.

    Dtype-stable: scores/softmax in f32, output in ``q.dtype`` — so the
    fallback path and the Pallas kernel (out dtype = q.dtype) agree, and
    vjp cotangents always match the forward output dtype (bf16 under AMP).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if k_mask is not None:
        s = s + (1.0 - k_mask[:, None, None, :].astype(jnp.float32)) \
            * NEG_INF
    if causal:
        S_q, S_k = q.shape[2], k.shape[2]
        row = jax.lax.broadcasted_iota(jnp.int32, (S_q, S_k), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (S_q, S_k), 1)
        s = s + jnp.where(col > row, NEG_INF, 0.0)[None, None]
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


try:  # pallas is TPU/GPU-oriented; import lazily-safe
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

_M_INIT = -1e30


def _causal_bias(i, j, block_q, block_k):
    row = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) \
        + i * block_q
    col = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) \
        + j * block_k
    return jnp.where(col > row, NEG_INF, 0.0)


def _block_scores(q, k, mask, scale, causal, i, j, block_q, block_k):
    """f32 [Bq, Bk] masked scaled scores for q block i vs k block j."""
    s = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    s = s + (1.0 - mask.astype(jnp.float32))[None, :] * NEG_INF
    if causal:
        s = s + _causal_bias(i, j, block_q, block_k)
    return s


def _flash_fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref,
                      acc, m_scr, l_scr, *, causal, scale, block_q,
                      block_k):
    """Online-softmax forward: K/V stream through VMEM one [Bk, D] block
    per grid step (sequential innermost axis), so VMEM use is O(Bq*Bk) —
    independent of sequence length."""
    i = pl.program_id(2)
    j = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)
        m_scr[...] = jnp.full_like(m_scr, _M_INIT)
        l_scr[...] = jnp.zeros_like(l_scr)

    # causal: blocks entirely above the diagonal contribute nothing —
    # skip their MXU work (roughly halves the causal grid's compute)
    live = (j * block_k <= (i + 1) * block_q - 1) if causal else True

    @pl.when(live)
    def _():
        q = q_ref[0, 0]                   # [Bq, D]
        k = k_ref[0, 0]                   # [Bk, D]
        v = v_ref[0, 0]                   # [Bk, Dv]
        s = _block_scores(q, k, mask_ref[0, 0], scale, causal, i, j,
                          block_q, block_k)
        m_prev = m_scr[...]               # [Bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)            # [Bq, Bk] f32
        alpha = jnp.exp(m_prev - m_new)   # [Bq, 1]
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc[...] = acc[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = m_new

    @pl.when(j == nk - 1)
    def _():
        # l > 0 always: each row's running max contributes exp(0) = 1, and
        # fully-masked rows softmax over the -1e9-shifted scores exactly
        # like _reference_attention
        l = l_scr[...]
        o_ref[0, 0] = (acc[...] / l).astype(o_ref.dtype)
        # residual saved as (m, log l) SEPARATELY: on fully-masked rows
        # m ~ -1e9 and fl(m + log l) == m in f32 (ulp(1e9) = 64), which
        # would make bwd's p = exp(s - lse) = 1 per entry instead of 1/n
        lse_ref[0, 0] = jnp.concatenate([m_scr[...], jnp.log(l)], axis=1)


def _flash_dkdv_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref,
                       delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                       causal, scale, block_q, block_k):
    """One (b, h, k-block); inner sequential axis streams q blocks."""
    i = pl.program_id(3)
    j = pl.program_id(2)
    nq = pl.num_programs(3)

    @pl.when(i == 0)
    def _():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    live = (j * block_k <= (i + 1) * block_q - 1) if causal else True

    @pl.when(live)
    def _():
        q = q_ref[0, 0]                   # [Bq, D]
        k = k_ref[0, 0]                   # [Bk, D]
        v = v_ref[0, 0]                   # [Bk, Dv]
        do = do_ref[0, 0]                 # [Bq, Dv]
        m = lse_ref[0, 0][:, 0:1]         # [Bq, 1]
        logl = lse_ref[0, 0][:, 1:2]      # [Bq, 1]
        delta = delta_ref[0, 0]           # [Bq, 1]
        s = _block_scores(q, k, mask_ref[0, 0], scale, causal, i, j,
                          block_q, block_k)
        # (s - m) first so the +-1e9 magnitudes cancel exactly, THEN the
        # O(1) log-denominator — true softmax probs, f32
        p = jnp.exp((s - m) - logl)
        # dv += p^T @ do
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dp = do @ v^T ; ds = p * (dp - delta) * scale
        dp = jax.lax.dot_general(
            do, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        # dk += ds^T @ q
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == nq - 1)
    def _():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_dq_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref,
                     delta_ref, dq_ref, dq_acc, *, causal, scale, block_q,
                     block_k):
    """One (b, h, q-block); inner sequential axis streams k blocks."""
    i = pl.program_id(2)
    j = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    live = (j * block_k <= (i + 1) * block_q - 1) if causal else True

    @pl.when(live)
    def _():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        m = lse_ref[0, 0][:, 0:1]         # [Bq, 1]
        logl = lse_ref[0, 0][:, 1:2]      # [Bq, 1]
        delta = delta_ref[0, 0]           # [Bq, 1]
        s = _block_scores(q, k, mask_ref[0, 0], scale, causal, i, j,
                          block_q, block_k)
        p = jnp.exp((s - m) - logl)
        dp = jax.lax.dot_general(
            do, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


def _pick_block(s, prefer=None):
    """Largest block size tiling ``s`` evenly (TPU wants the sublane dim a
    multiple of 8); None = no even tiling -> use the reference path.
    1024-blocks are the measured VMEM sweet spot (see _flash_blocks);
    a full-array block up to 1024 is the last resort."""
    if prefer is None:
        prefer = _BLOCK_PREFER
    for cand in prefer:
        if s % cand == 0:
            return cand
    return s if s <= 1024 else None  # full-array block as last resort


_BLOCK_PREFER = (1024, 512, 256, 128, 64, 32, 16, 8)


def _flash_blocks(S_q, S_k, interpret=False):
    # 1024-first: measured on v5e (fwd+bwd causal bf16, 64k tokens) —
    # (1024,1024) beats the old (256,512) by 27-30% at S>=2048 (smaller
    # S picks its own full-array block); (2048,2048) exceeds VMEM.
    block_q = _pick_block(S_q)
    block_k = _pick_block(S_k)
    if not interpret:
        # real TPU lowering: a block's last dim must be a multiple of 128
        # or equal to the array dim (the mask block's last dim is block_k)
        if block_k is not None and block_k % 128 and block_k != S_k:
            block_k = None
        if block_q is not None and block_q % 8 and block_q != S_q:
            block_q = None
    return block_q, block_k


# ---------------------------------------------------------------------------
# small-S single-pass kernels: when the whole [S, S] score tile fits VMEM
# there is no reason to stream K/V or keep online-softmax scratch.  Fold
# (B, H) into ONE grid axis with G bh-pairs per program (vs the streaming
# grid's (B, H, nq, nk) — 2048 tiny programs at transformer-base S=256),
# compute the softmax in one pass, and run ONE backward kernel producing
# dq/dk/dv together (the streaming backward is two kernels, each
# recomputing the scores).  Measured v5e fwd+bwd causal bf16, 64k tokens:
# S=256 15.6ms vs 18.1 XLA / 18.9 streaming-flash; S=512 16.2ms vs
# 19.9 / 18.0 (exp_smalls_attn.py artifact).
# ---------------------------------------------------------------------------

_SMALLS_MAX_S = 1024
_SMALLS_SCORE_VMEM = 4 << 20      # f32 score bytes per program; G8*512^2*4
                                  # = 8MB exceeded the 16MB scoped limit


def _smalls_group(BH, S):
    """Largest bh-group size whose unrolled score tiles fit the measured
    VMEM budget; None = shape not eligible for the single-pass path."""
    if S > _SMALLS_MAX_S or S % 128:
        return None
    for g in (8, 4, 2, 1):
        if BH % g == 0 and g * S * S * 4 <= _SMALLS_SCORE_VMEM:
            return g
    return None


def _causal_bias_full(S):
    row = jax.lax.broadcasted_iota(jnp.int32, (S, S), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (S, S), 1)
    return jnp.where(col > row, NEG_INF, 0.0)


def _smalls_scores(q, k, mask_col, scale, bias):
    """f32 [S, S] masked scaled scores for one bh pair; ``mask_col`` is
    the [S, 1] key mask."""
    s = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    s = s + (1.0 - mask_col[:, 0].astype(jnp.float32))[None, :] * NEG_INF
    if bias is not None:
        s = s + bias
    return s


def _smalls_fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, res_ref, *,
                       causal, scale, G, S):
    bias = _causal_bias_full(S) if causal else None
    for g in range(G):
        s = _smalls_scores(q_ref[g], k_ref[g], mask_ref[g], scale, bias)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[g]
        o = jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        o_ref[g] = (o / l).astype(o_ref.dtype)
        # (m, log l) separately — see the streaming kernel's note
        res_ref[g] = jnp.concatenate([m, jnp.log(l)], axis=1)


def _smalls_bwd_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, res_ref,
                       delta_ref, dq_ref, dk_ref, dv_ref, *, causal,
                       scale, G, S):
    bias = _causal_bias_full(S) if causal else None
    for g in range(G):
        q = q_ref[g]
        k = k_ref[g]
        v = v_ref[g]
        do = do_ref[g]
        m = res_ref[g][:, 0:1]
        logl = res_ref[g][:, 1:2]
        delta = delta_ref[g]
        s = _smalls_scores(q, k, mask_ref[g], scale, bias)
        p = jnp.exp((s - m) - logl)
        dv_ref[g] = jax.lax.dot_general(
            p.astype(do.dtype), do,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dv_ref.dtype)
        dp = jax.lax.dot_general(
            do, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_ref[g] = jax.lax.dot_general(
            ds.astype(k.dtype), k,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dq_ref.dtype)
        dk_ref[g] = jax.lax.dot_general(
            ds.astype(q.dtype), q,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dk_ref.dtype)


def _smalls_flat(q, k, v, k_mask):
    B, H, S, _ = q.shape
    BH = B * H
    mask = jnp.broadcast_to(k_mask[:, None, :], (B, H, S)) \
        .reshape(BH, S, 1)
    return ([x.reshape(BH, S, x.shape[3]) for x in (q, k, v)], mask)


def _smalls_attention(q, k, v, k_mask, causal, scale, G, interpret=False):
    B, H, S, D_k = q.shape
    D_v = v.shape[3]
    BH = B * H
    (qf, kf, vf), maskf = _smalls_flat(q, k, v, k_mask)

    def spec(width):
        return pl.BlockSpec((G, S, width), lambda t: (t, 0, 0),
                            memory_space=pltpu.VMEM)

    out, res = pl.pallas_call(
        functools.partial(_smalls_fwd_kernel, causal=causal, scale=scale,
                          G=G, S=S),
        grid=(BH // G,),
        in_specs=[spec(D_k), spec(D_k), spec(D_v), spec(1)],
        out_specs=[spec(D_v), spec(2)],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D_v), q.dtype),
            jax.ShapeDtypeStruct((BH, S, 2), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, maskf)
    return out.reshape(B, H, S, D_v), res.reshape(B, H, S, 2)


def _smalls_attention_bwd(q, k, v, k_mask, o, res, g, causal, scale, G,
                          interpret=False):
    B, H, S, D_k = q.shape
    D_v = v.shape[3]
    BH = B * H
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)
    (qf, kf, vf), maskf = _smalls_flat(q, k, v, k_mask)

    def spec(width):
        return pl.BlockSpec((G, S, width), lambda t: (t, 0, 0),
                            memory_space=pltpu.VMEM)

    dq, dk, dv = pl.pallas_call(
        functools.partial(_smalls_bwd_kernel, causal=causal, scale=scale,
                          G=G, S=S),
        grid=(BH // G,),
        in_specs=[spec(D_k), spec(D_k), spec(D_v), spec(1), spec(D_v),
                  spec(2), spec(1)],
        out_specs=[spec(D_k), spec(D_k), spec(D_v)],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D_k), q.dtype),
            jax.ShapeDtypeStruct((BH, S, D_k), k.dtype),
            jax.ShapeDtypeStruct((BH, S, D_v), v.dtype),
        ],
        interpret=interpret,
    )(qf, kf, vf, maskf, g.reshape(BH, S, D_v), res.reshape(BH, S, 2),
      delta.reshape(BH, S, 1))
    unflat = lambda x, w: x.reshape(B, H, S, w)
    return unflat(dq, D_k), unflat(dk, D_k), unflat(dv, D_v)


def _pallas_attention(q, k, v, k_mask, causal, scale, interpret=False):
    """Returns (out, res); res [B,H,S_q,2] packs the softmax running max
    and log-denominator, the residual consumed by the flash backward."""
    B, H, S_q, D_k = q.shape
    S_k = k.shape[2]
    D_v = v.shape[3]
    if S_q == S_k:
        G = _smalls_group(B * H, S_q)
        if G is not None:
            return _smalls_attention(q, k, v, k_mask, causal, scale, G,
                                     interpret)
    block_q, block_k = _flash_blocks(S_q, S_k, interpret)
    if block_q is None or block_k is None:
        return None
    grid = (B, H, S_q // block_q, S_k // block_k)
    kernel = functools.partial(_flash_fwd_kernel, causal=causal,
                               scale=scale, block_q=block_q,
                               block_k=block_k)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D_k),
                         lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, D_k),
                         lambda b, h, i, j: (b, h, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, D_v),
                         lambda b, h, i, j: (b, h, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k), lambda b, h, i, j: (b, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D_v),
                         lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q, 2),
                         lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S_q, D_v), q.dtype),
            jax.ShapeDtypeStruct((B, H, S_q, 2), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D_v), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, k_mask[:, None, :])
    return out, lse


def _pallas_attention_bwd(q, k, v, k_mask, o, res, g, causal, scale,
                          interpret=False):
    B, H, S_q, D_k = q.shape
    S_k = k.shape[2]
    D_v = v.shape[3]
    if S_q == S_k:
        G = _smalls_group(B * H, S_q)
        if G is not None:
            return _smalls_attention_bwd(q, k, v, k_mask, o, res, g,
                                         causal, scale, G, interpret)
    block_q, block_k = _flash_blocks(S_q, S_k, interpret)
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)        # [B, H, S_q, 1]
    mask3 = k_mask[:, None, :]

    common_in = [q, k, v, mask3, g, res, delta]
    in_specs = [
        pl.BlockSpec((1, 1, block_q, D_k), lambda b, h, i, j: (b, h, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_k, D_k), lambda b, h, i, j: (b, h, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_k, D_v), lambda b, h, i, j: (b, h, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_k), lambda b, h, i, j: (b, 0, j),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_q, D_v), lambda b, h, i, j: (b, h, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_q, 2), lambda b, h, i, j: (b, h, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0),
                     memory_space=pltpu.VMEM),
    ]

    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k),
        grid=(B, H, S_q // block_q, S_k // block_k),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, block_q, D_k),
                               lambda b, h, i, j: (b, h, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D_k), jnp.float32)],
        interpret=interpret,
    )(*common_in)

    # grid axes 2/3 swap roles: k-block outer, q-block inner (sequential)
    in_specs_kv = [
        pl.BlockSpec((1, 1, block_q, D_k), lambda b, h, j, i: (b, h, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_k, D_k), lambda b, h, j, i: (b, h, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_k, D_v), lambda b, h, j, i: (b, h, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_k), lambda b, h, j, i: (b, 0, j),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_q, D_v), lambda b, h, j, i: (b, h, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_q, 2), lambda b, h, j, i: (b, h, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1, block_q, 1), lambda b, h, j, i: (b, h, i, 0),
                     memory_space=pltpu.VMEM),
    ]
    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkdv_kernel, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k),
        grid=(B, H, S_k // block_k, S_q // block_q),
        in_specs=in_specs_kv,
        out_specs=[
            pl.BlockSpec((1, 1, block_k, D_k),
                         lambda b, h, j, i: (b, h, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, D_v),
                         lambda b, h, j, i: (b, h, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D_k), jnp.float32),
            pltpu.VMEM((block_k, D_v), jnp.float32),
        ],
        interpret=interpret,
    )(*common_in)
    return dq, dk, dv


def _use_interpret():
    return not any(d.platform == "tpu" for d in jax.devices())


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def fused_attention(q, k, v, k_mask, causal, scale, use_pallas):
    out, _ = _fused_fwd(q, k, v, k_mask, causal, scale, use_pallas)
    return out


def _fused_fwd(q, k, v, k_mask, causal, scale, use_pallas):
    if use_pallas and _HAS_PALLAS:
        res = _pallas_attention(q, k, v, k_mask, causal, scale,
                                interpret=_use_interpret())
        if res is not None:
            out, lse = res
            return out, (q, k, v, k_mask, out, lse)
    out = _reference_attention(q, k, v, k_mask, causal, scale)
    return out, (q, k, v, k_mask, None, None)


def _fused_bwd(causal, scale, use_pallas, res, g):
    q, k, v, k_mask, o, lse = res
    if lse is not None:
        dq, dk, dv = _pallas_attention_bwd(
            q, k, v, k_mask, o, lse, g, causal, scale,
            interpret=_use_interpret())
        return dq, dk, dv, None
    _, vjp_fn = jax.vjp(
        lambda q_, k_, v_: _reference_attention(q_, k_, v_, k_mask,
                                                causal, scale),
        q, k, v)
    dq, dk, dv = vjp_fn(g.astype(q.dtype))
    return dq, dk, dv, None


fused_attention.defvjp(_fused_fwd, _fused_bwd)


# ---------------------------------------------------------------------------
# IR op
# ---------------------------------------------------------------------------

def _infer_attn(op, block):
    q = block.var(op.input("Q")[0])
    v = block.var(op.input("V")[0])
    out = block.var(op.output("Out")[0])
    if q.shape is None or v.shape is None:
        raise ShapeInferenceSkip()
    out.shape = tuple(q.shape[:3]) + (v.shape[3],)
    out.dtype = q.dtype
    lse_names = op.output("Lse")
    if lse_names:
        lse = block.var(lse_names[0])
        # packed flash residual: (softmax running max, log denominator)
        lse.shape = tuple(q.shape[:3]) + (2,)
        lse.dtype = "float32"


def _attn_grad_lower(ctx: LowerContext):
    qe = ctx.env[ctx.op.input("Q")[0]]
    ke = ctx.env[ctx.op.input("K")[0]]
    ve = ctx.env[ctx.op.input("V")[0]]
    mask_names = ctx.op.input("KMask")
    k_mask = ctx.env[mask_names[0]] if mask_names else None
    if k_mask is None:
        k_mask = jnp.ones((qe.shape[0], ke.shape[2]), qe.dtype)
    causal = ctx.attr("causal", False)
    scale = ctx.attr("scale", 1.0)
    g = ctx.env[ctx.op.input("Out@GRAD")[0]]
    # mirror the forward's AMP cast so the vjp's output dtype matches the
    # cotangent coming back from (possibly bf16) downstream consumers;
    # emitted grads are cast back to the primal env dtypes
    amp = bool(ctx.aux.get("amp"))

    def cast_in(x):
        return x.astype(jnp.bfloat16) \
            if amp and x.dtype == jnp.float32 else x

    q, k, v = cast_in(qe), cast_in(ke), cast_in(ve)
    use_flash = bool(ctx.attr("use_flash", True))

    # if the forward saved its flash residuals (Out + Lse), reuse them —
    # the backward kernels run directly, no forward recompute
    out_names = ctx.op.input("Out")
    lse_names = ctx.op.input("Lse")
    o = ctx.env.get(out_names[0]) if out_names else None
    lse = ctx.env.get(lse_names[0]) if lse_names else None
    if use_flash and o is not None and lse is not None:
        dq, dk, dv = _pallas_attention_bwd(
            q, k, v, k_mask, o, lse, g.astype(q.dtype), causal,
            float(scale), interpret=_use_interpret())
    else:
        _, vjp_fn = jax.vjp(
            lambda q_, k_, v_: fused_attention(q_, k_, v_, k_mask,
                                               causal, scale, use_flash),
            q, k, v)
        dq, dk, dv = vjp_fn(g.astype(q.dtype))
    for slot, val, prim in (("Q@GRAD", dq, qe), ("K@GRAD", dk, ke),
                            ("V@GRAD", dv, ve)):
        names = ctx.op.output(slot)
        if names and names[0]:
            ctx.outputs[names[0]] = val.astype(prim.dtype)


@register_op("scaled_dot_product_attention", infer_shape=_infer_attn,
             grad_lower=_attn_grad_lower, no_grad_inputs=("KMask",),
             amp_cast=("Q", "K", "V"))
def sdpa_lower(ctx: LowerContext):
    """Q,K,V: [B, H, S, D]; KMask: [B, S_k] (1=attend); Out: [B, H, Sq, D].

    attrs: causal (bool), scale (float), use_flash (bool, default True).
    """
    q = ctx.input("Q")
    k = ctx.input("K")
    v = ctx.input("V")
    k_mask = ctx.input("KMask")
    if k_mask is None:
        k_mask = jnp.ones((q.shape[0], k.shape[2]), q.dtype)
    causal = ctx.attr("causal", False)
    scale = float(ctx.attr("scale", 1.0))
    use_flash = bool(ctx.attr("use_flash", True))
    # flash path has no attention-weight dropout; the graph builder falls
    # back to the composed path when dropout is requested in training
    if use_flash and _HAS_PALLAS:
        res = _pallas_attention(q, k, v, k_mask, causal, scale,
                                interpret=_use_interpret())
        if res is not None:
            out, lse = res
            ctx.set_output("Out", out)
            # saved residual; consumed by the grad op (flash backward)
            ctx.set_output("Lse", lse)
            return
    ctx.set_output("Out", _reference_attention(q, k, v, k_mask, causal,
                                               scale))


# ---------------------------------------------------------------------------
# ring_attention IR op — sequence/context parallelism (SURVEY.md §2.8:
# the reference has none; this supersedes its LoD-ragged long-sequence
# story).  Falls back to single-device attention when the executor's mesh
# has no populated sequence axis, so the same program runs anywhere.
# ---------------------------------------------------------------------------

def _infer_ring_attn(op, block):
    q = block.var(op.input("Q")[0])
    v = block.var(op.input("V")[0])
    out = block.var(op.output("Out")[0])
    if q.shape is None or v.shape is None:
        raise ShapeInferenceSkip()
    out.shape = tuple(q.shape[:-1]) + (v.shape[-1],)
    out.dtype = q.dtype


@register_op("ring_attention", infer_shape=_infer_ring_attn)
def ring_attention_lower(ctx):
    from paddle_tpu.parallel.ring_attention import ring_attention
    q, k, v = ctx.input("Q"), ctx.input("K"), ctx.input("V")
    causal = ctx.attr("causal", False)
    scale = ctx.attr("scale", None)
    seq_axis = ctx.attr("seq_axis", "seq")
    mesh = ctx.aux.get("mesh")
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape)) \
        if mesh is not None else {}
    if axis_sizes.get(seq_axis, 1) > 1 and \
            q.shape[2] % axis_sizes[seq_axis] == 0:
        out = ring_attention(q, k, v, mesh, axis=seq_axis, causal=causal,
                             scale=scale)
    else:
        out = _reference_attention(q, k, v, None, causal,
                                   scale if scale is not None
                                   else float(q.shape[-1]) ** -0.5)
    ctx.set_output("Out", out)


# ---------------------------------------------------------------------------
# fused last-axis softmax (+ additive attention bias) — the composed-path
# companion of the flash kernel.  Below the flash crossover (S < 512) the
# composed XLA path wins overall, but XLA materializes an f32 score
# temporary between the softmax reduction passes when the f32 bias add is
# fused in (measured r5: ~13 ms/step on Transformer-base B=256 S=256).
# This kernel reads the bf16 scores ONCE per pass, applies the bias and
# the full softmax in VMEM at f32, and writes bf16 — one read + one write
# in the forward, two reads + one write in the backward.
# ---------------------------------------------------------------------------

def _fsm_fwd_kernel(x_ref, rb_ref, tb_ref, o_ref):
    x = x_ref[0, 0].astype(jnp.float32)            # [bs, S]
    if rb_ref is not None:
        x = x + rb_ref[0, 0].astype(jnp.float32)[None, :]  # [S] row bias
    if tb_ref is not None:
        x = x + tb_ref[0].astype(jnp.float32)      # [bs, S] causal rows
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    o_ref[0, 0, ...] = (e / jnp.sum(e, axis=-1, keepdims=True)) \
        .astype(o_ref.dtype)


def _fsm_bwd_kernel(y_ref, dy_ref, dx_ref):
    y = y_ref[0, 0].astype(jnp.float32)
    dy = dy_ref[0, 0].astype(jnp.float32)
    dot = jnp.sum(dy * y, axis=-1, keepdims=True)
    dx_ref[0, 0, ...] = ((dy - dot) * y).astype(dx_ref.dtype)


def _fsm_block(S_rows):
    for cand in (256, 128, 64, 32, 16, 8):
        if S_rows % cand == 0:
            return cand
    return None


def _fsm_ok(Sq, Sk, interpret):
    """Shared fwd/bwd tiling + VMEM-budget gate."""
    bs = _fsm_block(Sq)
    if bs is None or (not interpret and Sk % 128):
        return None
    if Sk > 4096 or bs * Sk * 4 * 4 > 8 * 2**20:
        return None
    return bs


def _pallas_softmax_fwd(x, row_bias, tri_bias, interpret):
    """x [B,H,Sq,Sk]; row_bias [B,Sk] or None; tri_bias [Sq,Sk] shared,
    [1,Sq,Sk], or [B,Sq,Sk] per-batch (the decoder's combined
    padding+causal bias, one causal plane per batch row) or None."""
    B, H, Sq, Sk = x.shape
    bs = _fsm_ok(Sq, Sk, interpret)
    if bs is None:
        return None
    grid = (B, H, Sq // bs)
    in_specs = [pl.BlockSpec((1, 1, bs, Sk),
                             lambda b, h, i: (b, h, i, 0))]
    operands = [x]
    if row_bias is not None:
        # [B,1,Sk] with a full (1,1,Sk) block — Mosaic wants the last two
        # block dims (8,128)-aligned OR equal to the array dims
        in_specs.append(pl.BlockSpec((1, 1, Sk),
                                     lambda b, h, i: (b, 0, 0)))
        operands.append(row_bias.reshape(B, 1, Sk))
    if tri_bias is not None:
        if tri_bias.ndim == 2:
            tri_bias = tri_bias[None]
        if tri_bias.shape[0] not in (1, B):
            return None
        if tri_bias.shape[0] > 1:  # per-batch plane, indexed by b
            tb_index = lambda b, h, i: (b, i, 0)
        else:                      # one shared causal plane
            tb_index = lambda b, h, i: (0, i, 0)
        in_specs.append(pl.BlockSpec((1, bs, Sk), tb_index))
        operands.append(tri_bias)

    def kernel(*refs):
        xr = refs[0]
        k = 1
        rb = tb = None
        if row_bias is not None:
            rb = refs[k]
            k += 1
        if tri_bias is not None:
            tb = refs[k]
            k += 1
        _fsm_fwd_kernel(xr, rb, tb, refs[-1])

    try:
        return pl.pallas_call(
            kernel, grid=grid, in_specs=in_specs,
            out_specs=pl.BlockSpec((1, 1, bs, Sk),
                                   lambda b, h, i: (b, h, i, 0)),
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            interpret=interpret)(*operands)
    except Exception:  # pragma: no cover - lowering limits
        return None


def _pallas_softmax_bwd(y, dy, interpret):
    """Returns None when the shape fails the SAME gate as the forward
    (a fwd that fell back must not meet a bwd that launches).

    ``dy`` keeps the INCOMING cotangent dtype (f32 under AMP): block
    specs carry no dtype, so the kernel reads g at full precision from
    the operand itself, like the XLA fallback does; only dx is cast
    back to ``y.dtype`` on the way out (ADVICE r5)."""
    B, H, Sq, Sk = y.shape
    bs = _fsm_ok(Sq, Sk, interpret)
    if bs is None:
        return None
    spec = pl.BlockSpec((1, 1, bs, Sk), lambda b, h, i: (b, h, i, 0))
    try:
        return pl.pallas_call(
            _fsm_bwd_kernel, grid=(B, H, Sq // bs),
            in_specs=[spec, spec], out_specs=spec,
            out_shape=jax.ShapeDtypeStruct(y.shape, y.dtype),
            interpret=interpret)(y, dy)
    except Exception:  # pragma: no cover - lowering limits
        return None


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_softmax(x, row_bias, tri_bias, interpret=False):
    """softmax(x + biases) over the last axis, f32-internal, one VMEM
    pass; falls back to plain XLA when the shape doesn't tile."""
    out, _ = _fused_softmax_fwd(x, row_bias, tri_bias, interpret)
    return out


def _xla_softmax(x, row_bias, tri_bias):
    xf = x.astype(jnp.float32)
    if row_bias is not None:
        xf = xf + row_bias[:, None, None, :].astype(jnp.float32)
    if tri_bias is not None:
        if tri_bias.ndim == 3:   # [B|1, Sq, Sk] per-batch planes
            xf = xf + tri_bias[:, None].astype(jnp.float32)
        else:                    # [Sq, Sk] shared plane
            xf = xf + tri_bias[None, None].astype(jnp.float32)
    return jax.nn.softmax(xf, axis=-1).astype(x.dtype)


def _fused_softmax_fwd(x, row_bias, tri_bias, interpret):
    out = None
    if _HAS_PALLAS:
        out = _pallas_softmax_fwd(x, row_bias, tri_bias, interpret)
    if out is None:
        # tiling/VMEM-gate fallback: same coverage signal as the
        # bias-decomposition fallback in nn_ops.softmax_lower — the
        # counter's contract is "zero means every softmax ran the
        # kernel", so a shape that fails _fsm_ok must move it too
        # (fires at trace time: once per compiled signature)
        from paddle_tpu.profiler import runtime_metrics
        runtime_metrics.inc("attention.fused_softmax_fallback")
        out = _xla_softmax(x, row_bias, tri_bias)
    return out, out


def _fused_softmax_bwd(interpret, y, g):
    # g stays at the cotangent's own dtype (f32): casting it to bf16
    # before the kernel would hand the Pallas backward LOWER gradient
    # precision than its own XLA fallback below (ADVICE r5) — the
    # constant component of g cancels in (g - sum(g*y))*y, so exactly
    # the small differences a bf16 cast destroys are what dx is made of
    dx = None
    if _HAS_PALLAS:
        dx = _pallas_softmax_bwd(y, g, interpret)
    if dx is None:
        yf = y.astype(jnp.float32)
        gf = g.astype(jnp.float32)
        dx = ((gf - jnp.sum(gf * yf, axis=-1, keepdims=True)) * yf) \
            .astype(y.dtype)
    return dx, None, None


fused_softmax.defvjp(_fused_softmax_fwd, _fused_softmax_bwd)


# ---------------------------------------------------------------------------
# paged_attention IR op — occupancy-proportional decode reads over the gen
# KV pool (ROADMAP item 3).  The gen cache lives as [num_pages, page_len,
# H*D] pages plus a per-slot page table; each decode step appends the new
# token's K/V row into its slot's tail page, then attends ONLY the pages
# covering [0, len) — bytes read scale with live prefix length, not the
# padded max_len.  The page-table feed is bucketed by the predictor so the
# decode jit key stays constant per bucket.  A Pallas kernel (grid =
# (slot, page), page picked by a scalar-prefetch table lookup, online
# softmax across pages) serves TPUs; an XLA gather fallback shares the
# same lowering contract and is the default off-TPU — interpret-mode
# execution re-runs the kernel per call (unlike trace-once XLA), so tests
# opt in via PADDLE_TPU_PAGED_INTERPRET=1 instead.
# ---------------------------------------------------------------------------

def _paged_cache_update(kc, vc, k, v, page_table, lens):
    """Scatter this step's K/V row into each live slot's tail page.

    ``lens`` [S, 1] counts rows INCLUDING the token being decoded, so the
    write lands at position ``lens-1``; ``lens == 0`` marks a free slot
    and maps to an out-of-range page that ``mode="drop"`` discards —
    zero-filled warmup feeds therefore write nothing.
    """
    NP, PL, _ = kc.shape
    last = lens[:, 0] - 1
    idx = jnp.clip(last, 0)
    page = jnp.take_along_axis(page_table, (idx // PL)[:, None], axis=1)[:, 0]
    page = jnp.where(last >= 0, page, NP)
    row = idx % PL
    kc = kc.at[page, row].set(k.reshape(k.shape[0], -1), mode="drop")
    vc = vc.at[page, row].set(v.reshape(v.shape[0], -1), mode="drop")
    return kc, vc


def _xla_paged_attention(q, kc, vc, page_table, lens, n_head, scale):
    """Gather-based fallback: same contract as the kernel.  Reads only
    the ``P`` table-listed pages per slot ([S, P*PL] keys instead of the
    dense pool's [S, max_len]) — still occupancy-proportional, just
    without the VMEM-resident online softmax."""
    S, P = page_table.shape
    NP, PL, HD = kc.shape
    H = n_head
    D = HD // H
    T = P * PL
    kg = kc[page_table].reshape(S, T, H, D)
    vg = vc[page_table].reshape(S, T, H, D)
    qh = q.reshape(S, H, D).astype(jnp.float32)
    sc = jnp.einsum("shd,sthd->sht", qh, kg.astype(jnp.float32),
                    preferred_element_type=jnp.float32) * scale
    col = jax.lax.broadcasted_iota(jnp.int32, (S, 1, T), 2)
    sc = jnp.where(col < lens[:, :, None], sc, NEG_INF)
    probs = jax.nn.softmax(sc, axis=-1)
    out = jnp.einsum("sht,sthd->shd", probs, vg.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.reshape(q.shape).astype(q.dtype)


def _paged_decode_kernel(pt_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
                         acc_ref, m_ref, l_ref, *, page_len, scale):
    s = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _M_INIT)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[0].astype(jnp.float32)            # [H, D]
    k = k_ref[0].astype(jnp.float32)            # [PL, H, D]
    v = v_ref[0].astype(jnp.float32)
    sc = jax.lax.dot_general(                   # [H, PL]: batch H, contract D
        q, k, (((1,), (2,)), ((0,), (1,))),
        preferred_element_type=jnp.float32) * scale
    valid = lens_ref[s, 0] - p * page_len
    col = jax.lax.broadcasted_iota(jnp.int32, sc.shape, 1)
    sc = jnp.where(col < valid, sc, NEG_INF)

    m_prev = m_ref[...]
    m_new = jnp.maximum(m_prev, jnp.max(sc, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    e = jnp.exp(sc - m_new)
    l_ref[...] = l_ref[...] * alpha + jnp.sum(e, axis=1, keepdims=True)
    pv = jax.lax.dot_general(                   # [H, D]: batch H, contract PL
        e, v, (((1,), (0,)), ((0,), (1,))),
        preferred_element_type=jnp.float32)
    acc_ref[...] = acc_ref[...] * alpha + pv
    m_ref[...] = m_new

    @pl.when(p == pl.num_programs(1) - 1)
    def _finish():
        # a free slot (lens == 0) masks every page: l stays 0, the guard
        # yields finite garbage the scheduler never reads
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _pallas_paged_attention(q, kc, vc, page_table, lens, n_head, scale,
                            interpret=False):
    S, P = page_table.shape
    NP, PL, HD = kc.shape
    H = n_head
    D = HD // H
    if H * D != HD:
        return None
    if not interpret and (D % 128 or PL % 8):
        return None  # lane/sublane tiling gate
    q4 = q.reshape(S, H, D)
    kc4 = kc.reshape(NP, PL, H, D)
    vc4 = vc.reshape(NP, PL, H, D)
    kernel = functools.partial(_paged_decode_kernel, page_len=PL,
                               scale=scale)
    try:
        out = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(S, P),
                in_specs=[
                    pl.BlockSpec((1, H, D),
                                 lambda s, p, pt, ln: (s, 0, 0)),
                    pl.BlockSpec((1, PL, H, D),
                                 lambda s, p, pt, ln: (pt[s, p], 0, 0, 0)),
                    pl.BlockSpec((1, PL, H, D),
                                 lambda s, p, pt, ln: (pt[s, p], 0, 0, 0)),
                ],
                out_specs=pl.BlockSpec((1, H, D),
                                       lambda s, p, pt, ln: (s, 0, 0)),
                scratch_shapes=[
                    pltpu.VMEM((H, D), jnp.float32),
                    pltpu.VMEM((H, 1), jnp.float32),
                    pltpu.VMEM((H, 1), jnp.float32),
                ],
            ),
            out_shape=jax.ShapeDtypeStruct((S, H, D), q.dtype),
            interpret=interpret,
        )(page_table, lens, q4, kc4, vc4)
    except Exception:  # pragma: no cover - lowering limits
        return None
    return out.reshape(q.shape)


def _paged_kernel_enabled(interpret):
    if not _HAS_PALLAS:
        return False
    if not interpret:
        return True
    import os
    return os.environ.get("PADDLE_TPU_PAGED_INTERPRET", "0") == "1"


def _infer_paged_attn(op, block):
    q = block.var(op.input("Q")[0])
    out = block.var(op.output("Out")[0])
    if q.shape is None:
        raise ShapeInferenceSkip()
    out.shape = tuple(q.shape)
    out.dtype = q.dtype
    # KCacheOut/VCacheOut alias the persistable cache vars (in-place
    # update idiom) — their shapes are already declared


@register_op("paged_attention", infer_shape=_infer_paged_attn,
             no_gradient=True,
             stateful_outputs=("KCacheOut", "VCacheOut"))
def paged_attention_lower(ctx: LowerContext):
    """Q/K/V: [S, 1, H*D] this step's projections; KCache/VCache:
    [num_pages, page_len, H*D] persistable pool; PageTable: [S, P] int32
    (P = the step's page bucket); Lens: [S, 1] int32 rows INCLUDING the
    current token (0 = free slot).  Out: [S, 1, H*D]; KCacheOut/
    VCacheOut name the cache vars themselves (in-place update).

    attrs: n_head (int), scale (float).
    """
    q = ctx.input("Q")
    k = ctx.input("K")
    v = ctx.input("V")
    kc = ctx.input("KCache")
    vc = ctx.input("VCache")
    pt = ctx.input("PageTable")
    lens = ctx.input("Lens")
    n_head = int(ctx.attr("n_head", 1))
    scale = float(ctx.attr("scale", 1.0))
    kc, vc = _paged_cache_update(kc, vc, k, v, pt, lens)
    out = None
    interpret = _use_interpret()
    if _paged_kernel_enabled(interpret):
        out = _pallas_paged_attention(q, kc, vc, pt, lens, n_head, scale,
                                      interpret=interpret)
    if out is None:
        # same coverage contract as attention.fused_softmax_fallback:
        # fires at trace time, once per compiled signature, whenever a
        # decode bucket lowered without the Pallas kernel
        from paddle_tpu.profiler import runtime_metrics
        runtime_metrics.inc("gen.paged.fallback")
        out = _xla_paged_attention(q, kc, vc, pt, lens, n_head, scale)
    ctx.set_output("Out", out)
    ctx.set_output("KCacheOut", kc)
    ctx.set_output("VCacheOut", vc)
