"""Op registry + all op lowerings.

Importing this package registers every op type (the analogue of the
reference's ``USE_OP`` generated pybind stubs,
``paddle/fluid/operators/CMakeLists.txt:6-8``).
"""

from paddle_tpu.ops import registry  # noqa: F401
from paddle_tpu.ops import (  # noqa: F401
    csp_ops,
    detection_ops,
    reader_ops,
    sparse_ops,
    math_ops,
    tensor_ops,
    activation_ops,
    nn_ops,
    loss_ops,
    optimizer_ops,
    logic_ops,
    metric_ops,
    io_ops,
    persist_ops,
    control_flow_ops,
    sequence_ops,
    rnn_ops,
    attention_ops,
    crf_ops,
    ctc_ops,
    beam_search_ops,
    fused_ops,
)
