"""save / load / save_combine / load_combine — persistence as IR ops.

The reference serializes tensors from INSIDE programs (startup programs
run load ops; inference export runs save ops): ``save_op.cc:1-130``,
``load_op.cc:1-87``, ``save_load_combine_op_test.cc``.  These host-side
lowerings do the same for this framework, with a versioned container
format replacing the reference's LoDTensor proto header:

  record := magic b"PTT0" | u32 header_len | JSON header | raw bytes
  header := {"dtype": str, "shape": [int], "lod": [[int]]}

``save_combine``/``load_combine`` concatenate records in one file (the
order of the X/Out slots).  Data is little-endian C-order; bfloat16 is
stored as uint16 words with dtype "bfloat16" in the header.
"""

from __future__ import annotations

import json
import os
import struct

import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.registry import register_op

MAGIC = b"PTT0"


def _to_numpy(value):
    arr = np.asarray(value)
    if arr.dtype == jnp.bfloat16:
        return arr.view(np.uint16), "bfloat16"
    return arr, str(arr.dtype)


def write_tensor(f, value, lod=None, name=None):
    """Append one tensor record to an open binary file.  ``name`` is
    advisory metadata (load_combine assigns POSITIONALLY, the reference
    contract, but io.py uses recorded names to guard against skew)."""
    arr, dtype_name = _to_numpy(value)
    hdr = {
        "dtype": dtype_name,
        "shape": list(arr.shape),
        "lod": [list(map(int, level)) for level in (lod or [])],
    }
    if name is not None:
        hdr["name"] = str(name)
    header = json.dumps(hdr).encode("utf-8")
    f.write(MAGIC)
    f.write(struct.pack("<I", len(header)))
    f.write(header)
    f.write(np.ascontiguousarray(arr).tobytes())


def _read_header(f):
    magic = f.read(4)
    if magic != MAGIC:
        raise ValueError(
            f"bad tensor file: magic {magic!r} != {MAGIC!r} (wrong file "
            f"or unsupported version)")
    (hdr_len,) = struct.unpack("<I", f.read(4))
    header = json.loads(f.read(hdr_len).decode("utf-8"))
    shape = tuple(header["shape"])
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    itemsize = 2 if header["dtype"] == "bfloat16" else \
        np.dtype(header["dtype"]).itemsize
    return header, shape, n, itemsize


def read_tensor(f):
    """Read one tensor record; returns (ndarray, lod-list)."""
    header, shape, n, itemsize = _read_header(f)
    if header["dtype"] == "bfloat16":
        raw = np.frombuffer(f.read(2 * n), dtype=np.uint16)
        arr = raw.view(jnp.bfloat16).reshape(shape)
    else:
        dt = np.dtype(header["dtype"])
        arr = np.frombuffer(f.read(dt.itemsize * n),
                            dtype=dt).reshape(shape)
    return arr, header.get("lod", [])


def read_record_names(path):
    """Recorded names of a combined file, in order (header scan only —
    tensor payloads are seeked over, not read)."""
    names = []
    with open(path, "rb") as f:
        while f.peek(4)[:4] if hasattr(f, "peek") else True:
            probe = f.read(4)
            if not probe:
                break
            f.seek(-4, 1)
            header, _, n, itemsize = _read_header(f)
            names.append(header.get("name"))
            f.seek(n * itemsize, 1)
    return names


def _prepare_path(path, overwrite):
    if os.path.exists(path) and not overwrite:
        raise RuntimeError(
            f"save: {path!r} exists and overwrite is disabled "
            f"(reference save_op.cc PADDLE_ENFORCE)")
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)


@register_op("save", no_gradient=True, host=True)
def save_lower(ctx):
    """One variable -> one file (reference ``save_op.cc:1-130``)."""
    path = ctx.attr("file_path")
    _prepare_path(path, ctx.attr("overwrite", True))
    name = ctx.op.input("X")[0]
    with open(path, "wb") as f:
        write_tensor(f, ctx.env[name], ctx.input_lod("X"), name=name)


@register_op("load", no_gradient=True, host=True)
def load_lower(ctx):
    """One file -> one variable (reference ``load_op.cc:1-87``)."""
    path = ctx.attr("file_path")
    with open(path, "rb") as f:
        arr, lod = read_tensor(f)
    out_name = ctx.op.output("Out")[0]
    ctx.outputs[out_name] = jnp.asarray(arr)
    if lod:
        ctx.aux.setdefault("lod", {})[out_name] = lod


@register_op("save_combine", no_gradient=True, host=True)
def save_combine_lower(ctx):
    """All X inputs, in slot order, into one file (reference
    ``save_combine_op`` in save_load_combine_op_test.cc)."""
    path = ctx.attr("file_path")
    _prepare_path(path, ctx.attr("overwrite", True))
    names = ctx.op.input("X")
    with open(path, "wb") as f:
        for name in names:
            lod = ctx.aux.get("lod", {}).get(name)
            write_tensor(f, ctx.env[name], lod, name=name)


@register_op("load_combine", no_gradient=True, host=True)
def load_combine_lower(ctx):
    """One file -> all Out outputs, in slot order."""
    path = ctx.attr("file_path")
    names = ctx.op.output("Out")
    with open(path, "rb") as f:
        for name in names:
            arr, lod = read_tensor(f)
            ctx.outputs[name] = jnp.asarray(arr)
            if lod:
                ctx.aux.setdefault("lod", {})[name] = lod
