"""Loss ops — reference ``paddle/fluid/operators/{cross_entropy_op,
softmax_with_cross_entropy_op,sigmoid_cross_entropy_with_logits_op,
hinge_loss_op,huber_loss_op,log_loss_op,rank_loss_op,margin_rank_loss_op,
smooth_l1_loss_op,squared_l2_distance_op,...}``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.ops.registry import (
    register_op, infer_shape_unary, ShapeInferenceSkip)


def _infer_rowwise_loss(op, block, x_slot="X"):
    x = block.var(op.input(x_slot)[0])
    out = block.var(op.output("Out")[0] if op.output("Out")
                    else op.output("Loss")[0])
    if x.shape is not None:
        out.shape = (x.shape[0], 1)
    out.dtype = x.dtype


def _take_along_label(x, label):
    """x: (N, D), label: (N,) or (N,1) int -> x[i, label[i]] as (N,1)."""
    if label.ndim == 2 and label.shape[-1] == 1:
        label = label.reshape(-1)
    picked = jnp.take_along_axis(x, label[:, None].astype(jnp.int32), axis=1)
    return picked


def _infer_cross_entropy(op, block):
    _infer_rowwise_loss(op, block, "X")


@register_op("cross_entropy", infer_shape=_infer_cross_entropy,
             no_grad_inputs=("Label",), amp_upcast=("X",))
def cross_entropy_lower(ctx):
    x = ctx.input("X")  # probabilities (N, D)
    label = ctx.input("Label")
    if ctx.attr("soft_label", False):
        loss = -jnp.sum(label * jnp.log(jnp.maximum(x, 1e-20)), axis=-1,
                        keepdims=True)
    else:
        picked = _take_along_label(x, label)
        loss = -jnp.log(jnp.maximum(picked, 1e-20))
    ctx.set_output("Y", loss)
    ctx.set_output("Out", loss)


def _infer_softmax_ce(op, block):
    logits = block.var(op.input("Logits")[0])
    if logits.shape is None:
        raise ShapeInferenceSkip()
    sm = block.var(op.output("Softmax")[0])
    sm.shape = logits.shape
    sm.dtype = logits.dtype
    loss = block.var(op.output("Loss")[0])
    loss.shape = tuple(logits.shape[:-1]) + (1,)
    loss.dtype = logits.dtype


@register_op("softmax_with_cross_entropy", infer_shape=_infer_softmax_ce,
             no_grad_inputs=("Label",),
             stop_gradient_outputs=("Softmax",), amp_upcast=("Logits",))
def softmax_with_cross_entropy_lower(ctx):
    logits = ctx.input("Logits")
    label = ctx.input("Label")
    log_sm = jax.nn.log_softmax(logits, axis=-1)
    if ctx.attr("soft_label", False):
        loss = -jnp.sum(label * log_sm, axis=-1, keepdims=True)
    else:
        lbl = label.reshape(label.shape[:-1]) if label.shape[-1] == 1 \
            else label
        picked = jnp.take_along_axis(log_sm, lbl[..., None].astype(jnp.int32),
                                     axis=-1)
        loss = -picked
    ctx.set_output("Softmax", jnp.exp(log_sm))
    ctx.set_output("Loss", loss)


@register_op("sigmoid_cross_entropy_with_logits",
             infer_shape=infer_shape_unary(), no_grad_inputs=("Label",))
def sigmoid_ce_lower(ctx):
    x = ctx.input("X")
    label = ctx.input("Label")
    # max(x,0) - x*z + log(1 + exp(-|x|)) (numerically stable)
    loss = jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ctx.set_output("Out", loss)


def _infer_sqdist(op, block):
    x = block.var(op.input("X")[0])
    if x.shape is not None:
        sub = block.var(op.output("sub_result")[0])
        sub.shape = x.shape
        sub.dtype = x.dtype
        out = block.var(op.output("Out")[0])
        out.shape = (x.shape[0], 1)
        out.dtype = x.dtype


@register_op("squared_l2_distance", infer_shape=_infer_sqdist)
def squared_l2_distance_lower(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    sub = x - y
    ctx.set_output("sub_result", sub)
    ctx.set_output("Out", jnp.sum(jnp.square(sub), axis=-1, keepdims=True))


@register_op("smooth_l1_loss", no_grad_inputs=("InsideWeight",
                                               "OutsideWeight"))
def smooth_l1_loss_lower(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    sigma = ctx.attr("sigma", 1.0)
    s2 = sigma * sigma
    inw = ctx.input("InsideWeight")
    outw = ctx.input("OutsideWeight")
    diff = x - y
    if inw is not None:
        diff = diff * inw
    ad = jnp.abs(diff)
    val = jnp.where(ad < 1.0 / s2, 0.5 * s2 * diff * diff, ad - 0.5 / s2)
    ctx.set_output("Diff", diff)
    if outw is not None:
        val = val * outw
    ctx.set_output("Out", jnp.sum(val, axis=tuple(range(1, x.ndim)),
                                  keepdims=False)[:, None])


@register_op("hinge_loss", no_grad_inputs=("Labels",))
def hinge_loss_lower(ctx):
    logits = ctx.input("Logits")
    labels = ctx.input("Labels")
    ctx.set_output("Loss",
                   jnp.maximum(1.0 - (2.0 * labels - 1.0) * logits, 0.0))


@register_op("huber_loss", no_grad_inputs=())
def huber_loss_lower(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    delta = ctx.attr("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    loss = jnp.where(ar <= delta, 0.5 * r * r,
                     delta * (ar - 0.5 * delta))
    ctx.set_output("Residual", r)
    ctx.set_output("Out", loss)


@register_op("log_loss", no_grad_inputs=("Labels",))
def log_loss_lower(ctx):
    p = ctx.input("Predicted")
    y = ctx.input("Labels")
    eps = ctx.attr("epsilon", 1e-4)
    loss = -y * jnp.log(p + eps) - (1.0 - y) * jnp.log(1.0 - p + eps)
    ctx.set_output("Loss", loss)


@register_op("rank_loss", no_grad_inputs=("Label",))
def rank_loss_lower(ctx):
    label = ctx.input("Label")
    left, right = ctx.input("Left"), ctx.input("Right")
    d = left - right
    loss = jnp.log1p(jnp.exp(d)) - label * d
    ctx.set_output("Out", loss)


@register_op("margin_rank_loss", no_grad_inputs=("Label",))
def margin_rank_loss_lower(ctx):
    label = ctx.input("Label")
    x1, x2 = ctx.input("X1"), ctx.input("X2")
    margin = ctx.attr("margin", 0.0)
    out = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    ctx.set_output("Out", out)
    ctx.set_output("Activated", (out > 0).astype(x1.dtype))


@register_op("modified_huber_loss", no_grad_inputs=("Y",))
def modified_huber_loss_lower(ctx):
    x = ctx.input("X")
    y = ctx.input("Y")  # in {0, 1}
    z = (2.0 * y - 1.0) * x
    inter = jnp.where(z < -1.0, -4.0 * z, jnp.square(jnp.maximum(1.0 - z, 0)))
    ctx.set_output("IntermediateVal", z)
    ctx.set_output("Out", inter)
