"""Neural-net structural ops: conv, pool, normalization, dropout, softmax.

Reference: ``paddle/fluid/operators/{conv_op,conv_transpose_op,pool_op,
batch_norm_op,layer_norm_op,lrn_op,dropout_op,softmax_op}``.  Data layout is
NCHW like the reference's default; XLA re-lays out for the MXU internally.
Convolutions lower to ``lax.conv_general_dilated`` (one XLA HLO, tiled onto
the MXU) instead of the reference's im2col+GEMM / cuDNN split.
"""

from __future__ import annotations

import logging
import os

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.registry import (
    register_op, infer_shape_unary, ShapeInferenceSkip)

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# conv2d / depthwise_conv2d / conv2d_transpose / conv3d
# ---------------------------------------------------------------------------

def _conv_out_size(i, k, s, p, d=1):
    if i == -1:
        return -1
    ke = d * (k - 1) + 1
    return (i + 2 * p - ke) // s + 1


def _infer_conv2d(op, block):
    x = block.var(op.input("Input")[0])
    w = block.var(op.input("Filter")[0])
    if x.shape is None or w.shape is None:
        raise ShapeInferenceSkip()
    strides = op.attr("strides", [1, 1])
    paddings = op.attr("paddings", [0, 0])
    dilations = op.attr("dilations", [1, 1])
    n, _, h, wd = x.shape
    oc, _, kh, kw = w.shape
    out = block.var(op.output("Output")[0])
    out.shape = (n, oc,
                 _conv_out_size(h, kh, strides[0], paddings[0], dilations[0]),
                 _conv_out_size(wd, kw, strides[1], paddings[1], dilations[1]))
    out.dtype = x.dtype


def _conv2d_lower_impl(ctx, depthwise=False):
    x = ctx.input("Input")
    w = ctx.input("Filter")
    strides = tuple(ctx.attr("strides", [1, 1]))
    paddings = ctx.attr("paddings", [0, 0])
    dilations = tuple(ctx.attr("dilations", [1, 1]))
    groups = ctx.attr("groups", 1) or 1
    if depthwise:
        groups = x.shape[1]
    pad = [(paddings[0], paddings[0]), (paddings[1], paddings[1])]
    # NOTE: no preferred_element_type=f32 here — the TPU MXU accumulates
    # bf16 convs in f32 regardless, and requesting an f32 output makes the
    # conv's transpose rule pair an f32 cotangent with a bf16 operand
    # (dtype-mismatch TypeError under AMP training).
    import os
    if os.environ.get("PADDLE_TPU_CONV_IM2COL") and groups == 1 and \
            dilations == (1, 1) and x.shape[1] >= 8:
        out = _conv_im2col(x, w, strides, pad)
        ctx.set_output("Output", out.astype(x.dtype))
        return
    if os.environ.get("PADDLE_TPU_CONV_NHWC"):
        # layout experiment (r4): run the conv itself channels-last —
        # per-shape device profiling showed XLA's NHWC conv up to 1.8x
        # the NCHW one at ResNet's C=64 stage.  The IR/program layout
        # stays NCHW; XLA's transpose folding decides whether the
        # sandwich transposes materialize.
        out = jax.lax.conv_general_dilated(
            jnp.transpose(x, (0, 2, 3, 1)),
            jnp.transpose(w, (2, 3, 1, 0)),
            window_strides=strides, padding=pad,
            rhs_dilation=dilations, feature_group_count=groups,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        ctx.set_output("Output",
                       jnp.transpose(out, (0, 3, 1, 2)).astype(x.dtype))
        return
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pad,
        rhs_dilation=dilations, feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    ctx.set_output("Output", out.astype(x.dtype))


def _conv_im2col(x, w, strides, pad):
    """Experimental conv-as-explicit-GEMM (PADDLE_TPU_CONV_IM2COL=1):
    NHWC patches via shifted slices, one [N*Ho*Wo, kh*kw*Ci] @
    [kh*kw*Ci, Co] matmul; the caller gates unsupported configs
    (groups/dilation).  Measured 2.1x SLOWER than XLA's native conv on
    ResNet-50 (COVERAGE.md) — kept as the documented experiment."""
    oc, ci, kh, kw = w.shape
    n, _, h, wd = x.shape
    (pt, pb), (pl, pr) = pad
    sh, sw = strides
    ho = (h + pt + pb - kh) // sh + 1
    wo = (wd + pl + pr - kw) // sw + 1
    xh = jnp.pad(x.transpose(0, 2, 3, 1),
                 ((0, 0), (pt, pb), (pl, pr), (0, 0)))
    cols = [xh[:, i:i + sh * (ho - 1) + 1:sh, j:j + sw * (wo - 1) + 1:sw, :]
            for i in range(kh) for j in range(kw)]
    patches = jnp.concatenate(cols, axis=-1).reshape(n * ho * wo,
                                                     kh * kw * ci)
    # filter [Co, Ci, kh, kw] -> [kh*kw*Ci, Co] matching patch order
    wm = w.transpose(2, 3, 1, 0).reshape(kh * kw * ci, oc)
    y = patches @ wm
    return y.reshape(n, ho, wo, oc).transpose(0, 3, 1, 2)


@register_op("conv2d", infer_shape=_infer_conv2d,
             amp_cast=("Input", "Filter"))
def conv2d_lower(ctx):
    _conv2d_lower_impl(ctx)


@register_op("depthwise_conv2d", infer_shape=_infer_conv2d,
             amp_cast=("Input", "Filter"))
def depthwise_conv2d_lower(ctx):
    _conv2d_lower_impl(ctx, depthwise=True)


def _infer_conv2d_transpose(op, block):
    x = block.var(op.input("Input")[0])
    w = block.var(op.input("Filter")[0])
    if x.shape is None or w.shape is None:
        raise ShapeInferenceSkip()
    strides = op.attr("strides", [1, 1])
    paddings = op.attr("paddings", [0, 0])
    dilations = op.attr("dilations", [1, 1])
    n, _, h, wd = x.shape
    _, oc, kh, kw = w.shape  # filter layout (C_in, C_out/groups, kh, kw)
    def osize(i, k, s, p, d):
        if i == -1:
            return -1
        return (i - 1) * s - 2 * p + d * (k - 1) + 1
    out = block.var(op.output("Output")[0])
    out.shape = (n, oc * (op.attr("groups", 1) or 1),
                 osize(h, kh, strides[0], paddings[0], dilations[0]),
                 osize(wd, kw, strides[1], paddings[1], dilations[1]))
    out.dtype = x.dtype


@register_op("conv2d_transpose", infer_shape=_infer_conv2d_transpose,
             amp_cast=("Input", "Filter"))
def conv2d_transpose_lower(ctx):
    x = ctx.input("Input")
    w = ctx.input("Filter")  # (C_in, C_out, kh, kw)
    strides = tuple(ctx.attr("strides", [1, 1]))
    paddings = ctx.attr("paddings", [0, 0])
    dilations = tuple(ctx.attr("dilations", [1, 1]))
    pad = [(paddings[0], paddings[0]), (paddings[1], paddings[1])]
    # The reference deconv is the GRADIENT of a forward conv: scatter-add
    # out[i*s - p + d*k'] += x[i] * w[k'], with out = (i-1)s - 2p + d(k-1)+1.
    # In jax that is transpose_kernel=True (flip spatial axes + swap the
    # kernel's channel roles — hence the forward-conv spec "OIHW" for our
    # (C_in, C_out, kh, kw) layout) with use_consistent_padding=True
    # (integer pads read as the forward conv's padding).  The defaults
    # only coincide when p == d(k-1)/2 and the kernel is symmetric.
    # conv_transpose has no feature_group_count: grouped deconv runs one
    # transpose per channel group, concatenated on the channel axis.
    groups = ctx.attr("groups", 1) or 1

    def one(xg, wg):
        return jax.lax.conv_transpose(
            xg, wg, strides=strides, padding=pad, rhs_dilation=dilations,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            transpose_kernel=True, use_consistent_padding=True)

    if groups == 1:
        out = one(x, w)
    else:
        cg = x.shape[1] // groups
        out = jnp.concatenate(
            [one(x[:, g * cg:(g + 1) * cg], w[g * cg:(g + 1) * cg])
             for g in range(groups)], axis=1)
    ctx.set_output("Output", out)


def _infer_conv3d_transpose(op, block):
    x = block.var(op.input("Input")[0])
    w = block.var(op.input("Filter")[0])
    if x.shape is None or w.shape is None:
        raise ShapeInferenceSkip()
    s = op.attr("strides", [1, 1, 1])
    p = op.attr("paddings", [0, 0, 0])
    d = op.attr("dilations", [1, 1, 1])
    n = x.shape[0]
    spatial = x.shape[2:]
    _, oc = w.shape[0], w.shape[1]  # filter layout (C_in, C_out/groups, ...)
    ks = w.shape[2:]

    def osize(i, k, st, pd, dl):
        if i == -1:
            return -1
        return (i - 1) * st - 2 * pd + dl * (k - 1) + 1

    out = block.var(op.output("Output")[0])
    out.shape = (n, oc * (op.attr("groups", 1) or 1)) + tuple(
        osize(spatial[i], ks[i], s[i], p[i], d[i]) for i in range(3))
    out.dtype = x.dtype


@register_op("conv3d_transpose", infer_shape=_infer_conv3d_transpose,
             amp_cast=("Input", "Filter"))
def conv3d_transpose_lower(ctx):
    """NCDHW transposed 3-D convolution (reference
    ``conv_transpose_op.cc:314`` registers conv3d_transpose); filter
    layout (C_in, C_out, kd, kh, kw), same as conv2d_transpose."""
    x = ctx.input("Input")
    w = ctx.input("Filter")
    s = tuple(ctx.attr("strides", [1, 1, 1]))
    p = ctx.attr("paddings", [0, 0, 0])
    d = tuple(ctx.attr("dilations", [1, 1, 1]))
    pad = [(p[i], p[i]) for i in range(3)]
    # gradient-of-conv semantics + per-group transposes — see
    # conv2d_transpose_lower
    groups = ctx.attr("groups", 1) or 1

    def one(xg, wg):
        return jax.lax.conv_transpose(
            xg, wg, strides=s, padding=pad, rhs_dilation=d,
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
            transpose_kernel=True, use_consistent_padding=True)

    if groups == 1:
        out = one(x, w)
    else:
        cg = x.shape[1] // groups
        out = jnp.concatenate(
            [one(x[:, g * cg:(g + 1) * cg], w[g * cg:(g + 1) * cg])
             for g in range(groups)], axis=1)
    ctx.set_output("Output", out)


def _infer_conv3d(op, block):
    x = block.var(op.input("Input")[0])
    w = block.var(op.input("Filter")[0])
    if x.shape is None or w.shape is None:
        raise ShapeInferenceSkip()
    s = op.attr("strides", [1, 1, 1])
    p = op.attr("paddings", [0, 0, 0])
    d = op.attr("dilations", [1, 1, 1])
    n, _, d0, h, wd = x.shape
    oc, _, kd, kh, kw = w.shape
    out = block.var(op.output("Output")[0])
    out.shape = (n, oc, _conv_out_size(d0, kd, s[0], p[0], d[0]),
                 _conv_out_size(h, kh, s[1], p[1], d[1]),
                 _conv_out_size(wd, kw, s[2], p[2], d[2]))
    out.dtype = x.dtype


@register_op("conv3d", infer_shape=_infer_conv3d,
             amp_cast=("Input", "Filter"))
def conv3d_lower(ctx):
    x = ctx.input("Input")
    w = ctx.input("Filter")
    s = tuple(ctx.attr("strides", [1, 1, 1]))
    p = ctx.attr("paddings", [0, 0, 0])
    d = tuple(ctx.attr("dilations", [1, 1, 1]))
    pad = [(p[i], p[i]) for i in range(3)]
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=s, padding=pad, rhs_dilation=d,
        feature_group_count=ctx.attr("groups", 1) or 1,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
    ctx.set_output("Output", out)


# ---------------------------------------------------------------------------
# pooling  (reference pool_op.cc + math/pooling.cc)
# ---------------------------------------------------------------------------

def _infer_pool2d(op, block):
    x = block.var(op.input("X")[0])
    if x.shape is None:
        raise ShapeInferenceSkip()
    ksize = op.attr("ksize")
    strides = op.attr("strides", [1, 1])
    paddings = op.attr("paddings", [0, 0])
    gp = op.attr("global_pooling", False)
    ceil_mode = op.attr("ceil_mode", False)
    n, c, h, w = x.shape
    if gp:
        oh = ow = 1
    else:
        def osize(i, k, s, p):
            if i == -1:
                return -1
            if ceil_mode:
                return (i - k + 2 * p + s - 1) // s + 1
            return (i - k + 2 * p) // s + 1
        oh = osize(h, ksize[0], strides[0], paddings[0])
        ow = osize(w, ksize[1], strides[1], paddings[1])
    out = block.var(op.output("Out")[0])
    out.shape = (n, c, oh, ow)
    out.dtype = x.dtype


@register_op("pool2d", infer_shape=_infer_pool2d)
def pool2d_lower(ctx):
    x = ctx.input("X")
    ptype = ctx.attr("pooling_type", "max")
    ksize = list(ctx.attr("ksize"))
    strides = list(ctx.attr("strides", [1, 1]))
    paddings = list(ctx.attr("paddings", [0, 0]))
    if ctx.attr("global_pooling", False):
        ksize = [x.shape[2], x.shape[3]]
        strides = [1, 1]
        paddings = [0, 0]
    window = (1, 1, ksize[0], ksize[1])
    strides4 = (1, 1, strides[0], strides[1])
    pad4 = [(0, 0), (0, 0), (paddings[0], paddings[0]),
            (paddings[1], paddings[1])]
    if ctx.attr("ceil_mode", False):
        # extend right/bottom padding so the last partial window is included
        def extra(i, k, s, p):
            out = (i - k + 2 * p + s - 1) // s + 1
            needed = (out - 1) * s + k - i - p
            return max(needed - p, 0) + p
        pad4[2] = (paddings[0], extra(x.shape[2], ksize[0], strides[0],
                                      paddings[0]))
        pad4[3] = (paddings[1], extra(x.shape[3], ksize[1], strides[1],
                                      paddings[1]))
    if ptype == "max":
        init = -jnp.inf
        out = jax.lax.reduce_window(x, init, jax.lax.max, window, strides4,
                                    pad4)
    else:
        ssum = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides4,
                                     pad4)
        if ctx.attr("exclusive", True):
            ones = jnp.ones_like(x)
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                           strides4, pad4)
            out = ssum / counts
        else:
            out = ssum / (ksize[0] * ksize[1])
    ctx.set_output("Out", out)


@register_op("pool2d_with_index", infer_shape=None, no_grad_inputs=())
def pool2d_with_index_lower(ctx):
    x = ctx.input("X")
    ksize = list(ctx.attr("ksize"))
    strides = list(ctx.attr("strides", [1, 1]))
    paddings = list(ctx.attr("paddings", [0, 0]))
    if ctx.attr("global_pooling", False):
        ksize = [x.shape[2], x.shape[3]]
        strides = [1, 1]
        paddings = [0, 0]
    window = (1, 1, ksize[0], ksize[1])
    strides4 = (1, 1, strides[0], strides[1])
    pad4 = [(0, 0), (0, 0), (paddings[0], paddings[0]),
            (paddings[1], paddings[1])]
    out = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window, strides4,
                                pad4)
    # index of max within flattened H*W of input
    n, c, h, w = x.shape
    flat_idx = jnp.arange(h * w, dtype=jnp.float32).reshape(1, 1, h, w)
    flat_idx = jnp.broadcast_to(flat_idx, x.shape)
    # select index where value equals the max of its window: use a paired
    # reduce on (value, index)
    def sel_max(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv > av
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)
    vals, idxs = jax.lax.reduce_window(
        (x, flat_idx), (-jnp.inf, 0.0), sel_max, window, strides4, pad4)
    ctx.set_output("Out", vals)
    ctx.set_output("Mask", idxs.astype(jnp.int64))


# ---------------------------------------------------------------------------
# batch_norm  (reference batch_norm_op.cc)
# ---------------------------------------------------------------------------

def _infer_batch_norm(op, block):
    x = block.var(op.input("X")[0])
    y = block.var(op.output("Y")[0])
    y.shape = x.shape
    y.dtype = x.dtype
    if x.shape is not None:
        layout = op.attr("data_layout", "NCHW")
        c = x.shape[1] if layout == "NCHW" else x.shape[-1]
        for slot in ("MeanOut", "VarianceOut", "SavedMean", "SavedVariance"):
            names = op.output(slot)
            if names:
                v = block.var(names[0])
                v.shape = (c,)
                v.dtype = "float32"


@register_op("batch_norm", infer_shape=_infer_batch_norm,
             no_grad_inputs=("Mean", "Variance"))
def batch_norm_lower(ctx):
    x = ctx.input("X")
    scale, bias = ctx.input("Scale"), ctx.input("Bias")
    mean, var = ctx.input("Mean"), ctx.input("Variance")
    eps = ctx.attr("epsilon", 1e-5)
    momentum = ctx.attr("momentum", 0.9)
    layout = ctx.attr("data_layout", "NCHW")
    is_test = ctx.attr("is_test", False) or not ctx.training

    axes = tuple(i for i in range(x.ndim)
                 if i != (1 if layout == "NCHW" and x.ndim > 2 else x.ndim - 1))
    caxis = 1 if (layout == "NCHW" and x.ndim > 2) else x.ndim - 1
    bshape = [1] * x.ndim
    bshape[caxis] = x.shape[caxis]

    xf = x.astype(jnp.float32)
    if is_test:
        use_mean, use_var = mean, var
        saved_mean, saved_var = mean, var
        mean_out, var_out = mean, var
    else:
        use_mean = jnp.mean(xf, axis=axes)
        use_var = jnp.mean(jnp.square(xf - use_mean.reshape(bshape)),
                           axis=axes)
        saved_mean, saved_var = use_mean, use_var
        mean_out = mean * momentum + use_mean * (1.0 - momentum)
        var_out = var * momentum + use_var * (1.0 - momentum)

    inv_std = jax.lax.rsqrt(use_var + eps)
    y = (xf - use_mean.reshape(bshape)) * inv_std.reshape(bshape)
    y = y * scale.reshape(bshape) + bias.reshape(bshape)
    ctx.set_output("Y", y.astype(x.dtype))
    ctx.set_output("MeanOut", mean_out)
    ctx.set_output("VarianceOut", var_out)
    ctx.set_output("SavedMean", saved_mean)
    ctx.set_output("SavedVariance", jax.lax.rsqrt(saved_var + eps))


# ---------------------------------------------------------------------------
# layer_norm  (reference layer_norm_op.cc)
# ---------------------------------------------------------------------------

def _infer_layer_norm(op, block):
    x = block.var(op.input("X")[0])
    y = block.var(op.output("Y")[0])
    y.shape = x.shape
    y.dtype = x.dtype


@register_op("layer_norm", infer_shape=_infer_layer_norm,
             amp_cast=("X",))
def layer_norm_lower(ctx):
    """Under bf16 AMP the input (and hence the output, cast back to
    X's dtype) is bf16, keeping the transformer residual stream bf16
    end-to-end — the statistics are still computed in f32 below.  An
    f32-promoted residual stream doubles the HBM traffic of every
    LN/add pair (measured: exp_transformer_ceiling.py)."""
    x = ctx.input("X")
    begin = ctx.attr("begin_norm_axis", 1)
    eps = ctx.attr("epsilon", 1e-5)
    axes = tuple(range(begin, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    scale, bias = ctx.input("Scale"), ctx.input("Bias")
    norm_shape = (1,) * begin + tuple(x.shape[begin:])
    if scale is not None:
        y = y * scale.reshape(norm_shape)
    if bias is not None:
        y = y + bias.reshape(norm_shape)
    ctx.set_output("Y", y.astype(x.dtype))
    ctx.set_output("Mean", mean.reshape(x.shape[:begin]))
    ctx.set_output("Variance", var.reshape(x.shape[:begin]))


# ---------------------------------------------------------------------------
# lrn (local response normalization)
# ---------------------------------------------------------------------------

@register_op("lrn", infer_shape=infer_shape_unary())
def lrn_lower(ctx):
    x = ctx.input("X")  # NCHW
    n = ctx.attr("n", 5)
    k = ctx.attr("k", 2.0)
    alpha = ctx.attr("alpha", 1e-4)
    beta = ctx.attr("beta", 0.75)
    half = n // 2
    sq = jnp.square(x)
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = jnp.zeros_like(x)
    for i in range(n):
        acc = acc + pad[:, i:i + x.shape[1]]
    mid = k + alpha * acc
    ctx.set_output("Out", x / jnp.power(mid, beta))
    ctx.set_output("MidOut", mid)


# ---------------------------------------------------------------------------
# dropout  (reference dropout_op.cc; old-fluid "downgrade_in_infer": train
# multiplies by the 0/1 mask, inference scales by (1-p))
# ---------------------------------------------------------------------------

def _infer_dropout(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    out.shape = x.shape
    out.dtype = x.dtype
    masks = op.output("Mask")
    if masks:
        m = block.var(masks[0])
        m.shape = x.shape
        m.dtype = x.dtype


def _dropout_grad_lower(ctx):
    g_out = ctx.env[ctx.op.input("Out@GRAD")[0]]
    mask = ctx.env[ctx.op.input("Mask")[0]]
    gname = ctx.op.output("X@GRAD")[0]
    ctx.outputs[gname] = g_out * mask


def _dropout_grad_maker(op, block, no_grad_set):
    from paddle_tpu.framework import grad_var_name
    x = op.input("X")[0]
    if x in no_grad_set:
        return [], {}
    g_x = grad_var_name(x)
    desc = {"type": "dropout_grad",
            "inputs": {"Out@GRAD": [grad_var_name(op.output("Out")[0])],
                       "Mask": [op.output("Mask")[0]]},
            "outputs": {"X@GRAD": [g_x]},
            "attrs": dict(op.attrs)}
    return [desc], {x: g_x}


@register_op("dropout", infer_shape=_infer_dropout, uses_rng=True,
             grad_maker=_dropout_grad_maker, grad_lower=_dropout_grad_lower)
def dropout_lower(ctx):
    x = ctx.input("X")
    p = ctx.attr("dropout_prob", 0.5)
    is_test = ctx.attr("is_test", False) or not ctx.training
    impl = ctx.attr("dropout_implementation", "downgrade_in_infer")
    if is_test:
        out = x * (1.0 - p) if impl == "downgrade_in_infer" else x
        ctx.set_output("Out", out)
        ctx.set_output("Mask", jnp.ones_like(x))
        return
    seed = ctx.attr("seed", 0)
    key = jax.random.PRNGKey(seed) if ctx.attr("fix_seed", False) \
        else ctx.rng_key()
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    if impl == "upscale_in_train":
        mask = keep.astype(x.dtype) / (1.0 - p)
    else:
        mask = keep.astype(x.dtype)
    ctx.set_output("Out", x * mask)
    ctx.set_output("Mask", mask)


# ---------------------------------------------------------------------------
# softmax / log_softmax  (reference softmax_op.cc: normalizes the last dim)
# ---------------------------------------------------------------------------

@register_op("softmax", infer_shape=infer_shape_unary(),
             no_grad_inputs=("Bias",))
def softmax_lower(ctx):
    """Last-axis softmax with an optional fused additive ``Bias``
    (attention masks).  Internally f32, output in X's dtype: under bf16
    AMP the [B,H,S,S] score tensor then stays bf16 in HBM — the bias
    add and the f32 upcast fuse into the reduction passes instead of
    materializing an f32 score tensor (reference softmax_op.cc is plain
    f32; the fused-bias form is the TPU redesign of the transformer's
    ``scores + mask`` pattern)."""
    x = ctx.input("X")
    bias = ctx.input("Bias")
    if bias is not None and x.ndim == 4 and \
            os.environ.get("PADDLE_TPU_FUSED_SOFTMAX", "0") == "1":
        # attention-shaped: the Pallas single-pass kernel — measured
        # SLOWER in-model than the XLA path below (138.9 vs 132.7 ms/step
        # Transformer-base r5: the custom call splits the matmul/softmax
        # fusion clusters, the same effect that gates flash attention to
        # S >= 512) — kept as an opt-in experiment
        from paddle_tpu.ops.attention_ops import (fused_softmax,
                                                  _use_interpret)
        B, H, Sq, Sk = x.shape
        row_bias = tri_bias = None
        ok = True
        if bias.ndim == 4 and bias.shape[1] == 1 and \
                bias.shape[2] == 1 and bias.shape[3] == Sk:
            row_bias = bias.reshape(bias.shape[0], Sk)
            if bias.shape[0] not in (1, B):
                ok = False
            elif bias.shape[0] == 1:
                row_bias = jnp.broadcast_to(row_bias, (B, Sk))
        elif bias.ndim == 4 and bias.shape[1] == 1 and \
                bias.shape[2] == Sq and bias.shape[3] == Sk and \
                bias.shape[0] in (1, B):
            # one causal plane per batch row: covers BOTH the shared
            # causal mask [1,1,Sq,Sk] and the decoder's combined
            # padding+causal [B,1,Sq,Sk] bias (ADVICE r5 / ROADMAP
            # item 4 — the kernel now spans the full decoder)
            tri_bias = bias.reshape(bias.shape[0], Sq, Sk)
        else:
            ok = False
        if ok:
            ctx.set_output("Out", fused_softmax(
                x, row_bias, tri_bias, _use_interpret()))
            return
        # fallback SIGNAL (ADVICE r5): with the kernel opted in, a bias
        # the kernel cannot decompose silently takes the XLA path below
        # — the counter makes partial kernel coverage measurable (an
        # experiment reading "fused softmax on" checks it is zero), the
        # debug log names the offending shape
        from paddle_tpu.profiler import runtime_metrics
        runtime_metrics.inc("attention.fused_softmax_fallback")
        logger.debug(
            "fused softmax (PADDLE_TPU_FUSED_SOFTMAX=1) fell back to "
            "the XLA path for scores %s: bias shape %s is neither a "
            "per-row padding mask [B|1,1,1,Sk] nor a causal mask "
            "[B|1,1,Sq,Sk]",
            tuple(x.shape), tuple(bias.shape))
    elif bias is not None and \
            os.environ.get("PADDLE_TPU_FUSED_SOFTMAX", "0") == "1":
        from paddle_tpu.profiler import runtime_metrics
        runtime_metrics.inc("attention.fused_softmax_fallback")
        logger.debug(
            "fused softmax (PADDLE_TPU_FUSED_SOFTMAX=1) fell back to "
            "the XLA path: scores are rank %d, the Pallas kernel needs "
            "4-D attention-shaped [B,H,Sq,Sk] scores", x.ndim)
    out_dtype = x.dtype
    if bias is not None:
        # add in X's dtype: under bf16 AMP the materialization candidate
        # between the softmax reduction passes is then bf16, not f32
        # (-1e9 is representable in bf16; exp/sum still run in f32)
        x = x + bias.astype(x.dtype)
    ctx.set_output("Out", jax.nn.softmax(
        x.astype(jnp.float32), axis=-1).astype(out_dtype))


@register_op("log_softmax", infer_shape=infer_shape_unary())
def log_softmax_lower(ctx):
    ctx.set_output("Out", jax.nn.log_softmax(ctx.input("X"), axis=-1))


# ---------------------------------------------------------------------------
# label_smooth / im2sequence helpers
# ---------------------------------------------------------------------------

@register_op("label_smooth", infer_shape=infer_shape_unary())
def label_smooth_lower(ctx):
    x = ctx.input("X")
    eps = ctx.attr("epsilon", 0.0)
    dist = ctx.input("PriorDist")
    k = x.shape[-1]
    if dist is not None:
        out = (1.0 - eps) * x + eps * dist
    else:
        out = (1.0 - eps) * x + eps / k
    ctx.set_output("Out", out)


# ---------------------------------------------------------------------------
# pool3d — reference ``pool_op.cc`` 3-D variant (NCDHW).
# ---------------------------------------------------------------------------

def _infer_pool3d(op, block):
    x = block.var(op.input("X")[0])
    if x.shape is None:
        raise ShapeInferenceSkip()
    n, c, d, h, w = x.shape
    k = list(op.attr("ksize"))
    s = list(op.attr("strides", [1, 1, 1]))
    p = list(op.attr("paddings", [0, 0, 0, 0]))[:3] + [0, 0, 0]
    if op.attr("global_pooling", False):
        k, s, p = [d, h, w], [1, 1, 1], [0, 0, 0]
    ceil = op.attr("ceil_mode", False)
    dims = []
    for i, size in enumerate((d, h, w)):
        num = size - k[i] + 2 * p[i]
        dims.append((num + s[i] - 1) // s[i] + 1 if ceil
                    else num // s[i] + 1)
    out = block.var(op.output("Out")[0])
    out.shape = (n, c) + tuple(dims)
    out.dtype = x.dtype


@register_op("pool3d", infer_shape=_infer_pool3d)
def pool3d_lower(ctx):
    x = ctx.input("X")                   # [N, C, D, H, W]
    ptype = ctx.attr("pooling_type", "max")
    ksize = list(ctx.attr("ksize"))
    strides = list(ctx.attr("strides", [1, 1, 1]))
    paddings = list(ctx.attr("paddings", [0, 0, 0]))
    if ctx.attr("global_pooling", False):
        ksize = [x.shape[2], x.shape[3], x.shape[4]]
        strides = [1, 1, 1]
        paddings = [0, 0, 0]
    window = (1, 1) + tuple(ksize)
    strides5 = (1, 1) + tuple(strides)
    pad5 = [(0, 0), (0, 0)] + [(p, p) for p in paddings[:3]]
    if ctx.attr("ceil_mode", False):
        # extend trailing padding so the last partial window is included
        # (same recipe as pool2d above)
        for i, size in enumerate((x.shape[2], x.shape[3], x.shape[4])):
            k_, s_, p_ = ksize[i], strides[i], paddings[i]
            out_dim = (size - k_ + 2 * p_ + s_ - 1) // s_ + 1
            needed = (out_dim - 1) * s_ + k_ - size - p_
            pad5[2 + i] = (p_, max(needed, p_))
    if ptype == "max":
        out = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window,
                                    strides5, pad5)
    else:
        ssum = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides5,
                                     pad5)
        if ctx.attr("exclusive", True):
            counts = jax.lax.reduce_window(jnp.ones_like(x), 0.0,
                                           jax.lax.add, window, strides5,
                                           pad5)
            out = ssum / counts
        else:
            out = ssum / (ksize[0] * ksize[1] * ksize[2])
    ctx.set_output("Out", out)


# ---------------------------------------------------------------------------
# unpool — reference ``unpool_op.cc``: max-unpool via the flat indices from
# max_pool2d_with_index.
# ---------------------------------------------------------------------------

def _infer_unpool(op, block):
    x = block.var(op.input("X")[0])
    if x.shape is None:
        raise ShapeInferenceSkip()
    n, c, h, w = x.shape
    k = list(op.attr("ksize"))
    s = list(op.attr("strides", [2, 2]))
    p = list(op.attr("paddings", [0, 0]))
    oh = (h - 1) * s[0] - 2 * p[0] + k[0]
    ow = (w - 1) * s[1] - 2 * p[1] + k[1]
    out = block.var(op.output("Out")[0])
    out.shape = (n, c, oh, ow)
    out.dtype = x.dtype


@register_op("unpool", infer_shape=_infer_unpool,
             no_grad_inputs=("Indices",))
def unpool_lower(ctx):
    x = ctx.input("X")                   # [N, C, h, w] pooled values
    indices = ctx.input("Indices")       # [N, C, h, w] flat out positions
    n, c, h, w = x.shape
    k = list(ctx.attr("ksize"))
    s = list(ctx.attr("strides", [2, 2]))
    p = list(ctx.attr("paddings", [0, 0]))
    oh = (h - 1) * s[0] - 2 * p[0] + k[0]
    ow = (w - 1) * s[1] - 2 * p[1] + k[1]
    flat = jnp.zeros((n, c, oh * ow), x.dtype)
    ni = jnp.arange(n)[:, None, None]
    ci = jnp.arange(c)[None, :, None]
    idx = indices.reshape(n, c, h * w).astype(jnp.int32)
    flat = flat.at[jnp.broadcast_to(ni, idx.shape).reshape(-1),
                   jnp.broadcast_to(ci, idx.shape).reshape(-1),
                   idx.reshape(-1)].add(x.reshape(-1))
    ctx.set_output("Out", flat.reshape(n, c, oh, ow))


# ---------------------------------------------------------------------------
# spp — reference ``spp_op.h``: spatial pyramid pooling, levels 0..H-1 of
# 2^l x 2^l adaptive pooling, flattened and concatenated.
# ---------------------------------------------------------------------------

def _infer_spp(op, block):
    x = block.var(op.input("X")[0])
    if x.shape is None:
        raise ShapeInferenceSkip()
    n, c = x.shape[0], x.shape[1]
    ph = op.attr("pyramid_height")
    feats = sum(c * (2 ** l) * (2 ** l) for l in range(ph))
    out = block.var(op.output("Out")[0])
    out.shape = (n, feats)
    out.dtype = x.dtype


def _adaptive_pool_axis(x, axis, bins, ptype):
    """Adaptive pooling along one axis: bin i covers
    [floor(i*size/bins), ceil((i+1)*size/bins)) — never empty (the
    reference's spp bin boundaries; fixed-window padding can produce
    all-padding windows when bins doesn't divide the size)."""
    size = x.shape[axis]
    rows = jnp.arange(size)
    starts = np.floor(np.arange(bins) * size / bins).astype(int)
    ends = np.ceil((np.arange(bins) + 1) * size / bins).astype(int)
    mask = (rows[None, :] >= starts[:, None]) & \
        (rows[None, :] < ends[:, None])                 # [bins, size]
    xm = jnp.moveaxis(x, axis, -1)                      # [..., size]
    if ptype == "max":
        vals = jnp.where(mask, xm[..., None, :], -jnp.inf)  # [...,bins,size]
        out = jnp.max(vals, axis=-1)
    else:
        vals = jnp.where(mask, xm[..., None, :], 0.0)
        out = jnp.sum(vals, axis=-1) / jnp.sum(mask, axis=-1)
    return jnp.moveaxis(out, -1, axis)


@register_op("spp", infer_shape=_infer_spp)
def spp_lower(ctx):
    x = ctx.input("X")                   # [N, C, H, W]
    n = x.shape[0]
    ph = int(ctx.attr("pyramid_height"))
    ptype = ctx.attr("pooling_type", "max")
    parts = []
    for level in range(ph):
        bins = 2 ** level
        o = _adaptive_pool_axis(x, 2, bins, ptype)
        o = _adaptive_pool_axis(o, 3, bins, ptype)
        parts.append(o.reshape(n, -1))
    ctx.set_output("Out", jnp.concatenate(parts, axis=1))


# ---------------------------------------------------------------------------
# conv_shift — reference ``conv_shift_op.cc``: circular correlation
# out[i, j] = sum_k x[i, (j + k - M//2) mod N] * y[i, k].
# ---------------------------------------------------------------------------

def _infer_conv_shift(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    out.shape = x.shape
    out.dtype = x.dtype


@register_op("conv_shift", infer_shape=_infer_conv_shift)
def conv_shift_lower(ctx):
    x = ctx.input("X")                   # [B, N]
    y = ctx.input("Y")                   # [B, M], M odd, M <= N
    n = x.shape[1]
    m = y.shape[1]
    half = m // 2
    out = jnp.zeros_like(x)
    for k in range(m):
        out = out + jnp.roll(x, half - k, axis=1) * y[:, k:k + 1]
    ctx.set_output("Out", out)


# ---------------------------------------------------------------------------
# image_resize — spatial up/down-sampling of NCHW feature maps (reference
# BilinearInterpLayer.cpp / UpsampleLayer.cpp in paddle/gserver/layers).
# Lowered to jax.image.resize, which is differentiable.
# ---------------------------------------------------------------------------

def _infer_image_resize(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    if x.shape is None:
        raise ShapeInferenceSkip()
    n, c = x.shape[0], x.shape[1]
    out.shape = (n, c, op.attr("out_h"), op.attr("out_w"))
    out.dtype = x.dtype


def _bilinear_align_corners(x, out_h, out_w):
    """Align-corners bilinear resize of NCHW maps: source coordinate
    ``i * (in-1)/(out-1)`` per the reference BilinearInterpLayer ratios
    (vs jax.image.resize's half-pixel convention). Gather + lerp, so it
    is differentiable."""
    _, _, h, w = x.shape

    def axis(in_sz, out_sz):
        if out_sz == 1 or in_sz == 1:
            zero = jnp.zeros((out_sz,), jnp.int32)
            return zero, zero, jnp.zeros((out_sz,), x.dtype)
        pos = jnp.arange(out_sz, dtype=x.dtype) * ((in_sz - 1) / (out_sz - 1))
        lo = jnp.floor(pos).astype(jnp.int32)
        lo = jnp.minimum(lo, in_sz - 2)
        return lo, lo + 1, pos - lo.astype(x.dtype)

    h0, h1, fh = axis(h, out_h)
    w0, w1, fw = axis(w, out_w)
    fh = fh[None, None, :, None]
    fw = fw[None, None, None, :]
    rows = x[:, :, h0, :] * (1 - fh) + x[:, :, h1, :] * fh
    return rows[:, :, :, w0] * (1 - fw) + rows[:, :, :, w1] * fw


@register_op("image_resize", infer_shape=_infer_image_resize)
def image_resize_lower(ctx):
    x = ctx.input("X")                   # [N, C, H, W]
    method = ctx.attr("method", "bilinear")
    out_h, out_w = ctx.attr("out_h"), ctx.attr("out_w")
    xf = x.astype(jnp.float32)
    if method == "bilinear" and ctx.attr("align_corners", True):
        out = _bilinear_align_corners(xf, out_h, out_w)
    else:
        jmethod = {"bilinear": "linear", "nearest": "nearest"}[method]
        out = jax.image.resize(
            xf, (x.shape[0], x.shape[1], out_h, out_w), method=jmethod)
    ctx.set_output("Out", out.astype(x.dtype))
