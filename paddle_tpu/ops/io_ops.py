"""IO-ish ops: print (debug tensor peeking, reference ``print_op.cc``),
feed/fetch placeholders (the executor handles feed/fetch at the block
boundary, reference ``feed_op.cc``/``fetch_op.cc``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.ops.registry import register_op, infer_shape_unary


@register_op("feed", no_gradient=True)
def feed_lower(ctx):  # pragma: no cover - executor skips feed ops
    pass


@register_op("fetch", no_gradient=True)
def fetch_lower(ctx):  # pragma: no cover - executor skips fetch ops
    pass


@register_op("print", infer_shape=infer_shape_unary("In", "Out"))
def print_lower(ctx):
    x = ctx.input("In")
    msg = ctx.attr("message", "")
    phase = ctx.attr("print_phase", "BOTH")
    if phase in ("FORWARD", "BOTH"):
        jax.debug.print(msg + " {x}", x=x)
    ctx.set_output("Out", x)


@register_op("assign_from_scope", no_gradient=True)
def assign_from_scope_lower(ctx):  # internal helper
    pass
