"""Sparse / CTR op group.

Reference: ``paddle/fluid/operators/nce_op.h`` (noise-contrastive
estimation), ``split_ids_op.cc`` / ``split_selected_rows_op.cc`` (the
pserver sharding helpers).  On TPU the *distribution* of sparse tables is
GSPMD's job (shard the embedding param over the mesh 'model' axis and XLA
inserts the collectives — see ``parallel/distribute_transpiler.py``); these
ops provide the remaining compute/parity surface.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.registry import register_op, ShapeInferenceSkip
from paddle_tpu.selected_rows import SelectedRows, is_selected_rows


# ---------------------------------------------------------------------------
# nce
# ---------------------------------------------------------------------------

def _infer_nce(op, block):
    x = block.var(op.input("Input")[0])
    label = block.var(op.input("Label")[0])
    if x.shape is None or label.shape is None:
        raise ShapeInferenceSkip()
    n = x.shape[0]
    num_true = label.shape[1] if len(label.shape) == 2 else 1
    num_sampled = num_true + int(op.attr("num_neg_samples", 10))
    cost = block.var(op.output("Cost")[0])
    cost.shape = (n, 1)
    cost.dtype = x.dtype
    for slot, dt in (("SampleLogits", x.dtype), ("SampleLabels", "int64")):
        names = op.output(slot)
        if names:
            v = block.var(names[0])
            v.shape = (n, num_sampled)
            v.dtype = dt


def _nce_forward(x, w, bias, sample_labels, num_true, num_total_classes,
                 num_neg, sample_weight=None):
    """Shared by fwd lowering and the grad's vjp: returns (cost, logits).

    Reference nce_op.h NCEKernel: o = sigmoid(x·w[y] + b[y]);
    b_q = num_neg / num_classes (uniform sampler density);
    cost_i = sum_true -log(o/(o+b_q)) + sum_neg -log(b_q/(o+b_q)).
    """
    b_q = float(num_neg) / float(num_total_classes)
    w_rows = w[sample_labels]                     # [N, S, D]
    logits = jnp.einsum("nd,nsd->ns", x, w_rows)
    if bias is not None:
        logits = logits + bias.reshape(-1)[sample_labels]
    o = jax.nn.sigmoid(logits)
    s = sample_labels.shape[1]
    is_true = jnp.arange(s)[None, :] < num_true
    eps = 1e-12
    cost_elem = jnp.where(is_true,
                          -jnp.log(o / (o + b_q) + eps),
                          -jnp.log(b_q / (o + b_q) + eps))
    cost = jnp.sum(cost_elem, axis=1, keepdims=True)
    if sample_weight is not None:
        cost = cost * sample_weight.reshape(-1, 1)
    return cost, o


@register_op("nce", infer_shape=_infer_nce, uses_rng=True,
             no_grad_inputs=("Label", "SampleWeight"),
             stop_gradient_outputs=("SampleLogits", "SampleLabels"))
def nce_lower(ctx):
    x = ctx.input("Input")                    # [N, D]
    label = ctx.input("Label")                # [N, T] int64
    w = ctx.input("Weight")                   # [V, D]
    bias = ctx.input("Bias")                  # [V, 1] or None
    sample_weight = ctx.input("SampleWeight")
    num_total = int(ctx.attr("num_total_classes"))
    num_neg = int(ctx.attr("num_neg_samples", 10))
    custom_neg = ctx.attr("custom_neg_classes", []) or []
    if label.ndim == 1:
        label = label[:, None]
    n, num_true = label.shape
    if custom_neg:
        # the reference fills exactly num_neg_samples slots from
        # custom_neg_classes (uninitialized memory otherwise) — require
        # the lengths to agree
        if len(custom_neg) != num_neg:
            raise ValueError(
                f"nce: len(custom_neg_classes)={len(custom_neg)} must "
                f"equal num_neg_samples={num_neg}")
        neg = jnp.broadcast_to(
            jnp.asarray(custom_neg, label.dtype)[None, :],
            (n, len(custom_neg)))
    else:
        neg = jax.random.randint(ctx.rng_key(), (n, num_neg), 0,
                                 num_total).astype(label.dtype)
    sample_labels = jnp.concatenate([label, neg], axis=1)  # [N, T+S]
    cost, o = _nce_forward(x, w, bias, sample_labels, num_true, num_total,
                           num_neg, sample_weight)
    ctx.set_output("Cost", cost)
    ctx.set_output("SampleLogits", o)
    ctx.set_output("SampleLabels", sample_labels)


def _nce_grad_lower(ctx):
    """Analytic grads by vjp of the forward with SampleLabels FIXED (they
    were sampled in the forward; re-sampling in backward would decouple
    the two, reference NCEGradKernel reads SampleLogits for the same
    reason)."""
    x = ctx.input("Input")
    w = ctx.input("Weight")
    bias = ctx.input("Bias")
    sample_weight = ctx.input("SampleWeight")
    sample_labels = ctx.input("SampleLabels")
    dcost = ctx.input("Cost@GRAD")
    label = ctx.input("Label")
    num_true = label.shape[1] if label.ndim == 2 else 1
    num_total = int(ctx.attr("num_total_classes"))
    num_neg = int(ctx.attr("num_neg_samples", 10))

    has_bias = bias is not None

    def f(x_, w_, b_):
        cost, _ = _nce_forward(x_, w_, b_, sample_labels, num_true,
                               num_total, num_neg, sample_weight)
        return cost

    if has_bias:
        _, vjp = jax.vjp(f, x, w, bias)
        dx, dw, db = vjp(dcost)
    else:
        _, vjp = jax.vjp(lambda x_, w_: f(x_, w_, None), x, w)
        dx, dw = vjp(dcost)
        db = None
    for slot, g in (("Input@GRAD", dx), ("Weight@GRAD", dw),
                    ("Bias@GRAD", db)):
        names = ctx.op.output(slot)
        if names and names[0] and g is not None:
            ctx.outputs[names[0]] = g


from paddle_tpu.ops.registry import lookup as _lookup  # noqa: E402
_lookup("nce").grad_lower = _nce_grad_lower


# ---------------------------------------------------------------------------
# split_ids / split_selected_rows (host ops — data-dependent split sizes;
# the reference registers both as CPU kernels for pserver sharding)
# ---------------------------------------------------------------------------

@register_op("split_ids", no_gradient=True)
def split_ids_lower(ctx):
    """Partition ids by ``id % num_shards`` (reference split_ids_op.cc).

    TPU-traced (fully compiled, no host cliff): every shard output keeps
    the STATIC input length [N, 1], with out-of-shard slots = -1 — the
    same padding convention as kmax_seq_score.  The reference's CPU
    kernel emits exact-length lists instead; with static shapes the
    padded form is the whole-block-compilable equivalent.
    """
    ids = ctx.input("Ids").reshape(-1)
    out_names = ctx.op.output("Out")
    n_shard = len(out_names)
    for i, name in enumerate(out_names):
        mask = (ids % n_shard) == i
        ctx.outputs[name] = jnp.where(mask, ids, -1).reshape(-1, 1)


@register_op("merge_selected_rows", no_gradient=True,
             selected_rows_inputs=("X",))
def merge_selected_rows_lower(ctx):
    """Combine duplicate row ids by summation (reference
    merge_selected_rows_op.cc) — the canonical pre-step before a sparse
    optimizer applies a SelectedRows grad, so each touched row is
    updated once.  Dense inputs pass through unchanged (the reference
    kernel asserts SelectedRows; here a dense tensor is already
    'merged')."""
    x = ctx.input("X")
    ctx.set_output("Out", x.merge_duplicates() if is_selected_rows(x)
                   else x)


@register_op("get_tensor_from_selected_rows", no_gradient=True,
             selected_rows_inputs=("X",))
def get_tensor_from_selected_rows_lower(ctx):
    """Densify a SelectedRows into its [height, dim] tensor (reference
    get_tensor_from_selected_rows_op.cc) — the scatter-add that turns
    routed sparse rows back into a table-shaped tensor."""
    x = ctx.input("X")
    ctx.set_output("Out", x.to_dense() if is_selected_rows(x) else x)


@register_op("split_selected_rows", no_gradient=True,
             selected_rows_inputs=("X",))
def split_selected_rows_lower(ctx):
    """Split rows into height sections (reference
    split_selected_rows_op.cc); each output is a SelectedRows whose row
    indices are local to its section.

    TPU-traced: each output keeps all N (row, value) pairs; rows outside
    the section map to the section height — an out-of-range index that
    every scatter consumer (``to_dense``, sparse optimizer updates)
    drops, which is jax's default OOB-scatter semantics.  Static shapes,
    no host round-trip.
    """
    x = ctx.input("X")
    sections = ctx.attr("height_sections")
    out_names = ctx.op.output("Out")
    if not is_selected_rows(x):
        x = SelectedRows(jnp.arange(x.shape[0], dtype=jnp.int32), x,
                         x.shape[0])
    rows = x.rows
    offset = 0
    for name, h in zip(out_names, sections):
        in_sec = (rows >= offset) & (rows < offset + h)
        local = jnp.where(in_sec, rows - offset, h)
        ctx.outputs[name] = SelectedRows(local.astype(jnp.int32),
                                         x.value, int(h))
        offset += h
