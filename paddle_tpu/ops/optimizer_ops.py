"""Optimizer update ops — graph ops like the reference
(``paddle/fluid/operators/{sgd,momentum,adam,adamax,adagrad,adadelta,
decayed_adagrad,rmsprop,ftrl,proximal_gd,proximal_adagrad}_op``).

Each op consumes Param/Grad/state and emits ParamOut/state-out bound to the
SAME variable names, so the executor's persistable write-back gives in-place
update semantics; inside the compiled step XLA donates the buffers.

Deviation from the reference: the reference updates Adam's beta1^t/beta2^t
accumulators with separate ``scale`` ops appended by the Python optimizer
(`python/paddle/fluid/optimizer.py:414`); here the adam/adamax op emits
Beta1PowOut/Beta2PowOut itself so the op is self-contained.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.ops.registry import register_op, infer_shape_unary
from paddle_tpu.selected_rows import is_selected_rows


def _infer_param_out(op, block):
    for in_slot, out_slot in (("Param", "ParamOut"), ("Moment", "MomentOut"),
                              ("Moment1", "Moment1Out"),
                              ("Moment2", "Moment2Out"),
                              ("Velocity", "VelocityOut"),
                              ("InfNorm", "InfNormOut"),
                              ("AvgSquaredGrad", "AvgSquaredGradOut"),
                              ("AvgSquaredUpdate", "AvgSquaredUpdateOut"),
                              ("MeanSquare", "MeanSquareOut"),
                              ("SquaredAccumulator", "SquaredAccumOut"),
                              ("LinearAccumulator", "LinearAccumOut"),
                              ("Beta1Pow", "Beta1PowOut"),
                              ("Beta2Pow", "Beta2PowOut")):
        ins, outs = op.input(in_slot), op.output(out_slot)
        if ins and outs:
            try:
                iv = block.var(ins[0])
                ov = block.var(outs[0])
                ov.shape = iv.shape
                ov.dtype = iv.dtype
            except KeyError:
                pass


@register_op("sgd", infer_shape=_infer_param_out, no_gradient=True,
             stateful_outputs=("ParamOut",),
             selected_rows_inputs=("Grad",))
def sgd_lower(ctx):
    p = ctx.input("Param")
    g = ctx.input("Grad")
    lr = ctx.input("LearningRate").reshape(()).astype(p.dtype)
    if is_selected_rows(g):
        # sparse branch (reference sgd_op.h SelectedRows kernel): touch
        # only the gradient's rows; duplicates accumulate via scatter-add
        ctx.set_output("ParamOut",
                       p.at[g.rows].add((-lr * g.value).astype(p.dtype)))
        return
    ctx.set_output("ParamOut", p - lr * g)


@register_op("momentum", infer_shape=_infer_param_out, no_gradient=True,
             stateful_outputs=("ParamOut", "VelocityOut"))
def momentum_lower(ctx):
    p, g = ctx.input("Param"), ctx.input("Grad")
    v = ctx.input("Velocity")
    mu = ctx.attr("mu")
    lr = ctx.input("LearningRate").reshape(()).astype(p.dtype)
    v_new = mu * v + g
    if ctx.attr("use_nesterov", False):
        p_new = p - (g + mu * v_new) * lr
    else:
        p_new = p - lr * v_new
    ctx.set_output("ParamOut", p_new)
    ctx.set_output("VelocityOut", v_new)


@register_op("adam", infer_shape=_infer_param_out, no_gradient=True,
             stateful_outputs=("ParamOut", "Moment1Out", "Moment2Out",
                               "Beta1PowOut", "Beta2PowOut"),
             selected_rows_inputs=("Grad",))
def adam_lower(ctx):
    p, g = ctx.input("Param"), ctx.input("Grad")
    m1, m2 = ctx.input("Moment1"), ctx.input("Moment2")
    b1p = ctx.input("Beta1Pow").reshape(())
    b2p = ctx.input("Beta2Pow").reshape(())
    lr = ctx.input("LearningRate").reshape(()).astype(jnp.float32)
    beta1 = ctx.attr("beta1", 0.9)
    beta2 = ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    lr_t = lr * jnp.sqrt(1.0 - b2p) / (1.0 - b1p)
    ctx.set_output("Beta1PowOut", (b1p * beta1).reshape(1))
    ctx.set_output("Beta2PowOut", (b2p * beta2).reshape(1))
    if is_selected_rows(g):
        # reference adam_op.h SparseAdamFunctor: lazy row-wise update of
        # the moments/param at the (merged) gradient rows only
        sr = g.merge_duplicates()
        gv = sr.value
        m1_rows = beta1 * m1[sr.rows] + (1.0 - beta1) * gv
        m2_rows = beta2 * m2[sr.rows] + (1.0 - beta2) * jnp.square(gv)
        p_rows = p[sr.rows] - (lr_t * m1_rows /
                               (jnp.sqrt(m2_rows) + eps)).astype(p.dtype)
        ctx.set_output("ParamOut", p.at[sr.rows].set(p_rows))
        ctx.set_output("Moment1Out", m1.at[sr.rows].set(m1_rows))
        ctx.set_output("Moment2Out", m2.at[sr.rows].set(m2_rows))
        return
    m1n = beta1 * m1 + (1.0 - beta1) * g
    m2n = beta2 * m2 + (1.0 - beta2) * jnp.square(g)
    p_new = p - (lr_t * m1n / (jnp.sqrt(m2n) + eps)).astype(p.dtype)
    ctx.set_output("ParamOut", p_new)
    ctx.set_output("Moment1Out", m1n)
    ctx.set_output("Moment2Out", m2n)


@register_op("adamax", infer_shape=_infer_param_out, no_gradient=True,
             stateful_outputs=("ParamOut", "MomentOut", "InfNormOut",
                               "Beta1PowOut"))
def adamax_lower(ctx):
    p, g = ctx.input("Param"), ctx.input("Grad")
    m = ctx.input("Moment")
    inf_norm = ctx.input("InfNorm")
    b1p = ctx.input("Beta1Pow").reshape(())
    lr = ctx.input("LearningRate").reshape(())
    beta1 = ctx.attr("beta1", 0.9)
    beta2 = ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    m_new = beta1 * m + (1.0 - beta1) * g
    inf_new = jnp.maximum(beta2 * inf_norm, jnp.abs(g) + eps)
    lr_t = lr / (1.0 - b1p)
    ctx.set_output("ParamOut", p - lr_t * m_new / inf_new)
    ctx.set_output("MomentOut", m_new)
    ctx.set_output("InfNormOut", inf_new)
    ctx.set_output("Beta1PowOut", (b1p * beta1).reshape(1))


@register_op("adagrad", infer_shape=_infer_param_out, no_gradient=True,
             stateful_outputs=("ParamOut", "MomentOut"),
             selected_rows_inputs=("Grad",))
def adagrad_lower(ctx):
    p, g = ctx.input("Param"), ctx.input("Grad")
    m = ctx.input("Moment")
    lr = ctx.input("LearningRate").reshape(())
    eps = ctx.attr("epsilon", 1e-6)
    if is_selected_rows(g):
        # reference adagrad_op.h sparse kernel: merge duplicate rows, then
        # update moment/param only at those rows
        sr = g.merge_duplicates()
        gv = sr.value
        m_rows = m[sr.rows] + jnp.square(gv)
        m_new = m.at[sr.rows].set(m_rows)
        p_rows = p[sr.rows] - lr * gv / (jnp.sqrt(m_rows) + eps)
        ctx.set_output("ParamOut", p.at[sr.rows].set(p_rows.astype(p.dtype)))
        ctx.set_output("MomentOut", m_new)
        return
    m_new = m + jnp.square(g)
    ctx.set_output("ParamOut", p - lr * g / (jnp.sqrt(m_new) + eps))
    ctx.set_output("MomentOut", m_new)


@register_op("decayed_adagrad", infer_shape=_infer_param_out,
             no_gradient=True, stateful_outputs=("ParamOut", "MomentOut"))
def decayed_adagrad_lower(ctx):
    p, g = ctx.input("Param"), ctx.input("Grad")
    m = ctx.input("Moment")
    lr = ctx.input("LearningRate").reshape(())
    decay = ctx.attr("decay", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    m_new = decay * m + (1.0 - decay) * jnp.square(g)
    ctx.set_output("ParamOut", p - lr * g / (jnp.sqrt(m_new) + eps))
    ctx.set_output("MomentOut", m_new)


@register_op("adadelta", infer_shape=_infer_param_out, no_gradient=True,
             stateful_outputs=("ParamOut", "AvgSquaredGradOut",
                               "AvgSquaredUpdateOut"))
def adadelta_lower(ctx):
    p, g = ctx.input("Param"), ctx.input("Grad")
    asg = ctx.input("AvgSquaredGrad")
    asu = ctx.input("AvgSquaredUpdate")
    rho = ctx.attr("rho", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    asg_new = rho * asg + (1.0 - rho) * jnp.square(g)
    update = -jnp.sqrt((asu + eps) / (asg_new + eps)) * g
    asu_new = rho * asu + (1.0 - rho) * jnp.square(update)
    ctx.set_output("ParamOut", p + update)
    ctx.set_output("AvgSquaredGradOut", asg_new)
    ctx.set_output("AvgSquaredUpdateOut", asu_new)


@register_op("rmsprop", infer_shape=_infer_param_out, no_gradient=True,
             stateful_outputs=("ParamOut", "MomentOut", "MeanSquareOut"))
def rmsprop_lower(ctx):
    p, g = ctx.input("Param"), ctx.input("Grad")
    m = ctx.input("Moment")
    ms = ctx.input("MeanSquare")
    lr = ctx.input("LearningRate").reshape(())
    rho = ctx.attr("decay", 0.9)
    eps = ctx.attr("epsilon", 1e-10)
    momentum = ctx.attr("momentum", 0.0)
    ms_new = rho * ms + (1.0 - rho) * jnp.square(g)
    m_new = momentum * m + lr * g / jnp.sqrt(ms_new + eps)
    ctx.set_output("ParamOut", p - m_new)
    ctx.set_output("MomentOut", m_new)
    ctx.set_output("MeanSquareOut", ms_new)


@register_op("ftrl", infer_shape=_infer_param_out, no_gradient=True,
             stateful_outputs=("ParamOut", "SquaredAccumOut",
                               "LinearAccumOut"))
def ftrl_lower(ctx):
    p, g = ctx.input("Param"), ctx.input("Grad")
    sq = ctx.input("SquaredAccumulator")
    lin = ctx.input("LinearAccumulator")
    lr = ctx.input("LearningRate").reshape(())
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    lr_power = ctx.attr("lr_power", -0.5)
    sq_new = sq + jnp.square(g)
    sigma = (jnp.power(sq_new, -lr_power) - jnp.power(sq, -lr_power)) / lr
    lin_new = lin + g - sigma * p
    pre = jnp.where(jnp.abs(lin_new) > l1,
                    (jnp.sign(lin_new) * l1 - lin_new) /
                    (jnp.power(sq_new, -lr_power) / lr + 2.0 * l2),
                    jnp.zeros_like(p))
    ctx.set_output("ParamOut", pre)
    ctx.set_output("SquaredAccumOut", sq_new)
    ctx.set_output("LinearAccumOut", lin_new)


@register_op("proximal_gd", infer_shape=_infer_param_out, no_gradient=True,
             stateful_outputs=("ParamOut",))
def proximal_gd_lower(ctx):
    p, g = ctx.input("Param"), ctx.input("Grad")
    lr = ctx.input("LearningRate").reshape(())
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    prox = p - lr * g
    new_p = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) \
        / (1.0 + lr * l2)
    ctx.set_output("ParamOut", new_p)


@register_op("proximal_adagrad", infer_shape=_infer_param_out,
             no_gradient=True, stateful_outputs=("ParamOut", "MomentOut"))
def proximal_adagrad_lower(ctx):
    p, g = ctx.input("Param"), ctx.input("Grad")
    m = ctx.input("Moment")
    lr = ctx.input("LearningRate").reshape(())
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    m_new = m + jnp.square(g)
    lr_t = lr / jnp.sqrt(m_new)
    prox = p - lr_t * g
    new_p = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr_t * l1, 0.0) \
        / (1.0 + lr_t * l2)
    ctx.set_output("ParamOut", new_p)
    ctx.set_output("MomentOut", m_new)


@register_op("average_accumulates", infer_shape=_infer_param_out,
             no_gradient=True, stateful_outputs=("SumOut", "CountOut"))
def average_accumulates_lower(ctx):
    """ModelAverage accumulator (reference ``average_accumulates_op.cc``,
    simplified to a single running sum + count; the reference's 3-tier
    windowed sums exist to bound memory on CPU swaps, which XLA's on-device
    state makes unnecessary).  When the window is exceeded the accumulator
    restarts from the current parameter (max_average_window semantics)."""
    p = ctx.input("Param")
    s = ctx.input("Sum")
    c = ctx.input("Count").reshape(())
    max_window = ctx.attr("max_average_window", 10000)
    restart = c >= max_window
    s_new = jnp.where(restart, p.astype(s.dtype), s + p.astype(s.dtype))
    c_new = jnp.where(restart, 1.0, c + 1.0)
    ctx.set_output("SumOut", s_new)
    ctx.set_output("CountOut", c_new.reshape(1))
