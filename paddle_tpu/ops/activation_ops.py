"""Activation family — reference ``paddle/fluid/operators/activation_op.cc``
registers ~20 activations via functor macros; here each is a one-line
jax.numpy lowering (XLA fuses them into adjacent matmuls/convs).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.ops.registry import register_op, infer_shape_unary


def _unary(name, fn):
    @register_op(name, infer_shape=infer_shape_unary())
    def lower(ctx):
        ctx.set_output("Out", fn(ctx.input("X")))
    lower.__name__ = name + "_lower"
    return lower


_unary("sigmoid", jax.nn.sigmoid)
_unary("logsigmoid", jax.nn.log_sigmoid)
_unary("exp", jnp.exp)
_unary("relu", jax.nn.relu)
_unary("tanh", jnp.tanh)
_unary("tanh_shrink", lambda x: x - jnp.tanh(x))
_unary("sqrt", jnp.sqrt)
_unary("abs", jnp.abs)
_unary("ceil", jnp.ceil)
_unary("floor", jnp.floor)
_unary("round", jnp.round)
_unary("reciprocal", lambda x: 1.0 / x)
_unary("log", jnp.log)
_unary("square", jnp.square)
_unary("softplus", jax.nn.softplus)
_unary("softsign", lambda x: x / (1.0 + jnp.abs(x)))
_unary("sin", jnp.sin)
_unary("cos", jnp.cos)


@register_op("leaky_relu", infer_shape=infer_shape_unary())
def leaky_relu_lower(ctx):
    x = ctx.input("X")
    alpha = ctx.attr("alpha", 0.02)
    ctx.set_output("Out", jnp.where(x > 0, x, alpha * x))


@register_op("elu", infer_shape=infer_shape_unary())
def elu_lower(ctx):
    x = ctx.input("X")
    alpha = ctx.attr("alpha", 1.0)
    ctx.set_output("Out", jnp.where(x > 0, x, alpha * (jnp.exp(x) - 1.0)))


@register_op("relu6", infer_shape=infer_shape_unary())
def relu6_lower(ctx):
    threshold = ctx.attr("threshold", 6.0)
    ctx.set_output("Out", jnp.clip(ctx.input("X"), 0.0, threshold))


@register_op("pow", infer_shape=infer_shape_unary())
def pow_lower(ctx):
    ctx.set_output("Out", jnp.power(ctx.input("X"), ctx.attr("factor", 1.0)))


@register_op("stanh", infer_shape=infer_shape_unary())
def stanh_lower(ctx):
    x = ctx.input("X")
    a = ctx.attr("scale_a", 2.0 / 3.0)
    b = ctx.attr("scale_b", 1.7159)
    ctx.set_output("Out", b * jnp.tanh(a * x))


@register_op("brelu", infer_shape=infer_shape_unary())
def brelu_lower(ctx):
    ctx.set_output("Out", jnp.clip(ctx.input("X"), ctx.attr("t_min", 0.0),
                                   ctx.attr("t_max", 24.0)))


@register_op("soft_relu", infer_shape=infer_shape_unary())
def soft_relu_lower(ctx):
    x = ctx.input("X")
    t = ctx.attr("threshold", 40.0)
    ctx.set_output("Out", jnp.log(1.0 + jnp.exp(jnp.clip(x, -t, t))))


@register_op("hard_sigmoid", infer_shape=infer_shape_unary())
def hard_sigmoid_lower(ctx):
    x = ctx.input("X")
    slope = ctx.attr("slope", 0.2)
    offset = ctx.attr("offset", 0.5)
    ctx.set_output("Out", jnp.clip(slope * x + offset, 0.0, 1.0))


@register_op("swish", infer_shape=infer_shape_unary())
def swish_lower(ctx):
    x = ctx.input("X")
    beta = ctx.attr("beta", 1.0)
    ctx.set_output("Out", x * jax.nn.sigmoid(beta * x))


@register_op("hard_shrink", infer_shape=infer_shape_unary())
def hard_shrink_lower(ctx):
    x = ctx.input("X")
    t = ctx.attr("threshold", 0.5)
    ctx.set_output("Out", jnp.where(jnp.abs(x) > t, x, 0.0))


@register_op("softshrink", infer_shape=infer_shape_unary())
def softshrink_lower(ctx):
    x = ctx.input("X")
    lam = ctx.attr("lambda", 0.5)
    ctx.set_output("Out", jnp.where(x > lam, x - lam,
                                    jnp.where(x < -lam, x + lam, 0.0)))


@register_op("thresholded_relu", infer_shape=infer_shape_unary())
def thresholded_relu_lower(ctx):
    x = ctx.input("X")
    t = ctx.attr("threshold", 1.0)
    ctx.set_output("Out", jnp.where(x > t, x, 0.0))


@register_op("prelu", infer_shape=infer_shape_unary())
def prelu_lower(ctx):
    x = ctx.input("X")
    alpha = ctx.input("Alpha")
    mode = ctx.attr("mode", "all")
    if mode == "all":
        a = alpha.reshape(())
    elif mode == "channel":
        a = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    else:  # element
        a = alpha.reshape((1,) + x.shape[1:])
    ctx.set_output("Out", jnp.where(x > 0, x, a * x))


@register_op("gelu", infer_shape=infer_shape_unary())
def gelu_lower(ctx):
    ctx.set_output("Out", jax.nn.gelu(ctx.input("X"),
                                      approximate=ctx.attr("approximate", True)))


@register_op("maxout", infer_shape=None)
def maxout_lower(ctx):
    x = ctx.input("X")  # NCHW
    groups = ctx.attr("groups")
    n, c, h, w = x.shape
    ctx.set_output("Out",
                   x.reshape(n, c // groups, groups, h, w).max(axis=2))
