"""Comparison / logical / increment ops (reference ``compare_op.cc``,
``logical_op.cc``, ``increment_op.cc``, ``is_empty_op.cc``)."""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.ops.registry import register_op, infer_shape_unary


def _infer_compare(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    out.shape = x.shape
    out.dtype = "bool"


def _make_compare(name, fn):
    @register_op(name, infer_shape=_infer_compare, no_gradient=True)
    def lower(ctx):
        ctx.set_output("Out", fn(ctx.input("X"), ctx.input("Y")))
    lower.__name__ = name + "_lower"


_make_compare("less_than", jnp.less)
_make_compare("less_equal", jnp.less_equal)
_make_compare("greater_than", jnp.greater)
_make_compare("greater_equal", jnp.greater_equal)
_make_compare("equal", jnp.equal)
_make_compare("not_equal", jnp.not_equal)


def _make_logical(name, fn, binary=True):
    @register_op(name, infer_shape=_infer_compare, no_gradient=True)
    def lower(ctx):
        if binary:
            ctx.set_output("Out", fn(ctx.input("X"), ctx.input("Y")))
        else:
            ctx.set_output("Out", fn(ctx.input("X")))
    lower.__name__ = name + "_lower"


_make_logical("logical_and", jnp.logical_and)
_make_logical("logical_or", jnp.logical_or)
_make_logical("logical_xor", jnp.logical_xor)
_make_logical("logical_not", jnp.logical_not, binary=False)


@register_op("increment", infer_shape=infer_shape_unary(), no_gradient=True)
def increment_lower(ctx):
    x = ctx.input("X")
    ctx.set_output("Out", x + jnp.asarray(ctx.attr("step", 1.0), x.dtype))


@register_op("is_empty", no_gradient=True)
def is_empty_lower(ctx):
    x = ctx.input("X")
    ctx.set_output("Out", jnp.asarray(x.size == 0))


@register_op("isfinite", no_gradient=True)
def isfinite_lower(ctx):
    x = ctx.input("X")
    ctx.set_output("Out", jnp.all(jnp.isfinite(x)).reshape(1))
