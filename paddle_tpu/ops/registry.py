"""Operator registry: shape inference, XLA lowering, grad-op makers.

TPU-native replacement for the reference's op machinery
(``paddle/fluid/framework/op_registry.h:62``, ``op_proto_maker.h:23``,
``grad_op_desc_maker.h:33``).  Where the reference registers per-device
CPU/CUDA kernels keyed by ``OpKernelType``, here each op registers ONE
``lower`` function: a pure jax.numpy function from input arrays to output
arrays.  The Executor traces every op lowering in a block into a single
jaxpr and compiles it once with XLA — there is no per-op kernel dispatch
at run time.

Gradients: like the reference, autodiff is IR-level (``backward.py`` appends
``<type>_grad`` ops).  Unlike the reference — which hand-writes every grad
kernel — the default grad op lowering computes ``jax.vjp`` of the forward
lowering, so analytic gradients come from the same code path XLA compiles
for the forward (and XLA CSE folds the recomputed forward away when fwd and
bwd live in one computation).  Ops with data-dependent randomness or integer
semantics register explicit grad lowerings instead.
"""

from __future__ import annotations

import functools

import jax
import numpy as np

__all__ = [
    "OpDef", "register_op", "lookup", "all_ops", "LowerContext",
    "ShapeInferenceSkip", "infer_shape_unary", "infer_shape_elementwise",
    "GRAD_SUFFIX",
]

GRAD_SUFFIX = "@GRAD"

_REGISTRY = {}


class ShapeInferenceSkip(Exception):
    """Raised by infer_shape when shapes cannot be determined at build time."""


class OpDef:
    def __init__(self, type, lower=None, infer_shape=None, grad_maker=None,
                 grad_lower=None, no_grad_inputs=(), stop_gradient_outputs=(),
                 uses_rng=False, stateful_outputs=(), host=False,
                 host_dyn_ok=False, amp_cast=(), amp_upcast=(),
                 selected_rows_inputs=()):
        self.type = type
        self.lower = lower
        self.infer_shape = infer_shape
        # input slots whose lowering understands a SelectedRows value (the
        # sparse-grad path, selected_rows.py); every other slot densifies
        # a SelectedRows automatically, like the reference's data-transform
        # layer converts kernel-incompatible inputs (data_transform.cc)
        self.selected_rows_inputs = frozenset(selected_rows_inputs)
        # mixed precision (the reference's float16 story, platform/float16.h,
        # re-designed for TPU bf16): when the program runs with amp enabled,
        # float32 arrays read through the listed input slots are cast to
        # bfloat16 (amp_cast — compute-heavy MXU ops) or forced to float32
        # (amp_upcast — numerically sensitive ops).  bf16 shares f32's
        # exponent range, so no loss scaling is needed; parameters stay f32
        # in the scope (master weights) and jax.vjp of the cast returns f32
        # cotangents, so optimizer updates are full precision.
        self.amp_cast = frozenset(amp_cast)
        self.amp_upcast = frozenset(amp_upcast)
        # grad_maker: fn(op, block, no_grad_set) -> (list of op-desc dicts,
        #   dict fwd_input_name -> grad_name).  None => default auto maker.
        self.grad_maker = grad_maker
        # explicit grad lowering (lower fn for the <type>_grad op); None =>
        # auto-vjp of self.lower.
        self.grad_lower = grad_lower
        self.no_grad_inputs = frozenset(no_grad_inputs)  # slot names
        self.stop_gradient_outputs = frozenset(stop_gradient_outputs)
        self.uses_rng = uses_rng
        # outputs that alias an input buffer across steps (e.g. ParamOut for
        # optimizer ops); informs donation, not semantics.
        self.stateful_outputs = frozenset(stateful_outputs)
        # host ops need CONCRETE values (data-dependent output shapes /
        # numpy DP) — a block containing one runs in op-by-op interpret
        # mode, like the reference's CPU-only kernels.  host_dyn_ok marks
        # ops whose BUCKETED-dynamic-LoD branch is fully traced (lod.py),
        # so in bucketed mode they do not force interpret mode.
        self.host = host
        self.host_dyn_ok = host_dyn_ok
        self.has_grad = True  # flipped by register_op(no_gradient=True)


def register_op(type, *, infer_shape=None, grad_maker=None, grad_lower=None,
                no_grad_inputs=(), stop_gradient_outputs=(), uses_rng=False,
                no_gradient=False, stateful_outputs=(), host=False,
                host_dyn_ok=False, amp_cast=(), amp_upcast=(),
                selected_rows_inputs=()):
    """Decorator: register ``fn(ctx)`` as the lowering for op ``type``."""

    def deco(fn):
        opdef = OpDef(type, lower=fn, infer_shape=infer_shape,
                      grad_maker=grad_maker, grad_lower=grad_lower,
                      no_grad_inputs=no_grad_inputs,
                      stop_gradient_outputs=stop_gradient_outputs,
                      uses_rng=uses_rng, stateful_outputs=stateful_outputs,
                      host=host, host_dyn_ok=host_dyn_ok,
                      amp_cast=amp_cast, amp_upcast=amp_upcast,
                      selected_rows_inputs=selected_rows_inputs)
        opdef.has_grad = not no_gradient
        _REGISTRY[type] = opdef
        return fn

    return deco


def register_grad_lower(fwd_type):
    """Decorator: register an explicit lowering for ``<fwd_type>_grad``."""

    def deco(fn):
        opdef = _REGISTRY[fwd_type]
        opdef.grad_lower = fn
        return fn

    return deco


def lookup(type):
    return _REGISTRY.get(type)


def all_ops():
    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# common shape-inference helpers
# ---------------------------------------------------------------------------

def infer_shape_unary(in_slot="X", out_slot="Out"):
    """Out has the same shape/dtype as the (first) input."""

    def fn(op, block):
        xs = op.input(in_slot)
        outs = op.output(out_slot)
        if not xs or not outs:
            raise ShapeInferenceSkip()
        x = block.var(xs[0])
        for o in outs:
            ov = block.var(o)
            ov.shape = x.shape
            ov.dtype = x.dtype
            ov.lod_level = x.lod_level

    return fn


def _broadcast_shapes(a, b):
    if a is None or b is None:
        return None
    # numpy-style broadcast over trailing dims; -1 propagates
    out = []
    for da, db in zip(_pad(a, len(b)), _pad(b, len(a))):
        if da == -1 or db == -1:
            out.append(-1)
        else:
            out.append(max(da, db))
    return tuple(out)


def _pad(shape, n):
    shape = tuple(shape)
    return (1,) * (n - len(shape)) + shape


def infer_shape_elementwise(op, block):
    x = block.var(op.input("X")[0])
    ys = op.input("Y")
    out = block.var(op.output("Out")[0])
    if ys:
        y = block.var(ys[0])
        out.shape = x.shape  # paddle semantics: Out matches X (Y broadcasts)
    else:
        out.shape = x.shape
    out.dtype = x.dtype
    out.lod_level = x.lod_level


# ---------------------------------------------------------------------------
# Lowering context
# ---------------------------------------------------------------------------

class LowerContext:
    """Hands an op lowering its input arrays / attrs; collects outputs.

    ``env`` maps variable name -> jax array (tracers during tracing).
    """

    def __init__(self, op, env, block, rng_key=None, training=True,
                 aux=None):
        self.op = op
        self.env = env
        self.block = block
        self._rng_key = rng_key
        self.training = training
        # aux: executor-level services (scope access for control flow, mesh
        # info for collective ops, etc.)
        self.aux = aux or {}
        self.outputs = {}

    # -- inputs ------------------------------------------------------------
    def has_input(self, slot):
        names = self.op.input(slot)
        return bool(names) and names[0] in self.env

    def _amp_cast(self, slot, value):
        """bf16-downcast / f32-upcast per the op's AMP slot lists (active
        only when the executor enabled mixed precision for this program);
        also densifies SelectedRows values for slots whose lowering does
        not declare sparse support."""
        from paddle_tpu.selected_rows import SelectedRows
        if isinstance(value, SelectedRows):
            opdef_sr = lookup(self.op.type)
            if opdef_sr is None or \
                    slot not in opdef_sr.selected_rows_inputs:
                value = value.to_dense()
        if value is None or not self.aux.get("amp"):
            return value
        opdef = lookup(self.op.type)
        if opdef is None:
            return value
        dt = getattr(value, "dtype", None)
        if slot in opdef.amp_cast and dt == jax.numpy.float32:
            return value.astype(jax.numpy.bfloat16)
        if slot in opdef.amp_upcast and dt == jax.numpy.bfloat16:
            return value.astype(jax.numpy.float32)
        return value

    def input(self, slot):
        names = self.op.input(slot)
        if not names:
            return None
        return self._amp_cast(slot, self.env[names[0]])

    def inputs(self, slot):
        return [self._amp_cast(slot, self.env[n])
                for n in self.op.input(slot)]

    def input_var(self, slot):
        names = self.op.input(slot)
        return self.block.var(names[0]) if names else None

    # -- attrs -------------------------------------------------------------
    def attr(self, name, default=None):
        return self.op.attr(name, default)

    # -- outputs -----------------------------------------------------------
    def set_output(self, slot, value):
        names = self.op.output(slot)
        if not names:
            return
        self.outputs[names[0]] = value

    def set_outputs(self, slot, values):
        names = self.op.output(slot)
        for n, v in zip(names, values):
            self.outputs[n] = v

    def output_var(self, slot):
        names = self.op.output(slot)
        return self.block.var(names[0]) if names else None

    # -- LoD (static trace-time ragged metadata) ---------------------------
    def input_lod(self, slot):
        names = self.op.input(slot)
        if not names:
            return None
        return self.aux.get("lod", {}).get(names[0])

    def var_lod(self, name):
        return self.aux.get("lod", {}).get(name)

    def set_output_lod(self, slot, lod):
        names = self.op.output(slot)
        if names:
            self.aux.setdefault("lod", {})[names[0]] = lod

    # -- rng ---------------------------------------------------------------
    def rng_key(self):
        if self._rng_key is None:
            raise RuntimeError(
                f"op {self.op.type} needs an RNG key but none was provided")
        return self._rng_key


def run_lowering(op, env, block, rng_key=None, training=True, aux=None):
    """Execute one op's lowering against ``env``; merge outputs into env."""
    opdef = lookup(op.type)
    if opdef is None or opdef.lower is None:
        raise NotImplementedError(f"no lowering registered for op {op.type!r}")
    ctx = LowerContext(op, env, block, rng_key=rng_key, training=training,
                       aux=aux)
    opdef.lower(ctx)
    env.update(ctx.outputs)
    return ctx.outputs


# ---------------------------------------------------------------------------
# default grad maker (reference: DefaultGradOpDescMaker, grad_op_desc_maker.h)
# ---------------------------------------------------------------------------

def default_grad_maker(op, block, no_grad_set):
    """Build the ``<type>_grad`` op desc for a forward op.

    Inputs:  every forward input slot (same names), every forward output
             slot, and ``<out_slot>@GRAD`` for each forward output.
    Outputs: ``<in_slot>@GRAD`` for each differentiable forward input.
    Returns (grad_op_descs, input_grad_map) where input_grad_map maps
    forward input var name -> its grad var name.
    """
    from paddle_tpu.framework import grad_var_name

    opdef = lookup(op.type)
    inputs = {}
    outputs = {}
    input_grad_map = {}
    for slot, names in op.inputs.items():
        inputs[slot] = list(names)
    for slot, names in op.outputs.items():
        inputs[slot] = list(names)
        inputs[slot + GRAD_SUFFIX] = [grad_var_name(n) for n in names]
    for slot, names in op.inputs.items():
        if opdef is not None and slot in opdef.no_grad_inputs:
            continue
        grads = []
        has_any = False
        for n in names:
            try:
                v = block.var(n)
            except KeyError:
                v = None
            if n in no_grad_set or (v is not None and (
                    v.stop_gradient or v.dtype in ("int32", "int64", "bool",
                                                   "int8", "uint8", "int16"))):
                grads.append("")  # empty = no grad needed for this arg
            else:
                g = grad_var_name(n)
                grads.append(g)
                input_grad_map[n] = g
                has_any = True
        if has_any:
            outputs[slot + GRAD_SUFFIX] = grads
    if not outputs:
        return [], {}
    desc = {"type": op.type + "_grad", "inputs": inputs, "outputs": outputs,
            "attrs": dict(op.attrs)}
    return [desc], input_grad_map


# ---------------------------------------------------------------------------
# auto-vjp lowering for <type>_grad ops
# ---------------------------------------------------------------------------

def zeros_cotangent(value):
    """Zero cotangent matching jax.vjp's convention: float0 for integer /
    bool leaves (e.g. a TensorArray's length), zeros_like for inexact."""
    import numpy as np

    def z(x):
        dt = jax.numpy.asarray(x).dtype if not hasattr(x, "dtype") else x.dtype
        if jax.numpy.issubdtype(dt, jax.numpy.inexact):
            return jax.numpy.zeros_like(x)
        return np.zeros(jax.numpy.shape(x), jax.dtypes.float0)

    return jax.tree_util.tree_map(z, value)


def auto_vjp_grad_lower(fwd_type):
    """Generic lowering for a grad op: jax.vjp of the forward lowering.

    The forward lowering is re-run as a function of the differentiable
    inputs with the REAL variable names in a copy of the (backward-time)
    env, so lowerings that consult the env / LoD metadata by name keep
    working; XLA CSE folds the duplicated forward away.  Ops whose
    lowering consumes env state that is overwritten in place between
    forward and backward (e.g. ``while`` loop carries) set
    ``save_env_snapshot`` so the forward-time env is used instead.
    Integer/missing input grads are skipped; integer pytree leaves get
    float0 cotangents per jax convention.
    """
    fwd_def = _REGISTRY[fwd_type]

    def lower(ctx):
        op = ctx.op
        # Which forward input args need grads (slot, idx) -> grad var name
        wanted = []  # list of (slot, idx, grad_name)
        for slot, grad_names in op.outputs.items():
            if not slot.endswith(GRAD_SUFFIX):
                continue
            in_slot = slot[:-len(GRAD_SUFFIX)]
            for i, g in enumerate(grad_names):
                if g:
                    wanted.append((in_slot, i, g))
        if not wanted:
            return

        # Grad-op inputs partition into: forward outputs (slots S where both
        # S and S@GRAD are inputs), their grads, and forward inputs (the rest).
        fwd_out_slots = _fwd_output_slots(op)
        fwd_in_slots = [s for s in op.inputs
                        if not s.endswith(GRAD_SUFFIX) and s not in fwd_out_slots]
        wanted_set = {(s, i) for s, i, _ in wanted}

        # forward-time env snapshot, if the forward op saved one (keyed by
        # its sub_block identity — the only ops that need snapshots carry
        # sub-blocks)
        base_env = ctx.env
        sub = op.attrs.get("sub_block")
        if sub is not None:
            snap = ctx.aux.get("env_snapshots", {}).get(id(sub))
            if snap is not None:
                base_env = snap

        diff_args = []      # (slot, idx, name) of differentiable args
        primal_vals = []
        for slot in fwd_in_slots:
            for i, n in enumerate(op.input(slot)):
                if (slot, i) in wanted_set:
                    diff_args.append((slot, i, n))
                    primal_vals.append(base_env[n])

        from paddle_tpu.framework import Operator
        fop = Operator(ctx.block, fwd_type, {}, {}, dict(op.attrs))
        fop.inputs = {s: list(op.inputs[s]) for s in fwd_in_slots}
        fop.outputs = {s: list(op.inputs.get(s, [])) for s in fwd_out_slots}
        # only outputs the forward actually produced participate in the vjp
        # (e.g. sequence_pool's MaxIndex is absent unless pooltype==MAX)
        out_names = [n for slot in fwd_out_slots
                     for n in fop.outputs[slot] if n in ctx.env]

        def fwd_fn(*primals):
            env = dict(base_env)
            for (slot, i, n), v in zip(diff_args, primals):
                env[n] = v
            fctx = LowerContext(fop, env, ctx.block, rng_key=None,
                                training=ctx.training, aux=ctx.aux)
            fwd_def.lower(fctx)
            return tuple(fctx.outputs.get(n, env.get(n))
                         for n in out_names)

        _, vjp_fn = jax.vjp(fwd_fn, *primal_vals)

        # cotangents: Out@GRAD inputs, in out_names order
        grad_of_out = {}
        for slot in fwd_out_slots:
            onames = op.inputs.get(slot, [])
            gnames = op.inputs.get(slot + GRAD_SUFFIX, [])
            for i, n in enumerate(onames):
                if i < len(gnames) and gnames[i]:
                    grad_of_out[n] = gnames[i]
        cots = []
        for n in out_names:
            g = grad_of_out.get(n)
            if g and g in ctx.env:
                cots.append(_match_cotangent_dtype(ctx.env[g], ctx.env[n]))
            else:
                cots.append(zeros_cotangent(ctx.env[n]))
        grads = vjp_fn(tuple(cots))

        for (slot, i, n), g in zip(diff_args, grads):
            for ws, wi, gname in wanted:
                if ws == slot and wi == i:
                    ctx.outputs[gname] = g

    return lower


def _match_cotangent_dtype(cot, out_val):
    """Cast inexact array cotangents to the forward output's dtype — under
    mixed precision an op's output may be bf16 while the incoming grad is
    f32 (or vice versa), and jax.vjp requires an exact dtype match."""
    jnp = jax.numpy

    def c(ct, ov):
        if hasattr(ct, "dtype") and hasattr(ov, "dtype") \
                and ct.dtype != ov.dtype \
                and jnp.issubdtype(ct.dtype, jnp.inexact) \
                and jnp.issubdtype(ov.dtype, jnp.inexact):
            return ct.astype(ov.dtype)
        return ct

    try:
        return jax.tree_util.tree_map(c, cot, out_val)
    except ValueError:  # mismatched pytree structure — leave untouched
        return cot


def _fwd_output_slots(grad_op):
    """Forward output slots present on a default-maker grad op: slots S such
    that both S and S@GRAD appear among the grad op's inputs."""
    slots = []
    for slot in grad_op.inputs:
        if slot.endswith(GRAD_SUFFIX):
            base = slot[:-len(GRAD_SUFFIX)]
            if base in grad_op.inputs and base not in slots:
                slots.append(base)
    return slots


def _fwd_input_slots(grad_op):
    outs = _fwd_output_slots(grad_op)
    return [s for s in grad_op.inputs
            if not s.endswith(GRAD_SUFFIX) and s not in outs]


def resolve_lowering(op_type):
    """Find the lowering function for ``op_type``, synthesizing auto-vjp
    lowerings for ``*_grad`` ops whose forward registered no explicit one."""
    opdef = lookup(op_type)
    if opdef is not None and opdef.lower is not None:
        return opdef
    if op_type.endswith("_grad"):
        fwd = op_type[:-len("_grad")]
        fwd_def = lookup(fwd)
        if fwd_def is not None:
            if fwd_def.grad_lower is not None:
                lower = fwd_def.grad_lower
            else:
                if fwd_def.uses_rng:
                    raise NotImplementedError(
                        f"op {fwd!r} uses RNG; register an explicit grad "
                        f"lowering instead of auto-vjp")
                lower = auto_vjp_grad_lower(fwd)
            opdef = OpDef(op_type, lower=lower)
            _REGISTRY[op_type] = opdef
            return opdef
    raise NotImplementedError(f"no lowering registered for op {op_type!r}")
