"""The ``fused_elementwise`` op: one traced closure replaying a run of
pure elementwise member ops.

Emitted only by the optimization pipeline
(``analysis/opt/passes.py::fuse_elementwise_pass``) — never by the
layers API — so its contract is the pass's contract: members are pure
(no RNG, no state, no sub-blocks, no host), every intermediate is
internal to the run, and the single ``Out`` is the last member's
output.  The lowering replays each member's REGISTERED lowering (the
member lowerings ARE the semantics — AMP slot casts included, since
each member context resolves casts by its own op type), so a fused
program computes bit-identical arrays to its unfused form while the
executor pays one op's worth of per-op trace overhead for the whole
run.
"""

from __future__ import annotations

from paddle_tpu.ops.registry import LowerContext, lookup, register_op

__all__ = []


class _OverlayEnv(dict):
    """Local write overlay over the step env: member outputs land here
    (intermediates never leak into the outer env), reads fall through
    to the step env."""

    def __init__(self, base):
        super().__init__()
        self._base = base

    def __missing__(self, key):
        return self._base[key]

    def __contains__(self, key):
        return dict.__contains__(self, key) or key in self._base

    def get(self, key, default=None):
        try:
            return self[key]
        except KeyError:
            return default


def _member_ops(op, block):
    """Reconstruct (and cache on the op) the member Operator list from
    the serialized ``sub_ops`` attr."""
    cached = getattr(op, "_fused_members", None)
    if cached is not None:
        return cached
    from paddle_tpu.framework import Operator
    members = [Operator(block, d["type"], d["inputs"], d["outputs"],
                        d["attrs"])
               for d in op.attr("sub_ops", [])]
    op._fused_members = members
    return members


@register_op("fused_elementwise", no_gradient=True)
def fused_elementwise_lower(ctx):
    env = _OverlayEnv(ctx.env)
    for member in _member_ops(ctx.op, ctx.block):
        opdef = lookup(member.type)
        if opdef is None or opdef.lower is None:
            raise NotImplementedError(
                f"fused_elementwise member {member.type!r} has no "
                f"registered lowering")
        mctx = LowerContext(member, env, ctx.block, rng_key=None,
                            training=ctx.training, aux=ctx.aux)
        opdef.lower(mctx)
        env.update(mctx.outputs)
    out = ctx.op.output("Out")[0]
    ctx.set_output("Out", env[out])
