"""Linear-chain CRF ops (reference ``operators/linear_chain_crf_op.cc``,
``crf_decoding_op.cc`` — the label_semantic_roles workload).

TPU re-design: the forward-backward recursion runs as a ``lax.scan`` over
the padded time axis per sequence (the reference loops per sequence on
CPU only — these ops never had a CUDA kernel).  Transition layout matches
the reference: ``Transition`` is [n_tags + 2, n_tags]; row 0 = start
weights, row 1 = stop weights, rows 2.. = transition[from, to].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.registry import (
    register_op, LowerContext, ShapeInferenceSkip)
from paddle_tpu.ops.sequence_ops import _require_lod, _lengths


def _infer_skip(op, block):
    raise ShapeInferenceSkip()


def _pad_batch(x, lod):
    """[N, D] ragged -> [B, T, D] padded + [B] lengths (static tables)."""
    from paddle_tpu.ops.rnn_ops import _lod_pad_tables, _to_padded
    gather, scatter, lengths, B, T = _lod_pad_tables(lod)
    return _to_padded(x, gather), jnp.asarray(lengths), B, T, scatter


def _crf_log_alpha(emission, transition, lengths):
    """Forward recursion log-normalizer per sequence.

    emission [B, T, K] padded; returns log_Z [B]."""
    start = transition[0]        # [K]
    stop = transition[1]         # [K]
    trans = transition[2:]       # [K, K] trans[from, to]
    B, T, K = emission.shape

    alpha0 = start[None, :] + emission[:, 0]     # [B, K]

    def step(carry, t):
        alpha = carry
        # logsumexp over 'from' axis
        scores = alpha[:, :, None] + trans[None]  # [B, K_from, K_to]
        new = jax.scipy.special.logsumexp(scores, axis=1) + emission[:, t]
        keep = (t < lengths)[:, None]
        alpha = jnp.where(keep, new, alpha)
        return alpha, alpha

    alpha, alphas = jax.lax.scan(step, alpha0, jnp.arange(1, max(T, 1)))
    log_z = jax.scipy.special.logsumexp(alpha + stop[None], axis=1)
    # full forward-variable cache [B, T, K] (log space), t=0 row included
    log_alphas = jnp.concatenate(
        [alpha0[:, None], jnp.moveaxis(alphas, 0, 1)], axis=1) \
        if alphas.shape[0] else alpha0[:, None]
    return log_z, log_alphas


def _crf_gold_score(emission, transition, labels, lengths):
    """Score of the gold path per sequence; labels [B, T] int."""
    start = transition[0]
    stop = transition[1]
    trans = transition[2:]
    B, T = labels.shape
    t_idx = jnp.arange(T)[None, :]
    valid = (t_idx < lengths[:, None])

    emit = jnp.take_along_axis(emission, labels[..., None],
                               axis=2)[..., 0]          # [B, T]
    emit_score = (emit * valid).sum(1)
    first = labels[:, 0]
    start_score = start[first]
    last_idx = jnp.maximum(lengths - 1, 0)
    last = jnp.take_along_axis(labels, last_idx[:, None], axis=1)[:, 0]
    stop_score = stop[last]
    prev, nxt = labels[:, :-1], labels[:, 1:]
    pair_valid = valid[:, 1:]
    trans_score = (trans[prev, nxt] * pair_valid).sum(1)
    return start_score + emit_score + trans_score + stop_score


@register_op("linear_chain_crf", infer_shape=_infer_skip,
             no_grad_inputs=("Label",))
def linear_chain_crf_lower(ctx: LowerContext):
    """Outputs LogLikelihood [B, 1] (negative log-likelihood, matching the
    reference's sign convention: it emits -log p, minimized directly)."""
    emission_flat = ctx.input("Emission")     # [N, K]
    transition = ctx.input("Transition")      # [K+2, K]
    label_flat = ctx.input("Label")           # [N, 1]
    lod = _require_lod(ctx, "Emission")
    emission, lengths, B, T, scatter = _pad_batch(emission_flat, lod)
    labels_p, _, _, _, _ = _pad_batch(
        label_flat.reshape(-1, 1).astype(jnp.int32), lod)
    labels = labels_p[..., 0]

    log_z, log_alphas = _crf_log_alpha(emission, transition, lengths)
    gold = _crf_gold_score(emission, transition, labels, lengths)
    nll = (log_z - gold).reshape(B, 1)
    ctx.set_output("LogLikelihood", nll)
    # parity outputs: the reference caches the forward variables and the
    # exponentiated potentials for its manual grad
    # (linear_chain_crf_op.h Forward).  It stores EmissionExps row-max-
    # normalized (exp(x - max_row)) and Alpha per-step L1-normalized —
    # both to stay inside float32 range; the per-row scale factors cancel
    # in the L1 normalization, so normalized alpha == softmax(log_alpha).
    from paddle_tpu.ops.rnn_ops import _to_flat
    alpha_n = jax.nn.softmax(log_alphas, axis=-1)
    ctx.set_output("Alpha", _to_flat(alpha_n, scatter, B, T))
    ctx.set_output("EmissionExps", jnp.exp(
        emission_flat - emission_flat.max(axis=-1, keepdims=True)))
    ctx.set_output("TransitionExps", jnp.exp(transition))


@register_op("crf_decoding", infer_shape=_infer_skip, no_gradient=True)
def crf_decoding_lower(ctx: LowerContext):
    """Viterbi decode -> best tag per token [N, 1] (int64)."""
    emission_flat = ctx.input("Emission")
    transition = ctx.input("Transition")
    lod = _require_lod(ctx, "Emission")
    emission, lengths, B, T, scatter = _pad_batch(emission_flat, lod)
    start, stop, trans = (transition[0], transition[1], transition[2:])
    K = emission.shape[2]

    v0 = start[None] + emission[:, 0]                    # [B, K]

    def step(carry, t):
        v = carry
        scores = v[:, :, None] + trans[None]             # [B, from, to]
        best_prev = jnp.argmax(scores, axis=1)           # [B, K]
        new = jnp.max(scores, axis=1) + emission[:, t]
        keep = (t < lengths)[:, None]
        v = jnp.where(keep, new, v)
        bp = jnp.where(keep, best_prev,
                       jnp.arange(K)[None, :].astype(best_prev.dtype))
        return v, bp

    v, bps = jax.lax.scan(step, v0, jnp.arange(1, max(T, 1)))
    # bps: [T-1, B, K]
    last_tag = jnp.argmax(v + stop[None], axis=1)        # [B]

    def back(carry, bp):
        tag = carry
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        return prev, tag

    tag0, tags_rest = jax.lax.scan(back, last_tag, bps, reverse=True)
    # tags_rest[i] = tag at time i+1 (stacked in input order); tag0 = t=0
    tags = jnp.concatenate([tag0[None], tags_rest], axis=0)  # [T, B]
    tags_bt = jnp.moveaxis(tags, 0, 1)                   # [B, T]
    flat = tags_bt.reshape(-1)[jnp.asarray(scatter)]
    # label path correction: positions past each length hold stale tags
    # but scatter only addresses valid rows, so flat is exact
    path = flat.reshape(-1, 1).astype(jnp.int32)
    label = ctx.input("Label")
    if label is not None:
        # reference crf_decoding_op.h: with Label given, the output is a
        # per-token 0/1 correctness indicator, not the tag ids
        path = (path == label.reshape(-1, 1).astype(jnp.int32)) \
            .astype(jnp.int32)
    ctx.set_output("ViterbiPath", path)
    ctx.set_output_lod("ViterbiPath", [list(l) for l in lod])
