"""Beam search ops (reference ``paddle/fluid/operators/beam_search_op.cc``,
``beam_search_decode_op.cc`` — the seq2seq decoding workload,
``tests/book/test_machine_translation.py``).

TPU re-design: the reference tracks live beams as a 2-level LoDTensor whose
rows shrink as hypotheses finish; shrinking shapes cannot compile under
XLA, so here beams live in a STATIC ``[batch, beam_size]`` layout for the
whole decode:

  * ``beam_search`` prunes candidates one step: finished beams (last id ==
    end_id) survive as a single (end_id, pre_score) candidate — exactly the
    reference's keep-finished semantics — and the per-batch top-K runs over
    the flattened ``beam*cand`` axis on dense tensors.
  * Parent pointers are an explicit ``parent_idx`` output ([B, K] int64)
    instead of LoD bookkeeping.
  * ``beam_search_decode`` backtracks the (ids, parents) step arrays in one
    ``lax.scan`` to emit padded ``[B, K, T]`` sequences + final scores
    (the reference walks sentence vectors on the CPU,
    beam_search_decode_op.cc BeamSearchDecoder).

First-step convention: seed ``pre_scores`` with 0 for beam 0 and -1e9 for
beams 1..K-1 so the K initially identical beams don't flood the top-K
(the reference starts from a 1-beam LoD instead).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.registry import (
    register_op, LowerContext, ShapeInferenceSkip)
from paddle_tpu.ops.control_flow_ops import TensorArray

NEG_INF = -1e9


def _infer_beam_search(op, block):
    pre = block.var(op.input("pre_ids")[0])
    if pre.shape is None:
        raise ShapeInferenceSkip()
    B, K = pre.shape[0], op.attr("beam_size")
    for slot, dtype in (("selected_ids", "int64"),
                        ("selected_scores", "float32"),
                        ("parent_idx", "int64")):
        names = op.output(slot)
        if names:
            v = block.var(names[0])
            v.shape = (B, K)
            v.dtype = dtype


@register_op("beam_search", infer_shape=_infer_beam_search,
             no_gradient=True)
def beam_search_lower(ctx: LowerContext):
    """One pruning step.

    Inputs  (dense; C = number of candidates per beam, usually K):
      pre_ids    [B, K] int    last selected token per beam
      pre_scores [B, K] f32    accumulated log-prob per beam
      ids        [B, K, C] int candidate token ids (e.g. topk indices)
      scores     [B, K, C] f32 ACCUMULATED log-prob of each candidate
                               (pre_score + log p, as the reference's
                               callers compute, test_machine_translation.py)
    Attrs: beam_size K, end_id.
    Outputs: selected_ids / selected_scores / parent_idx, all [B, K].
    """
    pre_ids = ctx.input("pre_ids")
    pre_scores = ctx.input("pre_scores")
    ids = ctx.input("ids")
    scores = ctx.input("scores")
    K = int(ctx.attr("beam_size"))
    end_id = int(ctx.attr("end_id"))
    B, Kb, C = scores.shape

    finished = (pre_ids == end_id)                       # [B, K]
    # live beams offer their candidates; finished beams offer exactly one
    # candidate: (end_id, unchanged score) in slot 0
    cand_scores = jnp.where(finished[:, :, None],
                            jnp.float32(NEG_INF), scores)
    slot0 = jnp.where(finished, pre_scores,
                      cand_scores[:, :, 0])
    cand_scores = cand_scores.at[:, :, 0].set(slot0)
    cand_ids = jnp.where(finished[:, :, None],
                         jnp.asarray(end_id, ids.dtype), ids)

    flat_scores = cand_scores.reshape(B, Kb * C)
    sel_scores, flat_idx = jax.lax.top_k(flat_scores, K)  # [B, K]
    parent = (flat_idx // C).astype(jnp.int64)
    sel_ids = jnp.take_along_axis(
        cand_ids.reshape(B, Kb * C), flat_idx, axis=1).astype(jnp.int64)

    ctx.set_output("selected_ids", sel_ids)
    ctx.set_output("selected_scores", sel_scores.astype(jnp.float32))
    ctx.set_output("parent_idx", parent)


def _infer_bs_decode(op, block):
    raise ShapeInferenceSkip()


@register_op("beam_search_decode", infer_shape=_infer_bs_decode,
             no_gradient=True)
def beam_search_decode_lower(ctx: LowerContext):
    """Backtrack parent pointers into full hypotheses.

    Inputs:
      Ids       TensorArray of T steps, each [B, K] int64 selected ids
      ParentIdx TensorArray of T steps, each [B, K] int64 parent beams
      Scores    [B, K] f32 final accumulated scores
    Outputs:
      SentenceIds    [B, K, T] int64 (beams sorted best-first, padded with
                     end_id after the first end_id)
      SentenceScores [B, K] f32
    """
    ids_arr = ctx.input("Ids")
    par_arr = ctx.input("ParentIdx")
    scores = ctx.input("Scores")
    if not isinstance(ids_arr, TensorArray):
        raise TypeError("beam_search_decode Ids must be a TensorArray")
    T = ctx.attr("max_len", None)
    if T is None:
        try:
            T = int(ids_arr.length)
        except Exception as e:  # traced length: require the static attr
            raise ValueError(
                "beam_search_decode needs a static step count: set the "
                "'max_len' attr when decoding inside traced control flow"
            ) from e
    ids = ids_arr.data[:T].astype(jnp.int64)       # [T, B, K]
    parents = par_arr.data[:T].astype(jnp.int64)   # [T, B, K]
    B, K = ids.shape[1], ids.shape[2]

    # walk backwards: token at step t for final beam k follows the parent
    # chain from the last step
    init_ptr = jnp.tile(jnp.arange(K, dtype=jnp.int64)[None], (B, 1))

    def back(ptr, x):
        step_ids, step_par = x
        tok = jnp.take_along_axis(step_ids, ptr, axis=1)    # [B, K]
        nxt = jnp.take_along_axis(step_par, ptr, axis=1)
        return nxt, tok

    _, toks = jax.lax.scan(back, init_ptr, (ids[::-1], parents[::-1]))
    seqs = jnp.moveaxis(toks[::-1], 0, -1)          # [B, K, T]
    ctx.set_output("SentenceIds", seqs)
    ctx.set_output("SentenceScores", jnp.asarray(scores, jnp.float32))
