"""Beam search ops (reference ``paddle/fluid/operators/beam_search_op.cc``,
``beam_search_decode_op.cc`` — the seq2seq decoding workload,
``tests/book/test_machine_translation.py``).

TPU re-design: the reference tracks live beams as a 2-level LoDTensor whose
rows shrink as hypotheses finish; shrinking shapes cannot compile under
XLA, so here beams live in a STATIC ``[batch, beam_size]`` layout for the
whole decode:

  * ``beam_search`` prunes candidates one step: finished beams (last id ==
    end_id) survive as a single (end_id, pre_score) candidate — exactly the
    reference's keep-finished semantics — and the per-batch top-K runs over
    the flattened ``beam*cand`` axis on dense tensors.
  * Parent pointers are an explicit ``parent_idx`` output ([B, K] int64)
    instead of LoD bookkeeping.
  * ``beam_search_decode`` backtracks the (ids, parents) step arrays in one
    ``lax.scan`` to emit padded ``[B, K, T]`` sequences + final scores
    (the reference walks sentence vectors on the CPU,
    beam_search_decode_op.cc BeamSearchDecoder).

First-step convention: seed ``pre_scores`` with 0 for beam 0 and -1e9 for
beams 1..K-1 so the K initially identical beams don't flood the top-K
(the reference starts from a 1-beam LoD instead).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.registry import (
    register_op, LowerContext, ShapeInferenceSkip)
from paddle_tpu.ops.control_flow_ops import TensorArray

NEG_INF = -1e9


def _infer_beam_search(op, block):
    pre = block.var(op.input("pre_ids")[0])
    if pre.shape is None:
        raise ShapeInferenceSkip()
    B, K = pre.shape[0], op.attr("beam_size")
    for slot, dtype in (("selected_ids", "int64"),
                        ("selected_scores", "float32"),
                        ("parent_idx", "int64")):
        names = op.output(slot)
        if names:
            v = block.var(names[0])
            v.shape = (B, K)
            v.dtype = dtype


@register_op("beam_search", infer_shape=_infer_beam_search,
             no_gradient=True)
def beam_search_lower(ctx: LowerContext):
    """One pruning step.

    Inputs  (dense; C = number of candidates per beam, usually K):
      pre_ids    [B, K] int    last selected token per beam
      pre_scores [B, K] f32    accumulated log-prob per beam
      ids        [B, K, C] int candidate token ids (e.g. topk indices)
      scores     [B, K, C] f32 ACCUMULATED log-prob of each candidate
                               (pre_score + log p, as the reference's
                               callers compute, test_machine_translation.py)
    Attrs: beam_size K, end_id.
    Outputs: selected_ids / selected_scores / parent_idx, all [B, K].
    """
    pre_ids = ctx.input("pre_ids")
    pre_scores = ctx.input("pre_scores")
    ids = ctx.input("ids")
    scores = ctx.input("scores")
    K = int(ctx.attr("beam_size"))
    end_id = int(ctx.attr("end_id"))
    B, Kb, C = scores.shape

    finished = (pre_ids == end_id)                       # [B, K]
    # live beams offer their candidates; finished beams offer exactly one
    # candidate: (end_id, unchanged score) in slot 0
    cand_scores = jnp.where(finished[:, :, None],
                            jnp.float32(NEG_INF), scores)
    slot0 = jnp.where(finished, pre_scores,
                      cand_scores[:, :, 0])
    cand_scores = cand_scores.at[:, :, 0].set(slot0)
    cand_ids = jnp.where(finished[:, :, None],
                         jnp.asarray(end_id, ids.dtype), ids)

    flat_scores = cand_scores.reshape(B, Kb * C)
    sel_scores, flat_idx = jax.lax.top_k(flat_scores, K)  # [B, K]
    parent = (flat_idx // C).astype(jnp.int64)
    sel_ids = jnp.take_along_axis(
        cand_ids.reshape(B, Kb * C), flat_idx, axis=1).astype(jnp.int64)

    ctx.set_output("selected_ids", sel_ids)
    ctx.set_output("selected_scores", sel_scores.astype(jnp.float32))
    ctx.set_output("parent_idx", parent)


def _infer_bs_decode(op, block):
    raise ShapeInferenceSkip()


@register_op("beam_search_decode", infer_shape=_infer_bs_decode,
             no_gradient=True)
def beam_search_decode_lower(ctx: LowerContext):
    """Backtrack parent pointers into full hypotheses.

    Inputs:
      Ids       TensorArray of T steps, each [B, K] int64 selected ids
      ParentIdx TensorArray of T steps, each [B, K] int64 parent beams
      Scores    [B, K] f32 final accumulated scores
    Outputs:
      SentenceIds    [B, K, T] int64 (beams sorted best-first, padded with
                     end_id after the first end_id)
      SentenceScores [B, K] f32
    """
    ids_arr = ctx.input("Ids")
    par_arr = ctx.input("ParentIdx")
    scores = ctx.input("Scores")
    if not isinstance(ids_arr, TensorArray):
        raise TypeError("beam_search_decode Ids must be a TensorArray")
    T = ctx.attr("max_len", None)
    if T is None:
        try:
            T = int(ids_arr.length)
        except Exception as e:  # traced length: require the static attr
            raise ValueError(
                "beam_search_decode needs a static step count: set the "
                "'max_len' attr when decoding inside traced control flow"
            ) from e
    ids = ids_arr.data[:T].astype(jnp.int64)       # [T, B, K]
    parents = par_arr.data[:T].astype(jnp.int64)   # [T, B, K]
    B, K = ids.shape[1], ids.shape[2]

    # walk backwards: token at step t for final beam k follows the parent
    # chain from the last step
    init_ptr = jnp.tile(jnp.arange(K, dtype=jnp.int64)[None], (B, 1))

    def back(ptr, x):
        step_ids, step_par = x
        tok = jnp.take_along_axis(step_ids, ptr, axis=1)    # [B, K]
        nxt = jnp.take_along_axis(step_par, ptr, axis=1)
        return nxt, tok

    _, toks = jax.lax.scan(back, init_ptr, (ids[::-1], parents[::-1]))
    seqs = jnp.moveaxis(toks[::-1], 0, -1)          # [B, K, T]
    ctx.set_output("SentenceIds", seqs)
    ctx.set_output("SentenceScores", jnp.asarray(scores, jnp.float32))


# ---------------------------------------------------------------------------
# cross_entropy_over_beam — training against multi-step beam expansions
# (reference ``paddle/gserver/layers/CrossEntropyOverBeam.cpp:1-393``).
#
# E search steps ("expansions"), each a triple:
#   Scores[i]  candidate scores, a (nested-for-i>0) LoD sequence [N_i, 1];
#   Ids[i]     [R_i, beam_size] selected within-row candidate ids, -1 pad
#              (kmax_seq_score output); the rows of expansion i+1
#              correspond 1:1, in row-major order, with the non-(-1)
#              slots of Ids[i];
#   Gold[i]    [batch] the ground-truth candidate id per sequence.
#
# Per sequence: follow the gold id through the expansions until it falls
# off the beam (step t); every complete path through the first t
# expansions is a candidate, gold is appended as an extra path when it
# fell off; the cost is softmax cross-entropy over the summed path
# scores with gold as the hard label.
#
# Padding contract (resolves the reference's TODO(caoying)): a row of
# Ids[i] may be right-padded with -1 when the beam under-filled;
# expansion i+1 then has one sub-sequence per NON-(-1) slot of Ids[i],
# in row-major order — padded slots own no sub-sequence.  The reference
# instead indexed the parent-candidate matrix by raw ``row*beam_size +
# col`` slot, which its own TODO admits drifts off by one sub-sequence
# per preceding -1 pad (kmax_seq_score, its upstream, pads exactly
# this way).  This implementation keeps the enumerated-slot mapping —
# the layout kmax_seq_score and the reference's test generator both
# produce — as the DOCUMENTED behavior; the divergence from the
# reference's raw-slot indexing is intentional and pinned by
# ``tests/test_beam_search.py::TestCrossEntropyOverBeam::
# test_padded_row_maps_through_nonpad_slots``.
# ---------------------------------------------------------------------------

def _beam_cost_one_seq(scores, row_starts, ids, golds, beam_size):
    """Cost + per-expansion score-gradients for ONE sequence.

    ``scores[i]``: 1-D candidate scores at expansion i; ``row_starts[i]``:
    offset of each beam row's segment inside ``scores[i]``; ``ids[i]``:
    [R_i, beam_size] (-1 padded); ``golds[i]``: int gold id.
    """
    E = len(scores)
    gold_rows, gold_col, valid = [], -1, 0
    for i in range(E):
        if i:
            prev_flat = ids[i - 1].reshape(-1)
            slot = gold_rows[-1] * beam_size + gold_col
            gold_rows.append(int(np.count_nonzero(prev_flat[:slot] != -1)))
        else:
            gold_rows.append(0)
        valid = i + 1
        hit = np.nonzero(ids[i][gold_rows[-1]] == golds[i])[0]
        if hit.size == 0:
            gold_col = -1
            break
        gold_col = int(hit[0])
    gold_extra = gold_col == -1
    last = valid - 1

    slots_last = np.argwhere(ids[last] != -1)       # row-major
    n_paths = len(slots_last) + (1 if gold_extra else 0)
    path_rows = np.zeros((valid, n_paths), np.int64)
    parents = [int(r) for r, _ in slots_last]
    for p, (r, c) in enumerate(slots_last):
        path_rows[last, p] = ids[last][r, c] + row_starts[last][r]
    if gold_extra:
        path_rows[last, -1] = golds[last] + \
            row_starts[last][gold_rows[last]]
        parents.append(gold_rows[last])
        gold_path = n_paths - 1
    else:
        flat = ids[last].reshape(-1)
        goff = gold_rows[last] * beam_size + gold_col
        gold_path = int(np.count_nonzero(flat[:goff] != -1))

    n_real = len(slots_last)
    for i in range(last - 1, -1, -1):
        slots_i = np.argwhere(ids[i] != -1)
        for p in range(n_real):
            r, c = slots_i[parents[p]]
            path_rows[i, p] = ids[i][r, c] + row_starts[i][r]
            parents[p] = int(r)
        if gold_extra:
            path_rows[i, -1] = golds[i] + row_starts[i][gold_rows[i]]
            parents[-1] = gold_rows[i]

    path_scores = np.zeros(n_paths, np.float64)
    for i in range(valid):
        path_scores += scores[i][path_rows[i]]
    z = path_scores - path_scores.max()
    p = np.exp(z)
    p /= p.sum()
    cost = -float(np.log(max(p[gold_path], 1e-30)))

    dp = p.copy()
    dp[gold_path] -= 1.0
    grads = [np.zeros_like(scores[i], dtype=np.float64)
             for i in range(E)]
    for i in range(valid):
        np.add.at(grads[i], path_rows[i], dp)
    return cost, grads


def _ceob_split(ctx):
    """Slice the batched LoD inputs into per-sequence views; returns
    (batch, beam_size, per_seq) where per_seq[j] = (scores, row_starts,
    ids, golds)."""
    score_names = ctx.op.input("Scores")
    id_names = ctx.op.input("Ids")
    gold_names = ctx.op.input("Gold")
    E = len(score_names)
    if not (len(id_names) == len(gold_names) == E):
        raise ValueError("cross_entropy_over_beam wants E (Scores, Ids, "
                         "Gold) triples")
    scores = [np.asarray(ctx.env[n], np.float64).reshape(-1)
              for n in score_names]
    ids = [np.asarray(ctx.env[n]) for n in id_names]
    golds = [np.asarray(ctx.env[n]).reshape(-1) for n in gold_names]
    beam_size = ids[0].shape[1]

    lods = [ctx.aux.get("lod", {}).get(n) for n in score_names]
    if lods[0] is None:
        raise ValueError("cross_entropy_over_beam: Scores[0] needs a "
                         "1-level LoD (one segment per sequence)")
    starts0 = np.asarray(lods[0][-1] if len(lods[0]) == 1 else lods[0][0])
    batch = len(starts0) - 1

    per_seq = []
    for j in range(batch):
        seq_scores, seq_starts, seq_ids, seq_golds = [], [], [], []
        for i in range(E):
            if i == 0:
                lo, hi = int(starts0[j]), int(starts0[j + 1])
                seq_scores.append(scores[0][lo:hi])
                seq_starts.append(np.zeros(1, np.int64))
                seq_ids.append(ids[0][j:j + 1])
            else:
                lod = lods[i]
                if lod is None or len(lod) < 2:
                    raise ValueError(
                        f"cross_entropy_over_beam: Scores[{i}] must be a "
                        f"2-level nested sequence")
                outer = np.asarray(lod[0])
                inner = np.asarray(lod[1])
                sub_lo, sub_hi = int(outer[j]), int(outer[j + 1])
                row_lo = int(inner[sub_lo])
                seq_scores.append(scores[i][row_lo:int(inner[sub_hi])])
                seq_starts.append(
                    np.asarray(inner[sub_lo:sub_hi], np.int64) - row_lo)
                seq_ids.append(ids[i][sub_lo:sub_hi])
            seq_golds.append(int(golds[i][j]))
        per_seq.append((seq_scores, seq_starts, seq_ids, seq_golds))
    return batch, beam_size, per_seq, score_names


def _ceob_grad_maker(op, block, no_grad_set):
    from paddle_tpu.framework import grad_var_name
    score_names = op.input("Scores")
    g_scores = [grad_var_name(n) for n in score_names]
    desc = {"type": "cross_entropy_over_beam_grad",
            "inputs": {"Scores": list(score_names),
                       "Ids": list(op.input("Ids")),
                       "Gold": list(op.input("Gold")),
                       "Out@GRAD": [grad_var_name(op.output("Out")[0])]},
            "outputs": {"Scores@GRAD": g_scores},
            "attrs": dict(op.attrs)}
    return [desc], dict(zip(score_names, g_scores))


@register_op("cross_entropy_over_beam", host=True,
             grad_maker=_ceob_grad_maker)
def cross_entropy_over_beam_lower(ctx: LowerContext):
    batch, beam_size, per_seq, _ = _ceob_split(ctx)
    costs = np.zeros((batch, 1), np.float32)
    for j, (s, st, i_, g) in enumerate(per_seq):
        costs[j, 0], _ = _beam_cost_one_seq(s, st, i_, g, beam_size)
    ctx.set_output("Out", jnp.asarray(costs))


@register_op("cross_entropy_over_beam_grad", no_gradient=True, host=True)
def cross_entropy_over_beam_grad_lower(ctx: LowerContext):
    batch, beam_size, per_seq, score_names = _ceob_split(ctx)
    g_out = np.asarray(ctx.env[ctx.op.input("Out@GRAD")[0]],
                       np.float64).reshape(-1)
    full = [np.zeros(np.asarray(ctx.env[n]).reshape(-1).shape, np.float64)
            for n in score_names]
    offs = [0] * len(score_names)
    for j, (s, st, i_, g) in enumerate(per_seq):
        _, grads = _beam_cost_one_seq(s, st, i_, g, beam_size)
        for i, gr in enumerate(grads):
            full[i][offs[i]:offs[i] + len(gr)] += gr * g_out[j]
            offs[i] += len(gr)
    for name, gname, arr in zip(score_names,
                                ctx.op.output("Scores@GRAD"), full):
        shape = np.asarray(ctx.env[name]).shape
        ctx.outputs[gname] = jnp.asarray(
            arr.reshape(shape).astype(np.float32))
