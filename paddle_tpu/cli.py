"""Command-line entry points.

Reference L6 surface: the ``paddle_trainer`` CLI
(``paddle/trainer/TrainerMain.cpp:32``), the ``paddle`` shell wrapper
(``paddle/scripts/submit_local.sh.in``), the Go master binary
(``go/cmd/master/master.go``), and the cluster launcher
(``paddle/scripts/cluster_train/paddle.py``).

Usage: ``python -m paddle_tpu <command> ...``

  train   --config SCRIPT [--num-passes N]   run a training script
  infer   --model DIR --feed name=path.npy   load + run an inference model
  master  --files GLOB --port P              serve the task-dispatch master
  launch  --nproc N SCRIPT [args...]         spawn an N-process cluster on
                                             this host (jax.distributed)
  serve   --model DIR --port P               HTTP inference server
                                             (--batch --warmup
                                             --compile-cache DIR;
                                             --master HOST:PORT enrolls
                                             the replica in a fleet)
  router  --master HOST:PORT --port P        health-aware fleet router
                                             (or --replicas a,b,c)
  controller --master H:P --model DIR        router + closed-loop
                                             autoscaler: warm-standby
                                             scale-up, idle drain,
                                             admission backpressure
                                             (--policy POLICY.json or
                                             PADDLE_TPU_AUTOSCALE)
  stats   --addr HOST:PORT                   runtime metrics snapshot of
                                             a serving replica (/stats);
                                             --local for this process;
                                             --prom for Prometheus text
  trace   dump [--addr HOST:PORT|--local]    Chrome trace-event JSON of
                                             the span ring (PADDLE_TPU_
                                             TRACE); load in Perfetto;
                                             --fleet assembles the whole
                                             fleet's rings via the
                                             router (one pid/process)
  fleet-stats --router HOST:PORT             federated fleet metrics:
          | --master H:P | --replicas a,b    one exposition, per-replica
                                             labels, rollup rates,
                                             stale-marked corpses
  bench   check [--dry] | record             bench-trajectory gate over
                                             BENCH_TRAJECTORY.json:
                                             newest run vs recorded
                                             baseline per-metric
                                             tolerance bands; exit 1
                                             on regression
  replay  BUNDLE.pkl [--localize]            re-execute a sentinel-
                                             quarantined step on CPU and
                                             report whether the numerical
                                             fault reproduces (exit 0 =
                                             reproduced, 1 = clean);
                                             --localize probes every op
                                             and names the FIRST one
                                             producing a non-finite
                                             output, with its Python
                                             creation site + stat trail
  runs    tail|show DIR | compare A B        run-ledger readers: tail
                                             the last step rows of a
                                             ledger dir (-n N), digest
                                             a whole run, or compare
                                             two runs field by field
  lint    MODEL_DIR | --zoo NAME|all         static-analyze a program:
                                             def-before-use, shape/dtype
                                             inference, dead ops, donation
                                             hazards, int64 truncation —
                                             rustc-style diagnostics with
                                             stable PTA*** codes
                                             (docs/static_analysis.md);
                                             exit 1 on errors.  Multi-
                                             program: a gen-bundle dir
                                             lints prefill+decode as one
                                             unit; --pair T P lints a
                                             transpiled trainer/pserver
                                             pair; --pipeline N verifies
                                             an N-stage split; --dot OUT
                                             renders the program as a
                                             GraphViz graph
  opt     MODEL_DIR | --zoo NAME|all         run the Program-IR
                                             optimization pipeline
                                             offline: per-pass
                                             diff/stats report, cost
                                             before/after, donation
                                             plan, amortization-gate
                                             verdict (what
                                             PADDLE_TPU_OPT=1 does
                                             in-executor); exit 1 when
                                             any pass was sandwich-
                                             aborted
  ckpt    inspect DIR | verify DIR           checkpoint-dir survey:
                                             committed steps, per-shard
                                             manifest status, saved mesh
                                             topology, latest/last-good
                                             pointers; verify re-hashes
                                             every file and exits 1 on
                                             corruption (operator
                                             restorability probe — no
                                             program load, no device)
  selfcheck                                  strict zoo lint (single- and
                                             multi-program) + every
                                             scanner-enforced registry +
                                             SLO-spec and bench-
                                             trajectory schemas in one
                                             exit-coded pass
  profile [--model transformer|resnet ...]   per-op device-time table of
                                             one compiled training step
  version
"""

from __future__ import annotations

import argparse
import glob
import os
import runpy
import subprocess
import sys

__all__ = ["main"]

VERSION = "0.2.0"


def _cmd_version(args):
    import jax
    try:
        backend = jax.default_backend()
    except Exception as e:  # backend init can fail off-accelerator hosts
        backend = f"unavailable ({type(e).__name__})"
    print(f"paddle_tpu {VERSION} (jax {jax.__version__}, "
          f"backend {backend})")
    return 0


def _cmd_train(args):
    """Run a training script — the ``paddle_trainer --config`` analog.
    The script sees PADDLE_NUM_PASSES etc. like the reference's gflags."""
    if args.num_passes is not None:
        os.environ["PADDLE_NUM_PASSES"] = str(args.num_passes)
    if args.use_tpu is not None:
        os.environ["PADDLE_TPU_USE_TPU"] = str(int(args.use_tpu))
    if args.checkpoint_dir is not None:
        # consumed by fault.manager_from_env() in training scripts
        # (the paddle_trainer --save_dir analog)
        os.environ["PADDLE_TPU_CKPT_DIR"] = args.checkpoint_dir
        os.environ["PADDLE_TPU_CKPT_KEEP"] = str(args.keep_checkpoints)
    sys.argv = [args.config] + (args.script_args or [])
    runpy.run_path(args.config, run_name="__main__")
    return 0


def _cmd_infer(args):
    """Load a saved inference model and run it on .npy feeds
    (the C++ ``inference::Load`` + run flow, ``inference/io.h:35``)."""
    import numpy as np
    import paddle_tpu as fluid

    exe = fluid.Executor()
    program, feed_names, fetch_targets = \
        fluid.io.load_inference_model(args.model, exe)
    feed = {}
    for spec in args.feed or []:
        name, path = spec.split("=", 1)
        feed[name] = np.load(path)
    missing = [n for n in feed_names if n not in feed]
    if missing:
        print(f"missing feeds: {missing}; expected {feed_names}",
              file=sys.stderr)
        return 2
    outs = exe.run(program, feed=feed, fetch_list=fetch_targets)
    for target, value in zip(fetch_targets, outs):
        name = target.name if hasattr(target, "name") else str(target)
        arr = np.asarray(value)
        print(f"{name}: shape={arr.shape}")
        if args.output:
            np.save(os.path.join(args.output, f"{name}.npy"), arr)
    return 0


def _cmd_master(args):
    """Serve the fault-tolerant task master (go master binary analog)."""
    from paddle_tpu.parallel.master import (MasterServer, MasterService,
                                            partition_files)
    files = sorted(glob.glob(args.files))
    if not files:
        print(f"no files match {args.files!r}", file=sys.stderr)
        return 2
    tasks = partition_files(files, args.chunks_per_task)
    service = MasterService(tasks, timeout=args.timeout,
                            failure_max=args.failure_max,
                            snapshot_path=args.snapshot,
                            heartbeat_timeout=args.heartbeat_timeout)
    server = MasterServer(service, host=args.host, port=args.port)
    print(f"master serving {len(tasks)} tasks on "
          f"{server.addr[0]}:{server.addr[1]}", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


def _cmd_serve(args):
    """HTTP inference server over a saved model (L6 serving runtime).
    With --master the replica enrolls in the serving fleet: register on
    readiness, heartbeat-renew the lease, drain cleanly on SIGTERM."""
    from paddle_tpu.serving import serve
    if args.compile_cache:
        # before the predictor's Executor exists, so its compiles persist
        os.environ["PADDLE_TPU_COMPILE_CACHE"] = args.compile_cache
    warmup_sizes = None
    if args.warmup_batch_sizes:
        warmup_sizes = [int(s) for s in args.warmup_batch_sizes.split(",")]
    server_kwargs = dict(
        async_load=args.async_load,
        max_inflight=args.max_inflight,
        request_timeout=args.request_timeout, batching=args.batch,
        max_batch_size=args.max_batch_size,
        max_batch_delay=args.max_batch_delay,
        batch_queue_size=args.batch_queue_size, warmup=args.warmup,
        warmup_batch_sizes=warmup_sizes,
        gen_admission=args.gen_admission,
        gen_queue_size=args.gen_queue_size)
    if args.master:
        from paddle_tpu.fault import GracefulShutdown
        from paddle_tpu.fleet import FleetReplica
        replica = FleetReplica(args.model, args.master,
                               replica_id=args.replica_id,
                               host=args.host, port=args.port,
                               lease_ttl=args.lease_ttl,
                               advertise_host=args.advertise_host,
                               **server_kwargs)
        replica.start()
        print(f"fleet replica {replica.replica_id} serving {args.model} "
              f"on {replica.addr[0]}:{replica.addr[1]} "
              f"(master {args.master})", flush=True)
        # rolling restart contract: SIGTERM -> deregister (router stops
        # routing), finish in-flight, release the lease, exit 0
        with GracefulShutdown() as stop:
            stop.wait()
        migrated = replica.drain(deadline_s=args.drain_deadline_s)
        if migrated:
            print(f"drained: {len(migrated)} active session(s) "
                  f"checkpoint-migrated to survivors", flush=True)
        return 0
    serve(args.model, host=args.host, port=args.port, **server_kwargs)
    return 0


def _cmd_generate(args):
    """Streaming generation client: POST /generate and print tokens as
    the chunks arrive (directly against a replica, or through a fleet
    router — both stream incrementally)."""
    from paddle_tpu.serving import ServingClient
    prompt = [int(t) for t in args.prompt.replace(",", " ").split()]
    client = ServingClient(args.addr, timeout=args.timeout,
                           deadline=args.deadline)
    tokens = []
    for ev in client.generate(prompt, max_new_tokens=args.max_new,
                              eos_id=args.eos_id,
                              stream=not args.no_stream,
                              session_id=args.session_id,
                              resume=not args.no_resume):
        if "token" in ev:
            tokens.append(ev["token"])
            print(ev["token"], flush=True)
        elif ev.get("error"):
            err = ev["error"]
            print(f"error: {err.get('type')}: {err.get('message')}",
                  flush=True)
            return 1
        elif ev.get("done"):
            if ev.get("tokens") and not tokens:
                # stream=false: the buffered reply carries them all
                print(" ".join(str(t) for t in ev["tokens"]), flush=True)
            print(f"# done ({ev.get('finish_reason')})", flush=True)
    return 0


def _cmd_router(args):
    """Serve the health-aware fleet router (master-discovered or static
    replica list)."""
    from paddle_tpu.fleet import FleetRouter
    replicas = [a for a in (args.replicas or "").split(",") if a]
    router = FleetRouter(master_addr=args.master or None,
                         replicas=replicas or None,
                         host=args.host, port=args.port,
                         default_deadline=args.default_deadline,
                         poll_interval=args.poll_interval,
                         slo_spec=args.slo or None)
    n = len(router.live_replicas())
    print(f"fleet router on {router.addr[0]}:{router.addr[1]} "
          f"({'master ' + args.master if args.master else 'static'}; "
          f"{n} replica(s) live)", flush=True)
    try:
        router.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


def _cmd_controller(args):
    """Serve the fleet router WITH the closed control loop in-process:
    a FleetController senses SLO pressure / scraper rollups and scales
    a warm standby pool of replicas built from --model (pre-warmed
    through PADDLE_TPU_COMPILE_CACHE when set)."""
    import itertools

    from paddle_tpu.fault import GracefulShutdown
    from paddle_tpu.fleet import FleetController, FleetReplica, \
        FleetRouter
    if args.compile_cache:
        # before any standby's Executor exists, so warms hit the cache
        os.environ["PADDLE_TPU_COMPILE_CACHE"] = args.compile_cache
    router = FleetRouter(master_addr=args.master,
                         host=args.host, port=args.port,
                         default_deadline=args.default_deadline,
                         poll_interval=args.poll_interval,
                         slo_spec=args.slo or None)
    router.start_background()
    seq = itertools.count()

    def factory():
        return FleetReplica(args.model, args.master,
                            replica_id=f"auto-{os.getpid()}-{next(seq)}",
                            lease_ttl=args.lease_ttl, warmup=True)

    controller = FleetController(router, policy=args.policy or None,
                                 standby_factory=factory)
    warmed = controller.prewarm(raise_on_failure=False)
    controller.start()
    print(f"fleet controller on {router.addr[0]}:{router.addr[1]} "
          f"(master {args.master}; policy "
          f"{controller.policy.source or 'defaults'}; "
          f"{warmed} standby(s) warm)", flush=True)
    try:
        with GracefulShutdown() as stop:
            stop.wait()
    except KeyboardInterrupt:
        pass
    controller.shutdown(drain_owned=True)
    router.shutdown()
    return 0


def _cmd_stats(args):
    """Fetch and render a server's /stats metrics snapshot (or this
    process's own registry with --local — the datapipe/executor counters
    of an in-process run)."""
    import json as _json

    if args.prom:
        # Prometheus text exposition (the /metrics body) — what a
        # node-exporter-style scraper or a debugging curl wants
        if args.local:
            from paddle_tpu.obs.prom import render_prometheus
            print(render_prometheus(), end="")
        elif args.addr:
            from paddle_tpu.serving import ServingClient
            print(ServingClient(args.addr).prom_metrics(), end="")
        else:
            print("stats: need --addr HOST:PORT or --local",
                  file=sys.stderr)
            return 2
        return 0
    if args.local:
        from paddle_tpu.profiler import runtime_metrics
        snap = runtime_metrics.snapshot()
    elif args.addr:
        from paddle_tpu.serving import ServingClient
        snap = ServingClient(args.addr).stats()
    else:
        print("stats: need --addr HOST:PORT or --local", file=sys.stderr)
        return 2
    if args.json:
        print(_json.dumps(snap, indent=2, sort_keys=True))
        return 0
    for name, v in sorted((snap.get("counters") or {}).items()):
        print(f"{name:<36}{v:>12}")
    for name, s in sorted((snap.get("series") or {}).items()):
        p50, p95, p99 = s.get("p50"), s.get("p95"), s.get("p99")
        fmt = (lambda x: f"{x * 1e3:.2f}ms" if isinstance(x, (int, float))
               else "-")
        print(f"{name:<36}count={s.get('count', 0):<8}"
              f"p50={fmt(p50):<10}p95={fmt(p95):<10}p99={fmt(p99)}")
    for name, v in sorted((snap.get("gauges") or {}).items()):
        print(f"{name:<36}{v:>12g}")
    for name, hist in sorted((snap.get("histograms") or {}).items()):
        print(f"{name}: " + " ".join(f"{k}:{v}" for k, v in hist.items()))
    srv = snap.get("server") or {}
    if srv:
        print("server: " + " ".join(f"{k}={v}"
                                    for k, v in sorted(srv.items())))
    return 0


def _cmd_fleet_stats(args):
    """Fleet-level federated metrics: scrape every replica's /stats and
    render ONE Prometheus exposition with per-replica labels + rollups
    (dead replicas marked stale, never fatal).  Three target modes:
    --router proxies the router's own /metrics?fleet=1 (the router's
    scraper keeps rate state between pulls); --master discovers the
    lease table and scrapes in-process; --replicas scrapes a static
    list."""
    import json as _json
    import urllib.request

    from paddle_tpu.obs import aggregate

    if args.router:
        url = f"http://{args.router}/metrics?fleet=1"
        with urllib.request.urlopen(url, timeout=args.timeout) as r:
            print(r.read().decode(), end="")
        return 0
    if args.master:
        from paddle_tpu.parallel.master import MasterClient
        client = MasterClient(args.master)
        try:
            targets = [(r["addr"], r["id"])
                       for r in client.list_replicas()]
        finally:
            client.close()
    elif args.replicas:
        targets = [(a, a) for a in args.replicas.split(",") if a]
    else:
        print("fleet-stats: need --router, --master, or --replicas",
              file=sys.stderr)
        return 2
    scraper = aggregate.FleetScraper(lambda: targets,
                                     timeout=args.timeout)
    text, scrapes = scraper.federate()
    if args.json:
        print(_json.dumps(
            {"replicas": [{k: s[k] for k in
                           ("addr", "id", "ok", "error", "rtt_s")}
                          for s in scrapes]},
            indent=2, sort_keys=True))
    else:
        print(text, end="")
    return 0


def _cmd_bench(args):
    """Bench trajectory gate: `bench check` compares each bench's
    newest BENCH_TRAJECTORY.json run against its recorded baseline
    under per-metric tolerance bands (exit 1 on regression or schema
    problem); `bench record` imports a bench summary JSON (e.g.
    BENCH_DECODE.json) as a new trajectory run."""
    import json as _json

    from paddle_tpu.obs import bench_history

    if args.action == "record":
        if not args.bench or not args.summary:
            print("bench record: need --bench NAME --summary FILE",
                  file=sys.stderr)
            return 2
        try:
            with open(args.summary) as f:
                summary = _json.load(f)
            metrics = bench_history.summary_metrics(args.bench, summary)
            entry = bench_history.record(
                args.bench, metrics, path=args.trajectory,
                baseline=args.baseline, source=args.summary)
        except (OSError, ValueError, KeyError) as e:
            print(f"bench record: {e}", file=sys.stderr)
            return 2
        print(_json.dumps(entry, indent=2, sort_keys=True))
        return 0
    report = bench_history.check(path=args.trajectory, dry=args.dry)
    if args.json:
        print(_json.dumps(report, indent=2, sort_keys=True))
    else:
        for line in report["problems"]:
            print(f"schema: {line}")
        for bench, b in sorted(report.get("benches", {}).items()):
            for row in b["comparisons"]:
                mark = "ok  " if row["ok"] else "FAIL"
                print(f"[{mark}] {bench}.{row['metric']}: "
                      f"newest={row['newest']:g} vs "
                      f"baseline={row['baseline']:g} "
                      f"({row['direction']}, band={row['band']:g}, "
                      f"bound={row['bound']:g})")
        verdict = "PASS" if report["ok"] else "FAIL"
        what = "schema" if args.dry else "regression gate"
        print(f"bench check ({what}): {verdict} [{report['path']}]")
    return 0 if report["ok"] else 1


def _ckpt_report(dirname, step=None, deep=False):
    """The ``paddle_tpu ckpt`` survey of a checkpoint directory — pure
    directory/manifest reads (no executor, no program, no device):
    committed steps with per-step manifest status (and per-shard file
    presence for shard-format checkpoints), the saved mesh topology,
    the latest/last-good pointers, and quarantined dirs.  ``deep``
    re-hashes every file (``verify``); shallow reads manifests only."""
    from paddle_tpu.fault import checkpoint as ckpt_mod
    from paddle_tpu.fault import shard_ckpt
    from paddle_tpu.fault.checkpoint import (CorruptCheckpoint,
                                             GOOD_POINTER_NAME)

    report = {"dir": os.path.abspath(dirname), "steps": [],
              "latest": None, "last_good": None, "quarantined": [],
              "ok": True}
    for pointer, key in (("latest", "latest"),
                         (GOOD_POINTER_NAME, "last_good")):
        try:
            with open(os.path.join(dirname, pointer)) as f:
                report[key] = int(f.read().strip())
        except (OSError, ValueError):
            pass
    steps = []
    for name in sorted(os.listdir(dirname)):
        if name.endswith(".corrupt"):
            report["quarantined"].append(name)
            continue
        if not name.startswith("ckpt-") or \
                not name[len("ckpt-"):].isdigit():
            continue
        steps.append(int(name[len("ckpt-"):]))
    for s in sorted(steps):
        if step is not None and s != int(step):
            continue
        path = os.path.join(dirname, f"ckpt-{s}")
        row = {"step": s, "format": "legacy", "status": "unverifiable",
               "topology": None, "shards": None}
        manifest = shard_ckpt.read_manifest(path)
        if manifest is not None:
            row["format"] = "manifest"
            topo = manifest.get("topology")
            if topo is not None:
                row["format"] = "sharded"
                shards = topo.get("shards") or {}
                counts = [r.get("num_shards", 1) for r in shards.values()]
                row["topology"] = {
                    "mesh_shape": topo.get("mesh_shape"),
                    "axis_names": topo.get("axis_names"),
                    "processes": topo.get("processes"),
                }
                row["shards"] = {
                    "vars": len(shards),
                    "sharded_vars": sum(1 for c in counts if c > 1),
                    "shard_files": sum(counts),
                }
            try:
                if deep:
                    ckpt_mod.verify_checkpoint(path)
                else:
                    # shallow: file presence + size + topology
                    # self-consistency, no re-hash
                    for rel, want in manifest.get("files", {}).items():
                        p = os.path.join(path, rel)
                        if not os.path.exists(p):
                            raise CorruptCheckpoint(
                                f"{path}: missing file {rel!r}")
                        if os.path.getsize(p) != want["size"]:
                            raise CorruptCheckpoint(
                                f"{path}: {rel!r} size mismatch")
                    if topo is not None:
                        problems = shard_ckpt.validate_topology(manifest)
                        if problems:
                            raise CorruptCheckpoint("; ".join(problems))
                row["status"] = "verified" if deep else "present"
            except CorruptCheckpoint as e:
                row["status"] = "CORRUPT"
                row["error"] = str(e)
                report["ok"] = False
        report["steps"].append(row)
    if step is not None and not report["steps"]:
        report["ok"] = False
        report["error"] = f"no committed ckpt-{int(step)} in {dirname}"
    return report


def _cmd_ckpt(args):
    """Operator-facing checkpoint survey: ``inspect`` prints steps,
    per-shard manifest status, the saved mesh topology, and the
    latest/last-good pointers; ``verify`` re-hashes every file of every
    committed step (or ``--step N``) and exit-codes on corruption — so
    restorability is checkable from a cron job without loading a
    program or touching a device."""
    import json as _json

    if not os.path.isdir(args.dir):
        print(f"ckpt {args.action}: no such directory {args.dir!r}",
              file=sys.stderr)
        return 2
    deep = args.action == "verify"
    report = _ckpt_report(args.dir, step=args.step, deep=deep)
    if args.json:
        print(_json.dumps(report, indent=2, sort_keys=True))
    else:
        print(f"checkpoint dir: {report['dir']}")
        print(f"latest: {report['latest']}   "
              f"last_good: {report['last_good']}")
        for row in report["steps"]:
            line = (f"  ckpt-{row['step']}: {row['status']} "
                    f"[{row['format']}]")
            topo = row.get("topology")
            if topo:
                line += (f" mesh={topo['mesh_shape']}"
                         f"{topo['axis_names']}")
            sh = row.get("shards")
            if sh:
                line += (f" vars={sh['vars']} "
                         f"sharded={sh['sharded_vars']} "
                         f"shard_files={sh['shard_files']}")
            print(line)
            if row.get("error"):
                print(f"    {row['error']}")
        for q in report["quarantined"]:
            print(f"  {q}: quarantined")
        if report.get("error"):
            print(f"ckpt {args.action}: {report['error']}",
                  file=sys.stderr)
        verdict = "PASS" if report["ok"] else "FAIL"
        print(f"ckpt {args.action}: {verdict}")
    return 0 if report["ok"] else 1


def _cmd_trace(args):
    """Dump the span ring as Chrome trace-event JSON — this process's
    ring with --local (enable PADDLE_TPU_TRACE first), a serving
    replica's via its /trace endpoint, or (--fleet, against a router)
    the ASSEMBLED fleet timeline: every process's spans merged onto one
    clock with a distinct pid row per process.  The output loads
    directly in Perfetto (ui.perfetto.dev) or chrome://tracing."""
    import json as _json

    if args.action != "dump":
        print(f"trace: unknown action {args.action!r} (want: dump)",
              file=sys.stderr)
        return 2
    if args.fleet:
        import urllib.request
        if not args.addr:
            print("trace dump --fleet: need --addr ROUTER_HOST:PORT",
                  file=sys.stderr)
            return 2
        url = f"http://{args.addr}/trace?fleet=1"
        with urllib.request.urlopen(url, timeout=60) as r:
            obj = _json.loads(r.read())
    elif args.addr:
        from paddle_tpu.serving import ServingClient
        obj = ServingClient(args.addr).trace()
    else:
        from paddle_tpu.obs import trace as _trace
        obj = _trace.chrome_trace()
    body = _json.dumps(obj)
    if args.output:
        with open(args.output, "w") as f:
            f.write(body)
        print(f"wrote {len(obj['traceEvents'])} span(s) to {args.output}")
    else:
        print(body)
    return 0


def _cmd_replay(args):
    """Re-execute a quarantined training step from its repro bundle
    (``fault.Sentinel`` quarantine output) under the CPU platform — the
    offline debugging loop for a numerical fault seen on the chip.
    ``--localize`` re-executes op by op with per-op tensor-stat probes
    and names the FIRST op whose output went non-finite, with its
    Python creation site and the stat trail of the ops before it.
    Exit code 0 when the fault reproduces/localizes, 1 when the step
    replays clean, 2 on a malformed bundle."""
    import json as _json

    # pin the CPU platform BEFORE any backend initializes: the bundle
    # replays on CPU regardless of what killed the TPU run — even when
    # the launcher environment exported JAX_PLATFORMS=tpu.  The env
    # override is restored afterwards so in-process callers don't leak
    # it into subprocesses they spawn later.
    prev_platform = os.environ.get("JAX_PLATFORMS")
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass  # backend already initialized (in-process use): keep it
        try:
            if args.localize:
                from paddle_tpu.obs.numerics import localize_bundle
                report = localize_bundle(args.bundle)
            else:
                from paddle_tpu.fault.sentinel import replay_bundle
                report = replay_bundle(args.bundle)
        except (OSError, ValueError, KeyError) as e:
            print(f"replay: cannot load bundle {args.bundle!r}: {e}",
                  file=sys.stderr)
            return 2
    finally:
        if prev_platform is None:
            os.environ.pop("JAX_PLATFORMS", None)
        else:
            os.environ["JAX_PLATFORMS"] = prev_platform
    if args.localize:
        return _report_localize(report, json_out=args.json)
    if args.json:
        print(_json.dumps(report, indent=2, sort_keys=True))
    elif report["reproduced"]:
        bad = ", ".join(report["bad"][:6]) or "(loss spike)"
        print(f"step {report['step']}: fault REPRODUCED "
              f"({report['reason']}) in: {bad}"
              + (" [chaos-injected]" if report["injected"] else ""))
    else:
        print(f"step {report['step']}: replayed CLEAN — the fault did "
              f"not reproduce on CPU (suspect hardware/nondeterminism)")
    return 0 if report["reproduced"] else 1


def _report_localize(report, json_out=False):
    """Print a ``numerics.localize_bundle`` report; exit 0 = localized,
    1 = every op produced finite outputs."""
    import json as _json

    if json_out:
        print(_json.dumps(report, indent=2, sort_keys=True))
        return 0 if report["localized"] else 1
    if not report["localized"]:
        print(f"step {report['step']}: all {report['ops_probed']} op "
              f"execution(s) produced finite outputs — nothing to "
              f"localize (suspect hardware/nondeterminism)")
        return 1
    bad = report["first_bad_op"]
    site = bad.get("creation_site")
    where = f"{site[0]}:{site[1]}" if site else "(unknown site)"
    tag = " [chaos-injected]" if report["injected"] else ""
    print(f"step {report['step']}: first non-finite output at op "
          f"#{bad['index']} `{bad['type']}` created at {where}{tag}")
    for name, stats in (bad.get("outputs") or {}).items():
        print(f"  out {name}: {stats}")
    for name, stats in (bad.get("inputs") or {}).items():
        print(f"  in  {name}: {stats}")
    trail = bad.get("trail") or []
    if trail:
        print(f"  trail (last {len(trail)} op(s) before the fault):")
        for row in trail:
            outs = ", ".join(row.get("outputs", {}))
            print(f"    #{row['index']} {row['type']} -> {outs}")
    return 0


def _fmt_cell(value):
    if value is None:
        return "-"
    if isinstance(value, float):
        return f"{value:.6g}"
    return str(value)


def _cmd_runs(args):
    """Read-side of the run ledger (``obs.ledger``): ``tail`` prints
    the last N step rows of a ledger directory, ``show`` a whole-run
    digest (row/segment counts, per-field first/last/min/max),
    ``compare`` two runs side by side with last-value deltas.  Pure
    file readers — no executor, no device, usable while the training
    process is still appending.  Exit 2 on an unreadable ledger."""
    import json as _json

    from paddle_tpu.obs import ledger as _ledger

    try:
        if args.action == "tail":
            rows = _ledger.tail_rows(args.dir, n=args.n)
            if args.json:
                print(_json.dumps(rows, indent=2, sort_keys=True))
                return 0
            fields = [f for f in _ledger.ROW_FIELDS
                      if any(r.get(f) is not None for r in rows)]
            header = ["step", "time_unix"] + fields
            print("  ".join(header))
            for r in rows:
                print("  ".join(_fmt_cell(r.get(k)) for k in header))
            return 0
        if args.action == "show":
            body = _ledger.summarize(args.dir)
            if args.json:
                print(_json.dumps(body, indent=2, sort_keys=True))
                return 0
            print(f"{body['dir']}: {body['rows']} row(s) in "
                  f"{body['segments']} segment(s), steps "
                  f"{body['first_step']}..{body['last_step']}")
            for field, s in sorted(body["fields"].items()):
                print(f"  {field}: first={_fmt_cell(s['first'])} "
                      f"last={_fmt_cell(s['last'])} "
                      f"min={_fmt_cell(s['min'])} "
                      f"max={_fmt_cell(s['max'])} "
                      f"({s['samples']} sample(s))")
            return 0
        # compare
        if not args.dir_b:
            print("runs compare: need two ledger directories",
                  file=sys.stderr)
            return 2
        body = _ledger.compare(args.dir, args.dir_b)
        if args.json:
            print(_json.dumps(body, indent=2, sort_keys=True))
            return 0
        print(f"A: {body['a']['dir']} ({body['a']['rows']} row(s), "
              f"last step {body['a']['last_step']})")
        print(f"B: {body['b']['dir']} ({body['b']['rows']} row(s), "
              f"last step {body['b']['last_step']})")
        for field, d in sorted(body["deltas"].items()):
            print(f"  {field}: A last={_fmt_cell(d['a_last'])}  "
                  f"B last={_fmt_cell(d['b_last'])}  "
                  f"delta={_fmt_cell(d['delta_last'])}")
        return 0
    except ValueError as e:
        print(f"runs: {e}", file=sys.stderr)
        return 2


def _load_saved_program(target):
    """(program, feeds, fetches) from a save_inference_model dir or a
    ``__model__`` json file; raises the loader errors."""
    from paddle_tpu.analysis.distributed import load_saved_program
    return load_saved_program(target)


def _cmd_lint(args):
    """Static analysis over a Program IR (``paddle_tpu.analysis``):
    lint a saved inference model (its ``__model__`` program, no params
    or executor needed — the analysis is static) or a model-zoo
    program built forward+backward.  Multi-program modes lint a whole
    transpiled FAMILY as one unit: a dir with ``gen_meta.json`` lints
    the prefill+decode pair plus the cross-program signature checks, a
    ``--pair trainer pserver`` lints Send/Recv matching and split
    reassembly, ``--pipeline N`` splits the program into N stages and
    verifies boundary carriers and cross-stage collective sync.
    Prints rustc-style diagnostics with stable ``PTA***`` codes; exit
    0 = clean, 1 = findings (errors always; warnings only under
    --strict), 2 = bad target."""
    import json as _json

    from paddle_tpu import analysis

    # ---- multi-program modes: results come pre-analyzed ----
    results = None  # list of (label, AnalysisResult)
    if args.pair:
        members = []
        for role, target in zip(("trainer", "pserver"), args.pair):
            try:
                program, feeds, fetches = _load_saved_program(target)
            except (OSError, ValueError, KeyError) as e:
                print(f"lint: cannot load a program from {target!r}: "
                      f"{e}", file=sys.stderr)
                return 2
            members.append((role, program, feeds, fetches))
        results = [(label, analysis.lint_program(
            program, feed_names=feeds, fetch_names=fetches))
            for label, program, feeds, fetches in members]
        results.append(("pair", analysis.lint_pair(
            (members[0][0], members[0][1]),
            [(members[1][0], members[1][1])])))
    elif args.target and os.path.isdir(args.target) and \
            os.path.isfile(os.path.join(args.target, "gen_meta.json")):
        try:
            results = analysis.lint_gen_bundle(args.target)
        except (OSError, ValueError, KeyError) as e:
            print(f"lint: cannot load the gen bundle at "
                  f"{args.target!r}: {e}", file=sys.stderr)
            return 2
    if results is not None:
        if args.dot:
            print("lint: --dot renders exactly one main program "
                  "(not a --pair / gen-bundle family)", file=sys.stderr)
            return 2
        return _report_lint(results, args)

    targets = []  # (label, program, feed_names, fetch_names)
    if args.zoo:
        from paddle_tpu.models import ZOO_MODELS, build_train_program
        names = ZOO_MODELS if args.zoo == "all" else [args.zoo]
        for name in names:
            try:
                main, startup, feeds, fetches = build_train_program(
                    name, backward=not args.no_backward)
            except ValueError as e:
                print(f"lint: {e}", file=sys.stderr)
                return 2
            targets.append((name, main, feeds, fetches))
            targets.append((f"{name}/startup", startup, None, None))
    elif args.target:
        try:
            program, feeds, fetches = _load_saved_program(args.target)
        except (OSError, ValueError, KeyError) as e:
            print(f"lint: cannot load a program from "
                  f"{args.target!r}: {e}", file=sys.stderr)
            return 2
        targets.append((args.target, program, feeds, fetches))
    else:
        print("lint: need a MODEL_DIR, --zoo NAME|all, or --pair "
              "TRAINER PSERVER", file=sys.stderr)
        return 2

    # --feed/--fetch override the MAIN programs only: the auto-added
    # */startup companions have neither feeds nor the main's fetch vars
    if args.feed:
        feed_override = [s for s in args.feed.split(",") if s]
        targets = [(lbl, p,
                    fd if lbl.endswith("/startup") else feed_override, ft)
                   for lbl, p, fd, ft in targets]
    if args.fetch:
        fetch_override = [s for s in args.fetch.split(",") if s]
        targets = [(lbl, p, fd,
                    ft if lbl.endswith("/startup") else fetch_override)
                   for lbl, p, fd, ft in targets]

    if args.dot:
        mains = [(lbl, p) for lbl, p, _, _ in targets
                 if not lbl.endswith("/startup")]
        if len(mains) != 1:
            print(f"lint: --dot renders exactly one main program, got "
                  f"{len(mains)} (use one MODEL_DIR or --zoo NAME, not "
                  f"--zoo all)", file=sys.stderr)
            return 2
        from paddle_tpu.analysis.visualize import program_dot
        program_dot(mains[0][1], path=args.dot)
        print(f"wrote {args.dot} ({mains[0][0]})")

    results = []
    for label, program, feeds, fetches in targets:
        results.append((label, analysis.lint_program(
            program, feed_names=feeds, fetch_names=fetches)))
        # like --feed/--fetch, --pipeline applies to MAIN programs
        # only: splitting a */startup initializer into "stages"
        # verifies nothing and its host-op shape could abort the run
        if args.pipeline and not label.endswith("/startup"):
            try:
                results.append((f"{label}/pipeline{args.pipeline}",
                                analysis.lint_pipeline(
                                    program, args.pipeline, feeds,
                                    fetches)))
            except ValueError as e:
                # the split itself rejected the program (e.g. a
                # tensor_array would cross a cut) — a target problem,
                # not a diagnostic
                print(f"lint: {label}: {e}", file=sys.stderr)
                return 2
    return _report_lint(results, args)


def _cmd_opt(args):
    """Offline run of the ``analysis/opt`` pass pipeline: optimize a
    saved model (or zoo programs) and print the per-pass diff/stats
    report — what ``PADDLE_TPU_OPT=1`` would do to this program inside
    the executor, inspectable without running anything.  Exit 0 on a
    clean run, 1 when any pass was sandwich-aborted, 2 on a bad
    target."""
    import json as _json

    from paddle_tpu.analysis import cost
    from paddle_tpu.analysis.opt import optimize_program

    targets = []  # (label, program, feeds, fetches)
    if args.zoo:
        from paddle_tpu.models import ZOO_MODELS, build_train_program
        names = ZOO_MODELS if args.zoo == "all" else [args.zoo]
        for name in names:
            try:
                main, startup, feeds, fetches = build_train_program(
                    name, backward=not args.no_backward)
            except ValueError as e:
                print(f"opt: {e}", file=sys.stderr)
                return 2
            targets.append((name, main, feeds, fetches))
            targets.append((f"{name}/startup", startup, None, None))
    elif args.target:
        try:
            program, feeds, fetches = _load_saved_program(args.target)
        except (OSError, ValueError, KeyError) as e:
            print(f"opt: cannot load a program from {args.target!r}: "
                  f"{e}", file=sys.stderr)
            return 2
        targets.append((args.target, program, feeds, fetches))
    else:
        print("opt: need a MODEL_DIR or --zoo NAME|all",
              file=sys.stderr)
        return 2

    passes = None
    if args.passes:
        passes = [s for s in args.passes.split(",") if s]

    aborted = 0
    reports = []
    for label, program, feeds, fetches in targets:
        try:
            optimized, report = optimize_program(
                program, feed_names=feeds, fetch_names=fetches,
                passes=passes)
        except ValueError as e:
            print(f"opt: {e}", file=sys.stderr)
            return 2
        aborted += len(report.aborted_passes)
        if args.json:
            body = report.to_dict()
            body["target"] = label
            plan = getattr(optimized, "_donation_plan", None)
            body["donation_plan"] = plan.to_dict() if plan else None
            body["interpret"] = bool(getattr(optimized,
                                             "_opt_interpret", False))
            reports.append(body)
        else:
            print(f"== {label}")
            print(report.format())
            if report.flops_before is not None:
                print(f"  cost: {report.flops_before:,} -> "
                      f"{report.flops_after:,} static FLOPs")
            if getattr(optimized, "_opt_interpret", False):
                print("  amortization gate: run-once initializer — "
                      "will interpret instead of compile")
            plan = getattr(optimized, "_donation_plan", None)
            if plan is not None:
                print("  " + plan.report().splitlines()[0])
    if args.json:
        print(_json.dumps({"targets": reports}, indent=2))
    return 1 if aborted else 0


def _report_lint(results, args):
    """Shared tail of ``paddle_tpu lint``: print (or JSON-dump) a list
    of ``(label, AnalysisResult)`` and map findings to the exit code."""
    import json as _json

    n_err = sum(len(r.errors) for _, r in results)
    n_warn = sum(len(r.warnings) for _, r in results)
    uncovered = set()
    for _, r in results:
        uncovered.update(r.uncovered_op_types)
    if args.json:
        reports = [{
            "target": label,
            "diagnostics": [d.to_dict() for d in r.diagnostics],
            "uncovered_op_types": r.uncovered_op_types}
            for label, r in results]
        print(_json.dumps({"targets": reports, "errors": n_err,
                           "warnings": n_warn}, indent=2))
    else:
        for label, r in results:
            for d in r.diagnostics:
                print(f"[{label}] {d.format()}")
        print(f"lint: {len(results)} program(s): {n_err} error(s), "
              f"{n_warn} warning(s)")
        if uncovered and args.verbose:
            print(f"  warn-list ({len(uncovered)} op type(s) without an "
                  f"inference rule — shapes/dtypes not propagated "
                  f"through them): {', '.join(sorted(uncovered))}")
    return 1 if n_err or (args.strict and n_warn) else 0


def _cmd_selfcheck(args):
    """One exit-coded pass over every static gate (the pre-merge /
    pre-deploy command CI runs): strict lint of the whole model zoo in
    single-program AND multi-program (distribute-transpiled, pipeline-
    split, gen-exported) modes, plus the scanner-enforced registries —
    diagnostics, metrics, failpoints — that keep docs and code in
    lockstep.  Exit 0 = everything green, 1 = any section failed."""
    import json as _json

    from paddle_tpu.analysis.selfcheck import run_selfcheck

    report = run_selfcheck()
    if args.json:
        print(_json.dumps(report, indent=2, sort_keys=True))
    else:
        for section in report["sections"]:
            mark = "ok  " if section["ok"] else "FAIL"
            print(f"[{mark}] {section['name']}: {section['detail']}")
            for line in section.get("failures", []):
                print(f"       {line}")
        print(f"selfcheck: {'PASS' if report['ok'] else 'FAIL'} "
              f"({sum(s['ok'] for s in report['sections'])}/"
              f"{len(report['sections'])} sections green)")
    return 0 if report["ok"] else 1


def _cmd_launch(args):
    """Spawn an N-process jax.distributed cluster on this host (the
    cluster_train launcher analog; each process gets the reference's
    TRAINER_ID / TRAINERS env convention)."""
    port = args.port
    procs = []
    for rank in range(args.nproc):
        env = dict(os.environ)
        env["PADDLE_COORDINATOR"] = f"127.0.0.1:{port}"
        env["PADDLE_TRAINER_ID"] = str(rank)
        env["PADDLE_TRAINERS"] = str(args.nproc)
        procs.append(subprocess.Popen(
            [sys.executable, args.script] + (args.script_args or []),
            env=env))
    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    return rc


def _profile_build(args):
    """Shared model-building head of the ``profile op|step`` modes:
    returns ``(exe, main_prog, startup, feed, cost_name)``."""
    import numpy as np

    import paddle_tpu as fluid

    if args.model == "transformer":
        from paddle_tpu.models import transformer as T
        hp = T.ModelHyperParams()
        hp.d_model, hp.d_inner_hid, hp.n_layer = args.d_model, \
            2 * args.d_model, args.layers
        hp.n_head = max(1, args.d_model // 64)
        hp.d_key = hp.d_value = args.d_model // hp.n_head
        hp.src_vocab_size = hp.trg_vocab_size = 1000
        hp.max_length = max(64, args.seq)
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_prog, startup):
            cost, _ = T.transformer(args.batch, args.seq, args.seq, hp)
            fluid.optimizer.Adam(learning_rate=1e-4).minimize(cost)
        feed = T.fake_batch(args.batch, args.seq, args.seq, hp)
    elif args.model == "resnet":
        from paddle_tpu.models import resnet as R
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_prog, startup):
            cost, _, _ = R.resnet_train_program(
                args.batch, class_dim=1000, depth=50,
                image_shape=(3, args.seq, args.seq))
            fluid.optimizer.Momentum(learning_rate=0.01,
                                     momentum=0.9).minimize(cost)
        rng = np.random.RandomState(0)
        feed = {"image": rng.rand(args.batch, 3, args.seq,
                                  args.seq).astype("float32"),
                "label": rng.randint(0, 1000, (args.batch, 1))
                .astype("int64")}
    else:
        raise SystemExit(f"unknown --model {args.model!r}")
    exe = fluid.Executor()
    exe.run(startup)
    return exe, main_prog, startup, feed, cost.name


def _fmt_bytes(n):
    return "-" if n is None else f"{n / 1e6:.2f}MB"


def _fmt_ms(s):
    return "-" if s is None else f"{s * 1e3:.1f}ms"


def _profile_zoo_compile(args):
    """Fresh-compile a zoo model (startup + one synthetic train step)
    so every jit key lands a cost/memory record; returns the scope (for
    ``profile memory``'s census)."""
    from paddle_tpu.models import ZOO_MODELS, compile_zoo_step

    name = args.zoo or "mnist"
    if name not in ZOO_MODELS:
        raise SystemExit(f"unknown --zoo {name!r}; expected one of "
                         f"{ZOO_MODELS}")
    return compile_zoo_step(name)


def _cmd_profile_compile(args):
    """``paddle_tpu profile compile``: fresh-compile a zoo model and
    print the per-jit-key table — XLA cost-analysis FLOPs and bytes,
    the memory_analysis breakdown, and the trace/lower/backend phase
    wall times the compile actually paid."""
    import json as _json

    from paddle_tpu.obs import perf

    _profile_zoo_compile(args)
    report = perf.compile_report()
    if args.json:
        print(_json.dumps(report, indent=2, sort_keys=True))
        return 0
    print(f"backend={report['backend']} "
          f"peak={report['peak_flops_per_chip']:.3g} FLOP/s "
          f"({report['mfu_basis']})")
    print(f"{'key':<10}{'GFLOPs':>10}{'bytes':>12}{'arg':>10}"
          f"{'out':>10}{'temp':>10}{'trace':>9}{'lower':>9}"
          f"{'compile':>9}  label")
    for r in report["records"]:
        mem = r["memory"] or {}
        ph = r["phases"]
        flops = "-" if r["flops"] is None else f"{r['flops'] / 1e9:.3f}"
        print(f"{r['key']:<10}{flops:>10}"
              f"{_fmt_bytes(r['bytes_accessed']):>12}"
              f"{_fmt_bytes(mem.get('argument_bytes')):>10}"
              f"{_fmt_bytes(mem.get('output_bytes')):>10}"
              f"{_fmt_bytes(mem.get('temp_bytes')):>10}"
              f"{_fmt_ms(ph['trace_seconds']):>9}"
              f"{_fmt_ms(ph['lower_seconds']):>9}"
              f"{_fmt_ms(ph['backend_seconds']):>9}  {r['label']}")
    return 0


def _cmd_profile_memory(args):
    """``paddle_tpu profile memory``: the HBM census — live device
    bytes attributed to params / optimizer state / KV slots / prefetch
    / other, plus the high watermark and (when the backend or
    PADDLE_TPU_HBM_LIMIT_BYTES declares a limit) the headroom."""
    import json as _json

    from paddle_tpu.obs import perf

    scope = _profile_zoo_compile(args)
    census = perf.hbm_census(scope)
    if args.json:
        print(_json.dumps(census, indent=2, sort_keys=True))
        return 0
    for key in ("params", "optimizer", "kv_cache", "prefetch", "other",
                "total", "high_watermark", "limit", "headroom"):
        if key in census:
            print(f"hbm.{key:<16}{census[key]:>14} bytes")
    return 0


def _cmd_profile_step(args):
    """``paddle_tpu profile step``: N measured steps with the per-step
    breakdown armed (feed / dispatch / device-wait / fetch series) —
    composed with the jax.profiler plumbing via ``--trace-dir`` for an
    XProf/Perfetto device timeline of the same window — plus the live
    MFU the window sustained."""
    from paddle_tpu import profiler
    from paddle_tpu.obs import perf

    exe, main_prog, _startup, feed, cost_name = _profile_build(args)
    exe.run(main_prog, feed=feed, fetch_list=[cost_name])  # compile
    perf.enable_step_phases()
    try:
        if args.trace_dir:
            profiler.start_profiler(profile_path=args.trace_dir)
        for _ in range(args.steps):
            exe.run(main_prog, feed=feed, fetch_list=[cost_name])
    finally:
        if args.trace_dir:
            profiler.stop_profiler()
        perf.disable_step_phases()
    m = profiler.runtime_metrics
    print(f"{'phase':<14}{'p50':>10}{'p95':>10}")
    for phase in ("feed", "dispatch", "device_wait", "fetch"):
        p = m.percentiles(f"perf.step.{phase}_seconds", qs=(50, 95))
        print(f"{phase:<14}{_fmt_ms(p['p50']):>10}"
              f"{_fmt_ms(p['p95']):>10}")
    mfu = m.gauge("train.mfu")
    basis = perf.peak_flops_info()[1]
    if mfu is not None:
        print(f"train.mfu={mfu:.4f} ({basis})")
    if args.trace_dir:
        print(f"device trace written under {args.trace_dir} "
              f"(TensorBoard/XProf or Perfetto)")
    return 0


def _cmd_profile(args):
    """The ``paddle_tpu profile`` family: ``op`` (default) prints the
    per-IR-op device-time table of a compiled training step; ``compile``
    the per-jit-key cost/memory/phase table; ``memory`` the HBM census;
    ``step`` the N-step feed/dispatch/device-wait/fetch breakdown."""
    if args.action == "compile":
        return _cmd_profile_compile(args)
    if args.action == "memory":
        return _cmd_profile_memory(args)
    if args.action == "step":
        return _cmd_profile_step(args)
    from paddle_tpu import profiler
    exe, main_prog, _startup, feed, cost_name = _profile_build(args)
    exe.run(main_prog, feed=feed, fetch_list=[cost_name])  # compile
    with profiler.compiled_profiler(sorted_key=args.sorted_by):
        for _ in range(args.steps):
            exe.run(main_prog, feed=feed, fetch_list=[cost_name])
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(prog="paddle_tpu", description=__doc__)
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("version", help="print version info")
    p.set_defaults(fn=_cmd_version)

    p = sub.add_parser("train", help="run a training script")
    p.add_argument("--config", required=True, help="python training script")
    p.add_argument("--num-passes", type=int, default=None)
    p.add_argument("--use-tpu", type=int, default=None)
    p.add_argument("--checkpoint-dir", default=None,
                   help="export PADDLE_TPU_CKPT_DIR for the script's "
                        "fault.CheckpointManager")
    p.add_argument("--keep-checkpoints", type=int, default=5)
    p.add_argument("script_args", nargs="*")
    p.set_defaults(fn=_cmd_train)

    p = sub.add_parser("infer", help="run a saved inference model")
    p.add_argument("--model", required=True, help="save_inference_model dir")
    p.add_argument("--feed", action="append",
                   help="name=path.npy (repeatable)")
    p.add_argument("--output", default=None, help="dir for output .npy")
    p.set_defaults(fn=_cmd_infer)

    p = sub.add_parser("master", help="serve the data-task master")
    p.add_argument("--files", required=True, help="glob of input files")
    p.add_argument("--chunks-per-task", type=int, default=1)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8037)
    p.add_argument("--timeout", type=float, default=60.0)
    p.add_argument("--failure-max", type=int, default=3)
    p.add_argument("--snapshot", default=None,
                   help="snapshot file for restart recovery")
    p.add_argument("--heartbeat-timeout", type=float, default=None,
                   help="reclaim leases of trainers silent this long "
                        "(default: lease timeout only)")
    p.set_defaults(fn=_cmd_master)

    p = sub.add_parser("serve", help="HTTP inference server")
    p.add_argument("--model", required=True, help="save_inference_model dir")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8866)
    p.add_argument("--async-load", action="store_true",
                   help="serve /healthz immediately; load the model in "
                        "the background (/readyz gates traffic)")
    p.add_argument("--max-inflight", type=int, default=32,
                   help="concurrent /predict slots before 503 "
                        "load-shedding")
    p.add_argument("--request-timeout", type=float, default=None,
                   help="per-request deadline waiting on the predictor "
                        "(504 when exceeded)")
    p.add_argument("--batch", action="store_true",
                   help="coalesce concurrent /predict requests into "
                        "padded row-bucketed micro-batches")
    p.add_argument("--max-batch-size", type=int, default=8,
                   help="max requests coalesced into one dispatch")
    p.add_argument("--max-batch-delay", type=float, default=0.005,
                   help="seconds the batcher lingers for co-batchable "
                        "requests after the first arrives")
    p.add_argument("--batch-queue-size", type=int, default=128,
                   help="bounded batch queue depth before 503 "
                        "load-shedding")
    p.add_argument("--warmup", action="store_true",
                   help="AOT-compile declared feed shapes / serving "
                        "buckets before /readyz reports ready")
    p.add_argument("--warmup-batch-sizes", default=None,
                   help="comma-separated batch sizes to warm "
                        "(default: the batcher's bucket edges)")
    p.add_argument("--compile-cache", default=None,
                   help="persistent XLA compilation cache dir "
                        "(PADDLE_TPU_COMPILE_CACHE): restarts reuse "
                        "compiled executables instead of recompiling")
    p.add_argument("--master", default=None,
                   help="HOST:PORT of the fleet master: register this "
                        "replica for discovery and heartbeat-renew its "
                        "lease (SIGTERM drains cleanly)")
    p.add_argument("--replica-id", default=None,
                   help="stable replica id (default: generated)")
    p.add_argument("--lease-ttl", type=float, default=5.0,
                   help="fleet lease TTL seconds; missing renews this "
                        "long drops the replica from routing")
    p.add_argument("--drain-deadline-s", type=float, default=30.0,
                   help="rolling-restart drain bound: seconds in-flight "
                        "generative streams may run to completion "
                        "before the rest are checkpoint-migrated to "
                        "survivors")
    p.add_argument("--advertise-host", default=None,
                   help="host other machines should dial (default: the "
                        "bind host)")
    p.add_argument("--gen-admission", default="continuous",
                   choices=("continuous", "batch"),
                   help="generation-bundle scheduler policy: admit into "
                        "free KV slots between decode steps "
                        "(continuous) or only between whole batches "
                        "(batch — the request-level baseline)")
    p.add_argument("--gen-queue-size", type=int, default=64,
                   help="bounded /generate admission queue depth before "
                        "503 load-shedding")
    p.set_defaults(fn=_cmd_serve)

    p = sub.add_parser("generate", help="stream tokens from a "
                                        "generation server's /generate")
    p.add_argument("--addr", required=True,
                   help="host:port of a serving replica or fleet router")
    p.add_argument("--prompt", required=True,
                   help="prompt token ids (space/comma separated)")
    p.add_argument("--max-new", type=int, default=16,
                   help="max tokens to generate")
    p.add_argument("--eos-id", type=int, default=None,
                   help="per-request EOS token override")
    p.add_argument("--timeout", type=float, default=60.0)
    p.add_argument("--deadline", type=float, default=None,
                   help="end-to-end budget seconds (sent as "
                        "X-Deadline-Ms)")
    p.add_argument("--no-stream", action="store_true",
                   help="buffered reply instead of chunked streaming")
    p.add_argument("--session-id", default=None,
                   help="resumable-session id (default: minted per "
                        "request; reuse one to resume after a failure)")
    p.add_argument("--no-resume", action="store_true",
                   help="disable mid-stream resume: a dead replica "
                        "surfaces as a terminal error event instead")
    p.set_defaults(fn=_cmd_generate)

    p = sub.add_parser("router", help="health-aware fleet router over "
                                      "serving replicas")
    p.add_argument("--master", default=None,
                   help="HOST:PORT of the fleet master (live replica "
                        "discovery)")
    p.add_argument("--replicas", default=None,
                   help="comma-separated host:port list (static fleet, "
                        "no master)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8868)
    p.add_argument("--default-deadline", type=float, default=30.0,
                   help="end-to-end budget seconds for requests without "
                        "an X-Deadline-Ms header")
    p.add_argument("--poll-interval", type=float, default=0.25,
                   help="master discovery poll interval seconds")
    p.add_argument("--slo", default=None, metavar="SPEC.json",
                   help="SLO spec to evaluate in-router (breach "
                        "counters + post-mortem on sustained breach; "
                        "default: PADDLE_TPU_SLO when set)")
    p.set_defaults(fn=_cmd_router)

    p = sub.add_parser("controller",
                       help="fleet router + closed-loop autoscaler "
                            "(warm-standby scale-up, idle drain, "
                            "admission-control backpressure)")
    p.add_argument("--master", required=True,
                   help="HOST:PORT of the fleet master (replica "
                        "discovery AND standby enrollment)")
    p.add_argument("--model", required=True,
                   help="save_inference_model dir standbys serve")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8868)
    p.add_argument("--policy", default=None, metavar="POLICY.json",
                   help="autoscaler policy (default: "
                        "PADDLE_TPU_AUTOSCALE when set, else the "
                        "documented defaults; `paddle_tpu selfcheck` "
                        "validates the schema)")
    p.add_argument("--slo", default=None, metavar="SPEC.json",
                   help="SLO spec the controller steers by (default: "
                        "PADDLE_TPU_SLO when set)")
    p.add_argument("--default-deadline", type=float, default=30.0,
                   help="end-to-end budget seconds for requests without "
                        "an X-Deadline-Ms header")
    p.add_argument("--poll-interval", type=float, default=0.25,
                   help="master discovery poll interval seconds")
    p.add_argument("--lease-ttl", type=float, default=5.0,
                   help="fleet lease TTL seconds for promoted standbys")
    p.add_argument("--compile-cache", default=None,
                   help="persistent XLA compilation cache dir "
                        "(PADDLE_TPU_COMPILE_CACHE): standby warms "
                        "reuse compiled executables — scale-up is a "
                        "lease registration, not a compile")
    p.set_defaults(fn=_cmd_controller)

    p = sub.add_parser("stats", help="fetch a serving replica's /stats "
                                     "metrics snapshot")
    p.add_argument("--addr", default=None, help="host:port of the server")
    p.add_argument("--local", action="store_true",
                   help="this process's own metrics registry instead of "
                        "a remote server (datapipe/executor counters)")
    p.add_argument("--json", action="store_true",
                   help="raw JSON instead of the formatted table")
    p.add_argument("--prom", action="store_true",
                   help="Prometheus text exposition format (the /metrics "
                        "body) instead of the snapshot table")
    p.set_defaults(fn=_cmd_stats)

    p = sub.add_parser("trace", help="dump the span ring as Chrome "
                                     "trace-event JSON (Perfetto)")
    p.add_argument("action", choices=["dump"])
    p.add_argument("--addr", default=None,
                   help="host:port of a serving replica (/trace) or, "
                        "with --fleet, of the fleet router; "
                        "default: this process's ring (--local)")
    p.add_argument("--local", action="store_true",
                   help="this process's span ring (the default when "
                        "--addr is not given)")
    p.add_argument("--fleet", action="store_true",
                   help="assembled fleet timeline via the router's "
                        "/trace?fleet=1: every process's spans merged "
                        "onto one clock, one pid row per process")
    p.add_argument("--output", default=None,
                   help="write the JSON here instead of stdout")
    p.set_defaults(fn=_cmd_trace)

    p = sub.add_parser("fleet-stats",
                       help="federated fleet metrics: one Prometheus "
                            "exposition over every replica's registry "
                            "(per-replica labels + rollups; dead "
                            "replicas marked stale)")
    p.add_argument("--router", default=None,
                   help="host:port of the fleet router (proxies its "
                        "/metrics?fleet=1 — keeps rate state between "
                        "pulls)")
    p.add_argument("--master", default=None,
                   help="HOST:PORT of the fleet master: scrape the "
                        "current lease table in-process")
    p.add_argument("--replicas", default=None,
                   help="comma-separated host:port list to scrape "
                        "(static fleet, no master)")
    p.add_argument("--timeout", type=float, default=5.0,
                   help="per-replica scrape timeout seconds")
    p.add_argument("--json", action="store_true",
                   help="per-replica scrape health instead of the "
                        "exposition text")
    p.set_defaults(fn=_cmd_fleet_stats)

    p = sub.add_parser("bench",
                       help="bench trajectory: record runs into "
                            "BENCH_TRAJECTORY.json and gate on "
                            "regressions vs the recorded baseline")
    p.add_argument("action", choices=["check", "record"])
    p.add_argument("--trajectory", default=None,
                   help="trajectory file (default: the repo's "
                        "BENCH_TRAJECTORY.json)")
    p.add_argument("--dry", action="store_true",
                   help="with check: validate the schema only (the "
                        "selfcheck gate), no regression comparison")
    p.add_argument("--bench", default=None,
                   help="with record: bench name (serving|datapipe|"
                        "fleet|decode)")
    p.add_argument("--summary", default=None,
                   help="with record: the bench's summary JSON to "
                        "import (e.g. BENCH_DECODE.json)")
    p.add_argument("--baseline", action="store_true",
                   help="with record: flag the run as the bench's "
                        "comparison baseline")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report")
    p.set_defaults(fn=_cmd_bench)

    p = sub.add_parser("replay", help="re-execute a sentinel-quarantined "
                                      "step on CPU (exit 0 = fault "
                                      "reproduced)")
    p.add_argument("bundle", help="pickled repro bundle from the "
                                  "sentinel's quarantine dir")
    p.add_argument("--localize", action="store_true",
                   help="re-execute op by op with per-op tensor-stat "
                        "probes and name the FIRST op producing a "
                        "non-finite output (creation site + stat "
                        "trail); exit 0 = localized, 1 = clean")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report instead of prose")
    p.set_defaults(fn=_cmd_replay)

    p = sub.add_parser("runs",
                       help="run-ledger readers (obs.ledger JSONL "
                            "step series): tail the last rows, digest "
                            "a whole run, or compare two runs")
    p.add_argument("action", choices=["tail", "show", "compare"])
    p.add_argument("dir", help="ledger directory (RunLedger dirname)")
    p.add_argument("dir_b", nargs="?", default=None,
                   help="second ledger directory (compare only)")
    p.add_argument("-n", type=int, default=10,
                   help="with tail: number of rows (default 10)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report")
    p.set_defaults(fn=_cmd_runs)

    p = sub.add_parser("lint", help="static-analyze a program IR "
                                    "(PTA*** diagnostics; "
                                    "docs/static_analysis.md)")
    p.add_argument("target", nargs="?", default=None,
                   help="save_inference_model dir (or a __model__ json "
                        "file) to lint; a dir with gen_meta.json lints "
                        "the whole generation bundle (prefill + decode "
                        "+ cross-program signature checks)")
    p.add_argument("--pair", nargs=2, metavar=("TRAINER", "PSERVER"),
                   default=None,
                   help="lint a transpiled trainer/pserver pair as one "
                        "unit: Send/Recv matching, split reassembly, "
                        "collective sync (PTA011-PTA014)")
    p.add_argument("--pipeline", type=int, default=None, metavar="N",
                   help="also split each linted program into N "
                        "pipeline stages and verify boundary carriers "
                        "+ cross-stage collective sync (PTA011/PTA015)")
    p.add_argument("--zoo", default=None,
                   help="lint a built-in model's forward+backward "
                        "program instead (mnist|resnet|vgg|transformer|"
                        "seq2seq|stacked_lstm|all)")
    p.add_argument("--no-backward", action="store_true",
                   help="with --zoo: lint the forward program only")
    p.add_argument("--feed", default=None,
                   help="comma-separated feed names (default: the "
                        "model's declared feeds)")
    p.add_argument("--fetch", default=None,
                   help="comma-separated fetch names (default: the "
                        "model's declared fetch targets)")
    p.add_argument("--strict", action="store_true",
                   help="exit 1 on warnings too, not just errors")
    p.add_argument("--dot", default=None, metavar="OUT",
                   help="also render the (single) main program as a "
                        "GraphViz .dot graph here: blocks as clusters, "
                        "gradients/donation annotated, op creation "
                        "sites as tooltips")
    p.add_argument("--json", action="store_true",
                   help="machine-readable diagnostics")
    p.add_argument("--verbose", action="store_true",
                   help="also print the warn-list of op types without "
                        "an inference rule")
    p.set_defaults(fn=_cmd_lint)

    p = sub.add_parser("opt", help="run the Program-IR optimization "
                                   "pipeline offline and print the "
                                   "per-pass diff/stats report "
                                   "(docs/static_analysis.md)")
    p.add_argument("target", nargs="?", default=None,
                   help="save_inference_model dir (or a __model__ json "
                        "file) to optimize")
    p.add_argument("--zoo", default=None,
                   help="optimize a built-in model's forward+backward "
                        "program instead (mnist|...|all)")
    p.add_argument("--no-backward", action="store_true",
                   help="with --zoo: the forward program only")
    p.add_argument("--passes", default=None,
                   help="comma-separated pass subset (default: the "
                        "full pipeline)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report")
    p.set_defaults(fn=_cmd_opt)

    p = sub.add_parser("ckpt",
                       help="survey a checkpoint directory: steps, "
                            "per-shard manifest status, saved mesh "
                            "topology, last-good pointer; verify "
                            "re-hashes and exit-codes on corruption")
    p.add_argument("action", choices=["inspect", "verify"])
    p.add_argument("dir", help="checkpoint directory "
                               "(CheckpointManager dirname)")
    p.add_argument("--step", type=int, default=None,
                   help="limit to one committed step")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report")
    p.set_defaults(fn=_cmd_ckpt)

    p = sub.add_parser("selfcheck",
                       help="one exit-coded pass over every static "
                            "gate: strict zoo lint (single- AND "
                            "multi-program), the paged-KV export gate, "
                            "the scanner-enforced "
                            "diagnostic/metric/failpoint registries, "
                            "the SLO spec schema, the run-ledger "
                            "schema round-trip, and the bench-"
                            "trajectory schema (bench check --dry)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable section report")
    p.set_defaults(fn=_cmd_selfcheck)

    p = sub.add_parser("profile",
                       help="device-performance profiling family: "
                            "per-op device time (op), per-jit-key XLA "
                            "cost/memory + compile phases (compile), "
                            "HBM census (memory), N-step "
                            "feed/dispatch/device-wait/fetch breakdown "
                            "(step)")
    p.add_argument("action", nargs="?", default="op",
                   choices=["op", "compile", "memory", "step"],
                   help="op = per-IR-op device-time table (default); "
                        "compile = per-jit-key FLOPs/bytes/memory "
                        "breakdown + trace/lower/compile phase times; "
                        "memory = live-buffer HBM census by collection; "
                        "step = per-step phase breakdown (+ --trace-dir "
                        "for the XProf device timeline)")
    p.add_argument("--model", default="transformer",
                   choices=["transformer", "resnet"],
                   help="built-in model for op/step modes")
    p.add_argument("--zoo", default="mnist",
                   help="zoo model for compile/memory modes "
                        "(mnist|resnet|vgg|transformer|seq2seq|"
                        "stacked_lstm|gen_lm)")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=64,
                   help="sequence length (transformer) or image side "
                        "(resnet)")
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--steps", type=int, default=3)
    p.add_argument("--sorted-by", default="total",
                   choices=["total", "calls"])
    p.add_argument("--trace-dir", default=None,
                   help="with step: also capture a jax.profiler trace "
                        "of the measured window here")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report (compile/memory)")
    p.set_defaults(fn=_cmd_profile)

    p = sub.add_parser("launch", help="spawn a local N-process cluster")
    p.add_argument("--nproc", type=int, required=True)
    p.add_argument("--port", type=int, default=8357)
    p.add_argument("script")
    p.add_argument("script_args", nargs="*")
    p.set_defaults(fn=_cmd_launch)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
