"""LayerHelper — shared machinery for the layers DSL
(reference ``python/paddle/fluid/layer_helper.py``): creates parameters in
the startup+main programs, temp variables, bias/activation appendage.
"""

from __future__ import annotations

import copy

from paddle_tpu import framework
from paddle_tpu import initializer as init_mod
from paddle_tpu.framework import (default_main_program,
                                  default_startup_program, unique_name)
from paddle_tpu.param_attr import ParamAttr

__all__ = ["LayerHelper"]


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name if name is not None else unique_name(layer_type)

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    def append_op(self, *args, **kwargs):
        return self.main_program.current_block().append_op(*args, **kwargs)

    def multiple_input(self, input_param_name="input"):
        inputs = self.kwargs.get(input_param_name, [])
        if isinstance(inputs, framework.Variable):
            return [inputs]
        return list(inputs)

    def input(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        if len(inputs) != 1:
            raise ValueError(f"{self.layer_type} needs exactly one input")
        return inputs[0]

    @property
    def param_attr(self):
        return ParamAttr.to_attr(self.kwargs.get("param_attr"))

    @property
    def bias_attr(self):
        return ParamAttr.to_attr(self.kwargs.get("bias_attr"))

    def multiple_param_attr(self, length):
        attr = self.param_attr
        if isinstance(attr, ParamAttr):
            attr = [copy.deepcopy(attr) for _ in range(length)]
        if len(attr) != length:
            raise ValueError("param_attr length mismatch")
        return attr

    def iter_inputs_and_params(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        attrs = self.multiple_param_attr(len(inputs))
        yield from zip(inputs, attrs)

    def input_dtype(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        dtype = None
        for x in inputs:
            if dtype is None:
                dtype = x.dtype
            elif dtype != x.dtype:
                raise ValueError("all inputs must have the same dtype")
        return dtype

    # -- parameters --------------------------------------------------------
    def create_parameter(self, attr, shape, dtype, is_bias=False,
                         default_initializer=None):
        assert isinstance(attr, ParamAttr)
        attr = copy.deepcopy(attr)
        if attr.name is None:
            attr.name = unique_name(".".join([self.name, "w"])) if not is_bias \
                else unique_name(".".join([self.name, "b"]))
        if default_initializer is None:
            if is_bias:
                attr.set_default_initializer(init_mod.Constant(0.0))
            else:
                attr.set_default_initializer(init_mod.Xavier())
        else:
            attr.set_default_initializer(default_initializer)

        # declare in startup program with its init op
        startup_block = self.startup_program.global_block()
        sp = startup_block.create_parameter(
            shape=shape, dtype=dtype, **attr.to_kwargs(with_initializer=True))
        if attr.initializer is not None:
            attr.initializer(sp, startup_block)
        # declare in main program (no init op)
        return self.main_program.global_block().create_parameter(
            shape=shape, dtype=dtype, **attr.to_kwargs())

    def get_parameter(self, name):
        param = self.main_program.global_block().var(name)
        if not isinstance(param, framework.Parameter):
            raise ValueError(f"no parameter named {name}")
        return param

    # -- temp vars ---------------------------------------------------------
    def create_tmp_variable(self, dtype, stop_gradient=False, shape=None):
        return self.main_program.current_block().create_var(
            name=unique_name(".".join([self.name, "tmp"])), dtype=dtype,
            shape=shape, stop_gradient=stop_gradient)

    def create_variable(self, *args, **kwargs):
        return self.main_program.current_block().create_var(*args, **kwargs)

    def create_global_variable(self, persistable=False, *args, **kwargs):
        return self.main_program.global_block().create_var(
            *args, persistable=persistable, **kwargs)

    def set_variable_initializer(self, var, initializer):
        assert isinstance(var, framework.Variable)
        sb = self.startup_program.global_block()
        sv = sb.create_var(name=var.name, dtype=var.dtype, shape=var.shape,
                           persistable=True)
        initializer(sv, sb)
        return sv

    # -- bias / activation -------------------------------------------------
    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        size = list(input_var.shape[dim_start:dim_end])
        bias_attr = self.bias_attr
        if bias_attr is None:
            return input_var
        b = self.create_parameter(bias_attr, shape=size,
                                  dtype=input_var.dtype, is_bias=True)
        tmp = self.create_tmp_variable(dtype=input_var.dtype)
        self.append_op(
            type="elementwise_add",
            inputs={"X": [input_var], "Y": [b]},
            outputs={"Out": [tmp]},
            attrs={"axis": dim_start})
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        else:
            act = dict(act)
        act_type = act.pop("type")
        tmp = self.create_tmp_variable(dtype=input_var.dtype)
        self.append_op(type=act_type, inputs={"X": [input_var]},
                       outputs={"Out": [tmp]}, attrs=act)
        return tmp
