"""Initializers — append init ops to the startup program
(reference ``python/paddle/fluid/initializer.py``: Constant/Uniform/Normal/
Xavier/MSRA + force_init_on_cpu machinery, which has no TPU analog).
"""

from __future__ import annotations

import math

import numpy as np

__all__ = ["Constant", "Uniform", "Normal", "Xavier", "MSRA", "Bilinear",
           "ConstantInitializer", "UniformInitializer", "NormalInitializer",
           "XavierInitializer", "MSRAInitializer", "force_init_on_cpu",
           "init_on_cpu"]

import contextlib


def force_init_on_cpu():
    return False


@contextlib.contextmanager
def init_on_cpu():
    yield


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self.value = value

    def __call__(self, var, block):
        block.append_op(
            type="fill_constant", outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "value": float(self.value)})


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        block.append_op(
            type="uniform_random", outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "min": float(self.low), "max": float(self.high),
                   "seed": self.seed})


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op(
            type="gaussian_random", outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "dtype": var.dtype,
                   "mean": float(self.loc), "std": float(self.scale),
                   "seed": self.seed})


def _fan_in_out(var):
    shape = var.shape
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform = uniform
        self.fan_in, self.fan_out = fan_in, fan_out
        self.seed = seed

    def __call__(self, var, block):
        fi, fo = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / (fi + fo))
            NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform = uniform
        self.fan_in = fan_in
        self.seed = seed

    def __call__(self, var, block):
        fi, _ = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / fi)
            NormalInitializer(0.0, std, self.seed)(var, block)


class BilinearInitializer(Initializer):
    """For upsampling conv_transpose filters (reference initializer.py)."""

    def __call__(self, var, block):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("bilinear init needs a 4-D filter")
        c, _, h, w = shape
        f = np.ceil(w / 2.0)
        cc = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype=np.float32)
        grid = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
        filt = (1 - np.abs(grid[0] / f - cc)) * (1 - np.abs(grid[1] / f - cc))
        for i in range(c):
            weight[i, min(i, shape[1] - 1)] = filt
        block.append_op(
            type="assign_value", outputs={"Out": [var.name]},
            attrs={"shape": list(shape), "dtype": var.dtype,
                   "fp32_values": weight.flatten().tolist()})


class NumpyArrayInitializer(Initializer):
    """Initialize a variable from a host numpy array (reference
    ``initializer.py`` NumpyArrayInitializer; used e.g. for sinusoid
    position encodings)."""

    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        block.append_op(
            type="assign_value", outputs={"Out": [var.name]},
            attrs={"shape": list(self.value.shape), "dtype": var.dtype,
                   "fp32_values": self.value.astype(np.float32)
                   .flatten().tolist()})


Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer
NumpyArray = NumpyArrayInitializer
