"""Continuous-batching autoregressive generation runtime.

The serving-side composition of the prefill/decode phase split
(``models/gen_lm``), a slot-based bucketed KV cache that keeps the
decode jit signature constant, and an iteration-level scheduler that
admits/evicts requests BETWEEN decode steps — the vLLM/Orca-class
counterpart to PR 2's request-level :class:`~paddle_tpu.serving.
MicroBatcher`.  HTTP streaming lives in ``paddle_tpu/serving.py``
(``/generate``); incremental fleet forwarding in
``paddle_tpu/fleet/router.py``."""

from paddle_tpu.gen.predictor import GenPredictor, is_gen_bundle
from paddle_tpu.gen.scheduler import GenScheduler, GenStream, \
    SchedulerDraining, StreamMigrated

__all__ = ["GenPredictor", "GenScheduler", "GenStream",
           "SchedulerDraining", "StreamMigrated", "is_gen_bundle"]
