"""GenPredictor: the two-entry (prefill + decode) inference handle over
an exported generation bundle (``models/gen_lm.export_gen_model``).

The serving analog of :class:`paddle_tpu.serving.Predictor`, split along
the vLLM/Orca phase boundary:

* :meth:`prefill` runs one prompt (padded to a ``lod.row_bucket`` edge)
  through the full causal forward and returns the next-token logits plus
  the per-layer K/V rows that seed a cache slot.
* :meth:`decode_step` advances EVERY slot of the cache pool by one
  token.  The cache tensors are persistable state in the decode scope —
  they live on device across steps (the executor's donated in-place
  update path) and the step's feed signature is constant, so admission
  and eviction never change the jit key.
* :meth:`write_slot` / :meth:`clear_slot` are the (per-request, not
  per-token) host-side slot writes that seed and reclaim cache rows.

``warmup`` declares BOTH signature families — every prefill bucket and
the decode signature family — through ``Executor.warmup``, so a server
flips ``/readyz`` with the whole generation path compiled.

PAGED bundles (meta carries ``page_len``; the default export) keep the
KV pool as ``[num_pages, page_len, H*D]`` pages addressed through a
host-side per-slot page table.  The predictor owns the page allocator
(:meth:`alloc_slot_pages` / :meth:`free_slot_pages`, driven by the
scheduler's admit/evict), pads the page-table feed to a declared
``page_buckets`` edge each step (the decode jit key is the bucket), and
warms one decode signature per bucket.  Decode reads scale with live
prefix pages, not ``max_len``.
"""

from __future__ import annotations

import json
import os
import threading

import numpy as np

from paddle_tpu.obs.trace import span as _span

__all__ = ["GenPredictor", "is_gen_bundle"]

META_FILENAME = "gen_meta.json"


def is_gen_bundle(model_dir):
    """True when ``model_dir`` is a generation bundle (prefill + decode
    programs + ``gen_meta.json``) rather than a one-shot inference
    model."""
    return os.path.isfile(os.path.join(model_dir, META_FILENAME))


class GenPredictor:
    """Load-once handle over a generation bundle; thread-compatible (one
    internal lock serializes executor access, mirroring Predictor)."""

    def __init__(self, model_dir):
        import paddle_tpu as fluid

        with open(os.path.join(model_dir, META_FILENAME)) as f:
            self.meta = json.load(f)
        self.num_slots = int(self.meta["num_slots"])
        self.max_len = int(self.meta["max_len"])
        self.vocab_size = int(self.meta["vocab_size"])
        self.eos_id = int(self.meta.get("eos_id", -1))
        self.cache_vars = list(self.meta["cache_vars"])
        self.prompt_buckets = [int(b) for b in self.meta["prompt_buckets"]]
        self.max_prompt_len = min(self.prompt_buckets[-1], self.max_len)
        self.paged = "page_len" in self.meta
        if self.paged:
            self.page_len = int(self.meta["page_len"])
            self.num_pages = int(self.meta["num_pages"])
            self.page_buckets = [int(b)
                                 for b in self.meta["page_buckets"]]
            self.pages_per_slot = -(-self.max_len // self.page_len)
            # host-side page allocator state (all mutated under _lock):
            # the device only ever sees the bucketed table SLICE
            self._page_table = np.zeros(
                (self.num_slots, self.pages_per_slot), np.int32)
            self._slot_pages = {}
            self._free_list = list(range(self.num_pages))

        self._fluid = fluid
        self._scope = fluid.Scope()
        self._lock = threading.Lock()
        with fluid.scope_guard(self._scope):
            self._exe = fluid.Executor()
            (self._pre_prog, self._pre_feeds,
             self._pre_fetch) = fluid.io.load_inference_model(
                os.path.join(model_dir, "prefill"), self._exe)
            (self._dec_prog, self._dec_feeds,
             self._dec_fetch) = fluid.io.load_inference_model(
                os.path.join(model_dir, "decode"), self._exe)
        # load-time contract check (analysis/distributed.py): the
        # bundle's prefill/decode pair must satisfy the constant-jit-
        # key contract against gen_meta.json — a bundle that drifted
        # (hand-edited meta, mixed exports) fails HERE, before the
        # server ever flips /readyz, instead of recompiling per decode
        # step or seeding misshapen cache rows mid-request
        from paddle_tpu.analysis import (AnalysisResult,
                                         check_gen_bundle)
        AnalysisResult(check_gen_bundle(
            (self._pre_prog, self._pre_feeds, self._pre_fetch),
            (self._dec_prog, self._dec_feeds, self._dec_fetch),
            self.meta)).raise_on_errors(where="gen.GenPredictor")
        # decode dispatches derive gen.decode_mfu (not train.mfu): the
        # executor keys the gauge off this program attribute
        self._dec_prog._mfu_gauge = "gen.decode_mfu"
        if self.paged:
            dec_block = self._dec_prog.global_block()
            self._hd = int(dec_block.var(self.cache_vars[0]).shape[-1])
        # HBM census: the KV pool is its own collection — a paged
        # bundle's pool (plus its host page table) reports as
        # ``kv_pages``, the dense layout as ``kv_cache``; weakref'd so
        # a dropped predictor releases cleanly
        import weakref
        from paddle_tpu.obs import perf as _perf
        ref = weakref.ref(self)

        def _kv_buffers():
            p = ref()
            if p is None:
                return ()
            bufs = [v for v in (p._scope.find_var(n)
                                for n in p.cache_vars)
                    if v is not None and hasattr(v, "nbytes")]
            if p.paged:
                bufs.append(p._page_table)
            return bufs

        self._hbm_token = _perf.register_hbm_provider(
            "kv_pages" if self.paged else "kv_cache", _kv_buffers)
        # a reloaded predictor must not leave a dead provider behind
        weakref.finalize(self, _perf.unregister_hbm_provider,
                         self._hbm_token)
        # per-bucket constant prefill feeds (causal bias template)
        self._tri = {}
        # per-bucket static prefill FLOPs (analysis/cost): priced
        # lazily, consumed by GenScheduler's admission budget
        self._prefill_cost = {}
        self._length_cost_fn = None
        self._page_cost_fn = None

    # -- prefill -----------------------------------------------------------
    def _bucket(self, prompt_len):
        from paddle_tpu.lod import row_bucket
        b = row_bucket(prompt_len, edges=self.prompt_buckets)
        return min(b, self.max_len)

    def _cost_fn(self):
        """``flops(prompt_bucket)`` from the static cost model over the
        BUNDLE's actual prefill program (the ISSUE-15 wiring: admission
        weights and bucket planning price real programs, not guesses).
        Takes the predictor lock: the fit PROBES the prefill program by
        temporarily rewriting its feed var's length dim — that mutation
        must never interleave with another fit or a concurrent trace."""
        with self._lock:
            if self._length_cost_fn is None:
                from paddle_tpu.analysis import cost as _cost
                probe = (self.prompt_buckets[0],
                         max(self.prompt_buckets[-1],
                             self.prompt_buckets[0] + 1))
                self._length_cost_fn = _cost.row_cost_fn(
                    self._pre_prog, batch_var=self._pre_feeds[0],
                    dim=1, probe_rows=probe)
            return self._length_cost_fn

    def _page_write_cost(self, prompt_len):
        """Flop-equivalent of seeding a paged slot: every allocated
        prompt page is written whole (k + v, per layer) — the page
        dimension admission budgets must see on top of the prefill
        forward."""
        pages = -(-max(int(prompt_len), 1) // self.page_len)
        return (2.0 * float(self.meta.get("n_layer", 1)) *
                pages * self.page_len * self._hd)

    def prefill_cost(self, prompt_len):
        """Static FLOPs of prefilling a prompt of ``prompt_len`` tokens
        (priced at its padded bucket — what the device actually runs;
        paged bundles add the slot's page-seeding writes, so the memo
        key grows a page dimension).  The GenScheduler weighs
        admissions with this so one decode iteration never stalls
        behind an unbounded prefill burst.  Cheap after the first call
        per (bucket, pages); the underlying fit is warmed by
        GenScheduler construction."""
        prompt_len = int(prompt_len)
        bucket = self._bucket(prompt_len)
        if self.paged:
            pages = -(-max(prompt_len, 1) // self.page_len)
            key = (bucket, pages)
        else:
            key = bucket
        hit = self._prefill_cost.get(key)
        if hit is None:
            hit = float(self._cost_fn()(bucket))
            if self.paged:
                hit += self._page_write_cost(prompt_len)
            self._prefill_cost[key] = hit
        return hit

    def plan_prompt_buckets(self, observed_lengths, max_edges=4):
        """Cost-optimal prompt buckets for an OBSERVED length
        distribution: ``lod.select_bucket_edges`` weighted by the
        prefill program's static FLOPs-per-bucket (plus, for paged
        bundles, the candidate length's page-seeding writes).  Returns
        a sorted edge list (capped at the bundle's ``max_len``) an
        operator can bake into the next export's ``gen_meta.json``."""
        from paddle_tpu.lod import select_bucket_edges
        lengths = [min(max(int(n), 1), self.max_len)
                   for n in observed_lengths]
        cost_of = self._cost_fn()
        if self.paged:
            base = cost_of

            def cost_of(n):
                return float(base(n)) + self._page_write_cost(n)
        return select_bucket_edges(lengths, max_edges=max_edges,
                                   cost_of=cost_of)

    def plan_page_buckets(self, observed_lengths, max_edges=4):
        """Cost-optimal page-count bucket edges for an OBSERVED
        prefix-length distribution: ``lod.select_bucket_edges`` over
        live page counts, priced by the decode program's static cost as
        a function of the page-table width (``cost.row_cost_fn``
        probing the bucketed dim — the paged_attention cost rule makes
        that dimension carry the pages actually read).  Returns a
        sorted edge list an operator can bake into the next export's
        ``page_buckets``."""
        if not self.paged:
            raise ValueError("plan_page_buckets needs a paged bundle")
        from paddle_tpu.lod import select_bucket_edges
        counts = [min(max(-(-int(n) // self.page_len), 1),
                      self.pages_per_slot) for n in observed_lengths]
        with self._lock:
            if self._page_cost_fn is None:
                from paddle_tpu.analysis import cost as _cost
                self._page_cost_fn = _cost.row_cost_fn(
                    self._dec_prog, batch_var="gen_page_table", dim=1,
                    probe_rows=(1, max(self.pages_per_slot, 2)))
            fn = self._page_cost_fn
        return select_bucket_edges(counts, max_edges=max_edges,
                                   cost_of=fn)

    # -- page allocator (paged bundles; driven by the scheduler) -----------
    @property
    def free_pages(self):
        """Unallocated pool pages (paged bundles; 0 for dense)."""
        if not self.paged:
            return 0
        with self._lock:
            return len(self._free_list)

    def pages_needed(self, prompt_len, max_new_tokens=1):
        """Pages a request must hold to decode to its length horizon
        WITHOUT mid-request allocation (allocation happens once, at
        admission — growth can never fail mid-decode)."""
        horizon = min(self.max_len,
                      int(prompt_len) + max(int(max_new_tokens), 1))
        return -(-max(horizon, 1) // self.page_len)

    def alloc_slot_pages(self, slot, n):
        """Assign ``n`` pool pages to ``slot`` (prefix order).  Raises
        ``RuntimeError`` when the pool cannot cover it — callers check
        :attr:`free_pages` first (admission backpressure)."""
        n = max(1, min(int(n), self.pages_per_slot))
        with self._lock:
            if slot in self._slot_pages:
                raise ValueError(f"slot {slot} already holds pages")
            if len(self._free_list) < n:
                raise RuntimeError(
                    f"page pool exhausted: slot {slot} needs {n} "
                    f"page(s), {len(self._free_list)} free")
            pages = [self._free_list.pop(0) for _ in range(n)]
            self._slot_pages[slot] = pages
            self._page_table[slot, :] = 0
            self._page_table[slot, :n] = pages
            return list(pages)

    def free_all_pages(self):
        """Return EVERY slot's pages to the pool — the scheduler's
        crash-reset path, which discards all slots wholesale; returns
        the number of pages freed."""
        if not self.paged:
            return 0
        with self._lock:
            slots = list(self._slot_pages)
        return sum(self.free_slot_pages(s) for s in slots)

    def free_slot_pages(self, slot):
        """Return ``slot``'s pages to the free list (idempotent);
        returns the number freed.  The rows themselves are reclaimed
        lazily — re-allocation seeds pages via :meth:`write_slot`
        before any read addresses them."""
        if not self.paged:
            return 0
        with self._lock:
            pages = self._slot_pages.pop(slot, None)
            if not pages:
                return 0
            self._free_list.extend(pages)
            self._page_table[slot, :] = 0
            return len(pages)

    def _prefill_feed(self, prompt, bucket):
        from paddle_tpu.lod import pad_to_bucket
        p = len(prompt)
        ids = pad_to_bucket(
            np.asarray(prompt, np.int32).reshape(1, p), bucket, axis=1)
        pos = np.arange(bucket, dtype=np.int32).reshape(1, bucket)
        mask = pad_to_bucket(np.ones((1, p), np.float32), bucket, axis=1)
        tri = self._tri.get(bucket)
        if tri is None:
            tri = np.triu(np.full((bucket, bucket), -1e9, np.float32), 1)
            self._tri[bucket] = tri
        bias = tri[None, None] + (mask * 1e9 - 1e9)[:, None, None, :]
        last = np.zeros((1, bucket), np.float32)
        last[0, p - 1] = 1.0
        return {"gen_ids": ids, "gen_pos": pos, "gen_mask": mask,
                "gen_attn_bias": bias.astype(np.float32), "gen_last": last}

    def can_resume(self, total_len):
        """True when a resumed stream of ``total_len`` tokens (original
        prompt + every token already emitted) still fits a prefill
        bucket — the admissibility gate for deterministic re-prefill
        failover.  A stream that has decoded past ``max_prompt_len``
        cannot be re-prefilled on this bundle (the serving handler
        replies a non-retryable ``resume_unsupported`` rather than a
        confusing prompt-length 400)."""
        return 0 < int(total_len) <= self.max_prompt_len

    def prefill(self, prompt):
        """Run one prompt (list/array of token ids); returns
        ``(logits [V], kv)`` where ``kv`` is the per-layer masked K/V
        list ``[k_0, v_0, ...]`` each ``[1, bucket, H*D]`` (zeros on pad
        rows).  The prompt is padded to a declared bucket, so repeated
        lengths share one executable."""
        prompt = list(prompt)
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) > self.max_prompt_len:
            raise ValueError(
                f"prompt of {len(prompt)} tokens exceeds the bundle's "
                f"max prompt length {self.max_prompt_len}")
        feed = self._prefill_feed(prompt, self._bucket(len(prompt)))
        with self._lock:
            with self._fluid.scope_guard(self._scope):
                with _span("gen.prefill", tokens=len(prompt)):
                    outs = self._exe.run(self._pre_prog, feed=feed,
                                         fetch_list=self._pre_fetch)
        outs = [np.asarray(o) for o in outs]
        return outs[0][0], outs[1:]

    # -- cache-slot lifecycle (per request, host-side) ---------------------
    def write_slot(self, slot, kv, prompt_len):
        """Seed cache slot ``slot`` with a prefill's K/V rows (the rest
        of the row is zeroed — decode's add-writes land on zeros).

        A device-side slice update (``at[slot].set``): only the one
        seeded row crosses host->device, and the pool itself never
        round-trips — per-admission cost stays O(max_len), not
        O(num_slots * max_len).  Paged bundles write the slot's
        ALLOCATED pages instead (prompt rows + zero fill — re-used
        pages carry no stale rows), so the per-admission transfer is
        O(pages_needed * page_len)."""
        import jax.numpy as jnp
        with self._lock:
            if self.paged:
                pages = self._slot_pages.get(slot)
                if pages is None:
                    raise RuntimeError(
                        f"write_slot({slot}) before alloc_slot_pages")
                idx = np.asarray(pages, np.int64)
                cap = len(pages) * self.page_len
                for name, arr in zip(self.cache_vars, kv):
                    rows = min(arr.shape[1], self.max_len, cap)
                    buf = np.zeros(
                        (len(pages), self.page_len, arr.shape[2]),
                        arr.dtype)
                    buf.reshape(-1, arr.shape[2])[:rows] = arr[0, :rows]
                    cache = jnp.asarray(self._scope.find_var(name))
                    self._scope.set_var(name, cache.at[idx].set(buf))
                return
            for name, arr in zip(self.cache_vars, kv):
                rows = min(arr.shape[1], self.max_len)
                row = np.zeros((self.max_len, arr.shape[2]), arr.dtype)
                row[:rows] = arr[0, :rows]
                cache = jnp.asarray(self._scope.find_var(name))
                self._scope.set_var(name, cache.at[slot].set(row))

    def clear_slot(self, slot):
        """Zero a reclaimed slot's cache rows (device-side slice
        update).  Not strictly required — admission overwrites the
        whole row (or, paged, seeds every re-allocated page) — but
        keeps a freed slot from pinning stale request data."""
        import jax.numpy as jnp
        with self._lock:
            if self.paged:
                pages = self._slot_pages.get(slot)
                if not pages:
                    return
                idx = np.asarray(pages, np.int64)
                for name in self.cache_vars:
                    cache = jnp.asarray(self._scope.find_var(name))
                    self._scope.set_var(name, cache.at[idx].set(0.0))
                return
            for name in self.cache_vars:
                cache = jnp.asarray(self._scope.find_var(name))
                self._scope.set_var(name, cache.at[slot].set(0.0))

    # -- decode ------------------------------------------------------------
    def decode_step(self, tokens, positions, pos_onehot=None,
                    attn_mask=None, lens=None):
        """One decode iteration over the whole slot pool.

        ``tokens``/``positions``: int32 ``[S]`` (zeros for free slots).
        Dense bundles take ``pos_onehot``: f32 ``[S, L]`` write mask
        (all-zero rows for free slots — their cache is never touched)
        and ``attn_mask``: f32 ``[S, L]`` attendable-position mask.
        Paged bundles take ``lens``: int32 ``[S]`` prefix rows
        INCLUDING the current token (0 = free slot) — the page-table
        feed is sliced to the smallest declared page bucket covering
        ``max(lens)``, so the jit key is the bucket.  Returns logits
        ``[S, V]``.

        The ``gen.decode.stall`` failpoint fires INSIDE the lock: a
        ``delay`` action models per-iteration device time serialized per
        replica (the decode bench's cost model), an ``error`` a device
        fault in the decode step."""
        from paddle_tpu.fault import chaos
        S = self.num_slots
        feed = {
            "gen_token": np.asarray(tokens, np.int32).reshape(S, 1),
            "gen_pos": np.asarray(positions, np.int32).reshape(S, 1),
        }
        if self.paged:
            if lens is None:
                raise ValueError("paged decode_step needs lens")
            feed.update(self._paged_decode_feed(
                np.asarray(lens, np.int32).reshape(S, 1)))
        else:
            feed["gen_pos_onehot"] = np.asarray(pos_onehot, np.float32)
            feed["gen_attn_mask"] = np.asarray(attn_mask, np.float32)
        with self._lock:
            chaos.fire("gen.decode.stall", slots=S)
            with self._fluid.scope_guard(self._scope):
                with _span("gen.decode_step"):
                    (logits,) = self._exe.run(self._dec_prog, feed=feed,
                                              fetch_list=self._dec_fetch)
        return np.asarray(logits)

    def _paged_decode_feed(self, lens):
        """Page-table + lens feed for one paged step: slice the table
        to the smallest declared page bucket covering the longest live
        prefix (clamped to ``pages_per_slot`` — ``row_bucket`` past the
        declared ladder falls back to its power-of-two ladder, which
        must never widen the jit key beyond the pool)."""
        from paddle_tpu.lod import row_bucket
        from paddle_tpu.profiler import runtime_metrics
        live = lens[:, 0] > 0
        need = 1
        if live.any():
            need = int(-(-int(lens[live, 0].max()) // self.page_len))
        P = min(row_bucket(max(need, 1), edges=self.page_buckets),
                self.pages_per_slot)
        touched = int(np.sum(-(-lens[live, 0] // self.page_len)))
        runtime_metrics.observe("gen.paged.pages_touched",
                                float(touched))
        if touched:
            occupancy = (100.0 * float(lens[live, 0].sum()) /
                         (touched * self.page_len))
            runtime_metrics.bucket("gen.paged.page_occupancy",
                                   int(occupancy))
        with self._lock:
            table = np.ascontiguousarray(self._page_table[:, :P])
        return {"gen_page_table": table, "gen_lens": lens}

    # -- warmup ------------------------------------------------------------
    def warmup(self):
        """AOT-compile BOTH signature families — one prefill signature
        per declared prompt bucket plus the decode signature family
        (ONE signature for dense bundles; one per declared page bucket
        for paged bundles) — so the first real ``/generate`` pays zero
        compile time.  Returns a
        :class:`~paddle_tpu.obs.perf.WarmupReport` (int = fresh
        compiles; ``buckets`` carries one per-signature entry tagged
        ``program: prefill|decode`` with compile seconds and
        cold/persistent-hit/warm provenance — what ``/stats`` surfaces
        so a rolling restart's warm claim is checkable per bucket)."""
        sigs = []
        for b in self.prompt_buckets:
            if b > self.max_len:
                continue
            sigs.append({"gen_ids": (1, b), "gen_pos": (1, b),
                         "gen_mask": (1, b), "gen_attn_bias": (1, 1, b, b),
                         "gen_last": (1, b)})
        S, L = self.num_slots, self.max_len
        if self.paged:
            dec_sigs = [{"gen_token": (S, 1), "gen_pos": (S, 1),
                         "gen_page_table": (S, int(P)),
                         "gen_lens": (S, 1)}
                        for P in self.page_buckets
                        if P <= self.pages_per_slot]
        else:
            dec_sigs = [{"gen_token": (S, 1), "gen_pos": (S, 1),
                         "gen_pos_onehot": (S, L),
                         "gen_attn_mask": (S, L)}]
        from paddle_tpu.obs.perf import WarmupReport
        with self._lock:
            with self._fluid.scope_guard(self._scope):
                pre = self._exe.warmup(
                    self._pre_prog, sigs, fetch_list=self._pre_fetch,
                    scope=self._scope)
                # the decode step writes its (persistable) cache tensors
                # in place — declare exactly those as intended state
                # updates (a zero pos-onehot / zero lens feed writes
                # nothing, so warmup leaves the pool untouched)
                dec = self._exe.warmup(
                    self._dec_prog, dec_sigs,
                    fetch_list=self._dec_fetch, scope=self._scope,
                    allow_state_updates=self.cache_vars)
        return WarmupReport.merge(pre, dec, labels=("prefill", "decode"))
