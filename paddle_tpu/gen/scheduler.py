"""Iteration-level (continuous-batching) generation scheduler.

The vLLM/Orca scheduling idea composed from pieces the tree already
has: between decode steps the scheduler admits queued requests into free
KV-cache slots (prefill interleaved with decode), evicts finished
sequences (EOS / length cap / client disconnect), and streams each
request's tokens out as they are produced.  The
:class:`~paddle_tpu.serving.MicroBatcher` degradation contract is
reused at token granularity: a full admission queue raises
:class:`~paddle_tpu.serving.QueueFull` (503 load shedding), a request
whose ``X-Deadline-Ms`` budget expires while still queued for admission
fails with :class:`~paddle_tpu.serving.DeadlineExceeded` (504,
``gen.expired``) WITHOUT ever taking a slot, and an unexpected
scheduler-thread crash fails every live stream fast (retryable 503) and
restarts the thread within a bounded consecutive-crash budget.

``admission="batch"`` degrades the scheduler to PR 2's request-level
semantics — new requests are admitted only when the pool is EMPTY, so a
batch runs start-to-finish as a unit while later arrivals queue behind
it.  That mode exists as the benchmark baseline (``bench_decode.py``):
the measured gap between the two admission policies IS the
continuous-batching win.
"""

from __future__ import annotations

import logging
import threading
import time

import numpy as np

from paddle_tpu.obs import trace as _trace
from paddle_tpu.obs.slo import tick as _slo_tick
from paddle_tpu.obs.trace import span as _span

logger = logging.getLogger(__name__)

__all__ = ["GenScheduler", "GenStream", "SchedulerDraining",
           "StreamMigrated"]


class SchedulerDraining(RuntimeError):
    """The scheduler stopped admitting new sessions (rolling-restart
    drain): retryable by contract — a sibling replica will take the
    request."""


class StreamMigrated(RuntimeError):
    """A locally-iterated stream was checkpoint-migrated at a token
    boundary (drain-time hand-back); ``.checkpoint`` holds everything a
    survivor needs to continue token-identically."""

    def __init__(self, checkpoint):
        super().__init__("stream checkpoint-migrated at token boundary")
        self.checkpoint = checkpoint


class GenStream:
    """One request's token stream, produced by the scheduler thread and
    consumed by an HTTP handler (or any iterator)."""

    def __init__(self, prompt, max_new_tokens, eos_id, deadline_at,
                 trace_id=None):
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.deadline_at = deadline_at      # monotonic, None = unbounded
        self.trace_id = trace_id or _trace.current_trace_id()
        self.created_t = time.perf_counter()
        self.cancelled = False              # set by the consumer side
        self.finish_reason = None
        self.error = None
        self.tokens = []
        self._events = []
        self._cv = threading.Condition()

    # -- producer side (scheduler thread) ---------------------------------
    def _push(self, event):
        with self._cv:
            self._events.append(event)
            self._cv.notify_all()

    def emit(self, token):
        self.tokens.append(int(token))
        self._push(("token", int(token)))

    def finish(self, reason):
        self.finish_reason = reason
        self._push(("done", reason))

    def fail(self, exc):
        self.error = exc
        self._push(("error", exc))

    # -- consumer side -----------------------------------------------------
    def cancel(self):
        """Mark the consumer gone (client disconnect): the scheduler
        reclaims the slot and stops decoding for this stream on its next
        iteration."""
        self.cancelled = True

    def next_event(self, timeout=None):
        """Block for the next ``("token", id)`` / ``("done", reason)`` /
        ``("error", exc)`` event; returns None on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while not self._events:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._cv.wait(remaining if remaining is not None else 0.5)
            return self._events.pop(0)

    def __iter__(self):
        """Yield token ids until the stream finishes; raises the
        stream's error if it failed."""
        while True:
            kind, value = self.next_event()
            if kind == "token":
                yield value
            elif kind == "done":
                return
            elif kind == "migrate":
                raise StreamMigrated(value)
            else:
                raise value


class _Slot:
    __slots__ = ("stream", "pos", "steps", "last_token", "last_emit_t")

    def __init__(self, stream, prompt_len, first_token):
        self.stream = stream
        # the NEXT decode step consumes first_token and writes its K/V
        # at position prompt_len
        self.pos = prompt_len
        self.steps = 0
        self.last_token = first_token
        self.last_emit_t = time.perf_counter()


class GenScheduler:
    """Continuous-batching decode loop over a :class:`GenPredictor`."""

    def __init__(self, predictor, queue_size=64, admission="continuous",
                 max_restarts=5, slo_watchdog=None,
                 prefill_budget=None):
        if admission not in ("continuous", "batch"):
            raise ValueError(
                f"admission must be 'continuous' or 'batch', "
                f"got {admission!r}")
        # admission weighting (analysis/cost): cap the static prefill
        # FLOPs admitted between two decode iterations at
        # ``prefill_budget`` (None = unbounded, the pre-ISSUE-15
        # behavior).  Prefills interleave with decode on ONE device, so
        # an unbounded admission burst stalls every live stream's next
        # token; the budget bounds that stall by compute actually
        # admitted (weighted by GenPredictor.prefill_cost — the real
        # program's cost at the prompt's padded bucket, not a guess).
        # At least one request is always admitted per pass, so the
        # queue drains even when one prefill exceeds the budget.
        # CONTINUOUS admission only: batch mode refills the pool as one
        # unit by definition (the request-level baseline) — a budget
        # cut mid-refill would strand the unfilled slots for the whole
        # batch generation, not one decode iteration.
        self.prefill_budget = None if prefill_budget is None \
            or admission != "continuous" else float(prefill_budget)
        if self.prefill_budget is not None and \
                hasattr(predictor, "prefill_cost"):
            # warm the cost model's affine fit HERE (it walks the
            # prefill program twice) so no _admit pass pays it while
            # holding the scheduler lock
            predictor.prefill_cost(1)
        # SLO watchdog (obs.slo): evaluated from the scheduler loop so
        # TTFT/tokens-per-sec objectives are judged by the thread that
        # produces them.  Default arms from PADDLE_TPU_SLO; unarmed the
        # per-iteration cost is one None check (tick()).
        if slo_watchdog is None:
            from paddle_tpu.obs import slo as _slo
            slo_watchdog = _slo.watchdog_from_env()
        self.slo_watchdog = slo_watchdog
        self.predictor = predictor
        self.queue_size = max(1, int(queue_size))
        self.admission = admission
        self.max_restarts = max(0, int(max_restarts))
        self._queue = []
        self._slots = {}          # slot index -> _Slot
        self._free = list(range(predictor.num_slots))
        self._cv = threading.Condition()
        self._closed = False
        self._restarts = 0
        self._failed = None
        # drain-time migration (rolling restarts): _draining rejects
        # new admissions; _migrate_req asks the scheduler thread to
        # checkpoint every remaining stream at the next token boundary
        # (between decode iterations — the only place a stream is
        # guaranteed whole-token); _abort_exc is the in-process
        # hard-kill analog (fail everything retryable, no checkpoint)
        self._draining = False
        self._migrate_req = False
        self._migrate_done = None
        self._abort_exc = None
        # streams popped from _queue but not yet seated in _slots
        # (prefill in flight): drain()'s all-idle check must count
        # these or it can declare the scheduler empty mid-admission
        self._admitting = 0
        self.migrated = []        # checkpoints handed back by drain()
        self._thread = self._spawn_thread()

    # -- public surface ----------------------------------------------------
    @property
    def queue_depth(self):
        with self._cv:
            return len(self._queue)

    @property
    def active_slots(self):
        with self._cv:
            return len(self._slots)

    @property
    def failed(self):
        """Terminal crash once the consecutive-restart budget is spent
        (None while alive) — the /readyz pull-the-replica signal."""
        with self._cv:
            return self._failed

    def submit(self, prompt, max_new_tokens=16, deadline=None,
               eos_id=None, timeout=None):
        """Enqueue one generation request; returns a :class:`GenStream`.

        ``deadline``: seconds of end-to-end admission budget (the
        ``X-Deadline-Ms`` contract) — expiry while queued fails the
        stream with DeadlineExceeded without taking a slot.  ``eos_id``
        overrides the bundle's EOS token for this request."""
        from paddle_tpu import profiler as _profiler
        from paddle_tpu.serving import BatcherCrashed, QueueFull

        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if any(t < 0 or t >= self.predictor.vocab_size for t in prompt):
            raise ValueError("prompt token out of vocabulary range")
        if len(prompt) > self.predictor.max_prompt_len:
            raise ValueError(
                f"prompt of {len(prompt)} tokens exceeds the bundle's "
                f"max prompt length {self.predictor.max_prompt_len}")
        if int(max_new_tokens) < 1:
            raise ValueError("max_new_tokens must be >= 1")
        eos = self.predictor.eos_id if eos_id is None else int(eos_id)
        deadline_at = None
        if deadline is not None:
            deadline_at = time.monotonic() + float(deadline)
        elif timeout is not None:
            deadline_at = time.monotonic() + float(timeout)
        stream = GenStream(prompt, max_new_tokens, eos, deadline_at)
        with self._cv:
            if self._closed:
                raise RuntimeError("generation scheduler is shut down")
            if self._draining:
                raise SchedulerDraining(
                    "replica is draining: not admitting new sessions")
            if self._failed is not None:
                raise BatcherCrashed(
                    f"generation scheduler is down after "
                    f"{self._restarts} restarts: {self._failed}")
            if len(self._queue) >= self.queue_size:
                _profiler.runtime_metrics.inc("gen.queue_rejections")
                raise QueueFull(
                    f"generation queue full ({self.queue_size} pending)")
            self._queue.append(stream)
            self._cv.notify_all()
        return stream

    def close(self):
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=10)

    def drain(self, deadline_s=None):
        """Stop admitting new sessions, await the live ones to natural
        completion for up to ``deadline_s`` seconds (None = unbounded),
        then checkpoint-migrate whatever remains at the next token
        boundary.  Returns the list of checkpoints handed back (empty
        when every stream finished inside the deadline) — each one is
        ``{"prompt", "tokens", "remaining_tokens", "eos_id",
        "reason"}``, everything a survivor replica needs to continue
        the stream token-identically via deterministic re-prefill.

        A length-cap decode used to be able to hold a rolling restart
        open for minutes; with a deadline it costs at most
        ``deadline_s`` plus one decode iteration."""
        with self._cv:
            self._draining = True
            self._cv.notify_all()
        deadline_at = None if deadline_s is None \
            else time.monotonic() + float(deadline_s)
        while True:
            with self._cv:
                if (not self._queue and not self._slots and
                        not self._admitting) or \
                        self._closed or self._failed is not None:
                    return list(self.migrated)
            if deadline_at is not None and \
                    time.monotonic() >= deadline_at:
                break
            time.sleep(0.005)
        done = threading.Event()
        with self._cv:
            self._migrate_done = done
            self._migrate_req = True
            self._cv.notify_all()
        done.wait(timeout=30.0)
        return list(self.migrated)

    def abort_streams(self, exc=None):
        """In-process hard-kill support: ask the scheduler thread to
        fail every queued and active stream with a RETRYABLE error at
        the next token boundary — what a real ``kill -9`` looks like to
        a resume-capable client, minus the socket corpse.  Returns
        immediately (the kill is asynchronous, like a crash)."""
        if exc is None:
            from paddle_tpu.serving import BatcherCrashed
            exc = BatcherCrashed(
                "replica hard-killed mid-decode; stream aborted — "
                "resume on a survivor")
        with self._cv:
            self._abort_exc = exc
            self._cv.notify_all()

    # -- scheduler thread --------------------------------------------------
    def _spawn_thread(self):
        t = threading.Thread(target=self._run, daemon=True,
                             name="paddle-tpu-gen-scheduler")
        t.start()
        return t

    def _run(self):
        try:
            self._loop()
        except BaseException as e:
            self._crash(e)

    def _crash(self, exc):
        from paddle_tpu import profiler as _profiler
        from paddle_tpu.serving import BatcherCrashed
        logger.exception("generation scheduler thread crashed")
        with self._cv:
            queued, self._queue = self._queue, []
            active, self._slots = list(self._slots.values()), {}
            self._free = list(range(self.predictor.num_slots))
            restart = not self._closed and \
                self._restarts < self.max_restarts
            if restart:
                self._restarts += 1
            elif not self._closed:
                self._failed = exc
        # the wholesale slot reset above must also reset the page
        # pool, or a crash strands every live allocation and the
        # restarted loop livelocks on page-aware admission
        if getattr(self.predictor, "paged", False):
            self.predictor.free_all_pages()
        if restart:
            _profiler.runtime_metrics.inc("gen.scheduler_restarts")
            self._thread = self._spawn_thread()
        err = BatcherCrashed(
            f"generation scheduler crashed ({type(exc).__name__}: {exc});"
            f" request aborted — retry")
        err.__cause__ = exc
        for slot in active:
            slot.stream.fail(err)
        for stream in queued:
            stream.fail(err)

    def _loop(self):
        from paddle_tpu import profiler as _profiler
        while True:
            with self._cv:
                while not self._queue and not self._slots and \
                        not self._closed and self._abort_exc is None \
                        and not self._migrate_req:
                    self._cv.wait(0.05)
                if self._closed:
                    queued, self._queue = self._queue, []
                    active, self._slots = list(self._slots.items()), {}
                    break
            # kill/migrate run HERE — between decode iterations, the
            # only point every live stream is at a whole-token boundary
            if self._abort_exc is not None:
                self._do_abort()
            if self._migrate_req:
                self._do_migrate()
            self._sweep_queue()
            self._admit()
            if self._slots:
                self._decode_iteration()
                # a completed iteration is forward progress: the restart
                # budget bounds CONSECUTIVE crashes, not lifetime ones
                with self._cv:
                    self._restarts = 0
            _profiler.runtime_metrics.set_gauge("gen.slots_active",
                                                len(self._slots))
            _slo_tick(self.slo_watchdog)
        # shutdown discards the slots wholesale; return their pages so
        # a later scheduler over the SAME predictor starts with a full
        # pool (the test suite reuses warmed predictors this way)
        if getattr(self.predictor, "paged", False):
            self.predictor.free_all_pages()
        err = RuntimeError("generation scheduler shut down")
        for _, slot in active:
            slot.stream.fail(err)
        for stream in queued:
            stream.fail(err)

    def _do_abort(self):
        """Scheduler-thread half of :meth:`abort_streams`: wholesale
        reset (slots, free list, page pool), every stream failed with
        the retryable kill error."""
        with self._cv:
            exc, self._abort_exc = self._abort_exc, None
            queued, self._queue = self._queue, []
            active, self._slots = list(self._slots.values()), {}
            self._free = list(range(self.predictor.num_slots))
        if getattr(self.predictor, "paged", False):
            self.predictor.free_all_pages()
        for slot in active:
            slot.stream.fail(exc)
        for stream in queued:
            stream.fail(exc)

    def _do_migrate(self):
        """Scheduler-thread half of :meth:`drain`'s expiry path:
        checkpoint every remaining stream at its current token boundary
        and hand it back as a ``("migrate", checkpoint)`` event, then
        release the slot/pages.  Queued (never-admitted) streams
        migrate with zero emitted tokens."""
        with self._cv:
            queued, self._queue = self._queue, []
            active = sorted(self._slots.items())
        for idx, slot in active:
            if not slot.stream.cancelled:
                self._checkpoint_out(slot.stream)
            else:
                slot.stream.finish("disconnect")
            if getattr(self.predictor, "paged", False):
                self.predictor.free_slot_pages(idx)
            with self._cv:
                self._slots.pop(idx, None)
                self._free.append(idx)
        for stream in queued:
            if not stream.cancelled:
                self._checkpoint_out(stream)
            else:
                stream.finish("disconnect")
        with self._cv:
            self._migrate_req = False
            done, self._migrate_done = self._migrate_done, None
        if done is not None:
            done.set()

    def _checkpoint_out(self, stream):
        from paddle_tpu import profiler as _profiler
        ckpt = {"prompt": list(stream.prompt),
                "tokens": list(stream.tokens),
                "remaining_tokens": max(
                    0, stream.max_new_tokens - len(stream.tokens)),
                "eos_id": stream.eos_id,
                "reason": "draining"}
        self.migrated.append(ckpt)
        _profiler.runtime_metrics.inc("gen.session.migrations")
        stream.finish_reason = "migrated"
        stream._push(("migrate", ckpt))

    def _sweep_queue(self):
        """Fail expired/abandoned QUEUED requests immediately — an
        expired deadline gets its 504 now, not when a slot frees up."""
        from paddle_tpu import profiler as _profiler
        from paddle_tpu.serving import DeadlineExceeded
        now = time.monotonic()
        with self._cv:
            keep = []
            for stream in self._queue:
                if stream.cancelled:
                    stream.finish("disconnect")
                    continue
                if stream.deadline_at is not None and \
                        now > stream.deadline_at:
                    _profiler.runtime_metrics.inc("gen.expired")
                    stream.fail(DeadlineExceeded(
                        "deadline expired while queued for admission"))
                    continue
                keep.append(stream)
            self._queue = keep

    def _admit(self):
        """Move queued requests into free slots (continuous mode), or —
        batch mode — refill the pool only once it is completely empty,
        and then fill it WHOLE (the refill decision is made once per
        call, so one batch admission loads every free slot rather than
        degrading to serial batch-of-1)."""
        from paddle_tpu import profiler as _profiler
        refill = None
        spent = 0.0
        admitted_n = 0
        while True:
            with self._cv:
                if not self._queue or not self._free:
                    return
                if self.admission == "batch":
                    if refill is None:
                        refill = not self._slots
                    if not refill:
                        return
                if getattr(self.predictor, "paged", False):
                    # page-aware admission: a request is only admitted
                    # when the pool can cover its WHOLE length horizon
                    # (allocation happens once, at admission), so decode
                    # growth never fails mid-request; otherwise the
                    # head-of-line request waits for an eviction to
                    # return pages — backpressure, like the FLOPs
                    # budget below, not an error
                    head = self._queue[0]
                    need = self.predictor.pages_needed(
                        len(head.prompt), head.max_new_tokens)
                    if need > self.predictor.free_pages:
                        return
                if self.prefill_budget is not None and admitted_n:
                    # cost-weighted admission: stop once this pass has
                    # admitted its budget of static prefill FLOPs (the
                    # first admission is always free so the queue
                    # drains); the rest of the queue waits one decode
                    # iteration instead of stalling every live stream
                    cost = self.predictor.prefill_cost(
                        len(self._queue[0].prompt))
                    if spent + cost > self.prefill_budget:
                        return
                stream = self._queue.pop(0)
                slot_idx = self._free.pop(0)
                self._admitting += 1
            if self.prefill_budget is not None:
                cost = self.predictor.prefill_cost(len(stream.prompt))
                spent += cost
                _profiler.runtime_metrics.observe("gen.admission_cost",
                                                  cost)
            admitted_n += 1
            admitted = False
            try:
                admitted = self._prefill_into(slot_idx, stream)
            finally:
                with self._cv:
                    self._admitting -= 1
                    if not admitted:
                        self._free.append(slot_idx)

    def _prefill_into(self, slot_idx, stream):
        """Prefill one request and seed its slot; returns True when the
        slot stays occupied (request still generating)."""
        from paddle_tpu import profiler as _profiler
        t0 = time.perf_counter()
        with _trace.trace_context(stream.trace_id):
            try:
                logits, kv = self.predictor.prefill(stream.prompt)
            except BaseException as e:
                stream.fail(e)
                return False
        # counted only when prefill actually ran for an admitted
        # request — a failed prefill above never takes the slot
        _profiler.runtime_metrics.inc("gen.admissions")
        _profiler.runtime_metrics.observe("gen.prefill_seconds",
                                          time.perf_counter() - t0)
        first = int(np.argmax(logits))
        now = time.perf_counter()
        _profiler.runtime_metrics.observe("gen.ttft_seconds",
                                          now - stream.created_t)
        _profiler.runtime_metrics.inc("gen.tokens")
        stream.emit(first)
        prompt_len = len(stream.prompt)
        if first == stream.eos_id:
            return self._finish(stream, "eos")
        if stream.max_new_tokens <= 1 or prompt_len >= self.predictor.max_len:
            return self._finish(stream, "length")
        if getattr(self.predictor, "paged", False):
            try:
                self.predictor.alloc_slot_pages(
                    slot_idx, self.predictor.pages_needed(
                        prompt_len, stream.max_new_tokens))
            except BaseException as e:
                stream.fail(e)
                return False
            try:
                self.predictor.write_slot(slot_idx, kv, prompt_len)
            except BaseException:
                self.predictor.free_slot_pages(slot_idx)
                raise
        else:
            self.predictor.write_slot(slot_idx, kv, prompt_len)
        with self._cv:
            self._slots[slot_idx] = _Slot(stream, prompt_len, first)
        return True

    def _finish(self, stream, reason):
        from paddle_tpu import profiler as _profiler
        stream.finish(reason)
        _profiler.runtime_metrics.inc("gen.requests_ok")
        return False

    def _evict(self, slot_idx, reason=None):
        # Eviction runs only on the scheduler thread, so the slot
        # cannot be re-admitted while this is in flight.  The slot is
        # removed from `_slots`/returned to `_free` LAST: once
        # `active_slots` reads 0, the slot's pages are already back in
        # the pool — observers (and page-aware admission) never see a
        # half-evicted slot.
        from paddle_tpu import profiler as _profiler
        with self._cv:
            slot = self._slots.get(slot_idx)
            if slot is None:
                return
        if reason == "disconnect":
            _profiler.runtime_metrics.inc("gen.disconnects")
            self.predictor.clear_slot(slot_idx)
            # terminal event even though the usual consumer is gone: a
            # LOCAL consumer that cancelled must not block forever on a
            # stream nobody will ever finish
            slot.stream.finish("disconnect")
        # paged bundles: EVERY eviction (eos / length / disconnect)
        # returns the slot's pages to the pool — the admission
        # backpressure above turns a leak here into a livelock; for
        # disconnects this runs AFTER clear_slot, which addresses
        # pages through the still-live allocation
        if getattr(self.predictor, "paged", False):
            self.predictor.free_slot_pages(slot_idx)
        with self._cv:
            self._slots.pop(slot_idx, None)
            self._free.append(slot_idx)
        _profiler.runtime_metrics.inc("gen.evictions")

    def _decode_iteration(self):
        """One token for every live slot: sweep disconnects, build the
        (constant-signature) step feeds, dispatch, scatter tokens."""
        from paddle_tpu import profiler as _profiler
        # reclaim disconnected streams BEFORE paying a step for them
        with self._cv:
            live = list(self._slots.items())
        for idx, slot in live:
            if slot.stream.cancelled:
                self._evict(idx, reason="disconnect")
        with self._cv:
            live = sorted(self._slots.items())
        if not live:
            return
        S, L = self.predictor.num_slots, self.predictor.max_len
        tokens = np.zeros(S, np.int32)
        positions = np.zeros(S, np.int32)
        paged = getattr(self.predictor, "paged", False)
        if paged:
            lens = np.zeros(S, np.int32)
        else:
            pos_onehot = np.zeros((S, L), np.float32)
            attn_mask = np.zeros((S, L), np.float32)
        for idx, slot in live:
            tokens[idx] = slot.last_token
            positions[idx] = slot.pos
            if paged:
                lens[idx] = slot.pos + 1
            else:
                pos_onehot[idx, slot.pos] = 1.0
                attn_mask[idx, :slot.pos + 1] = 1.0
        _profiler.runtime_metrics.bucket("gen.slot_occupancy", len(live))
        t0 = time.perf_counter()
        if paged:
            logits = self.predictor.decode_step(tokens, positions,
                                                lens=lens)
        else:
            logits = self.predictor.decode_step(tokens, positions,
                                                pos_onehot, attn_mask)
        now = time.perf_counter()
        _profiler.runtime_metrics.observe("gen.decode_step_seconds",
                                          now - t0)
        for idx, slot in live:
            stream = slot.stream
            token = int(np.argmax(logits[idx]))
            slot.steps += 1
            slot.pos += 1
            slot.last_token = token
            _profiler.runtime_metrics.inc("gen.tokens")
            _profiler.runtime_metrics.observe("gen.intertoken_seconds",
                                              now - slot.last_emit_t)
            slot.last_emit_t = now
            stream.emit(token)
            done = 1 + slot.steps
            if token == stream.eos_id:
                self._finish(stream, "eos")
                self._evict(idx)
            elif done >= stream.max_new_tokens or slot.pos >= L:
                self._finish(stream, "length")
                self._evict(idx)
