"""Legacy ``*_layer`` DSL names over the v2 shim (reference
``trainer_config_helpers/layers.py``; each legacy function name keeps its
signature shape, the body emits Program IR through ``paddle_tpu.v2``)."""

from __future__ import annotations

from paddle_tpu.v2 import layer as _v2

__all__ = [
    "data_layer", "fc_layer", "embedding_layer", "img_conv_layer",
    "img_pool_layer", "batch_norm_layer", "dropout_layer", "concat_layer",
    "lstmemory", "grumemory", "pooling_layer", "last_seq", "first_seq",
    "classification_cost", "cross_entropy", "square_error_cost",
    "regression_cost", "mse_cost", "LayerOutput",
]

# In the reference every DSL call returns a LayerOutput handle; here the
# IR Variable plays that role directly.
LayerOutput = object


def data_layer(name, size, height=None, width=None, type=None):
    from paddle_tpu.v2 import data_type as dt
    input_type = type if type is not None else dt.dense_vector(size)
    return _v2.data(name=name, type=input_type, height=height, width=width)


def fc_layer(input, size, act=None, param_attr=None, bias_attr=None,
             name=None, layer_attr=None):
    return _v2.fc(input=input, size=size, act=act, param_attr=param_attr,
                  bias_attr=bias_attr, name=name)


def embedding_layer(input, size, param_attr=None):
    return _v2.embedding(input=input, size=size, param_attr=param_attr)


def img_conv_layer(input, filter_size, num_filters, num_channel=None,
                   act=None, padding=0, stride=1, bias_attr=None,
                   param_attr=None, name=None, **kwargs):
    return _v2.img_conv(input=input, filter_size=filter_size,
                        num_filters=num_filters, num_channel=num_channel,
                        act=act, padding=padding, stride=stride,
                        bias_attr=bias_attr, param_attr=param_attr)


def img_pool_layer(input, pool_size, name=None, num_channels=None,
                   pool_type=None, stride=None, padding=0, **kwargs):
    return _v2.img_pool(input=input, pool_size=pool_size,
                        pool_type=pool_type, stride=stride, padding=padding)


def batch_norm_layer(input, act=None, name=None, **kwargs):
    return _v2.batch_norm(input=input, act=act, **kwargs)


def dropout_layer(input, dropout_rate, name=None):
    return _v2.dropout(input=input, dropout_rate=dropout_rate)


def concat_layer(input, act=None, name=None):
    out = _v2.concat(input=input, name=name)
    act_name = _v2._act_name(act)
    if act_name and act_name not in ("linear", "identity"):
        from paddle_tpu import layers as F
        out = getattr(F, act_name)(out)
    return out


def lstmemory(input, size=None, reverse=False, act=None, name=None,
              **kwargs):
    return _v2.lstmemory(input=input, size=size, reverse=reverse, act=act,
                         **kwargs)


def grumemory(input, size=None, reverse=False, act=None, name=None,
              **kwargs):
    return _v2.gru(input=input, size=size, reverse=reverse, act=act,
                   **kwargs)


def pooling_layer(input, pooling_type=None, name=None, **kwargs):
    return _v2.pooling(input=input, pooling_type=pooling_type, name=name)


def last_seq(input, name=None, **kwargs):
    return _v2.last_seq(input=input, name=name)


def first_seq(input, name=None, **kwargs):
    return _v2.first_seq(input=input, name=name)


def classification_cost(input, label, name=None, **kwargs):
    return _v2.classification_cost(input=input, label=label, name=name)


def cross_entropy(input, label, name=None, **kwargs):
    return _v2.cross_entropy_cost(input=input, label=label, name=name)


def square_error_cost(input, label, name=None, **kwargs):
    return _v2.square_error_cost(input=input, label=label, name=name)


regression_cost = square_error_cost
mse_cost = square_error_cost
