"""Legacy ``*_layer`` DSL (reference ``trainer_config_helpers/layers.py``,
7,610 LoC over ``paddle/gserver/layers/`` ~110 layer types).

Each legacy function keeps its reference signature shape; the body emits
Program IR through the fluid layer set (``paddle_tpu.layers``) — the path
the reference takes through config_parser + gserver C++ Layer subclasses
is replaced by IR ops lowered to XLA.  Projections/operators are deferred
graph fragments summed by ``mixed_layer`` (reference MixedLayer.cpp);
``recurrent_group`` maps onto ``DynamicRNN`` (one masked
``lax.while_loop``); the generation-side ``beam_search`` unrolls under a
deterministic name scope so timesteps share weights.
"""

from __future__ import annotations

import numpy as np

import paddle_tpu.layers as F
from paddle_tpu.framework import unique_name_scope
from paddle_tpu.param_attr import ParamAttr as _ParamAttr
from paddle_tpu.v2 import layer as _v2
from paddle_tpu.v2.layer import _act_name

__all__ = [
    # projections / operators / mixed
    "full_matrix_projection", "trans_full_matrix_projection",
    "table_projection", "identity_projection", "slice_projection",
    "scaling_projection", "dotmul_projection", "context_projection",
    "conv_projection", "dotmul_operator", "conv_operator", "mixed_layer",
    # io / basic
    "data_layer", "fc_layer", "embedding_layer", "img_conv_layer",
    "img_conv3d_layer", "img_pool_layer", "img_pool3d_layer",
    "batch_norm_layer", "dropout_layer", "concat_layer", "seq_concat_layer",
    "printer_layer",
    # recurrent
    "lstmemory", "grumemory", "memory", "recurrent_group",
    "recurrent_layer", "lstm_step_layer", "gru_step_layer",
    "gru_step_naive_layer", "get_output_layer", "StaticInput",
    "SubsequenceInput", "GeneratedInput", "beam_search",
    # sequence
    "pooling_layer", "last_seq", "first_seq", "expand_layer",
    "repeat_layer", "seq_reshape_layer", "seq_slice_layer",
    "sub_seq_layer", "sub_nested_seq_layer", "kmax_seq_score_layer",
    "cross_entropy_over_beam", "BeamInput",
    "ctc_layer", "warp_ctc_layer",
    # elementwise / math
    "addto_layer", "interpolation_layer", "bilinear_interp_layer",
    "power_layer", "scaling_layer", "slope_intercept_layer", "trans_layer",
    "rotate_layer", "cos_sim", "l2_distance_layer", "dot_prod_layer",
    "out_prod_layer", "linear_comb_layer", "tensor_layer",
    "selective_fc_layer", "sampling_id_layer", "maxid_layer", "eos_layer",
    "pad_layer", "conv_shift_layer", "block_expand_layer", "maxout_layer",
    "multiplex_layer", "prelu_layer", "gated_unit_layer",
    "switch_order_layer", "crop_layer", "clip_layer", "resize_layer",
    "row_conv_layer", "scale_sub_region_layer",
    "scale_shift_layer", "factorization_machine", "upsample_layer",
    # norm
    "sum_to_one_norm_layer", "row_l2_norm_layer", "img_cmrnorm_layer",
    "cross_channel_norm_layer", "spp_layer",
    # costs
    "classification_cost", "cross_entropy", "square_error_cost",
    "regression_cost", "mse_cost", "sum_cost", "cross_entropy_with_selfnorm",
    "multi_binary_label_cross_entropy", "smooth_l1_cost",
    "huber_regression_cost", "huber_classification_cost", "rank_cost",
    "lambda_cost", "crf_layer", "crf_decoding_layer", "nce_layer",
    "hsigmoid",
    # detection / vision
    "priorbox_layer", "detection_output_layer", "roi_pool_layer",
    "multibox_loss_layer",
    "LayerOutput",
]

# In the reference every DSL call returns a LayerOutput handle; here the
# IR Variable plays that role directly.
LayerOutput = object


def _to_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _apply_act(out, act):
    name = _act_name(act)
    if name and name not in ("linear", "identity"):
        out = getattr(F, name)(out)
    return out


def _constant(values, dtype):
    """Trace-time constant tensor (host numpy -> device)."""
    return F.assign(np.asarray(values, dtype))


# ---------------------------------------------------------------------------
# projections & operators (reference Projection.h / Operator.h; deferred
# fragments summed by mixed_layer / MixedLayer.cpp)
# ---------------------------------------------------------------------------

class BaseProjection:
    """Deferred fragment: ``build(size)`` emits IR and returns the
    [N, size] output Variable."""

    def __init__(self, build_fn):
        self._build_fn = build_fn

    def build(self, size):
        return self._build_fn(size)


def full_matrix_projection(input, size=0, param_attr=None):
    """x @ W (reference ``layers.py:430`` over FullMatrixProjection.cpp)."""
    return BaseProjection(lambda sz: F.fc(
        input=input, size=sz or size, bias_attr=False,
        param_attr=param_attr))


def trans_full_matrix_projection(input, size=0, param_attr=None):
    """x @ W^T (reference ``layers.py:470``): the parameter is stored
    [size, in_dim] and used transposed — weight sharing with a forward
    projection of the same name."""
    def build(sz):
        sz = sz or size
        in_dim = input.shape[-1]
        w = F.create_parameter(shape=[sz, in_dim], dtype=input.dtype,
                               attr=param_attr)
        return F.matmul(input, w, transpose_y=True)
    return BaseProjection(build)


def table_projection(input, size=0, param_attr=None):
    """Embedding-table row lookup (reference ``layers.py:506``)."""
    def build(sz):
        return _v2.embedding(input=input, size=sz or size,
                             param_attr=param_attr)
    return BaseProjection(build)


def identity_projection(input, offset=None, size=None):
    """Identity, or a column slice [offset, offset+size) (reference
    ``layers.py:550``)."""
    def build(sz):
        if offset is None:
            return input
        width = size if size is not None else (sz or None)
        if width is None:
            raise ValueError("identity_projection with offset needs size")
        return F.slice(input, axes=[1], starts=[offset],
                       ends=[offset + width])
    return BaseProjection(build)


def slice_projection(input, slices):
    """Concat of column slices [(s, e), ...] (reference ``layers.py:604``)."""
    def build(sz):
        parts = [F.slice(input, axes=[1], starts=[s], ends=[e])
                 for s, e in slices]
        return parts[0] if len(parts) == 1 else F.concat(parts, axis=1)
    return BaseProjection(build)


def scaling_projection(input, param_attr=None):
    """w * x with a single learned scalar (reference ``layers.py:642``)."""
    def build(sz):
        w = F.create_parameter(shape=[1], dtype=input.dtype,
                               attr=param_attr)
        return F.elementwise_mul(input, w)
    return BaseProjection(build)


def dotmul_projection(input, param_attr=None):
    """x .* w with a per-dimension learned vector (reference
    ``layers.py:668`` over DotMulProjection.cpp)."""
    def build(sz):
        w = F.create_parameter(shape=[input.shape[-1]], dtype=input.dtype,
                               attr=param_attr)
        return F.elementwise_mul(input, w, axis=1)
    return BaseProjection(build)


def context_projection(input, context_len, context_start=None,
                       padding_attr=False):
    """Sliding-window concatenation within each sequence (reference
    ``layers.py:738`` over operators/math/context_project.h); zero padding
    at boundaries (trainable padding unsupported)."""
    def build(sz):
        from paddle_tpu.layer_helper import LayerHelper
        helper = LayerHelper("sequence_context")
        out = helper.create_tmp_variable(dtype=input.dtype)
        start = context_start if context_start is not None \
            else -(context_len // 2)
        helper.append_op(type="sequence_context", inputs={"X": [input]},
                         outputs={"Out": [out]},
                         attrs={"contextLength": context_len,
                                "contextStart": start})
        return out
    return BaseProjection(build)


def conv_projection(input, filter_size, num_filters, num_channels=None,
                    stride=1, padding=0, groups=1, param_attr=None,
                    trans=False):
    """Convolution as a mixed-layer fragment (reference ``layers.py:4838``);
    output is the flattened feature map."""
    def build(sz):
        if trans:
            conv = F.conv2d_transpose(input=input, num_filters=num_filters,
                                      filter_size=filter_size,
                                      stride=stride, padding=padding,
                                      param_attr=param_attr,
                                      bias_attr=False)
        else:
            conv = F.conv2d(input=input, num_filters=num_filters,
                            filter_size=filter_size, stride=stride,
                            padding=padding, groups=groups,
                            param_attr=param_attr, bias_attr=False)
        n, c, h, w = conv.shape
        return F.reshape(conv, shape=[-1, c * h * w])
    return BaseProjection(build)


def dotmul_operator(a=None, b=None, scale=1, **kwargs):
    """a .* b * scale (reference ``layers.py:697``; operators carry no
    parameters)."""
    x = a if a is not None else kwargs.get("x")
    y = b if b is not None else kwargs.get("y")

    def build(sz):
        out = F.elementwise_mul(x, y)
        if scale != 1:
            out = F.scale(out, scale=float(scale))
        return out
    return BaseProjection(build)


def conv_operator(img, filter, filter_size, num_filters, num_channels=None,
                  stride=1, padding=0, filter_size_y=None, stride_y=None,
                  padding_y=None):
    """Convolution whose filter comes from the graph (reference
    ``layers.py:4749`` ConvOperator): ``filter`` is reshaped to
    [num_filters, C, kh, kw] and correlated with ``img``; flattened
    output."""
    def build(sz):
        fs_y = filter_size_y or filter_size
        st_y = stride_y or stride
        pd_y = padding_y if padding_y is not None else padding
        nc = num_channels or img.shape[1]
        fmap = F.reshape(filter, shape=[num_filters, nc, fs_y, filter_size])
        from paddle_tpu.layer_helper import LayerHelper
        helper = LayerHelper("conv2d")
        out = helper.create_tmp_variable(dtype=img.dtype)
        helper.append_op(
            type="conv2d", inputs={"Input": [img], "Filter": [fmap]},
            outputs={"Output": [out]},
            attrs={"strides": [st_y, stride], "paddings": [pd_y, padding],
                   "dilations": [1, 1], "groups": 1})
        n, c, h, w = out.shape
        return F.reshape(out, shape=[-1, c * h * w])
    return BaseProjection(build)


class _MixedLayerWith:
    """``with mixed_layer(size=...) as m: m += proj`` support; after the
    block, ``m.output`` (also ``m()``) is the summed Variable."""

    def __init__(self, size, act, bias_attr, name):
        self.size = size
        self.act = act
        self.bias_attr = bias_attr
        self.name = name
        self.projections = []
        self.output = None

    def __iadd__(self, proj):
        self.projections.append(proj)
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.output = mixed_layer(size=self.size,
                                      input=self.projections, act=self.act,
                                      bias_attr=self.bias_attr,
                                      name=self.name)
        return False

    def __call__(self):
        return self.output


def mixed_layer(size=0, input=None, name=None, act=None, bias_attr=False,
                layer_attr=None):
    """Sum of projection/operator fragments (+ bias, activation)
    (reference ``layers.py:869`` over MixedLayer.cpp)."""
    if input is None:
        return _MixedLayerWith(size, act, bias_attr, name)
    parts = []
    for p in _to_list(input):
        parts.append(p.build(size) if isinstance(p, BaseProjection) else p)
    out = parts[0] if len(parts) == 1 else F.sums(parts)
    if bias_attr is not False and bias_attr is not None:
        b = F.create_parameter(shape=[size or out.shape[-1]],
                               dtype=out.dtype, is_bias=True,
                               attr=None if bias_attr is True else bias_attr)
        out = F.elementwise_add(out, b, axis=1)
    out = _apply_act(out, act)
    return _named(out, name)


# ---------------------------------------------------------------------------
# recurrent machinery: memory / recurrent_group / step layers
# (reference ``layers.py:3669`` memory, ``:4161`` recurrent_group over
# RecurrentGradientMachine.cpp — here one DynamicRNN while_loop)
# ---------------------------------------------------------------------------

class _RecurrentCtx:
    """Active recurrent_group (or generation loop) bookkeeping: memories
    pending name-binding and layers registered under a DSL ``name``."""

    def __init__(self, kind, drnn=None):
        self.kind = kind          # "group" | "gen"
        self.drnn = drnn
        self.pending = {}         # memory name -> pre-state Variable
        self.named = {}           # DSL name -> produced Variable
        self.boots = {}           # memory name -> boot spec (gen loops)


_ACTIVE = []


def _named(out, name):
    """Register ``out`` under the DSL ``name`` inside an active recurrent
    context (the reference binds memories to same-named layers)."""
    if name and _ACTIVE:
        _ACTIVE[-1].named[name] = out
    return out


class StaticInput:
    """Non-sequence (or whole-sequence) input visible at every step
    (reference ``layers.py`` StaticInput)."""

    def __init__(self, input, is_seq=False, size=None):
        self.input = input
        self.is_seq = is_seq
        self.size = size


def SubsequenceInput(input):
    """Nested-sequence step input (reference ``layers.py:4146``); the
    TPU DynamicRNN consumes the outer level."""
    return input


class GeneratedInput:
    """Generation-loop input spec (reference ``layers.py`` GeneratedInput):
    the previous step's beam token, embedded."""

    def __init__(self, size, embedding_name, embedding_size):
        self.size = size
        self.embedding_name = embedding_name
        self.embedding_size = embedding_size


class _NeedBoot(Exception):
    def __init__(self, name):
        self.name = name
        super().__init__(name)


def memory(name=None, size=None, boot_layer=None, is_seq=False,
           boot_with_const_id=None, boot_bias=None, value=0.0):
    """Previous-step state inside recurrent_group (reference
    ``layers.py:3669``).  Bind by creating a layer with the same ``name``
    in the step (fc_layer/mixed_layer/gru_step_layer/... all register
    their ``name``)."""
    if not _ACTIVE:
        raise ValueError("memory() must be called inside recurrent_group "
                         "or beam_search")
    ctx = _ACTIVE[-1]
    if ctx.kind == "gen":
        # generation loop: current value if materialized, else signal the
        # driver to create boots and re-run the step
        if name in ctx.named:
            return ctx.named[name]
        ctx.boots[name] = {"size": size, "boot_layer": boot_layer,
                           "value": value}
        raise _NeedBoot(name)
    mem = ctx.drnn.memory(init=boot_layer) if boot_layer is not None \
        else ctx.drnn.memory(shape=[size], value=value)
    if name:
        ctx.pending[name] = mem
    return mem


def recurrent_group(step, input, reverse=False, name=None,
                    targetInlink=None):
    """Run ``step`` over each timestep of the input sequence(s)
    (reference ``layers.py:4161`` over RecurrentGradientMachine.cpp).

    TPU mapping: ONE masked ``lax.while_loop`` via DynamicRNN — ragged
    sequences ride the LoD rank table, memories are loop carries.
    ``reverse=True`` reverses the sequences in and the outputs back out
    (sequence_reverse op), matching the reference's backward-time group.
    Memories bind by name: create the new state with the same DSL
    ``name=`` the memory was declared with.  If ``step`` returns a dict,
    outputs keep their keys and ``get_output_layer`` selects by key.
    """
    inputs = _to_list(input)
    seq_inputs = [i for i in inputs if not isinstance(i, StaticInput)]
    if not seq_inputs:
        raise ValueError("recurrent_group needs at least one sequence "
                         "input")
    if reverse:
        seq_inputs = [F.sequence_reverse(x) for x in seq_inputs]

    drnn = F.DynamicRNN()
    ctx = _RecurrentCtx("group", drnn)
    _ACTIVE.append(ctx)
    names = None
    try:
        with drnn.block():
            # step_input first: it builds the lod rank table that
            # static_input reorders by
            seq_it = iter(seq_inputs)
            step_args = [None if isinstance(i, StaticInput)
                         else drnn.step_input(next(seq_it)) for i in inputs]
            for k, i in enumerate(inputs):
                if isinstance(i, StaticInput):
                    step_args[k] = drnn.static_input(i.input)
            result = step(*step_args)
            if isinstance(result, dict):
                names = list(result)
                outs = [result[k] for k in names]
            else:
                outs = _to_list(result)
            for mem_name, pre in ctx.pending.items():
                new = ctx.named.get(mem_name)
                if new is None:
                    raise ValueError(
                        f"memory(name={mem_name!r}) was never bound: "
                        f"create a layer with name={mem_name!r} in the "
                        f"step function")
                drnn.update_memory(pre, new)
            drnn.output(*outs)
    finally:
        _ACTIVE.pop()
    result = drnn()
    result_list = _to_list(result)
    # propagate feature shapes lost through the tensor-array round-trip
    for res, step_out in zip(result_list, outs):
        if res.shape is None and step_out.shape is not None:
            res.shape = (-1,) + tuple(step_out.shape[1:])
            res.dtype = step_out.dtype
            res.lod_level = max(res.lod_level, 1)
    if reverse:
        result_list = [F.sequence_reverse(o) for o in result_list]
    first = result_list[0]
    if names:
        first._rg_named_outputs = dict(zip(names, result_list))
        return _named(first, name)
    if len(result_list) > 1:
        return result_list
    return _named(first, name)


def get_output_layer(input, arg_name, name=None, layer_attr=None):
    """Select a non-default output of a multi-output step layer
    (reference ``layers.py:4023``): dict-returning recurrent_group keys,
    or an lstm_step_layer's ``'state'``."""
    if arg_name == "state" and hasattr(input, "_lstm_state"):
        return _named(input._lstm_state, name)
    named = getattr(input, "_rg_named_outputs", None)
    if named and arg_name in named:
        return _named(named[arg_name], name)
    raise ValueError(f"get_output_layer: {arg_name!r} is not an output "
                     f"of this layer")


def recurrent_layer(input, act=None, bias_attr=None, param_attr=None,
                    name=None, reverse=False):
    """Simple full-matrix recurrence h_t = act(x_t + h_{t-1} @ W)
    (reference ``layers.py:4067`` over RecurrentLayer.cpp)."""
    size = input.shape[-1]
    mem_name = f"{name or 'recurrent'}@mem"

    def step(x):
        prev = memory(name=mem_name, size=size)
        h = F.elementwise_add(x, F.fc(input=prev, size=size,
                                      bias_attr=bias_attr,
                                      param_attr=param_attr))
        h = _apply_act(h, act or "tanh")
        _named(h, mem_name)
        return h

    return _named(recurrent_group(step, input, reverse=reverse), name)


def lstm_step_layer(input, state, size=None, act=None, gate_act=None,
                    state_act=None, bias_attr=None, name=None,
                    layer_attr=None):
    """One LSTM cell update from a pre-projected [N, 4H] input
    (reference ``layers.py:3765`` over LstmStepLayer.cpp; gate order
    i, g, f, o).  Returns the hidden; the new cell rides
    ``get_output_layer(..., arg_name='state')``."""
    size = size or state.shape[-1]
    i, g, f, o = F.split(input, 4, dim=-1)
    i = _apply_act(i, gate_act or "sigmoid")
    f = _apply_act(f, gate_act or "sigmoid")
    o = _apply_act(o, gate_act or "sigmoid")
    g = _apply_act(g, state_act or "tanh")
    c = F.elementwise_add(F.elementwise_mul(f, state),
                          F.elementwise_mul(i, g))
    h = F.elementwise_mul(o, _apply_act(c, act or "tanh"))
    h._lstm_state = c
    return _named(h, name)


def gru_step_layer(input, output_mem, size=None, act=None, gate_act=None,
                   name=None, bias_attr=None, param_attr=None,
                   layer_attr=None):
    """One GRU cell update from a pre-projected [N, 3H] input; owns the
    recurrent weights U (reference ``layers.py:3863`` over
    GruStepLayer.cpp)."""
    size = size or input.shape[-1] // 3
    u_rz = F.create_parameter(shape=[size, 2 * size],
                              dtype=input.dtype, attr=param_attr)
    u_c = F.create_parameter(
        shape=[size, size], dtype=input.dtype,
        attr=_ParamAttr(name=f"{param_attr.name}.candidate")
        if isinstance(param_attr, _ParamAttr) and param_attr.name else None)
    x_r, x_z, x_c = F.split(input, 3, dim=-1)
    h_rz = F.matmul(output_mem, u_rz)
    h_r, h_z = F.split(h_rz, 2, dim=-1)
    if bias_attr is not False and bias_attr is not None:
        b = F.create_parameter(shape=[3 * size], dtype=input.dtype,
                               is_bias=True,
                               attr=None if bias_attr is True else bias_attr)
        b_r, b_z, b_c = F.split(b, 3, dim=-1)
        x_r = F.elementwise_add(x_r, b_r, axis=1)
        x_z = F.elementwise_add(x_z, b_z, axis=1)
        x_c = F.elementwise_add(x_c, b_c, axis=1)
    r = _apply_act(F.elementwise_add(x_r, h_r), gate_act or "sigmoid")
    z = _apply_act(F.elementwise_add(x_z, h_z), gate_act or "sigmoid")
    c = _apply_act(
        F.elementwise_add(x_c, F.matmul(F.elementwise_mul(r, output_mem),
                                        u_c)),
        act or "tanh")
    one_minus_z = F.scale(z, scale=-1.0, bias=1.0)
    h = F.elementwise_add(F.elementwise_mul(z, output_mem),
                          F.elementwise_mul(one_minus_z, c))
    return _named(h, name)


gru_step_naive_layer = gru_step_layer


# ---------------------------------------------------------------------------
# generation: legacy beam_search (reference ``layers.py:4485`` over
# RecurrentGradientMachine generation mode).  TPU mapping: unrolled dense
# [B*K] decode under a deterministic name scope (weights shared across
# timesteps), beam_search/beam_search_decode IR ops per step.
# ---------------------------------------------------------------------------

def beam_search(step, input, bos_id, eos_id, beam_size,
                max_length=30, name=None, num_results_per_sample=None):
    """Beam-search generation.  ``input`` mixes StaticInput (encoder
    context, tiled over beams) and one GeneratedInput (previous token,
    embedded with the trained embedding).  ``step`` is the same function
    used for the training-time recurrent_group; memories bind by name.
    Returns (sentence_ids [B, K, T], scores [B, K]) Variables.
    """
    inputs = _to_list(input)
    gens = [i for i in inputs if isinstance(i, GeneratedInput)]
    statics = [i for i in inputs if isinstance(i, StaticInput)]
    if len(gens) != 1:
        raise ValueError("beam_search needs exactly one GeneratedInput")
    if not statics:
        raise ValueError("beam_search needs a StaticInput for the batch "
                         "shape (encoder context)")
    gen = gens[0]
    K = beam_size

    # tile static inputs over the beam axis: [B, D] -> [B*K, D]
    tiled = {}
    batch_var = statics[0].input
    for s in statics:
        v = s.input
        d = v.shape[-1]
        tiled[id(s)] = F.reshape(
            F.expand(F.reshape(v, shape=[-1, 1, d]), expand_times=[1, K, 1]),
            shape=[-1, d])

    # initial beams: token bos, scores [0, -inf, ...] per batch row
    ones_b = F.fill_constant_batch_size_like(input=batch_var, shape=[-1, K],
                                             dtype="int64", value=1)
    pre_ids = F.cast(F.scale(F.cast(ones_b, "float32"),
                             scale=float(bos_id)), "int64")
    zeros_b = F.fill_constant_batch_size_like(
        input=batch_var, shape=[-1, 1], dtype="float32", value=0.0)
    if K > 1:
        ninf_b = F.fill_constant_batch_size_like(
            input=batch_var, shape=[-1, K - 1], dtype="float32", value=-1e9)
        pre_scores = F.concat([zeros_b, ninf_b], axis=1)
    else:
        pre_scores = zeros_b
    # arange(B)*K per-row offset for flattening parent indices
    row_ones = F.fill_constant_batch_size_like(
        input=batch_var, shape=[-1, 1], dtype="float32", value=1.0)
    arange_b = F.scale(F.cumsum(row_ones, axis=0), scale=1.0, bias=-1.0)
    beam_offset = F.cast(
        F.expand(F.scale(arange_b, scale=float(K)), expand_times=[1, K]),
        "int64")

    ctx = _RecurrentCtx("gen")
    mems = {}                 # memory name -> current [B*K, D] value
    ids_arr = par_arr = None
    _ACTIVE.append(ctx)
    try:
        for t in range(max_length):
            cur_emb = F.embedding(
                input=F.reshape(pre_ids, shape=[-1, 1]),
                size=[gen.size, gen.embedding_size],
                param_attr=_ParamAttr(name=gen.embedding_name))
            step_args = []
            for i in inputs:
                if isinstance(i, StaticInput):
                    step_args.append(tiled[id(i)])
                else:
                    step_args.append(cur_emb)
            # run the step; each not-yet-materialized memory() raises
            # _NeedBoot — materialize its boot value (tiled over beams,
            # OUTSIDE the name scope so per-t vars stay distinct) and
            # retry until the step completes
            probs = None
            for _ in range(16):
                ctx.named = dict(mems)
                ctx.boots = {}
                try:
                    with unique_name_scope(f"{name or 'beam'}@step/"):
                        probs = step(*step_args)
                    break
                except _NeedBoot:
                    pass
                for mname, spec in ctx.boots.items():
                    if mname in mems:
                        continue
                    if spec["boot_layer"] is not None:
                        bl = spec["boot_layer"]
                        d = bl.shape[-1]
                        mems[mname] = F.reshape(
                            F.expand(F.reshape(bl, shape=[-1, 1, d]),
                                     expand_times=[1, K, 1]),
                            shape=[-1, d])
                    else:
                        mems[mname] = F.fill_constant_batch_size_like(
                            input=cur_emb, shape=[-1, spec["size"]],
                            dtype="float32", value=spec["value"])
            if probs is None:
                raise ValueError("beam_search step kept declaring new "
                                 "memories (>16)")

            vocab = probs.shape[-1]
            probs3 = F.reshape(probs, shape=[-1, K, vocab])
            topk_scores, topk_idx = F.topk(probs3, k=K)
            acc = F.elementwise_add(
                F.ops.log(topk_scores),
                F.reshape(pre_scores, shape=[-1, K, 1]))
            sel_ids, sel_scores, parent = F.beam_search(
                pre_ids, pre_scores, topk_idx, acc, K, end_id=eos_id)
            flat_parent = F.reshape(
                F.elementwise_add(F.cast(parent, "int64"), beam_offset),
                shape=[-1])
            # reorder memories by winning parent beam
            new_mems = {}
            for mname in list(mems):
                new_val = ctx.named.get(mname)
                if new_val is None or new_val is mems[mname]:
                    raise ValueError(
                        f"beam_search memory {mname!r} was never updated "
                        f"by the step function (bind a layer with "
                        f"name={mname!r})")
                new_mems[mname] = F.gather(new_val, flat_parent)
            mems = new_mems
            it = F.fill_constant(shape=[1], dtype="int64", value=t)
            if ids_arr is None:
                ids_arr = F.array_write(sel_ids, i=it)
                par_arr = F.array_write(parent, i=it)
            else:
                F.array_write(sel_ids, i=it, array=ids_arr)
                F.array_write(parent, i=it, array=par_arr)
            pre_ids, pre_scores = sel_ids, sel_scores
    finally:
        _ACTIVE.pop()
    sentences, scores = F.beam_search_decode(ids_arr, par_arr, pre_scores,
                                             max_len=max_length)
    return sentences, scores


def eos_layer(input, eos_id, name=None, layer_attr=None):
    """1.0 where the id equals ``eos_id`` (reference ``layers.py:4445``)."""
    ids = F.cast(input, "int64")
    eos = F.fill_constant_batch_size_like(input=ids, shape=[-1, 1],
                                          dtype="int64", value=eos_id)
    out = F.cast(F.equal(ids, eos), "float32")
    return _named(out, name)


# ---------------------------------------------------------------------------
# io / basic layers
# ---------------------------------------------------------------------------

def data_layer(name, size, height=None, width=None, type=None):
    from paddle_tpu.v2 import data_type as dt
    input_type = type if type is not None else dt.dense_vector(size)
    return _v2.data(name=name, type=input_type, height=height, width=width)


def fc_layer(input, size, act=None, param_attr=None, bias_attr=None,
             name=None, layer_attr=None):
    out = _v2.fc(input=input, size=size, act=act, param_attr=param_attr,
                 bias_attr=bias_attr)
    return _named(out, name)


def embedding_layer(input, size, name=None, param_attr=None,
                    layer_attr=None):
    return _named(_v2.embedding(input=input, size=size,
                                param_attr=param_attr), name)


def img_conv_layer(input, filter_size, num_filters, num_channel=None,
                   act=None, padding=0, stride=1, bias_attr=None,
                   param_attr=None, name=None, groups=1, dilation=1,
                   trans=False, **kwargs):
    if trans:
        out = F.conv2d_transpose(input=input, num_filters=num_filters,
                                 filter_size=filter_size, stride=stride,
                                 padding=padding, act=_act_name(act),
                                 bias_attr=bias_attr, param_attr=param_attr)
    else:
        out = F.conv2d(input=input, num_filters=num_filters,
                       filter_size=filter_size, stride=stride,
                       padding=padding, dilation=dilation,
                       groups=groups or 1, act=_act_name(act),
                       bias_attr=bias_attr, param_attr=param_attr)
    return _named(out, name)


def img_conv3d_layer(input, filter_size, num_filters, num_channels=None,
                     act=None, padding=0, stride=1, bias_attr=None,
                     param_attr=None, name=None, groups=1, **kwargs):
    out = F.conv3d(input=input, num_filters=num_filters,
                   filter_size=filter_size, stride=stride, padding=padding,
                   groups=groups or 1, act=_act_name(act),
                   bias_attr=bias_attr, param_attr=param_attr)
    return _named(out, name)


def img_pool_layer(input, pool_size, name=None, num_channels=None,
                   pool_type=None, stride=None, padding=0, **kwargs):
    return _named(_v2.img_pool(input=input, pool_size=pool_size,
                               pool_type=pool_type, stride=stride,
                               padding=padding), name)


def img_pool3d_layer(input, pool_size, name=None, num_channels=None,
                     pool_type=None, stride=None, padding=0, **kwargs):
    ptype = getattr(pool_type, "name", pool_type) or "max"
    ptype = "avg" if ptype in ("average", "avg") else ptype
    return _named(F.pool3d(input=input, pool_size=pool_size,
                           pool_type=ptype,
                           pool_stride=stride or pool_size,
                           pool_padding=padding), name)


def batch_norm_layer(input, act=None, name=None, **kwargs):
    return _named(_v2.batch_norm(input=input, act=act), name)


def dropout_layer(input, dropout_rate, name=None):
    return _named(_v2.dropout(input=input, dropout_rate=dropout_rate), name)


def concat_layer(input, act=None, name=None, layer_attr=None,
                 bias_attr=None):
    out = _v2.concat(input=input, name=name)
    return _named(_apply_act(out, act), name)


def seq_concat_layer(a, b, act=None, name=None, **kwargs):
    return _named(_apply_act(_v2.seq_concat(a, b), act), name)


def printer_layer(input, format=None, name=None):
    for v in _to_list(input):
        F.Print(v, message=format or name or "printer")
    return input


def lstmemory(input, size=None, reverse=False, act=None, name=None,
              **kwargs):
    return _named(_v2.lstmemory(input=input, size=size, reverse=reverse,
                                act=act, **kwargs), name)


def grumemory(input, size=None, reverse=False, act=None, name=None,
              **kwargs):
    return _named(_v2.gru(input=input, size=size, reverse=reverse, act=act,
                          **kwargs), name)


# ---------------------------------------------------------------------------
# sequence layers
# ---------------------------------------------------------------------------

def pooling_layer(input, pooling_type=None, name=None, **kwargs):
    return _named(_v2.pooling(input=input, pooling_type=pooling_type), name)


def last_seq(input, name=None, **kwargs):
    return _named(_v2.last_seq(input=input), name)


def first_seq(input, name=None, **kwargs):
    return _named(_v2.first_seq(input=input), name)


def expand_layer(input, expand_as, name=None, bias_attr=None,
                 expand_level=None):
    return _named(_v2.expand(input=input, expand_as=expand_as), name)


def repeat_layer(input, num_repeats, as_row_vector=True, act=None,
                 name=None, layer_attr=None):
    """Tile each row ``num_repeats`` times along the feature axis
    (reference ``layers.py:1916``): [a, b] x3 -> [a, b, a, b, a, b]
    (as_row_vector) or [a, a, a, b, b, b]."""
    if as_row_vector:
        out = F.concat([input] * num_repeats, axis=1)
    else:
        d = input.shape[-1]
        out = F.reshape(
            F.expand(F.reshape(input, shape=[-1, d, 1]),
                     expand_times=[1, 1, num_repeats]),
            shape=[-1, d * num_repeats])
    return _named(_apply_act(out, act), name)


def seq_reshape_layer(input, reshape_size, act=None, name=None,
                      bias_attr=None, layer_attr=None):
    return _named(_apply_act(F.sequence_reshape(input, reshape_size), act),
                  name)


def seq_slice_layer(input, starts, ends, name=None):
    """Per-sequence slice [starts, ends) (reference ``layers.py:7125``);
    starts/ends are [B]-shaped layers."""
    if starts is None or ends is None:
        raise ValueError("seq_slice_layer needs both starts and ends")
    length = F.elementwise_sub(ends, starts)
    return _named(F.sequence_slice(input, starts, length), name)


def sub_seq_layer(input, offsets, sizes, act=None, bias_attr=None,
                  name=None):
    return _named(_apply_act(F.sequence_slice(input, offsets, sizes), act),
                  name)


def sub_nested_seq_layer(input, selected_indices, name=None):
    """Trim a nested sequence to the sub-sequences picked by
    ``selected_indices`` (e.g. kmax_seq_score_layer output) — beam
    training (reference ``layers.py:7045`` over
    SubNestedSequenceLayer.cpp)."""
    from paddle_tpu.layer_helper import LayerHelper
    helper = LayerHelper("sub_nested_seq", name=name)
    out = helper.create_tmp_variable(dtype=input.dtype)
    # gradients flow to X (row gather); the indices are non-differentiable
    helper.append_op(type="sub_nested_seq",
                     inputs={"X": [input],
                             "SelectedIndices": [selected_indices]},
                     outputs={"Out": [out]})
    return _named(out, name)


def kmax_seq_score_layer(input, name=None, beam_size=1):
    """Top-k scores within each sequence (reference ``layers.py:7191``
    over KmaxSeqScoreLayer.cpp).  k=1 is a sequence max pool; general k
    pads each sequence to the dense [B, T] layout once and runs topk —
    static shapes, MXU/VPU friendly."""
    from paddle_tpu.layer_helper import LayerHelper
    helper = LayerHelper("kmax_seq_score", name=name)
    out = helper.create_tmp_variable(dtype="int64")
    out.stop_gradient = True
    helper.append_op(type="kmax_seq_score", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"beam_size": beam_size})
    return _named(out, name)


def ctc_layer(input, label, size=None, name=None, norm_by_times=False,
              layer_attr=None):
    """CTC cost (reference ``layers.py:5602`` over warp-ctc); pass the
    PRE-softmax projection — the lowering normalizes internally."""
    return _named(F.mean(F.warpctc(input, label,
                                   norm_by_times=norm_by_times)), name)


warp_ctc_layer = ctc_layer


# ---------------------------------------------------------------------------
# elementwise / math layers
# ---------------------------------------------------------------------------

def addto_layer(input, act=None, name=None, bias_attr=None,
                layer_attr=None):
    """Elementwise sum of inputs (+bias, act) (reference
    ``layers.py:3451`` over AddtoLayer.cpp)."""
    parts = _to_list(input)
    out = parts[0] if len(parts) == 1 else F.sums(parts)
    if bias_attr is not False and bias_attr is not None:
        b = F.create_parameter(shape=[out.shape[-1]], dtype=out.dtype,
                               is_bias=True,
                               attr=None if bias_attr is True else bias_attr)
        out = F.elementwise_add(out, b, axis=1)
    return _named(_apply_act(out, act), name)


def interpolation_layer(input, weight, name=None, layer_attr=None):
    """w*a + (1-w)*b with per-row weight [N, 1] (reference
    ``layers.py:2036`` over InterpolationLayer.cpp)."""
    a, b = input
    wa = F.elementwise_mul(a, weight, axis=0)
    one_minus = F.scale(weight, scale=-1.0, bias=1.0)
    wb = F.elementwise_mul(b, one_minus, axis=0)
    return _named(F.elementwise_add(wa, wb), name)


def bilinear_interp_layer(input, out_size_x=None, out_size_y=None,
                          name=None, layer_attr=None):
    """Bilinear upsampling of NCHW maps (reference ``layers.py:2089`` over
    BilinearInterpLayer.cpp)."""
    return _named(F.image_resize(input, (out_size_y, out_size_x),
                                 method="bilinear"), name)


def upsample_layer(input, scale=2, upsample_size=None, name=None,
                   **kwargs):
    """Nearest-neighbour upsampling (reference UpsampleLayer.cpp)."""
    h, w = input.shape[2], input.shape[3]
    out_hw = upsample_size or (h * scale, w * scale)
    return _named(F.image_resize(input, out_hw, method="nearest"), name)


def power_layer(input, weight, name=None, layer_attr=None):
    """x ** w per row, weight [N, 1] (reference ``layers.py:2144``)."""
    return _named(F.elementwise_pow(input, weight, axis=0), name)


def scaling_layer(input, weight, name=None, layer_attr=None):
    return _named(_v2.scaling(input, weight), name)


def slope_intercept_layer(input, slope=1.0, intercept=0.0, name=None,
                          layer_attr=None):
    return _named(_v2.slope_intercept(input, slope, intercept), name)


def trans_layer(input, name=None, layer_attr=None):
    """Matrix transpose (reference ``layers.py:2232`` TransLayer.cpp)."""
    return _named(F.transpose(input, perm=[1, 0]), name)


def rotate_layer(input, height, width, name=None, layer_attr=None):
    """Rotate each row's [height, width] map 90° counter-clockwise
    (reference ``layers.py:2268`` RotateLayer.cpp)."""
    x = F.reshape(input, shape=[-1, height, width])
    xt = F.transpose(x, perm=[0, 2, 1])            # [N, W, H]
    rev = _constant(np.arange(width - 1, -1, -1), "int64")
    # flip the (new) row axis: gather is axis-0, so route through
    # transpose: [N, W, H] -> [W, N, H] -> gather -> back
    wnh = F.transpose(xt, perm=[1, 0, 2])
    flipped = F.gather(wnh, rev)
    out = F.transpose(flipped, perm=[1, 0, 2])
    return _named(F.reshape(out, shape=[-1, height * width]), name)


def cos_sim(a, b, scale=1, size=1, name=None, layer_attr=None):
    """Row-wise cosine similarity * scale (reference ``layers.py:2317``)."""
    out = F.cos_sim(a, b)
    if scale != 1:
        out = F.scale(out, scale=float(scale))
    return _named(out, name)


def l2_distance_layer(x, y, name=None, layer_attr=None):
    """Row-wise euclidean distance (reference ``layers.py:2376``)."""
    diff = F.elementwise_sub(x, y)
    return _named(F.sqrt(F.reduce_sum(F.square(diff), dim=1,
                                      keep_dim=True)), name)


def dot_prod_layer(input1, input2, name=None, layer_attr=None):
    """Row-wise inner product (reference ``layers.py:4367``)."""
    return _named(F.reduce_sum(F.elementwise_mul(input1, input2), dim=1,
                               keep_dim=True), name)


def out_prod_layer(input1, input2, name=None, layer_attr=None):
    """Row-wise outer product, flattened (reference ``layers.py:4406``)."""
    da, db = input1.shape[-1], input2.shape[-1]
    a = F.reshape(input1, shape=[-1, da, 1])
    b = F.reshape(input2, shape=[-1, 1, db])
    return _named(F.reshape(F.matmul(a, b), shape=[-1, da * db]), name)


def linear_comb_layer(weights, vectors, size=None, name=None,
                      layer_attr=None):
    """z = w . reshape(v, [M, size]) per row (reference
    ``layers.py:5367`` LinearCombinationLayer)."""
    m = weights.shape[-1]
    size = size or vectors.shape[-1] // m
    v = F.reshape(vectors, shape=[-1, m, size])
    w = F.reshape(weights, shape=[-1, m, 1])
    return _named(F.reshape(F.reduce_sum(F.elementwise_mul(v, w), dim=1),
                            shape=[-1, size]), name)


def tensor_layer(a, b, size, act=None, name=None, param_attr=None,
                 bias_attr=None, layer_attr=None):
    """Bilinear tensor product y_k = a W_k b (reference ``layers.py:5118``
    over TensorLayer.cpp; lowered through bilinear_tensor_product)."""
    from paddle_tpu.layer_helper import LayerHelper
    helper = LayerHelper("bilinear_tensor_product", param_attr=param_attr,
                         bias_attr=bias_attr, act=_act_name(act), name=name)
    da, db = a.shape[-1], b.shape[-1]
    w = helper.create_parameter(helper.param_attr, shape=[size, da, db],
                                dtype=a.dtype)
    out = helper.create_tmp_variable(a.dtype)
    helper.append_op(type="bilinear_tensor_product",
                     inputs={"X": [a], "Y": [b], "Weight": [w]},
                     outputs={"Out": [out]})
    pre = helper.append_bias_op(out)
    return _named(helper.append_activation(pre), name)


def selective_fc_layer(input, size, select=None, act=None,
                       param_attr=None, bias_attr=None, name=None,
                       **kwargs):
    """FC whose output is masked by ``select`` (reference
    ``layers.py:5188``; the dense TPU lowering computes all columns and
    masks — MXU-friendly, no gather)."""
    out = _v2.fc(input=input, size=size, act=act, param_attr=param_attr,
                 bias_attr=bias_attr)
    if select is not None:
        out = F.elementwise_mul(out, select)
    return _named(out, name)


def sampling_id_layer(input, name=None, layer_attr=None):
    """Sample one id per row from a probability row (reference
    ``layers.py:5291`` over SamplingIdLayer.cpp): inverse-CDF with
    PER-ROW uniforms drawn from the traced RNG key (sampling_id op)."""
    from paddle_tpu.layer_helper import LayerHelper
    helper = LayerHelper("sampling_id", name=name)
    out = helper.create_tmp_variable(dtype="int64")
    out.stop_gradient = True
    helper.append_op(type="sampling_id", inputs={"X": [input]},
                     outputs={"Out": [out]})
    return _named(out, name)


def maxid_layer(input, name=None, layer_attr=None):
    return _named(_v2.max_id(input), name)


def pad_layer(input, pad_c=None, pad_h=None, pad_w=None, name=None,
              layer_attr=None):
    """Zero-pad NCHW maps per axis (reference ``layers.py:4961``)."""
    pads = [0, 0] + list(pad_c or [0, 0]) + list(pad_h or [0, 0]) + \
        list(pad_w or [0, 0])
    return _named(F.pad(input, paddings=pads), name)


def conv_shift_layer(a, b, name=None, layer_attr=None):
    return _named(F.conv_shift(a, b), name)


def block_expand_layer(input, block_x=1, block_y=1, stride_x=1, stride_y=1,
                       padding_x=0, padding_y=0, num_channels=None,
                       name=None, layer_attr=None):
    """Image -> sequence of patches (reference ``layers.py:5437`` over
    BlockExpandLayer.cpp == fluid im2sequence)."""
    return _named(F.im2sequence(input, filter_size=[block_y, block_x],
                                stride=[stride_y, stride_x],
                                padding=[padding_y, padding_x]), name)


def maxout_layer(input, groups, num_channels=None, name=None,
                 layer_attr=None):
    return _named(F.maxout(input, groups), name)


def multiplex_layer(input, name=None, layer_attr=None):
    """Row-wise select among inputs[1:] by index column inputs[0]
    (reference ``layers.py:6606``)."""
    index = input[0]
    return _named(F.multiplex(list(input[1:]), index), name)


def prelu_layer(input, name=None, partial_sum=1, param_attr=None,
                layer_attr=None):
    return _named(F.prelu(input, mode="all", param_attr=param_attr), name)


def gated_unit_layer(input, size, act=None, name=None, gate_attr=None,
                     gate_param_attr=None, gate_bias_attr=None,
                     inproj_attr=None, inproj_param_attr=None,
                     inproj_bias_attr=None, layer_attr=None):
    """GLU: fc(x) * sigmoid(fc_g(x)) (reference ``layers.py:6852``)."""
    proj = _v2.fc(input=input, size=size, act=act,
                  param_attr=inproj_param_attr, bias_attr=inproj_bias_attr)
    gate = _v2.fc(input=input, size=size, act="sigmoid",
                  param_attr=gate_param_attr, bias_attr=gate_bias_attr)
    return _named(F.elementwise_mul(proj, gate), name)


def switch_order_layer(input, name=None, reshape_to=None, **kwargs):
    """Permute axis order, e.g. NCHW <-> NHWC (reference
    ``layers.py:6945``); ``reshape_to`` lists axis groups, flattened to
    the permutation."""
    if not reshape_to:
        raise ValueError("switch_order_layer needs reshape_to, e.g. "
                         "[[0], [2, 3, 1]]")
    perm = [a for grp in reshape_to for a in grp]
    return _named(F.transpose(input, perm=perm), name)


def crop_layer(input, offset, axis=2, shape=None, name=None,
               layer_attr=None):
    """Static crop along trailing axes (reference ``layers.py:6994``)."""
    if shape is None:
        raise ValueError("crop_layer needs the target shape")
    sizes = shape[axis:axis + len(offset)] if len(shape) > len(offset) \
        else shape
    axes = list(range(axis, axis + len(offset)))
    starts = list(offset)
    ends = [o + s for o, s in zip(offset, sizes)]
    return _named(F.slice(input, axes=axes, starts=starts, ends=ends), name)


def clip_layer(input, min, max, name=None):
    return _named(F.clip(input, min=min, max=max), name)


def row_conv_layer(input, context_len, act=None, name=None, param_attr=None,
                   layer_attr=None):
    """Lookahead (row) convolution (reference ``layers.py:6690`` over
    ``gserver/layers/RowConvLayer.cpp``); ``context_len`` is the lookahead
    step count plus one.  Shim over the fluid op
    (``ops/sequence_ops.py`` row_conv)."""
    out = F.row_conv(input, future_context_size=context_len - 1,
                     param_attr=param_attr)
    return _named(_apply_act(out, act), name)


def scale_sub_region_layer(input, indices, value, name=None):
    """Multiply a per-sample sub-region by ``value`` (reference
    ``layers.py:7493`` over ``gserver/layers/ScaleSubRegionLayer.cpp``).
    ``input`` is a dense [N, C, H, W] variable (the legacy flattened
    row-vector + frame-size convention is replaced by real shapes);
    ``indices`` [N, 6] holds one-based inclusive
    (c0, c1, h0, h1, w0, w1) ranges."""
    from paddle_tpu.layers.detection import scale_sub_region
    return _named(scale_sub_region(input, indices, value=float(value)),
                  name)


def resize_layer(input, size, name=None):
    return _named(F.reshape(input, shape=[-1, size]), name)


def scale_shift_layer(input, name=None, param_attr=None, bias_attr=None):
    """w*x + b with scalar w, b (reference ``layers.py:7378``)."""
    w = F.create_parameter(shape=[1], dtype=input.dtype, attr=param_attr)
    out = F.elementwise_mul(input, w)
    if bias_attr is not False:
        b = F.create_parameter(
            shape=[1], dtype=input.dtype, is_bias=True,
            attr=None if bias_attr in (None, True) else bias_attr)
        out = F.elementwise_add(out, b)
    return _named(out, name)


def factorization_machine(input, factor_size, name=None, param_attr=None,
                          layer_attr=None):
    """2nd-order FM interaction 0.5*sum((xV)^2 - (x^2)(V^2)) (reference
    ``layers.py:7547`` over FactorizationMachineLayer.cpp)."""
    d = input.shape[-1]
    v = F.create_parameter(shape=[d, factor_size], dtype=input.dtype,
                           attr=param_attr)
    xv = F.matmul(input, v)                        # [N, F]
    x2v2 = F.matmul(F.square(input), F.square(v))  # [N, F]
    out = F.scale(F.reduce_sum(F.elementwise_sub(F.square(xv), x2v2),
                               dim=1, keep_dim=True), scale=0.5)
    return _named(out, name)


# ---------------------------------------------------------------------------
# norm layers
# ---------------------------------------------------------------------------

def sum_to_one_norm_layer(input, name=None, layer_attr=None):
    """Row L1 normalization (reference ``layers.py:3374``)."""
    s = F.reduce_sum(input, dim=1, keep_dim=True)
    return _named(F.elementwise_div(input, s, axis=0), name)


def row_l2_norm_layer(input, name=None, layer_attr=None):
    return _named(F.l2_normalize(input, axis=1), name)


def img_cmrnorm_layer(input, size=5, scale=0.0128, power=0.75, name=None,
                      num_channels=None, layer_attr=None):
    """Cross-map response normalization == LRN (reference
    ``layers.py:3199`` over CMRProjectionNormLayer.cpp). The reference
    config_parser divides ``scale`` by the window size for
    cmrnorm-projection (``norm_conf.scale /= norm.size``), so the LRN
    alpha is ``scale / size``."""
    return _named(F.lrn(input, n=size, alpha=scale / size, beta=power),
                  name)


def cross_channel_norm_layer(input, name=None, param_attr=None):
    """L2 norm across channels with a learned per-channel scale
    (reference ``layers.py:1377`` over CrossChannelNormLayer.cpp)."""
    normed = F.l2_normalize(input, axis=1)
    c = input.shape[1]
    w = F.create_parameter(shape=[c], dtype=input.dtype, attr=param_attr)
    return _named(F.elementwise_mul(normed, w, axis=1), name)


def spp_layer(input, name=None, num_channels=None, pool_type=None,
              pyramid_height=3, layer_attr=None):
    ptype = getattr(pool_type, "name", pool_type) or "max"
    ptype = "avg" if ptype in ("average", "avg") else ptype
    return _named(F.spp(input, pyramid_height=pyramid_height,
                        pool_type=ptype), name)


# ---------------------------------------------------------------------------
# cost layers
# ---------------------------------------------------------------------------

def classification_cost(input, label, weight=None, name=None,
                        evaluator=None, layer_attr=None, coeff=1.0):
    return _named(_v2.classification_cost(input=input, label=label), name)


def cross_entropy(input, label, name=None, coeff=1.0, weight=None,
                  layer_attr=None):
    out = _v2.cross_entropy_cost(input=input, label=label)
    if coeff != 1.0:
        out = F.scale(out, scale=float(coeff))
    return _named(out, name)


def square_error_cost(input, label, name=None, coeff=1.0, layer_attr=None):
    out = _v2.square_error_cost(input=input, label=label)
    if coeff != 1.0:
        out = F.scale(out, scale=float(coeff))
    return _named(out, name)


regression_cost = square_error_cost
mse_cost = square_error_cost


def sum_cost(input, name=None, layer_attr=None):
    """Sum of all input elements as the cost (reference
    ``layers.py:6250`` over SumCostLayer.cpp)."""
    return _named(F.reduce_sum(input), name)


def cross_entropy_with_selfnorm(input, label, name=None, coeff=1.0,
                                softmax_selfnorm_alpha=0.1,
                                layer_attr=None):
    """CE + alpha * (log Z)^2 keeping the softmax close to self-normalized
    (reference ``layers.py:6199``)."""
    ce = F.mean(F.cross_entropy(input=input, label=label))
    z = F.reduce_sum(input, dim=1, keep_dim=True)
    selfnorm = F.mean(F.square(F.ops.log(z)))
    out = F.elementwise_add(ce, F.scale(selfnorm,
                                        scale=softmax_selfnorm_alpha))
    if coeff != 1.0:
        out = F.scale(out, scale=float(coeff))
    return _named(out, name)


def multi_binary_label_cross_entropy(input, label, name=None, coeff=1.0,
                                     layer_attr=None):
    """Independent sigmoid CE per class (reference ``layers.py:6390``);
    ``input`` should be pre-sigmoid logits."""
    out = F.mean(F.sigmoid_cross_entropy_with_logits(input, label))
    if coeff != 1.0:
        out = F.scale(out, scale=float(coeff))
    return _named(out, name)


def smooth_l1_cost(input, label, name=None, coeff=1.0, layer_attr=None):
    out = F.mean(F.smooth_l1(input, label))
    if coeff != 1.0:
        out = F.scale(out, scale=float(coeff))
    return _named(out, name)


def huber_regression_cost(input, label, name=None, delta=1.0, coeff=1.0,
                          layer_attr=None):
    out = F.mean(F.huber_loss(input, label, delta=delta))
    if coeff != 1.0:
        out = F.scale(out, scale=float(coeff))
    return _named(out, name)


def huber_classification_cost(input, label, name=None, coeff=1.0,
                              layer_attr=None):
    """Huberized hinge on {0,1} labels mapped to {-1,+1} (reference
    ``layers.py:6337`` over HuberTwoClassification.cpp):
    -4m if m < -1; (1-m)^2 if -1 <= m < 1; 0 otherwise (m = y'f)."""
    y = F.scale(F.cast(label, "float32"), scale=2.0, bias=-1.0)
    m = F.elementwise_mul(input, y)                # margin y'f
    sq = F.square(F.scale(m, scale=-1.0, bias=1.0))  # (1-m)^2
    lin = F.scale(m, scale=-4.0)                   # -4m
    below = F.cast(F.less_than(m, _constant([-1.0], "float32")), "float32")
    inside = F.elementwise_mul(
        F.cast(F.less_than(m, _constant([1.0], "float32")), "float32"),
        F.scale(below, scale=-1.0, bias=1.0))      # -1 <= m < 1
    loss = F.elementwise_add(F.elementwise_mul(below, lin),
                             F.elementwise_mul(inside, sq))
    out = F.mean(loss)
    if coeff != 1.0:
        out = F.scale(out, scale=float(coeff))
    return _named(out, name)


def rank_cost(left, right, label, weight=None, name=None, coeff=1.0,
              layer_attr=None):
    return _named(_v2.rank_cost(left, right, label), name)


def lambda_cost(input, score, name=None, NDCG_num=5, max_sort_size=-1,
                layer_attr=None):
    """LambdaRank surrogate (reference ``layers.py:6094`` over
    LambdaCost.cpp).  TPU simplification: pairwise logistic rank loss over
    all in-batch pairs where the true score differs — the dense,
    static-shape analog of the reference's per-query lambda sort."""
    s = F.reshape(input, shape=[-1, 1])
    y = F.reshape(F.cast(score, "float32"), shape=[-1, 1])
    diff_s = F.elementwise_sub(s, F.transpose(s, perm=[1, 0]))
    diff_y = F.elementwise_sub(y, F.transpose(y, perm=[1, 0]))
    zero = F.fill_constant(shape=[1], dtype="float32", value=0.0)
    pij = F.cast(F.greater_than(diff_y, zero), "float32")
    # log(1 + exp(-diff_s)) via softplus — numerically stable for large
    # score gaps (naive exp overflows f32 past ~88)
    log_term = F.softplus(F.scale(diff_s, scale=-1.0))
    return _named(F.mean(F.elementwise_mul(pij, log_term)), name)


def crf_layer(input, label, size=None, weight=None, param_attr=None,
              name=None, layer_attr=None):
    return _named(_v2.crf(input=input, label=label, size=size,
                          param_attr=param_attr), name)


def crf_decoding_layer(input, size=None, label=None, param_attr=None,
                       name=None, layer_attr=None):
    return _named(_v2.crf_decoding(input=input, size=size, label=label,
                                   param_attr=param_attr), name)


def nce_layer(input, label, num_classes=None, param_attr=None, weight=None,
              num_neg_samples=10, neg_distribution=None, bias_attr=None,
              name=None, layer_attr=None):
    """Noise-contrastive estimation cost (reference ``layers.py:5896``
    over NCELayer.cpp)."""
    ins = _to_list(input)
    x = ins[0] if len(ins) == 1 else F.concat(ins, axis=1)
    out = F.nce(input=x, label=label, num_total_classes=num_classes,
                num_neg_samples=num_neg_samples, param_attr=param_attr,
                bias_attr=bias_attr)
    return _named(F.mean(out), name)


def hsigmoid(input, label, num_classes, name=None, bias_attr=None,
             param_attr=None, layer_attr=None):
    """Hierarchical sigmoid over a complete binary tree (reference
    ``layers.py:2423`` over HierarchicalSigmoidLayer.cpp).

    TPU design: the tree paths (inner-node ids + left/right codes) are
    precomputed host-side into [C, D] constant tables; the per-sample
    path logits come from ONE gather + batched dot — dense, static
    shapes, scatter-free forward."""
    ins = _to_list(input)
    x = ins[0] if len(ins) == 1 else F.concat(ins, axis=1)
    d = x.shape[-1]
    num_inner = num_classes - 1
    depth = max(1, int(np.ceil(np.log2(max(2, num_classes)))))
    # complete-binary-tree paths: class c <-> leaf node (num_inner + c);
    # walk up to the root collecting (inner node, am-I-right-child code)
    path_ids = np.zeros((num_classes, depth), np.int64)
    path_codes = np.zeros((num_classes, depth), np.float32)
    path_mask = np.zeros((num_classes, depth), np.float32)
    for c in range(num_classes):
        node = num_inner + c
        lvl = 0
        while node > 0 and lvl < depth:
            parent = (node - 1) // 2
            path_ids[c, lvl] = parent
            path_codes[c, lvl] = float(node == 2 * parent + 2)
            path_mask[c, lvl] = 1.0
            node = parent
            lvl += 1
    w = F.create_parameter(shape=[num_inner, d], dtype=x.dtype,
                           attr=param_attr)
    # bias_attr=False disables the bias entirely (same gating as
    # mixed_layer/addto_layer); None/True means a default bias.
    b = None if bias_attr is False else F.create_parameter(
        shape=[num_inner, 1], dtype=x.dtype, is_bias=True,
        attr=None if bias_attr in (None, True) else bias_attr)
    ids_t = _constant(path_ids, "int64")      # [C, D]
    codes_t = _constant(path_codes, "float32")
    mask_t = _constant(path_mask, "float32")
    lbl = F.reshape(label, shape=[-1])
    sample_ids = F.gather(ids_t, lbl)         # [N, D]
    sample_codes = F.gather(codes_t, lbl)     # [N, D]
    sample_mask = F.gather(mask_t, lbl)       # [N, D]
    flat_ids = F.reshape(sample_ids, shape=[-1])
    w_rows = F.gather(w, flat_ids)            # [N*D, d]
    n_d = F.reshape(w_rows, shape=[-1, depth, d])
    logits = F.reduce_sum(
        F.elementwise_mul(n_d, F.reshape(x, shape=[-1, 1, d])), dim=2)
    if b is not None:
        b_rows = F.reshape(F.gather(b, flat_ids), shape=[-1, depth])
        logits = F.elementwise_add(logits, b_rows)   # [N, D]
    # sigmoid CE: code 1 -> right-child target
    ce = F.sigmoid_cross_entropy_with_logits(logits, sample_codes)
    loss = F.reduce_sum(F.elementwise_mul(ce, sample_mask), dim=1,
                        keep_dim=True)
    return _named(F.mean(loss), name)


# ---------------------------------------------------------------------------
# detection / vision layers
# ---------------------------------------------------------------------------

def priorbox_layer(input, image, aspect_ratio, variance, min_size,
                   max_size=None, name=None, **kwargs):
    box, var = F.prior_box(input=input, image=image, min_sizes=min_size,
                           max_sizes=max_size or [],
                           aspect_ratios=aspect_ratio, variance=variance)
    return _named(box, name)


def detection_output_layer(input_loc, input_conf, priorbox, num_classes,
                           nms_threshold=0.45, nms_top_k=400,
                           keep_top_k=200, confidence_threshold=0.01,
                           background_id=0, name=None):
    loc = input_loc if not isinstance(input_loc, (list, tuple)) \
        else F.concat(list(input_loc), axis=1)
    conf = input_conf if not isinstance(input_conf, (list, tuple)) \
        else F.concat(list(input_conf), axis=1)
    pb, pbv = priorbox if isinstance(priorbox, (list, tuple)) \
        else (priorbox, None)
    out = F.detection_output(loc, conf, pb, pbv,
                             background_label=background_id,
                             nms_threshold=nms_threshold,
                             nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                             score_threshold=confidence_threshold)
    return _named(out, name)


def roi_pool_layer(input, rois, pooled_width, pooled_height,
                   spatial_scale, num_channels=None, name=None):
    return _named(F.roi_pool(input, rois, pooled_height=pooled_height,
                             pooled_width=pooled_width,
                             spatial_scale=spatial_scale), name)


def multibox_loss_layer(input_loc, input_conf, priorbox, label, num_classes,
                        overlap_threshold=0.5, neg_pos_ratio=3.0,
                        neg_overlap=0.5, background_id=0, name=None):
    loc = input_loc if not isinstance(input_loc, (list, tuple)) \
        else F.concat(list(input_loc), axis=1)
    conf = input_conf if not isinstance(input_conf, (list, tuple)) \
        else F.concat(list(input_conf), axis=1)
    pb, pbv = priorbox if isinstance(priorbox, (list, tuple)) \
        else (priorbox, None)
    gt_box, gt_label = label
    out = F.ssd_loss(loc, conf, gt_box, gt_label, pb, pbv,
                     background_label=background_id,
                     overlap_threshold=overlap_threshold,
                     neg_pos_ratio=neg_pos_ratio,
                     neg_overlap=neg_overlap)
    return _named(F.mean(out), name)


class BeamInput:
    """One beam-expansion triple for :func:`cross_entropy_over_beam`
    (reference ``layers.py`` BeamInput)."""

    def __init__(self, candidate_scores, selected_candidates, gold):
        self.candidate_scores = candidate_scores
        self.selected_candidates = selected_candidates
        self.gold = gold


def cross_entropy_over_beam(input, name=None):
    """Cross entropy over multi-step beam expansions — the
    learning-to-search criterion (reference ``layers.py`` over
    ``CrossEntropyOverBeam.cpp:1-393``).  ``input`` is one
    :class:`BeamInput` or a list of them, one per search step; returns
    the per-sequence cost [batch, 1] (wrap with sum_cost / mean to
    scalarize).  Gradients flow into every ``candidate_scores`` input."""
    if isinstance(input, BeamInput):
        input = [input]
    for beam in input:
        if not isinstance(beam, BeamInput):
            raise TypeError("cross_entropy_over_beam wants BeamInput "
                            "objects")
    from paddle_tpu.layer_helper import LayerHelper
    helper = LayerHelper("cross_entropy_over_beam", name=name)
    out = helper.create_tmp_variable(dtype="float32")
    helper.append_op(
        type="cross_entropy_over_beam",
        inputs={"Scores": [b.candidate_scores for b in input],
                "Ids": [b.selected_candidates for b in input],
                "Gold": [b.gold for b in input]},
        outputs={"Out": [out]}, attrs={})
    return _named(out, name)
