"""Pooling type declarations (reference
``trainer_config_helpers/poolings.py``)."""

from paddle_tpu.v2.layer import Max, Avg, Sum  # noqa: F401

MaxPooling = Max
AvgPooling = Avg
SumPooling = Sum

__all__ = ["MaxPooling", "AvgPooling", "SumPooling", "Max", "Avg", "Sum"]
