"""define_py_data_sources2 (reference
``trainer_config_helpers/data_sources.py``): records the data-provider
module/object for the parsed config."""

from __future__ import annotations

__all__ = ["define_py_data_sources2", "current_data_sources"]

_current = {}


def current_data_sources():
    return dict(_current)


def define_py_data_sources2(train_list, test_list, module, obj, args=None):
    global _current
    _current = {"train_list": train_list, "test_list": test_list,
                "module": module, "obj": obj, "args": args or {}}
    return _current
