"""Optimizer settings DSL (reference
``trainer_config_helpers/optimizers.py`` settings()): records the chosen
optimizer into the active parse context (proto_config.parse_config)."""

from __future__ import annotations

__all__ = ["settings", "AdamOptimizer", "MomentumOptimizer", "current_settings"]

_current = {}


def current_settings():
    return dict(_current)


class _OptSpec:
    def __init__(self, name, **kwargs):
        self.name = name
        self.kwargs = kwargs

    def to_dict(self):
        return {"type": self.name, **self.kwargs}


def AdamOptimizer(beta1=0.9, beta2=0.999, epsilon=1e-8):
    return _OptSpec("adam", beta1=beta1, beta2=beta2, epsilon=epsilon)


def MomentumOptimizer(momentum=0.9):
    return _OptSpec("momentum", momentum=momentum)


def settings(batch_size=None, learning_rate=1e-3, learning_method=None,
             regularization=None, **kwargs):
    """reference settings(): global trainer config for the parsed model."""
    global _current
    _current = {
        "batch_size": batch_size,
        "learning_rate": learning_rate,
        "learning_method": (learning_method.to_dict()
                            if learning_method is not None
                            else {"type": "sgd"}),
    }
    _current.update(kwargs)
    return _current
