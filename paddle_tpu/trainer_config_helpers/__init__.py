"""trainer_config_helpers: the legacy layer-config DSL surface.

Reference: ``python/paddle/trainer_config_helpers/`` (layers.py 7,610 LoC,
plus activations/attrs/optimizers/poolings/networks/evaluators).  The DSL's
``*_layer`` functions configure the same graphs the v2 API builds, so this
package maps the legacy names onto the v2 shim (``paddle_tpu/v2``), which
emits the Program IR directly — the path the reference takes through
``config_parser.py:4398`` is replaced by ``proto_config.parse_config``.
"""

from paddle_tpu.trainer_config_helpers.layers import *  # noqa: F401,F403
from paddle_tpu.trainer_config_helpers.activations import *  # noqa: F401,F403
from paddle_tpu.trainer_config_helpers.attrs import *  # noqa: F401,F403
from paddle_tpu.trainer_config_helpers.poolings import *  # noqa: F401,F403
from paddle_tpu.trainer_config_helpers.networks import *  # noqa: F401,F403
from paddle_tpu.trainer_config_helpers.data_sources import *  # noqa: F401,F403
from paddle_tpu.trainer_config_helpers.optimizers import *  # noqa: F401,F403
from paddle_tpu.trainer_config_helpers.evaluators import *  # noqa: F401,F403
