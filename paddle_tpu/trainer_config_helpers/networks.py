"""Composite network helpers (reference
``trainer_config_helpers/networks.py``, 1,813 LoC): convolution groups,
VGG stacks, LSTM/GRU units & groups, bidirectional wrappers, attention
blocks, and the ``inputs``/``outputs`` config markers — built from the
legacy layer DSL so a reference-style config runs unchanged."""

from __future__ import annotations

import paddle_tpu.layers as F
from paddle_tpu.v2.layer import Sum as _SumPooling
from paddle_tpu.v2.networks import (  # noqa: F401
    simple_img_conv_pool, img_conv_group, sequence_conv_pool, simple_lstm,
    simple_gru, bidirectional_lstm)
from paddle_tpu.trainer_config_helpers import layers as L

__all__ = [
    "simple_img_conv_pool", "img_conv_group", "sequence_conv_pool",
    "simple_lstm", "simple_gru", "bidirectional_lstm",
    "img_conv_bn_pool", "img_separable_conv", "small_vgg",
    "vgg_16_network", "lstmemory_unit", "lstmemory_group", "gru_unit",
    "gru_group", "simple_gru2", "bidirectional_gru", "simple_attention",
    "dot_product_attention", "inputs", "outputs",
]


def img_conv_bn_pool(input, filter_size, num_filters, pool_size,
                     num_channel=None, act=None, groups=1, conv_stride=1,
                     conv_padding=0, pool_stride=1, pool_type=None,
                     name=None, **kwargs):
    """conv -> batch_norm -> pool (reference ``networks.py:231``)."""
    conv = L.img_conv_layer(input=input, filter_size=filter_size,
                            num_filters=num_filters, num_channel=num_channel,
                            act=None, groups=groups, stride=conv_stride,
                            padding=conv_padding, bias_attr=False)
    bn = L.batch_norm_layer(input=conv, act=act)
    return L.img_pool_layer(input=bn, pool_size=pool_size,
                            pool_type=pool_type, stride=pool_stride)


def img_separable_conv(input, num_channels, num_out_channels, filter_size,
                       stride=1, padding=0, depth_multiplier=1, act=None,
                       bias_attr=None, param_attr=None, name=None,
                       **kwargs):
    """Depthwise conv + pointwise 1x1 conv (reference ``networks.py:439``)."""
    depthwise = L.img_conv_layer(
        input=input, filter_size=filter_size,
        num_filters=num_channels * depth_multiplier, stride=stride,
        padding=padding, groups=num_channels, act=None,
        bias_attr=bias_attr, param_attr=param_attr)
    return L.img_conv_layer(input=depthwise, filter_size=1,
                            num_filters=num_out_channels, stride=1,
                            padding=0, act=act, bias_attr=bias_attr)


def small_vgg(input_image, num_channels, num_classes):
    """The tutorial's small VGG (reference ``networks.py:517``)."""
    def block(ipt, num_filter, times, dropouts):
        return img_conv_group(input=ipt, conv_num_filter=[num_filter] * times,
                              pool_size=2, conv_padding=1,
                              conv_filter_size=3, conv_act="relu",
                              conv_with_batchnorm=True,
                              conv_batchnorm_drop_rate=dropouts,
                              pool_stride=2, pool_type="max")

    tmp = block(input_image, 64, 2, [0.3, 0])
    tmp = block(tmp, 128, 2, [0.4, 0])
    tmp = block(tmp, 256, 3, [0.4, 0.4, 0])
    tmp = block(tmp, 512, 3, [0.4, 0.4, 0])
    tmp = L.img_pool_layer(input=tmp, pool_size=2, stride=2)
    tmp = L.dropout_layer(input=tmp, dropout_rate=0.5)
    tmp = L.fc_layer(input=tmp, size=512, act=None)
    bn = L.batch_norm_layer(input=tmp, act="relu")
    bn = L.dropout_layer(input=bn, dropout_rate=0.5)
    tmp = L.fc_layer(input=bn, size=512, act=None)
    return L.fc_layer(input=tmp, size=num_classes, act="softmax")


def vgg_16_network(input_image, num_channels, num_classes=1000):
    """VGG-16 (reference ``networks.py:547``)."""
    def block(ipt, num_filter, times):
        return img_conv_group(input=ipt, conv_num_filter=[num_filter] * times,
                              pool_size=2, conv_padding=1,
                              conv_filter_size=3, conv_act="relu",
                              pool_stride=2, pool_type="max")

    tmp = block(input_image, 64, 2)
    tmp = block(tmp, 128, 2)
    tmp = block(tmp, 256, 3)
    tmp = block(tmp, 512, 3)
    tmp = block(tmp, 512, 3)
    tmp = L.fc_layer(input=tmp, size=4096, act="relu")
    tmp = L.dropout_layer(input=tmp, dropout_rate=0.5)
    tmp = L.fc_layer(input=tmp, size=4096, act="relu")
    tmp = L.dropout_layer(input=tmp, dropout_rate=0.5)
    return L.fc_layer(input=tmp, size=num_classes, act="softmax")


def lstmemory_unit(input, out_memory=None, name=None, size=None,
                   param_attr=None, act=None, gate_act=None, state_act=None,
                   input_proj_bias_attr=None, input_proj_layer_attr=None,
                   lstm_bias_attr=None, lstm_layer_attr=None):
    """One LSTM step for recurrent_group (reference ``networks.py:717``):
    mixed projection of [x, prev_out] -> lstm_step_layer, memories bound
    by name."""
    if size is None:
        size = input.shape[-1] // 4
    name = name or "lstmemory_unit"
    out_mem = out_memory if out_memory is not None \
        else L.memory(name=name, size=size)
    state_mem = L.memory(name=f"{name}@state", size=size)
    with L.mixed_layer(size=size * 4, bias_attr=input_proj_bias_attr) as m:
        m += L.full_matrix_projection(input, param_attr=param_attr)
        m += L.full_matrix_projection(out_mem)
    lstm_out = L.lstm_step_layer(input=m.output, state=state_mem, size=size,
                                 act=act, gate_act=gate_act,
                                 state_act=state_act,
                                 bias_attr=lstm_bias_attr, name=name)
    L.get_output_layer(input=lstm_out, arg_name="state",
                       name=f"{name}@state")
    return lstm_out


def lstmemory_group(input, size=None, name=None, out_memory=None,
                    reverse=False, param_attr=None, act=None,
                    gate_act=None, state_act=None,
                    input_proj_bias_attr=None, input_proj_layer_attr=None,
                    lstm_bias_attr=None, lstm_layer_attr=None):
    """LSTM over a sequence via recurrent_group (reference
    ``networks.py:836``); use when the step needs to compose with other
    layers — otherwise ``lstmemory`` (the fused scan) is faster."""
    name = name or "lstmemory_group"

    def step(ipt):
        return lstmemory_unit(
            input=ipt, name=name, size=size, param_attr=param_attr,
            act=act, gate_act=gate_act, state_act=state_act,
            input_proj_bias_attr=input_proj_bias_attr,
            lstm_bias_attr=lstm_bias_attr)

    return L.recurrent_group(step=step, input=input, reverse=reverse,
                             name=f"{name}_group")


def gru_unit(input, memory_boot=None, size=None, name=None,
             gru_param_attr=None, act=None, gate_act=None,
             gru_bias_attr=None, gru_layer_attr=None, naive=False):
    """One GRU step for recurrent_group (reference ``networks.py:940``)."""
    if size is None:
        size = input.shape[-1] // 3
    name = name or "gru_unit"
    out_mem = L.memory(name=name, size=size, boot_layer=memory_boot)
    return L.gru_step_layer(input=input, output_mem=out_mem, size=size,
                            act=act, gate_act=gate_act, name=name,
                            bias_attr=gru_bias_attr,
                            param_attr=gru_param_attr)


def gru_group(input, memory_boot=None, size=None, name=None, reverse=False,
              gru_param_attr=None, act=None, gate_act=None,
              gru_bias_attr=None, gru_layer_attr=None, naive=False):
    """GRU over a sequence via recurrent_group (reference
    ``networks.py:1002``)."""
    name = name or "gru_group"

    def step(ipt):
        return gru_unit(input=ipt, memory_boot=memory_boot, name=name,
                        size=size, gru_param_attr=gru_param_attr, act=act,
                        gate_act=gate_act, gru_bias_attr=gru_bias_attr)

    return L.recurrent_group(step=step, input=input, reverse=reverse,
                             name=f"{name}_group")


def simple_gru2(input, size, name=None, reverse=False, mixed_param_attr=None,
                mixed_bias_attr=None, gru_param_attr=None,
                gru_bias_attr=None, act=None, gate_act=None,
                mixed_layer_attr=None, gru_cell_attr=None):
    """input projection + gru_group (reference ``networks.py:1163``)."""
    name = name or "simple_gru2"
    with L.mixed_layer(size=size * 3, name=f"{name}_transform",
                       bias_attr=mixed_bias_attr) as m:
        m += L.full_matrix_projection(input, param_attr=mixed_param_attr)
    return gru_group(input=m.output, size=size, name=name, reverse=reverse,
                     gru_param_attr=gru_param_attr,
                     gru_bias_attr=gru_bias_attr, act=act,
                     gate_act=gate_act)


def bidirectional_gru(input, size, name=None, return_seq=False,
                      fwd_mixed_param_attr=None, fwd_gru_param_attr=None,
                      bwd_mixed_param_attr=None, bwd_gru_param_attr=None,
                      **kwargs):
    """Forward + backward GRU, concat (reference ``networks.py:1226``):
    ``return_seq=False`` concatenates [fwd last, bwd first]."""
    name = name or "bidirectional_gru"
    fwd = simple_gru2(input=input, size=size, name=f"{name}_fwd",
                      mixed_param_attr=fwd_mixed_param_attr,
                      gru_param_attr=fwd_gru_param_attr)
    bwd = simple_gru2(input=input, size=size, name=f"{name}_bwd",
                      reverse=True, mixed_param_attr=bwd_mixed_param_attr,
                      gru_param_attr=bwd_gru_param_attr)
    if return_seq:
        return L.concat_layer(input=[fwd, bwd])
    return L.concat_layer(input=[L.last_seq(fwd), L.first_seq(bwd)])


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     transform_param_attr=None, softmax_param_attr=None,
                     weight_act=None, name=None):
    """Bahdanau additive attention (reference ``networks.py:1400``):
    scores = v . act(W s_{t-1} + U h_j); context = sum_j softmax_j * h_j.
    ``encoded_proj`` is U h_j precomputed outside the group."""
    name = name or "attention"
    proj_size = encoded_proj.shape[-1]
    with L.mixed_layer(size=proj_size, name=f"{name}_transform") as m:
        m += L.full_matrix_projection(decoder_state,
                                      param_attr=transform_param_attr)
    expanded = L.expand_layer(input=m.output, expand_as=encoded_sequence,
                              name=f"{name}_expand")
    with L.mixed_layer(size=proj_size, act=weight_act or "tanh",
                       name=f"{name}_combine") as m:
        m += L.identity_projection(expanded)
        m += L.identity_projection(encoded_proj)
    scores = L.fc_layer(input=m.output, size=1, act=None,
                        param_attr=softmax_param_attr, bias_attr=False,
                        name=f"{name}_score")
    attention_weight = F.sequence_softmax(scores)
    scaled = L.scaling_layer(input=encoded_sequence,
                             weight=attention_weight,
                             name=f"{name}_scaling")
    return L.pooling_layer(input=scaled, pooling_type=_SumPooling(),
                           name=f"{name}_pooling")


def dot_product_attention(encoded_sequence, attended_sequence,
                          transformed_state, softmax_param_attr=None,
                          name=None):
    """Dot-product attention (reference ``networks.py:1498``): scores are
    inner products of the (expanded) state with each encoder step."""
    name = name or "dot_attention"
    expanded = L.expand_layer(input=transformed_state,
                              expand_as=encoded_sequence,
                              name=f"{name}_expand")
    scores = L.dot_prod_layer(input1=expanded, input2=encoded_sequence,
                              name=f"{name}_score")
    attention_weight = F.sequence_softmax(scores)
    scaled = L.scaling_layer(input=attended_sequence,
                             weight=attention_weight,
                             name=f"{name}_scaling")
    return L.pooling_layer(input=scaled, pooling_type=_SumPooling(),
                           name=f"{name}_pooling")


def inputs(layers, *args):
    """Declare the config's input order (reference ``networks.py:1707``);
    feed order is by data-layer name here, so this is a no-op marker."""
    return None


def outputs(layers, *args):
    """Declare the config's output layers (reference ``networks.py:1725``);
    returns them so parse_config captures the targets."""
    from paddle_tpu.trainer_config_helpers.layers import _to_list
    outs = _to_list(layers) + list(args)
    return outs if len(outs) > 1 else outs[0]
