"""Composite network helpers (reference
``trainer_config_helpers/networks.py``)."""

from paddle_tpu.v2.networks import (  # noqa: F401
    simple_img_conv_pool, img_conv_group, sequence_conv_pool, simple_lstm,
    simple_gru, bidirectional_lstm)

__all__ = ["simple_img_conv_pool", "img_conv_group", "sequence_conv_pool",
           "simple_lstm", "simple_gru", "bidirectional_lstm"]
