"""Evaluator DSL (reference ``trainer_config_helpers/evaluators.py``,
813 LoC).  Each ``*_evaluator`` call emits the metric ops into the current
program and registers the fetchable outputs on the program
(``program._evaluators``) so trainers/tests can fetch them by name —
replacing the reference's Evaluator protobuf + C++ evaluator objects
(``paddle/gserver/evaluators/``)."""

from __future__ import annotations

import paddle_tpu.layers as F
from paddle_tpu.framework import default_main_program
from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "classification_error_evaluator", "auc_evaluator", "pnpair_evaluator",
    "precision_recall_evaluator", "ctc_error_evaluator", "chunk_evaluator",
    "sum_evaluator", "column_sum_evaluator", "detection_map_evaluator",
    "value_printer_evaluator", "gradient_printer_evaluator",
    "maxid_printer_evaluator", "maxframe_printer_evaluator",
    "seqtext_printer_evaluator", "classification_error_printer_evaluator",
]


def _register(name, outputs):
    """Attach {metric_name: Variable} to the program's evaluator table;
    returns the primary Variable (reference evaluator_base semantics:
    evaluators are config-side objects polled by the trainer loop).
    Colliding default names are uniquified (reference wrap_name_default)
    so two unnamed evaluators never shadow each other."""
    from paddle_tpu.framework import unique_name
    prog = default_main_program()
    if not hasattr(prog, "_evaluators"):
        prog._evaluators = {}
    if name in prog._evaluators:
        name = unique_name(name)
    prog._evaluators[name] = outputs
    return next(iter(outputs.values()))


def evaluators_of(program=None):
    """All evaluators registered while building ``program``."""
    prog = program or default_main_program()
    return getattr(prog, "_evaluators", {})


def classification_error_evaluator(input, label, name=None, weight=None,
                                   top_k=1, **kwargs):
    """Error rate = 1 - accuracy@k (reference ``evaluators.py:220`` over
    gserver ClassificationErrorEvaluator)."""
    acc = F.accuracy(input=input, label=label, k=top_k)
    err = F.scale(acc, scale=-1.0, bias=1.0)
    return _register(name or "classification_error_evaluator",
                     {"error": err})


def auc_evaluator(input, label, name=None, weight=None, **kwargs):
    """ROC AUC (reference ``evaluators.py:272`` over AucEvaluator)."""
    auc = F.auc(input=input, label=label)
    return _register(name or "auc_evaluator", {"auc": auc})


def pnpair_evaluator(input, label, query_id, weight=None, name=None,
                     **kwargs):
    """Positive-negative pair ratio per query (reference
    ``evaluators.py:306`` over PnpairEvaluator)."""
    helper = LayerHelper("positive_negative_pair")
    pos = helper.create_tmp_variable("float32")
    neg = helper.create_tmp_variable("float32")
    ratio = helper.create_tmp_variable("float32")
    helper.append_op(
        type="positive_negative_pair",
        inputs={"Score": [input], "Label": [label], "QueryID": [query_id]},
        outputs={"PositivePair": [pos], "NegativePair": [neg],
                 "NeutralPair": [ratio]})
    return _register(name or "pnpair_evaluator",
                     {"pos": pos, "neg": neg, "neutral": ratio})


def precision_recall_evaluator(input, label, positive_label=None,
                               weight=None, name=None, **kwargs):
    """Per-class precision/recall/F1 (reference ``evaluators.py:353`` over
    PrecisionRecallEvaluator)."""
    helper = LayerHelper("precision_recall")
    cls = input.shape[-1]
    metrics = helper.create_tmp_variable("float32")
    states = helper.create_tmp_variable("float32")
    helper.append_op(
        type="precision_recall",
        inputs={"MaxProbs": [F.reduce_max(input, dim=1, keep_dim=True)],
                "Indices": [F.argmax(input, axis=-1)], "Labels": [label]},
        outputs={"BatchMetrics": [metrics], "AccumMetrics": [states]},
        attrs={"class_number": cls})
    return _register(name or "precision_recall_evaluator",
                     {"metrics": metrics})


def ctc_error_evaluator(input, label, name=None, **kwargs):
    """Sequence edit-distance after CTC greedy decode (reference
    ``evaluators.py:398`` over CTCErrorEvaluator)."""
    decoded = F.ctc_greedy_decoder(input)
    helper = LayerHelper("edit_distance")
    dist = helper.create_tmp_variable("float32")
    seq_num = helper.create_tmp_variable("int64")
    helper.append_op(type="edit_distance",
                     inputs={"Hyps": [decoded], "Refs": [label]},
                     outputs={"Out": [dist], "SequenceNum": [seq_num]},
                     attrs={"normalized": True})
    return _register(name or "ctc_error_evaluator",
                     {"edit_distance": dist, "seq_num": seq_num})


def chunk_evaluator(input, label, chunk_scheme, num_chunk_types,
                    name=None, excluded_chunk_types=None, **kwargs):
    """Chunk precision/recall/F1 (reference ``evaluators.py:425`` over
    ChunkEvaluator)."""
    precision, recall, f1, n_infer, n_label, n_correct = F.chunk_eval(
        input=input, label=label, chunk_scheme=chunk_scheme,
        num_chunk_types=num_chunk_types,
        excluded_chunk_types=excluded_chunk_types)
    return _register(name or "chunk_evaluator",
                     {"precision": precision, "recall": recall, "f1": f1,
                      "num_infer": n_infer, "num_label": n_label,
                      "num_correct": n_correct})


def sum_evaluator(input, name=None, weight=None, **kwargs):
    """Sum of the input over the batch (reference ``evaluators.py:532``)."""
    return _register(name or "sum_evaluator",
                     {"sum": F.reduce_sum(input)})


def column_sum_evaluator(input, name=None, weight=None, **kwargs):
    """Per-column sums (reference ``evaluators.py:558``)."""
    return _register(name or "column_sum_evaluator",
                     {"column_sum": F.reduce_sum(input, dim=0)})


def detection_map_evaluator(input, label, overlap_threshold=0.5,
                            background_id=0, evaluate_difficult=False,
                            ap_type="11point", name=None, class_num=None,
                            **kwargs):
    """Detection mAP (reference ``evaluators.py:170`` over
    DetectionMAPEvaluator)."""
    from paddle_tpu.layers import detection as det
    m = det.detection_map(input, label, class_num=class_num or 21,
                          background_label=background_id,
                          overlap_threshold=overlap_threshold,
                          evaluate_difficult=evaluate_difficult,
                          ap_version=ap_type)
    return _register(name or "detection_map_evaluator", {"map": m})


# --- printer evaluators (reference ``evaluators.py:588-831``): each is a
# Print op on the relevant tensor, the TPU-side analog of the gserver
# printer evaluators which write to the trainer log ---------------------

def value_printer_evaluator(input, name=None, **kwargs):
    F.Print(input, message=name or "value_printer")
    return input


def gradient_printer_evaluator(input, name=None, **kwargs):
    from paddle_tpu.framework import grad_var_name
    F.Print(input, message=(name or "gradient_printer") +
            f" (grad of {input.name}: fetch {grad_var_name(input.name)})")
    return input


def maxid_printer_evaluator(input, name=None, **kwargs):
    F.Print(F.argmax(input, axis=-1), message=name or "maxid_printer")
    return input


def maxframe_printer_evaluator(input, name=None, **kwargs):
    F.Print(F.reduce_max(input, dim=-1), message=name or "maxframe_printer")
    return input


def seqtext_printer_evaluator(input, result_file=None, name=None, **kwargs):
    F.Print(input, message=name or "seqtext_printer")
    return input


def classification_error_printer_evaluator(input, label, name=None,
                                           **kwargs):
    acc = F.accuracy(input=input, label=label)
    F.Print(F.scale(acc, scale=-1.0, bias=1.0),
            message=name or "classification_error_printer")
    return input
