"""Activation declarations (reference
``trainer_config_helpers/activations.py``): legacy ``FooActivation``
names aliased onto the v2 activation classes (same objects)."""

from paddle_tpu.v2 import activation as _act
from paddle_tpu.v2.activation import BaseActivation  # noqa: F401

__all__ = ["BaseActivation"]

for _name in dir(_act):
    _cls = getattr(_act, _name)
    if isinstance(_cls, type) and issubclass(_cls, BaseActivation) and \
            _cls is not BaseActivation:
        _legacy = f"{_name}Activation"
        globals()[_legacy] = _cls
        __all__.append(_legacy)
# the reference also names identity "IdentityActivation"
if "LinearActivation" in globals():
    globals()["IdentityActivation"] = globals()["LinearActivation"]
    __all__.append("IdentityActivation")
