"""Attribute declarations (reference ``trainer_config_helpers/attrs.py``)."""

from paddle_tpu.v2.attr import ParamAttr, ExtraAttr  # noqa: F401

ParameterAttribute = ParamAttr
ExtraLayerAttribute = ExtraAttr

__all__ = ["ParamAttr", "ExtraAttr", "ParameterAttribute",
           "ExtraLayerAttribute"]
