"""Optimizers: build backward + update sub-graphs
(reference ``python/paddle/fluid/optimizer.py:34``: ``minimize:224`` =
append_backward + regularization + clip + per-param optimize ops +
accumulator creation).
"""

from __future__ import annotations

import contextlib
from collections import defaultdict

from paddle_tpu import framework
from paddle_tpu.framework import (Variable, default_main_program,
                                  default_startup_program, program_guard,
                                  unique_name)
from paddle_tpu.backward import append_backward
from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu import initializer as init_mod
from paddle_tpu.regularizer import append_regularization_ops
from paddle_tpu.clip import append_gradient_clip_ops, error_clip_callback

__all__ = [
    "SGD", "Momentum", "Adagrad", "Adam", "Adamax", "DecayedAdagrad",
    "Adadelta", "RMSProp", "Ftrl", "SGDOptimizer", "MomentumOptimizer",
    "AdagradOptimizer", "AdamOptimizer", "AdamaxOptimizer",
    "DecayedAdagradOptimizer", "AdadeltaOptimizer", "RMSPropOptimizer",
    "FtrlOptimizer", "Optimizer", "ModelAverage",
]


class Optimizer:
    """Base optimizer (reference ``optimizer.py:34``)."""

    def __init__(self, learning_rate, regularization=None, name=None):
        if not isinstance(learning_rate, (float, Variable)):
            raise TypeError("learning rate must be float or Variable")
        self._name = name
        self.regularization = regularization
        self._learning_rate = learning_rate
        self._learning_rate_map = {}
        self._accumulators = defaultdict(dict)
        self.helper = None

    def _create_global_learning_rate(self):
        program = default_main_program()
        lr = self._learning_rate_map.get(program)
        if lr is not None:
            return
        if isinstance(self._learning_rate, float):
            name = unique_name("learning_rate")
            var = self.helper.create_global_variable(
                name=name, persistable=True, dtype="float32", shape=[1])
            var.stop_gradient = True
            self.helper.set_variable_initializer(
                var, init_mod.Constant(float(self._learning_rate)))
            self._learning_rate_map[program] = var
        else:
            self._learning_rate_map[program] = self._learning_rate

    def _global_learning_rate(self, program=None):
        program = program or default_main_program()
        return self._learning_rate_map.get(program)

    def _create_param_lr(self, param_and_grad):
        param = param_and_grad[0]
        param_lr = getattr(param, "optimize_attr",
                           {"learning_rate": 1.0}).get("learning_rate", 1.0)
        base = self._global_learning_rate()
        if param_lr == 1.0:
            return base
        from paddle_tpu.layers import nn
        return nn.scale(base, scale=float(param_lr))

    # -- accumulators ------------------------------------------------------
    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0,
                         shape=None):
        if param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        assert self.helper is not None
        var = self.helper.create_global_variable(
            name=unique_name(".".join([name, param.name])),
            persistable=True, dtype=dtype or param.dtype,
            shape=shape or param.shape)
        var.stop_gradient = True
        self.helper.set_variable_initializer(
            var, init_mod.Constant(float(fill_value)))
        self._accumulators[name][param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    def _finish_update(self, block):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    # -- the main entry ----------------------------------------------------
    def _create_optimization_pass(self, parameters_and_grads, loss,
                                  startup_program=None):
        program = loss.block.program
        with program_guard(program, startup_program or
                           default_startup_program()):
            self.helper = LayerHelper(self.__class__.__name__)
            self._create_global_learning_rate()
            self._create_accumulators(
                loss.block, [p for p, g in parameters_and_grads
                             if g is not None and p.trainable])

            optimize_ops = []
            for param_and_grad in parameters_and_grads:
                if param_and_grad[1] is None or not param_and_grad[0].trainable:
                    continue
                optimize_ops.append(
                    self._append_optimize_op(loss.block, param_and_grad))
            self._finish_update(loss.block)
        return optimize_ops

    def _create_accumulators(self, block, parameters):
        pass

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        """reference ``optimizer.py:224``."""
        params_grads = append_backward(loss, parameter_list, no_grad_set,
                                       [error_clip_callback])
        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        optimize_ops = self._create_optimization_pass(params_grads, loss,
                                                      startup_program)
        return optimize_ops, params_grads


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0]],
                    "Grad": [param_and_grad[1]],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]]})


class MomentumOptimizer(Optimizer):
    _velocity_acc_str = "velocity"

    def __init__(self, learning_rate, momentum, use_nesterov=False, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.type = "momentum"
        self._momentum = momentum
        self._use_nesterov = bool(use_nesterov)

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._velocity_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        velocity_acc = self._get_accumulator(self._velocity_acc_str,
                                             param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0]],
                    "Grad": [param_and_grad[1]],
                    "Velocity": [velocity_acc],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "VelocityOut": [velocity_acc]},
            attrs={"mu": self._momentum,
                   "use_nesterov": self._use_nesterov})


class AdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.type = "adagrad"
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment_acc = self._get_accumulator(self._moment_acc_str,
                                           param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0]],
                    "Grad": [param_and_grad[1]],
                    "Moment": [moment_acc],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "MomentOut": [moment_acc]},
            attrs={"epsilon": self._epsilon})


class AdamOptimizer(Optimizer):
    _moment1_acc_str = "moment1"
    _moment2_acc_str = "moment2"
    _beta1_pow_acc_str = "beta1_pow_acc"
    _beta2_pow_acc_str = "beta2_pow_acc"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.type = "adam"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment1_acc_str, p)
            self._add_accumulator(self._moment2_acc_str, p)
            self._add_accumulator(self._beta1_pow_acc_str, p, shape=[1],
                                  fill_value=self._beta1)
            self._add_accumulator(self._beta2_pow_acc_str, p, shape=[1],
                                  fill_value=self._beta2)

    def _append_optimize_op(self, block, param_and_grad):
        p = param_and_grad[0]
        m1 = self._get_accumulator(self._moment1_acc_str, p)
        m2 = self._get_accumulator(self._moment2_acc_str, p)
        b1p = self._get_accumulator(self._beta1_pow_acc_str, p)
        b2p = self._get_accumulator(self._beta2_pow_acc_str, p)
        return block.append_op(
            type=self.type,
            inputs={"Param": [p], "Grad": [param_and_grad[1]],
                    "LearningRate": [self._create_param_lr(param_and_grad)],
                    "Moment1": [m1], "Moment2": [m2],
                    "Beta1Pow": [b1p], "Beta2Pow": [b2p]},
            outputs={"ParamOut": [p], "Moment1Out": [m1],
                     "Moment2Out": [m2], "Beta1PowOut": [b1p],
                     "Beta2PowOut": [b2p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})


class AdamaxOptimizer(Optimizer):
    _moment_acc_str = "moment"
    _inf_norm_acc_str = "inf_norm"
    _beta1_pow_acc_str = "beta1_pow_acc"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.type = "adamax"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)
            self._add_accumulator(self._inf_norm_acc_str, p)
            self._add_accumulator(self._beta1_pow_acc_str, p, shape=[1],
                                  fill_value=self._beta1)

    def _append_optimize_op(self, block, param_and_grad):
        p = param_and_grad[0]
        moment = self._get_accumulator(self._moment_acc_str, p)
        inf_norm = self._get_accumulator(self._inf_norm_acc_str, p)
        b1p = self._get_accumulator(self._beta1_pow_acc_str, p)
        return block.append_op(
            type=self.type,
            inputs={"Param": [p], "Grad": [param_and_grad[1]],
                    "LearningRate": [self._create_param_lr(param_and_grad)],
                    "Moment": [moment], "InfNorm": [inf_norm],
                    "Beta1Pow": [b1p]},
            outputs={"ParamOut": [p], "MomentOut": [moment],
                     "InfNormOut": [inf_norm], "Beta1PowOut": [b1p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})


class DecayedAdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.type = "decayed_adagrad"
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment_acc = self._get_accumulator(self._moment_acc_str,
                                           param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0]],
                    "Grad": [param_and_grad[1]],
                    "Moment": [moment_acc],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "MomentOut": [moment_acc]},
            attrs={"decay": self._decay, "epsilon": self._epsilon})


class AdadeltaOptimizer(Optimizer):
    _avg_squared_grad_acc_str = "_avg_squared_grad"
    _avg_squared_update_acc_str = "_avg_squared_update"

    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.type = "adadelta"
        self._epsilon = epsilon
        self._rho = rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._avg_squared_grad_acc_str, p)
            self._add_accumulator(self._avg_squared_update_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        asg = self._get_accumulator(self._avg_squared_grad_acc_str,
                                    param_and_grad[0])
        asu = self._get_accumulator(self._avg_squared_update_acc_str,
                                    param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0]],
                    "Grad": [param_and_grad[1]],
                    "AvgSquaredGrad": [asg], "AvgSquaredUpdate": [asu]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "AvgSquaredGradOut": [asg],
                     "AvgSquaredUpdateOut": [asu]},
            attrs={"epsilon": self._epsilon, "rho": self._rho})


class RMSPropOptimizer(Optimizer):
    _momentum_acc_str = "momentum"
    _mean_square_acc_str = "mean_square"

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.type = "rmsprop"
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._momentum_acc_str, p)
            self._add_accumulator(self._mean_square_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        momentum_acc = self._get_accumulator(self._momentum_acc_str,
                                             param_and_grad[0])
        mean_square_acc = self._get_accumulator(self._mean_square_acc_str,
                                                param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0]],
                    "Grad": [param_and_grad[1]],
                    "Moment": [momentum_acc],
                    "MeanSquare": [mean_square_acc],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "MomentOut": [momentum_acc],
                     "MeanSquareOut": [mean_square_acc]},
            attrs={"epsilon": self._epsilon, "decay": self._rho,
                   "momentum": self._momentum})


class FtrlOptimizer(Optimizer):
    _squared_acc_str = "squared"
    _linear_acc_str = "linear"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.type = "ftrl"
        self._l1 = l1
        self._l2 = l2
        self._lr_power = lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._squared_acc_str, p)
            self._add_accumulator(self._linear_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        squared_acc = self._get_accumulator(self._squared_acc_str,
                                            param_and_grad[0])
        linear_acc = self._get_accumulator(self._linear_acc_str,
                                           param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": [param_and_grad[0]],
                    "Grad": [param_and_grad[1]],
                    "SquaredAccumulator": [squared_acc],
                    "LinearAccumulator": [linear_acc],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param_and_grad[0]],
                     "SquaredAccumOut": [squared_acc],
                     "LinearAccumOut": [linear_acc]},
            attrs={"l1": self._l1, "l2": self._l2,
                   "lr_power": self._lr_power})


class ModelAverage(Optimizer):
    """Running average of parameters (reference ``optimizer.py:811``).

    Simplified TPU-native realization: maintains a sum accumulator and a
    count; ``apply()`` swaps averaged params in, ``restore()`` swaps back.
    """

    def __init__(self, average_window_rate=0.15, min_average_window=10000,
                 max_average_window=10000, **kwargs):
        super().__init__(0.0, **kwargs)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self.params_grads = []
        # append accumulate ops for every parameter of the current main
        # program (reference appends average_accumulates ops per param)
        block = framework.default_main_program().global_block()
        self._avg_names = {}
        for param in block.all_parameters():
            self._append_average_accumulate_op(param)

    def _append_average_accumulate_op(self, param):
        helper = LayerHelper("model_average")
        sum_acc = helper.create_global_variable(
            name=param.name + "@SUM_ACC", shape=param.shape,
            dtype=param.dtype, persistable=True)
        cnt_acc = helper.create_global_variable(
            name=param.name + "@CNT_ACC", shape=(1,), dtype="float32",
            persistable=True)
        helper.set_variable_initializer(sum_acc, init_mod.Constant(0.0))
        helper.set_variable_initializer(cnt_acc, init_mod.Constant(0.0))
        helper.append_op(
            type="average_accumulates",
            inputs={"Param": [param], "Sum": [sum_acc], "Count": [cnt_acc]},
            outputs={"SumOut": [sum_acc], "CountOut": [cnt_acc]},
            attrs={"max_average_window": self.max_average_window})
        self._avg_names[param.name] = (sum_acc.name, cnt_acc.name)

    @contextlib.contextmanager
    def apply(self, executor, need_restore=True):
        """Swap averaged parameter values in (reference ``optimizer.py:811``
        ModelAverage.apply context manager)."""
        import numpy as np
        from paddle_tpu.scope import global_scope
        scope = global_scope()
        backups = {}
        for pname, (sname, cname) in self._avg_names.items():
            p = scope.find_var(pname)
            s = scope.find_var(sname)
            c = scope.find_var(cname)
            if p is None or s is None or c is None:
                continue
            cnt = float(np.asarray(c).reshape(-1)[0])
            if cnt <= 0:
                continue
            backups[pname] = p
            scope.set_var(pname, (np.asarray(s) / cnt).astype(
                np.asarray(p).dtype))
        self._backups = backups
        try:
            yield
        finally:
            if need_restore:
                self.restore(executor)

    def restore(self, executor=None):
        """Swap the pre-average parameter values back in (reference
        ``optimizer.py`` ModelAverage.restore); used after
        ``apply(need_restore=False)``."""
        from paddle_tpu.scope import global_scope
        scope = global_scope()
        for pname, val in getattr(self, "_backups", {}).items():
            scope.set_var(pname, val)
        self._backups = {}


# naming parity with reference: both Foo and FooOptimizer exist
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
