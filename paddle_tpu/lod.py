"""Dynamic LoD: bounded-recompile handling of streaming ragged batches.

The static design keys every distinct LoD into the jit cache
(``executor._signature``) — exact and fast for repeating shapes, but a
streaming corpus where every batch has new sequence lengths would compile
per step (VERDICT r1 weak #4).  This module adds the BUCKETED mode
(``PADDLE_TPU_LOD_BUCKETS=1`` or ``program.lod_buckets = True``):

* the feed's row count and max sequence length are rounded UP to a small
  bucket set (powers of two), values zero-padded to the bucket;
* the row-splits themselves become a RUNTIME int32 tensor fed alongside
  the values (``<name>@lod0``), so the compiled executable is keyed only
  by ``(rows_bucket, seq_count, maxlen_bucket)`` — O(log max_len)
  executables for an arbitrary corpus;
* sequence-op lowerings detect a :class:`DynLoD` in the aux lod table and
  build their gather/segment tables as traced jnp computations instead of
  trace-time numpy (see ``ops/sequence_ops.py`` / ``ops/rnn_ops.py``).

Batch SIZE (sequence count) is not bucketed — dense companion feeds
(labels) fix it anyway; lengths within the batch ride the buckets.
"""

from __future__ import annotations

import numpy as np

__all__ = ["DynLoD", "next_bucket", "row_bucket", "bucket_edges",
           "select_bucket_edges", "bucket_ragged_feed", "pad_to_bucket",
           "SPLITS_SUFFIX"]

SPLITS_SUFFIX = "@lod0"

_MIN_BUCKET = 8


def next_bucket(n):
    """Smallest power-of-two bucket >= n (min 8)."""
    b = _MIN_BUCKET
    while b < n:
        b *= 2
    return b


def row_bucket(n, edges=None):
    """Round a row count up to a stable jit-cache bucket.

    ``edges``: optional sorted iterable of custom bucket edges (the
    serving batcher's knob); counts past the largest edge fall back to
    the power-of-two ladder so the key stays bounded either way."""
    n = max(int(n), 1)
    if edges:
        for e in edges:
            if n <= int(e):
                return int(e)
    return next_bucket(n)


def bucket_edges(lo, hi, edges=None):
    """The distinct buckets covering row counts in [lo, hi] — what a
    server warms up ahead of time so no real request compiles."""
    out = []
    for n in range(max(int(lo), 1), max(int(hi), 1) + 1):
        b = row_bucket(n, edges)
        if not out or b != out[-1]:
            out.append(b)
    return out


def select_bucket_edges(counts, max_edges=4, cost_of=None):
    """Cost-optimal bucket edges for an OBSERVED size distribution.

    ``counts``: observed row counts / lengths (an iterable, repeats =
    frequency).  ``cost_of(edge) -> cost`` prices one dispatch padded
    to ``edge`` — pass :func:`paddle_tpu.analysis.cost.row_cost_fn`'s
    result to price in static FLOPs of the actual program (the
    ISSUE-15 wiring); default is the padded size itself.  Chooses at
    most ``max_edges`` edges (each an observed value — padding to a
    size nothing reaches is never optimal) minimizing the total padded
    cost ``sum_n freq(n) * cost_of(edge(n))``, by interval dynamic
    programming.  Returns a sorted edge list for
    :func:`row_bucket`/:func:`bucket_edges`; sizes past the largest
    edge still fall back to the power-of-two ladder there, so the jit
    key stays bounded regardless."""
    freq = {}
    for n in counts:
        n = max(int(n), 1)
        freq[n] = freq.get(n, 0) + 1
    if not freq:
        return []
    values = sorted(freq)
    cost_of = cost_of or (lambda e: float(e))
    k = min(int(max_edges), len(values))
    # interval DP: cost(i, j) = all observations in values[i..j] pad to
    # values[j]; best[j][e] = min total cost covering values[0..j] with
    # e edges, the last at values[j]
    m = len(values)
    pad = [[0.0] * m for _ in range(m)]
    for j in range(m):
        c = float(cost_of(values[j]))
        acc = 0.0
        for i in range(j, -1, -1):
            acc += freq[values[i]] * c
            pad[i][j] = acc
    INF = float("inf")
    best = [[INF] * (k + 1) for _ in range(m)]
    choice = [[None] * (k + 1) for _ in range(m)]
    for j in range(m):
        best[j][1] = pad[0][j]
    for e in range(2, k + 1):
        for j in range(e - 1, m):
            for i in range(e - 2, j):
                c = best[i][e - 1] + pad[i + 1][j]
                if c < best[j][e]:
                    best[j][e] = c
                    choice[j][e] = i
    e = min(range(1, k + 1), key=lambda e: best[m - 1][e])
    edges = []
    j = m - 1
    while e >= 1:
        edges.append(values[j])
        prev = choice[j][e]
        if e == 1 or prev is None:
            break
        j, e = prev, e - 1
    return sorted(edges)


def pad_to_bucket(value, bucket, axis=0):
    """Zero-pad ``value`` along ``axis`` up to ``bucket`` entries (a
    no-op when already that size).  The shared padding idiom behind
    every bucketed feed: serving's row-bucketed micro-batches and the
    generation runtime's bucketed prompt prefill."""
    value = np.asarray(value)
    n = value.shape[axis]
    if n > bucket:
        raise ValueError(
            f"cannot pad {n} entries down into a bucket of {bucket}")
    if n == bucket:
        return value
    shape = list(value.shape)
    shape[axis] = bucket - n
    return np.concatenate(
        [value, np.zeros(shape, value.dtype)], axis=axis)


class DynLoD:
    """Marker in the aux lod table: the variable's row-splits live in the
    env under ``splits_name`` ([num_seqs+1] int32; splits[-1] = real row
    count, rows beyond it are zero padding)."""

    def __init__(self, splits_name, num_seqs, maxlen_bucket):
        self.splits_name = splits_name
        self.num_seqs = int(num_seqs)          # static (batch size)
        self.maxlen_bucket = int(maxlen_bucket)  # static T bound

    def splits(self, env):
        return env[self.splits_name]

    def key(self):
        return ("dyn", self.splits_name, self.num_seqs, self.maxlen_bucket)

    def __repr__(self):
        return (f"DynLoD({self.splits_name}, B={self.num_seqs}, "
                f"T<={self.maxlen_bucket})")

    # ops without a dynamic branch treat the lod as a nested list
    # (len/index/iterate); fail those with a recipe, not a TypeError
    def _unsupported(self):
        raise NotImplementedError(
            "this sequence op does not support bucketed dynamic LoD "
            "(PADDLE_TPU_LOD_BUCKETS / program.lod_buckets) yet — run it "
            "with exact static LoD, or keep it out of the bucketed "
            "program")

    def __len__(self):
        self._unsupported()

    def __getitem__(self, i):
        self._unsupported()

    def __iter__(self):
        self._unsupported()


def bucket_ragged_feed(name, value, lod, n_bucket=None, t_bucket=None):
    """(value [N, ...], single-level lod) -> (padded value [N_b, ...],
    splits int32 [B+1], meta tuple for the scope lod slot).

    ``n_bucket``/``t_bucket`` force a common bucket — run_steps pads a
    WINDOW of per-step batches to one signature so the whole window
    rides one executable."""
    splits = np.asarray(lod[-1], dtype=np.int64)
    n = int(splits[-1])
    if value.shape[0] != n:
        raise ValueError(
            f"feed {name!r}: lod rows {n} != value rows {value.shape[0]}")
    lengths = splits[1:] - splits[:-1]
    maxlen = int(lengths.max()) if len(lengths) else 0
    if n_bucket is None:
        n_bucket = next_bucket(max(n, 1))
    if t_bucket is None:
        t_bucket = next_bucket(max(maxlen, 1))
    padded = np.zeros((n_bucket,) + value.shape[1:], dtype=value.dtype)
    padded[:n] = value
    meta = ("dyn", len(splits) - 1, t_bucket)
    return padded, splits.astype(np.int32), meta
