"""Reader creators/decorators (reference ``python/paddle/reader/``)."""

from paddle_tpu.reader.decorator import (
    map_readers, buffered, compose, chain, shuffle, firstn, xmap_readers,
    cache)
from paddle_tpu.reader import creator
from paddle_tpu.reader import decorator

__all__ = ["map_readers", "buffered", "compose", "chain", "shuffle",
           "firstn", "xmap_readers", "cache", "creator", "decorator"]
