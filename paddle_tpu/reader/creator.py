"""Reader creators (reference ``python/paddle/reader/creator.py``)."""

from __future__ import annotations

import os

import numpy as np

__all__ = ["np_array", "text_file", "recordio"]


def np_array(x):
    def reader():
        if x.ndim < 1:
            yield x
        for e in x:
            yield e
    return reader


def text_file(path):
    def reader():
        with open(path) as f:
            for l in f:
                yield l.rstrip("\n")
    return reader


def recordio(paths, buf_size=100):
    """Read from recordio files (native reader in paddle_tpu.recordio)."""
    from paddle_tpu.recordio import RecordIOReader

    def reader():
        if isinstance(paths, str):
            path_list = paths.split(",")
        else:
            path_list = list(paths)
        for path in path_list:
            with RecordIOReader(path) as r:
                yield from r
    return reader
