"""Reader creators (reference ``python/paddle/reader/creator.py``)."""

from __future__ import annotations

import os

import numpy as np

__all__ = ["np_array", "text_file", "recordio", "cloud_reader"]


def np_array(x):
    def reader():
        if x.ndim < 1:
            yield x
        for e in x:
            yield e
    return reader


def text_file(path):
    def reader():
        with open(path) as f:
            for l in f:
                yield l.rstrip("\n")
    return reader


def recordio(paths, buf_size=100):
    """Read pickled samples from recordio files (native scanner)."""
    import pickle

    from paddle_tpu.recordio_writer import RecordIOScanner

    def reader():
        if isinstance(paths, str):
            path_list = paths.split(",")
        else:
            path_list = list(paths)
        for path in path_list:
            for rec in RecordIOScanner(path):
                yield pickle.loads(rec)
    return reader


def cloud_reader(master_addr, pass_num=1, timeout=30.0):
    """Fault-tolerant cluster reader: lease record-file tasks from the
    master service, read them, report completion (reference
    ``python/paddle/v2/reader/creator.py`` cloud_reader over the etcd
    master client, ``v2/master/client.py:29``).  A task whose read fails
    is reported failed and will be re-leased (to this or another
    trainer) up to the master's failure_max."""
    import pickle
    import time

    from paddle_tpu.parallel.master import MasterClient
    from paddle_tpu.recordio_writer import RecordIOScanner

    def reader():
        client = MasterClient(master_addr, timeout=timeout)
        try:
            for pass_idx in range(pass_num):
                if pass_idx > 0:
                    # re-seed the drained queue (single-coordinator pass
                    # semantics: this reader drives the epoch boundary)
                    client.reset_pass()
                while True:
                    task = client.get_task()
                    if task is None:
                        if client.all_done():
                            break
                        time.sleep(0.05)  # tasks may return via timeout
                        continue
                    try:
                        for path in task.chunks:
                            for rec in RecordIOScanner(path):
                                yield pickle.loads(rec)
                    except GeneratorExit:
                        raise
                    except Exception:
                        # report + continue: the master re-leases the task
                        # (up to failure_max) to this or another trainer
                        client.task_failed(task.id, task.epoch)
                        continue
                    client.task_finished(task.id, task.epoch)
        finally:
            client.close()
    return reader
