"""Reader decorators.

A *reader* is a nullary callable returning an iterable of samples — the
reference's data-pipeline protocol (``python/paddle/reader/decorator.py``
declares the same surface).  Each decorator here wraps one reader (or
several) and returns a new reader; the threaded ones (``buffered``,
``xmap_readers``) use a shared ``_STOP`` sentinel plus bounded queues,
and ordered ``xmap_readers`` re-sequences results with a heap on the
consumer side instead of busy-waiting in the workers.
"""

from __future__ import annotations

import heapq
import itertools
import queue
import random
import threading

from paddle_tpu.fault import chaos as _chaos

__all__ = ["map_readers", "buffered", "compose", "chain", "shuffle",
           "firstn", "xmap_readers", "cache"]

# end-of-stream marker shared by the threaded decorators (identity
# compared, so samples can be anything — including numpy arrays)
_STOP = object()


def _put_unless(abandoned, q, item, timeout=0.1):
    """Queue ``item``, polling so a producer blocked on a full queue
    notices the consumer abandoning the stream; False once abandoned."""
    while not abandoned.is_set():
        try:
            q.put(item, timeout=timeout)
            return True
        except queue.Full:
            continue
    return False


class _Raised:
    """A producer/worker exception, carried through the queue so it
    re-raises on the CONSUMER side instead of vanishing in a daemon
    thread (which would read as a clean, silently-truncated stream)."""

    def __init__(self, exc):
        self.exc = exc


def map_readers(func, *readers):
    """``func`` applied elementwise across the readers' parallel streams."""

    def _read():
        yield from map(func, *(r() for r in readers))

    return _read


def shuffle(reader, buf_size):
    """Local shuffling: collect a window of ``buf_size`` samples, emit it
    in random order, repeat; the tail window is shuffled too."""

    def _read():
        window = []
        for sample in reader():
            window.append(sample)
            if len(window) == buf_size:
                random.shuffle(window)
                yield from window
                window = []
        random.shuffle(window)
        yield from window

    return _read


def chain(*readers):
    """All samples of the first reader, then the second, and so on."""

    def _read():
        for r in readers:
            yield from r()

    return _read


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    """Zip several readers into one: each output sample is the
    concatenation of one (tuple-ified) sample from every input.  With
    ``check_alignment=True`` (default) a length mismatch raises
    :class:`ComposeNotAligned`; otherwise the shortest stream wins."""
    check_alignment = kwargs.pop("check_alignment", True)

    def _as_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def _read():
        streams = [r() for r in readers]
        if check_alignment:
            groups = itertools.zip_longest(*streams, fillvalue=_STOP)
        else:
            groups = zip(*streams)
        for group in groups:
            if any(s is _STOP for s in group):
                raise ComposeNotAligned(
                    "composed readers produced streams of different "
                    "lengths")
            yield tuple(itertools.chain.from_iterable(
                map(_as_tuple, group)))

    return _read


def buffered(reader, size):
    """Decouple producer from consumer: a daemon thread pumps the wrapped
    reader into a queue bounded at ``size`` samples, hiding producer
    latency behind consumption.

    A consumer that abandons iteration early (breaks out, drops the
    generator) closes it, which flips the ``abandoned`` event — the pump
    thread sees it at its next (timeout-polled) ``put`` and exits
    instead of blocking forever on the full queue."""

    def _read():
        q = queue.Queue(maxsize=size)
        abandoned = threading.Event()

        def pump():
            try:
                for sample in reader():
                    _chaos.fire("reader.pump")
                    if not _put_unless(abandoned, q, sample):
                        return
            except BaseException as e:  # re-raised consumer-side
                _put_unless(abandoned, q, _Raised(e))
            else:
                _put_unless(abandoned, q, _STOP)

        threading.Thread(target=pump, daemon=True).start()
        try:
            while True:
                sample = q.get()
                if sample is _STOP:
                    return
                if isinstance(sample, _Raised):
                    raise sample.exc
                yield sample
        finally:
            abandoned.set()
            try:  # unblock a put stuck on the (bounded) queue right now
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass

    return _read


def firstn(reader, n):
    """Only the first ``n`` samples."""

    def _read():
        return itertools.islice(reader(), n)

    return _read


def cache(reader):
    """Materialize the stream on first full pass; replay from memory on
    every later pass.  (A pass abandoned midway is not cached.)"""
    memo = []
    complete = [False]

    def _read():
        if complete[0]:
            yield from memo
            return
        fresh = []
        for sample in reader():
            fresh.append(sample)
            yield sample
        memo[:] = fresh
        complete[0] = True

    return _read


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Run ``mapper`` over the stream on ``process_num`` worker threads.

    Every sample is tagged with its position; with ``order=True`` the
    consumer re-sequences results through a min-heap keyed on that
    position (workers never wait on each other).  Total in-flight
    samples — queues, worker hands, and the re-sequencing heap — are
    bounded by a sliding window of ``2 * buffer_size + process_num``
    un-yielded samples, enforced at the feeder.  Exceptions from the
    reader or the mapper re-raise on the consumer side.
    """

    def _read():
        inq = queue.Queue(maxsize=buffer_size)
        outq = queue.Queue()     # bounded by the window semaphore
        window = threading.Semaphore(2 * buffer_size + process_num)
        # flipped when the consumer abandons the generator: the feeder's
        # window.acquire/inq.put and the workers' loops poll it so no
        # thread is left blocked forever on a stream nobody reads
        abandoned = threading.Event()

        def feed():
            try:
                for tagged in enumerate(reader()):
                    while not window.acquire(timeout=0.1):
                        if abandoned.is_set():
                            return
                    if not _put_unless(abandoned, inq, tagged):
                        return
            except BaseException as e:
                outq.put(_Raised(e))
            finally:
                for _ in range(process_num):
                    if not _put_unless(abandoned, inq, _STOP):
                        break

        def work():
            try:
                while not abandoned.is_set():
                    try:
                        item = inq.get(timeout=0.1)
                    except queue.Empty:
                        continue
                    if item is _STOP:
                        return
                    pos, sample = item
                    _chaos.fire("reader.worker")
                    outq.put((pos, mapper(sample)))
            except BaseException as e:
                outq.put(_Raised(e))
            finally:
                outq.put(_STOP)

        for target in [feed] + [work] * process_num:
            threading.Thread(target=target, daemon=True).start()

        def drain():
            item = outq.get()
            if isinstance(item, _Raised):
                raise item.exc
            return item

        try:
            live_workers = process_num
            if not order:
                while live_workers:
                    item = drain()
                    if item is _STOP:
                        live_workers -= 1
                    else:
                        window.release()
                        yield item[1]
                return

            ahead = []          # results that arrived before their turn
            next_pos = 0
            while live_workers or ahead:
                if ahead and ahead[0][0] == next_pos:
                    window.release()
                    yield heapq.heappop(ahead)[1]
                    next_pos += 1
                else:
                    item = drain()
                    if item is _STOP:
                        live_workers -= 1
                    else:
                        heapq.heappush(ahead, item)
        finally:
            abandoned.set()

    return _read
