"""paddle_tpu: a TPU-native deep-learning framework with the capabilities of
2018-era PaddlePaddle (Fluid + v2), re-designed for JAX/XLA/Pallas/pjit.

Public API mirrors ``python/paddle/fluid/__init__.py`` of the reference:
Program/Block IR built by a layers DSL, IR-level autodiff and graph-op
optimizers, an Executor that compiles whole blocks to single XLA
computations, and mesh-sharded data/model parallelism in place of
NCCL/pserver distribution.
"""

from paddle_tpu import framework
from paddle_tpu.framework import (
    Program, Block, Operator, Variable, Parameter,
    default_main_program, default_startup_program, program_guard,
    switch_main_program, switch_startup_program, unique_name,
)
from paddle_tpu.place import CPUPlace, TPUPlace, CUDAPlace, is_tpu_available
from paddle_tpu.scope import Scope, global_scope, scope_guard
from paddle_tpu import ops  # registers all op lowerings
from paddle_tpu.executor import Executor, fetch_var
from paddle_tpu.ops.reader_ops import EOFException
from paddle_tpu import memory_optimization_transpiler
from paddle_tpu.memory_optimization_transpiler import (memory_optimize,
                                                       release_memory)
from paddle_tpu import v2
from paddle_tpu import pydataprovider2
from paddle_tpu import concurrency
from paddle_tpu.concurrency import (Go, Select, make_channel, channel_send,
                                    channel_recv, channel_close)
from paddle_tpu.channel import Channel as CSPChannel, ChannelClosedError
from paddle_tpu.backward import append_backward, calc_gradient
from paddle_tpu import initializer
from paddle_tpu.param_attr import ParamAttr, WeightNormParamAttr
from paddle_tpu import layers
from paddle_tpu import nets
from paddle_tpu import optimizer
from paddle_tpu.optimizer import (
    SGD, Momentum, Adagrad, Adam, Adamax, DecayedAdagrad, Adadelta, RMSProp,
    Ftrl, SGDOptimizer, MomentumOptimizer, AdagradOptimizer, AdamOptimizer,
    AdamaxOptimizer, DecayedAdagradOptimizer, AdadeltaOptimizer,
    RMSPropOptimizer, FtrlOptimizer, ModelAverage,
)
from paddle_tpu import regularizer
from paddle_tpu import clip
from paddle_tpu import metrics
from paddle_tpu import evaluator
from paddle_tpu import profiler
from paddle_tpu.data_feeder import DataFeeder
from paddle_tpu import io
from paddle_tpu.io import (
    save_vars, save_params, save_persistables, load_vars, load_params,
    load_persistables, save_inference_model, load_inference_model,
)
from paddle_tpu.parallel import ParallelExecutor
from paddle_tpu import parallel
from paddle_tpu import reader
from paddle_tpu import dataset
from paddle_tpu import fault
from paddle_tpu import datapipe
from paddle_tpu import obs
from paddle_tpu import analysis

__version__ = "0.1.0"

Tensor = Variable  # convenience alias


def __getattr__(name):
    # deprecated modules import (and warn) only on first touch, so a
    # plain `import paddle_tpu` stays warning-free
    if name == "debuger":
        import importlib
        return importlib.import_module("paddle_tpu.debuger")
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")
