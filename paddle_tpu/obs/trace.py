"""Span-based structured tracing: the "where did step N spend its time"
layer.

Dapper-style spans (trace id propagated across threads and process
boundaries, parent/child nesting via ``contextvars``) recorded into a
thread-safe bounded ring buffer, exported in the Chrome trace-event JSON
convention (``chrome://tracing`` / Perfetto / ``ui.perfetto.dev`` load
the dump directly) — the same convention ``profiler.iter_trace_events``
already parses on the XProf side.

Design constraints, in order:

1. **Near-zero cost when disabled.**  ``span(...)`` is one module-global
   read + one shared no-op object when tracing is off — no allocation,
   no contextvar traffic, no lock.  Hot loops (``Executor.run``, the
   datapipe pull path, the serving batcher) stay instrumented
   permanently.
2. **Bounded memory.**  Spans land in a ``deque(maxlen=ring)``; a
   week-long trainer holds the last N spans, which is exactly what the
   flight recorder wants on a crash.
3. **Cross-boundary context.**  A trace id set with
   :func:`trace_context` (serving does this per ``X-Request-Id``;
   ``MasterClient`` ships it in the RPC frame) tags every span recorded
   under it, including spans recorded on OTHER threads via
   :func:`record_span` — how a batched request's queue-wait, dispatch,
   and scatter stitch back into one timeline.

Enable with ``PADDLE_TPU_TRACE=1`` (default ring 4096 spans) or
``PADDLE_TPU_TRACE=<ring-size>``; ``0``/empty disables.  Programmatic:
:func:`enable` / :func:`disable`.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import os
import threading
import time
import collections

__all__ = ["span", "record_span", "enable", "disable", "enabled",
           "configure_from_env", "trace_context", "current_trace_id",
           "new_trace_id", "snapshot_spans", "snapshot_payload", "clear",
           "chrome_trace", "dump_chrome_trace", "set_process_name",
           "process_name", "epoch_unix", "DEFAULT_RING"]

DEFAULT_RING = 4096

# one steady clock for every span: ts/dur subtract against this epoch so
# nesting math (child inside parent interval) is exact within a process
_EPOCH = time.perf_counter()

# human-readable process role for merged fleet timelines ("router",
# "replica:r0", ...); None renders as "pid <pid>" in the Chrome export
_proc_name = None


def set_process_name(name):
    """Name this process's timeline row in merged fleet traces.  The
    first caller wins by default (a FleetReplica must not rename a
    process the operator already labelled); pass ``name=None`` to
    clear."""
    global _proc_name
    if name is None:
        _proc_name = None
    elif _proc_name is None:
        _proc_name = str(name)
    return _proc_name


def process_name():
    return _proc_name


def epoch_unix():
    """Wall-clock time (``time.time()``) of this process's trace epoch:
    ``epoch_unix() + span["ts"]`` is a span's absolute start time, the
    anchor cross-process assembly normalizes clock skew against."""
    return time.time() - (time.perf_counter() - _EPOCH)

_current_span = contextvars.ContextVar("paddle_tpu_span", default=None)
_ambient_trace = contextvars.ContextVar("paddle_tpu_trace_id",
                                        default=None)

_span_ids = itertools.count(1)
_trace_seq = itertools.count(1)
_lock = threading.Lock()
_ring = collections.deque(maxlen=DEFAULT_RING)
_enabled = False


def new_trace_id():
    """Process-unique trace id (pid-prefixed so ids from different
    processes of one job never collide in a merged timeline)."""
    return f"{os.getpid():x}-{next(_trace_seq):x}-{os.urandom(4).hex()}"


def current_trace_id():
    """Trace id of the innermost active span, else the ambient id set by
    :func:`trace_context`, else None."""
    sp = _current_span.get()
    if sp is not None:
        return sp.trace_id
    return _ambient_trace.get()


@contextlib.contextmanager
def trace_context(trace_id):
    """Bind an ambient trace id (e.g. an ``X-Request-Id``): spans opened
    inside — on this thread/context — join that trace."""
    token = _ambient_trace.set(trace_id)
    try:
        yield trace_id
    finally:
        _ambient_trace.reset(token)


class _NoopSpan:
    """Shared do-nothing span returned while tracing is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("name", "attrs", "trace_id", "span_id", "parent_id",
                 "t0", "dur", "tid", "_token")

    def __init__(self, name, attrs):
        self.name = name
        self.attrs = attrs
        self.span_id = next(_span_ids)
        self.parent_id = None
        self.trace_id = None
        self.t0 = 0.0
        self.dur = 0.0
        self.tid = 0
        self._token = None

    def set(self, **attrs):
        """Attach/override attributes mid-span."""
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        parent = _current_span.get()
        if parent is not None:
            self.trace_id = parent.trace_id
            self.parent_id = parent.span_id
        else:
            self.trace_id = _ambient_trace.get() or new_trace_id()
        self._token = _current_span.set(self)
        self.tid = threading.get_ident()
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.dur = time.perf_counter() - self.t0
        if self._token is not None:
            _current_span.reset(self._token)
        if exc_type is not None:
            # time under failure is still attributed — tagged, never
            # swallowed or misfiled (same contract as record_latency)
            self.attrs["error"] = True
            self.attrs.setdefault("error_type", exc_type.__name__)
        _ring.append(self)
        return False


def span(name, **attrs):
    """Open a span: ``with span("executor.dispatch", step=i): ...``.

    Returns a shared no-op object when tracing is disabled — the check
    is one global read, so this belongs in hot paths.  The yielded span
    supports ``.set(key=value)`` for attributes known only mid-body.
    """
    if not _enabled:
        return _NOOP
    return _Span(name, attrs)


def record_span(name, t0, dur, trace_id=None, parent_id=None, **attrs):
    """Record an already-measured interval (``t0`` from
    ``time.perf_counter()``): for cross-thread measurements like a
    request's queue wait, where enter/exit happen on different threads.
    No-op while disabled.

    With no explicit ``trace_id`` and no ambient context the span's
    trace id stays None (it still renders on its thread timeline) —
    minting a fresh id here would cost a syscall per sample on the
    datapipe pull path and correlate nothing."""
    if not _enabled:
        return None
    sp = _Span(name, attrs)
    sp.trace_id = trace_id or current_trace_id()
    sp.parent_id = parent_id
    sp.t0 = t0
    sp.dur = dur
    sp.tid = threading.get_ident()
    _ring.append(sp)
    return sp


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------

def enable(ring_size=None):
    """Turn tracing on; ``ring_size`` (spans kept) rebuilds the ring
    when it differs from the current bound."""
    global _enabled, _ring
    with _lock:
        if ring_size is not None and int(ring_size) != _ring.maxlen:
            _ring = collections.deque(_ring, maxlen=max(1, int(ring_size)))
        _enabled = True


def disable():
    global _enabled
    _enabled = False


def enabled():
    return _enabled


def clear():
    """Drop recorded spans (tests; ring bound and enabled flag kept)."""
    _ring.clear()


def configure_from_env(value=None):
    """Parse ``PADDLE_TPU_TRACE``: ``0``/empty/false = off, ``1``/true =
    on with the default ring, an integer > 1 = on with that ring size.
    A malformed value WARNS and disables — an observability knob must
    never veto ``import paddle_tpu`` (this runs at import)."""
    raw = (value if value is not None
           else os.environ.get("PADDLE_TPU_TRACE", "")).strip().lower()
    if raw in ("", "0", "false", "off", "no"):
        disable()
        return False
    if raw in ("1", "true", "on", "yes"):
        enable(DEFAULT_RING)
        return True
    try:
        size = int(raw)
    except ValueError:
        import warnings
        warnings.warn(
            f"PADDLE_TPU_TRACE={raw!r} is not 0, 1, or a ring size — "
            f"tracing stays disabled")
        disable()
        return False
    enable(size)
    return True


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------

def snapshot_spans():
    """Recorded spans, oldest first, as JSON-able dicts.  ``ts``/``dur``
    are seconds relative to the process trace epoch; every dict carries
    the recording process's ``pid`` (and ``proc`` role name) so span
    lists from several processes stay self-describing when merged."""
    spans = list(_ring)  # atomic under the GIL; appends during the copy
    # land in later snapshots
    pid = os.getpid()
    return [{"name": sp.name, "trace_id": sp.trace_id,
             "span_id": sp.span_id, "parent_id": sp.parent_id,
             "ts": sp.t0 - _EPOCH, "dur": sp.dur, "tid": sp.tid,
             "pid": pid, "proc": _proc_name,
             "attrs": dict(sp.attrs)} for sp in spans]


def snapshot_payload():
    """The ``/spans`` scrape body: this process's span ring plus the
    identity and clock anchors cross-process trace assembly needs —
    ``pid``/``process_name`` pick the timeline row, ``epoch_unix``
    converts span ``ts`` to absolute time, and ``now_unix`` (this
    process's wall clock at serialization) lets the scraper estimate
    clock skew against its own send/recv envelope."""
    return {"pid": os.getpid(), "process_name": _proc_name,
            "epoch_unix": epoch_unix(), "now_unix": time.time(),
            "spans": snapshot_spans()}


def chrome_trace(spans=None):
    """Chrome trace-event JSON object (Perfetto-loadable): complete
    ``ph: "X"`` events with microsecond ``ts``/``dur``, one ``tid`` row
    per recording thread, span attributes + ids under ``args``.

    Each span's OWN ``pid`` is honored (falling back to this process),
    and every distinct pid gets a ``process_name`` metadata event — so a
    merged fleet span list renders one labelled row group per process
    instead of interleaving every process into this one's."""
    if spans is None:
        spans = snapshot_spans()
    own_pid = os.getpid()
    events = []
    proc_names = {}  # pid -> process_name metadata value
    for sp in spans:
        args = dict(sp["attrs"])
        if sp["trace_id"] is not None:
            args["trace_id"] = sp["trace_id"]
        args["span_id"] = sp["span_id"]
        if sp["parent_id"] is not None:
            args["parent_id"] = sp["parent_id"]
        pid = sp.get("pid") or own_pid
        proc = sp.get("proc") or (_proc_name if pid == own_pid else None)
        if proc or pid not in proc_names:
            proc_names[pid] = proc or f"pid {pid}"
        events.append({"name": sp["name"], "ph": "X", "cat": "paddle_tpu",
                       "ts": sp["ts"] * 1e6, "dur": sp["dur"] * 1e6,
                       "pid": pid, "tid": sp["tid"], "args": args})
    meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": name}}
            for pid, name in sorted(proc_names.items())]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def dump_chrome_trace(path=None, spans=None):
    """Serialize :func:`chrome_trace` to ``path`` (atomic: tmp +
    rename), or return the JSON string when ``path`` is None."""
    body = json.dumps(chrome_trace(spans))
    if path is None:
        return body
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(body)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


configure_from_env()
