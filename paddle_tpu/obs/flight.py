"""Flight recorder: "what was the process doing when it died".

On a crash — an uncaught exception, a :class:`fault.GracefulShutdown`
signal, a chaos-failpoint hard kill, or a ``fault.Sentinel`` rollback
(the numerical-fault analog of a crash: the run survived, the state
did not) — the recorder atomically writes a post-mortem JSON file
holding the last N spans from the trace ring plus a full
``RuntimeMetrics.snapshot()``.  The span tail reconstructs
the final step's phase timeline (feed/dispatch/fetch, datapipe pulls,
checkpoint commits); the metrics snapshot carries the counters the
grafana board would have shown at the moment of death.

Arming: set ``PADDLE_TPU_POSTMORTEM`` to a file path (or a directory —
the file becomes ``postmortem-<pid>.json`` inside it).  The fault layer
calls :func:`write_postmortem` from its kill/shutdown paths whenever the
env var is set; :func:`install_excepthook` (installed automatically at
import when armed) covers uncaught exceptions.  Unarmed, every hook is a
no-op.

The write is tmp-file + ``os.replace``: a crash during the dump itself
leaves either the previous complete post-mortem or none — never a torn
JSON (the same commit discipline as ``fault.checkpoint``).
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import threading
import time
import traceback

from paddle_tpu.obs import trace

__all__ = ["POSTMORTEM_ENV", "postmortem_path", "write_postmortem",
           "install_excepthook", "install_from_env", "read_postmortem"]

POSTMORTEM_ENV = "PADDLE_TPU_POSTMORTEM"
POSTMORTEM_FORMAT = 1

_excepthook_installed = False
_dump_seq = itertools.count()


def postmortem_path(path=None):
    """Resolve the post-mortem target: explicit ``path`` wins, else the
    ``PADDLE_TPU_POSTMORTEM`` env var; a directory value maps to
    ``postmortem-<pid>.json`` inside it.  None = recorder unarmed."""
    p = path or os.environ.get(POSTMORTEM_ENV, "").strip()
    if not p:
        return None
    if os.path.isdir(p):
        return os.path.join(p, f"postmortem-{os.getpid()}.json")
    return p


def write_postmortem(path=None, reason="", extra=None):
    """Atomically dump spans + metrics to the post-mortem file.

    Returns the path written, or None when unarmed.  Never raises: this
    runs from signal handlers, excepthooks, and the instant before
    ``os._exit`` — a recorder failure must not mask the original death.
    """
    target = postmortem_path(path)
    if target is None:
        return None
    try:
        from paddle_tpu.profiler import runtime_metrics
        body = {
            "format": POSTMORTEM_FORMAT,
            "reason": reason,
            "pid": os.getpid(),
            "time_unix": time.time(),
            "argv": list(sys.argv),
            "spans": trace.snapshot_spans(),
            "metrics": runtime_metrics.snapshot(),
        }
        if extra:
            body["extra"] = extra
        try:
            from paddle_tpu.obs import ledger as _ledger
            rows = _ledger.active_tail(32)
            if rows:
                # the loss/grad trajectory INTO the fault, next to the
                # span timeline (obs/ledger.py)
                body["ledger_tail"] = rows
        except Exception:
            pass
        # the tmp name must be unique PER CALL, not per process: a
        # graceful shutdown dumps twice concurrently (the async
        # signal-handler thread and the __exit__ backstop), and two
        # writers sharing one tmp inode interleave into torn JSON —
        # unique names keep every rename a complete document, last
        # writer wins
        tmp = (f"{target}.tmp-{os.getpid()}"
               f"-{threading.get_ident()}-{next(_dump_seq)}")
        with open(tmp, "w") as f:
            json.dump(body, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, target)
        return target
    except Exception:  # pragma: no cover - by-design last resort
        return None


def read_postmortem(path):
    """Load a post-mortem file (forensics helper; plain ``json.load``)."""
    with open(path) as f:
        return json.load(f)


def install_excepthook():
    """Chain a post-mortem dump in front of the current
    ``sys.excepthook`` (idempotent).  The previous hook still runs, so
    tracebacks print exactly as before."""
    global _excepthook_installed
    if _excepthook_installed:
        return
    _excepthook_installed = True
    previous = sys.excepthook

    def hook(exc_type, exc, tb):
        write_postmortem(
            reason=f"uncaught {exc_type.__name__}: {exc}",
            extra={"traceback": traceback.format_exception(exc_type, exc,
                                                           tb)})
        previous(exc_type, exc, tb)

    sys.excepthook = hook


def install_from_env():
    """Arm the uncaught-exception hook iff ``PADDLE_TPU_POSTMORTEM`` is
    set (called at ``paddle_tpu.obs`` import; unarmed = zero change)."""
    if os.environ.get(POSTMORTEM_ENV, "").strip():
        install_excepthook()
        return True
    return False
