"""Bench trajectory: machine-checked performance history.

Every bench script (``bench_serving.py``, ``bench_datapipe.py``,
``bench_fleet.py``, ``bench_decode.py``) can append its headline
metrics to ``BENCH_TRAJECTORY.json`` through :func:`record`, and
``paddle_tpu bench check`` compares the NEWEST run of each bench
against its recorded BASELINE under per-metric tolerance bands —
exiting nonzero on regression, so a change that quietly halves
tokens/s fails a gate instead of landing silently (the repo's
BENCH_*.json artifacts record point-in-time runs; the trajectory is
the line through them).

File format (``"format": 1``)::

    {"format": 1, "runs": [
        {"bench": "decode", "time_unix": 1753900000.0,
         "baseline": true,                  # optional; first run else
         "source": "BENCH_DECODE.json",     # optional provenance
         "metrics": {"tokens_per_sec": 217.8, ...}}
    ]}

Baseline selection per bench: the LAST run flagged ``"baseline":
true``, else the first recorded run.  Newest = the last recorded run.
Tolerance bands live in :data:`BENCH_METRICS` (direction + band per
metric); a baseline entry may override them via a ``"tolerances"``
mapping of the same shape.  Metrics absent from the table (or from
either run) are reported but never judged — a bench may grow metrics
without invalidating its history.
"""

from __future__ import annotations

import json
import os
import time

__all__ = ["TRAJECTORY_FILE", "BENCH_METRICS", "MFU_BASES", "record",
           "check",
           "load_trajectory", "validate_trajectory", "summary_metrics",
           "default_path", "add_record_args", "record_from_args"]

TRAJECTORY_FILE = "BENCH_TRAJECTORY.json"
FORMAT = 1

# direction: "higher" / "lower" with a RELATIVE tolerance band (0.25 =
# newest may be up to 25% worse than baseline before it counts as a
# regression — the 2-vCPU bench hosts are noisy); "max_abs" is an
# ABSOLUTE ceiling above baseline (failures: 0 means zero, always).
BENCH_METRICS = {
    "serving": {"rps_batched": ("higher", 0.30),
                "speedup": ("higher", 0.30),
                "p99_ms": ("lower", 0.75)},
    "datapipe": {"samples_per_sec": ("higher", 0.30),
                 "speedup": ("higher", 0.30)},
    "fleet": {"rps_aggregate": ("higher", 0.30),
              "scaling": ("higher", 0.25),
              "kill_failures": ("max_abs", 0.0)},
    "decode": {"tokens_per_sec": ("higher", 0.30),
               "tokens_per_sec_ratio": ("higher", 0.25),
               "ttft_p99_ms": ("lower", 0.75),
               "lost_requests": ("max_abs", 0.0)},
    "paged": {"speedup": ("higher", 0.30),
              "bytes_ratio": ("lower", 0.10),
              "paged_step_ms": ("lower", 0.75)},
    "elastic": {"resume_seconds": ("lower", 1.00),
                "loss_delta_rel": ("max_abs", 1e-3),
                "reshard_failures": ("max_abs", 0.0)},
    # ISSUE-18 sharded-embedding gate: per-device table bytes must stay
    # ~1/N of replicated (the memory-scaling claim), the dp4->dp2
    # shrink drill must restore the sharded table + sparse moments
    # within the acceptance loss tolerance, and the sparse update must
    # keep scaling with touched rows, not vocab (a 4x vocab may not
    # move the step time past noise)
    "embedding": {"table_bytes_ratio": ("lower", 0.10),
                  "loss_delta_rel": ("max_abs", 1e-6),
                  "reshard_failures": ("max_abs", 0.0),
                  "step_time_vocab_ratio": ("lower", 0.75)},
    # ISSUE-15 cold-start gate: the second-best per-model trace+compile
    # reduction IS the "at least two zoo models improve >=15%"
    # acceptance floor, and the steady step must stay ~1 (the passes
    # may only remove work XLA would have DCE'd anyway)
    "compile": {"reduction_best": ("higher", 0.35),
                "reduction_second_best": ("higher", 0.35),
                "step_time_ratio_worst": ("lower", 0.15)},
    # ISSUE-16 autoscale gate: the controller fleet's p99 under the 5×
    # step, at least as many scale-ups as baseline (the loop must keep
    # acting), and the two zero-always invariants — no lost accepted
    # requests in the kill drill, no shed without a Retry-After hint
    "autoscale": {"p99_controller_ms": ("lower", 0.75),
                  "scale_ups": ("higher", 0.50),
                  "lost_accepted": ("max_abs", 0.0),
                  "sheds_without_retry_after": ("max_abs", 0.0)},
    # ISSUE-20 resumable-session gate: the kill-owner chaos drill must
    # lose/duplicate ZERO tokens and error ZERO streams (exactly-once
    # delivery is an invariant, not a tolerance), the worst
    # failover-induced token gap must stay bounded, and a resumed
    # stream may not cost more than the band over an unkilled one
    "gen_failover": {"ttft_after_failover_ms": ("lower", 0.75),
                     "resume_overhead_ratio": ("lower", 0.50),
                     "lost_tokens": ("max_abs", 0.0),
                     "dup_tokens": ("max_abs", 0.0),
                     "client_errors": ("max_abs", 0.0)},
    "train_transformer": {"tokens_per_sec_per_chip": ("higher", 0.10),
                          "mfu": ("higher", 0.05),
                          # measured (cost-analysis-based) MFU from the
                          # live train.mfu gauge, and the cold-process
                          # compile wall time (trace+lower+backend
                          # across captured jit keys) — ROADMAP item
                          # 5's optimizer passes are judged against
                          # exactly these two
                          "measured_mfu": ("higher", 0.10),
                          "compile_seconds": ("lower", 0.50)},
}

#: legal values of a run's ``mfu_basis`` tag — one definition, owned
#: by the module that emits the tag (peak_flops_info)
from paddle_tpu.obs.perf import MFU_BASES  # noqa: E402


def default_path():
    """Repo-root ``BENCH_TRAJECTORY.json`` (next to the BENCH_*.json
    artifacts), resolved relative to the installed package."""
    import paddle_tpu
    root = os.path.dirname(os.path.dirname(
        os.path.abspath(paddle_tpu.__file__)))
    return os.path.join(root, TRAJECTORY_FILE)


# ---------------------------------------------------------------------------
# schema
# ---------------------------------------------------------------------------

def validate_trajectory(obj):
    """Schema problems as a list of strings (empty = valid); the
    ``bench check --dry`` / selfcheck gate."""
    problems = []
    if not isinstance(obj, dict):
        return [f"trajectory must be a JSON object, "
                f"got {type(obj).__name__}"]
    if obj.get("format") != FORMAT:
        problems.append(f"format must be {FORMAT}, "
                        f"got {obj.get('format')!r}")
    runs = obj.get("runs")
    if not isinstance(runs, list):
        return problems + ["runs must be a list"]
    for i, run in enumerate(runs):
        where = f"runs[{i}]"
        if not isinstance(run, dict):
            problems.append(f"{where}: must be an object")
            continue
        if not isinstance(run.get("bench"), str) or not run.get("bench"):
            problems.append(f"{where}: needs a non-empty bench name")
        t = run.get("time_unix")
        if not isinstance(t, (int, float)) or isinstance(t, bool) or \
                t <= 0:
            problems.append(f"{where}: needs a positive time_unix")
        metrics = run.get("metrics")
        if not isinstance(metrics, dict) or not metrics:
            problems.append(f"{where}: needs a non-empty metrics object")
        else:
            for k, v in metrics.items():
                if not isinstance(k, str):
                    problems.append(f"{where}: metric keys must be "
                                    f"strings")
                    break
                if not isinstance(v, (int, float)) or \
                        isinstance(v, bool) or v != v:
                    problems.append(f"{where}: metric {k!r} must be a "
                                    f"finite number, got {v!r}")
        if "baseline" in run and not isinstance(run["baseline"], bool):
            problems.append(f"{where}: baseline must be a boolean")
        if "ledger" in run:
            # optional provenance pointer at the run's ledger directory
            # (obs.ledger): `bench check` refuses a record whose ledger
            # schema version this build cannot read — comparing against
            # rows it would misparse proves nothing
            from paddle_tpu.obs.ledger import LEDGER_FORMAT
            led = run["ledger"]
            if not isinstance(led, dict):
                problems.append(f"{where}: ledger must be an object")
            else:
                if not isinstance(led.get("path"), str) \
                        or not led.get("path"):
                    problems.append(f"{where}: ledger.path must be a "
                                    f"non-empty string")
                if led.get("format") != LEDGER_FORMAT:
                    problems.append(
                        f"{where}: ledger.format must be "
                        f"{LEDGER_FORMAT}, got {led.get('format')!r} "
                        f"(malformed ledger schema version)")
        if "mfu_basis" in run and run["mfu_basis"] not in MFU_BASES:
            problems.append(f"{where}: mfu_basis must be one of "
                            f"{MFU_BASES}, got {run['mfu_basis']!r}")
        if "tolerances" in run:
            tol = run["tolerances"]
            if not isinstance(tol, dict):
                problems.append(f"{where}: tolerances must be an object")
            else:
                for k, v in tol.items():
                    if (not isinstance(v, (list, tuple)) or len(v) != 2
                            or v[0] not in ("higher", "lower", "max_abs")
                            or not isinstance(v[1], (int, float))
                            or isinstance(v[1], bool) or v[1] < 0):
                        problems.append(
                            f"{where}: tolerances[{k!r}] must be "
                            f"[\"higher\"|\"lower\"|\"max_abs\", "
                            f"band>=0]")
    return problems


def load_trajectory(path=None):
    """Load and schema-validate; raises ``ValueError`` on any problem
    (including unreadable/non-JSON files)."""
    path = path or default_path()
    try:
        with open(path) as f:
            obj = json.load(f)
    except OSError as e:
        raise ValueError(f"cannot read trajectory {path!r}: {e}")
    except json.JSONDecodeError as e:
        raise ValueError(f"trajectory {path!r} is not JSON: {e}")
    problems = validate_trajectory(obj)
    if problems:
        raise ValueError(f"trajectory {path!r} fails schema:\n  "
                         + "\n  ".join(problems))
    return obj


# ---------------------------------------------------------------------------
# recording
# ---------------------------------------------------------------------------

def record(bench, metrics, path=None, baseline=False, source=None,
           meta=None, now=None, mfu_basis=None):
    """Append one run to the trajectory (atomic tmp+rename; creates the
    file on first use).  Returns the run entry written.

    ``mfu_basis`` tags what peak the run's MFU numbers were computed
    against (``"tpu-peak"`` / ``"cpu-fallback"`` — see
    ``obs.perf.peak_flops_info``); :func:`check` REFUSES to compare a
    bench across bases, so a CPU smoke run can neither pass nor fail
    against a real-chip baseline."""
    from paddle_tpu import profiler as _profiler
    path = path or default_path()
    entry = {"bench": str(bench),
             "time_unix": float(now if now is not None else time.time()),
             "metrics": {str(k): float(v) for k, v in metrics.items()}}
    if baseline:
        entry["baseline"] = True
    if mfu_basis is not None:
        entry["mfu_basis"] = str(mfu_basis)
    if source:
        entry["source"] = str(source)
    if meta:
        entry["meta"] = meta
    problems = validate_trajectory({"format": FORMAT, "runs": [entry]})
    if problems:
        raise ValueError("refusing to record an invalid run:\n  "
                         + "\n  ".join(problems))
    if os.path.exists(path):
        obj = load_trajectory(path)
    else:
        obj = {"format": FORMAT, "runs": []}
    obj["runs"].append(entry)
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=2, sort_keys=True)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _profiler.runtime_metrics.inc("bench.recorded")
    return entry


def summary_metrics(bench, summary):
    """Flatten a bench script's summary dict into the trajectory's
    headline metrics for that bench (the shared extraction the scripts
    and the import path both use)."""
    if bench == "serving":
        return {"rps_batched": summary["batched"]["rps"],
                "speedup": summary["speedup"],
                "p99_ms": summary["batched"]["latency_ms"]["p99"]}
    if bench == "datapipe":
        return {"samples_per_sec": summary["datapipe"]
                ["samples_per_sec"],
                "speedup": summary["speedup"]}
    if bench == "fleet":
        scale_key = max((k for k in summary["fleet"] if k != "1"),
                        key=int)
        return {"rps_aggregate": summary["fleet"][scale_key]["rps"],
                "scaling": summary["scaling"],
                "kill_failures": summary["kill_drill"]["failures"]}
    if bench == "decode":
        cont = summary["modes"]["continuous"]
        return {"tokens_per_sec": cont["tokens_per_sec"],
                "tokens_per_sec_ratio": summary["tokens_per_sec_ratio"],
                "ttft_p99_ms": summary["ttft_p99_ms"]["continuous"],
                "lost_requests": cont["failures"]}
    if bench == "paged":
        return {"speedup": summary["speedup"],
                "bytes_ratio": summary["bytes_ratio"],
                "paged_step_ms": summary["paged"]["decode_step_ms"]}
    if bench == "compile":
        return {"reduction_best": summary["reduction_best"],
                "reduction_second_best":
                    summary["reduction_second_best"],
                "models_ge_15pct": summary["models_ge_15pct"],
                "step_time_ratio_worst":
                    summary["step_time_ratio_worst"]}
    if bench == "elastic":
        return {"resume_seconds": summary["resume"]["restore_seconds"],
                "loss_delta_rel": summary["loss_delta_rel"],
                "reshard_failures": summary["reshard_failures"]}
    if bench == "embedding":
        return {"table_bytes_ratio": summary["table_bytes_ratio"],
                "loss_delta_rel": summary["loss_delta_rel"],
                "reshard_failures": summary["reshard_failures"],
                "step_time_vocab_ratio":
                    summary["sparse_scaling"]["step_time_vocab_ratio"]}
    if bench == "autoscale":
        ctrl = summary["modes"]["controller"]
        return {"p99_controller_ms": ctrl["p99_ms"],
                "scale_ups": ctrl["scale_ups"],
                "lost_accepted":
                    summary["kill_drill"]["traffic"]["lost_accepted"],
                "sheds_without_retry_after":
                    summary["sheds_without_retry_after"]}
    if bench == "gen_failover":
        kill = summary["kill_drill"]
        return {"ttft_after_failover_ms": kill["ttft_after_failover_ms"],
                "resume_overhead_ratio":
                    summary["resume_overhead_ratio"],
                "lost_tokens": kill["lost_tokens"],
                "dup_tokens": kill["dup_tokens"],
                "client_errors": (kill["client_errors"]
                                  + summary["drain_drill"]
                                  ["client_errors"])}
    if bench == "train_transformer":
        out = {"tokens_per_sec_per_chip":
               summary["tokens_per_sec_per_chip"],
               "mfu": summary["mfu"]}
        for opt in ("measured_mfu", "compile_seconds"):
            if summary.get(opt) is not None:
                out[opt] = summary[opt]
        return out
    raise ValueError(f"no trajectory extraction for bench {bench!r} "
                     f"(known: serving, datapipe, fleet, decode, paged, "
                     f"elastic, embedding, compile, train_transformer, "
                     f"autoscale, gen_failover)")


def add_record_args(parser):
    """The bench scripts' shared ``--record-trajectory`` /
    ``--record-baseline`` argparse flags (one definition, four
    scripts)."""
    parser.add_argument(
        "--record-trajectory", default=None, metavar="PATH",
        help="append this run's headline metrics to the bench "
             "trajectory ('default' = the repo's BENCH_TRAJECTORY.json;"
             " `paddle_tpu bench check` gates on it)")
    parser.add_argument(
        "--record-baseline", action="store_true",
        help="flag the recorded run as the comparison baseline")


def record_from_args(bench, summary, args, source, mfu_basis=None):
    """The bench scripts' shared recording tail: extract ``bench``'s
    headline metrics from ``summary`` and append them per the
    :func:`add_record_args` flags.  No-op (returns None) when
    ``--record-trajectory`` was not given."""
    if not getattr(args, "record_trajectory", None):
        return None
    return record(
        bench, summary_metrics(bench, summary),
        path=(None if args.record_trajectory == "default"
              else args.record_trajectory),
        baseline=args.record_baseline, source=source,
        mfu_basis=mfu_basis)


# ---------------------------------------------------------------------------
# checking
# ---------------------------------------------------------------------------

def _judge(direction, band, base, new):
    """(ok, bound) under one tolerance band.  The relative slack is
    ``|base| * band`` — for a NEGATIVE baseline, ``base * (1 - band)``
    would tighten instead of loosen (a -2% compile-reduction baseline
    must not fail an identical -2% run)."""
    slack = abs(base) * band
    if direction == "higher":
        bound = base - slack
        return new >= bound, bound
    if direction == "lower":
        bound = base + slack
        return new <= bound, bound
    # max_abs: absolute ceiling above baseline
    bound = base + band
    return new <= bound, bound


def check(path=None, dry=False):
    """Compare each bench's newest run against its baseline.

    Returns ``{"ok", "problems", "benches": {name: {"baseline",
    "newest", "comparisons", "regressions"}}}``.  ``dry=True`` stops
    after schema validation (the selfcheck gate).  Schema problems OR
    any regression flip ``ok`` to False."""
    from paddle_tpu import profiler as _profiler
    path = path or default_path()
    report = {"ok": True, "path": path, "problems": [], "benches": {}}
    _profiler.runtime_metrics.inc("bench.checks")
    try:
        obj = load_trajectory(path)
    except ValueError as e:
        report["ok"] = False
        report["problems"] = str(e).splitlines()
        return report
    if dry:
        return report
    by_bench = {}
    for run in obj["runs"]:
        by_bench.setdefault(run["bench"], []).append(run)
    for bench, runs in sorted(by_bench.items()):
        baselines = [r for r in runs if r.get("baseline")]
        base = baselines[-1] if baselines else runs[0]
        newest = runs[-1]
        base_basis = base.get("mfu_basis")
        new_basis = newest.get("mfu_basis")
        if base_basis != new_basis and (base_basis or new_basis):
            # comparing a cpu-fallback MFU (peak 1e12, "meaningless but
            # finite") against a tpu-peak baseline — or an untagged run
            # against a tagged one — proves nothing either way: refuse
            # instead of silently passing or failing
            report["ok"] = False
            report["problems"].append(
                f"bench {bench!r}: baseline mfu_basis="
                f"{base_basis!r} but newest run is {new_basis!r} — "
                f"refusing to compare MFU records across bases "
                f"(re-record the baseline on this hardware, or drop "
                f"the cross-basis run)")
            report["benches"][bench] = {
                "runs": len(runs),
                "baseline_time_unix": base["time_unix"],
                "newest_time_unix": newest["time_unix"],
                "comparisons": [],
                "regressions": [],
                "basis_mismatch": {"baseline": base_basis,
                                   "newest": new_basis},
            }
            continue
        tolerances = dict(BENCH_METRICS.get(bench, {}))
        tolerances.update({k: tuple(v) for k, v
                           in (base.get("tolerances") or {}).items()})
        comparisons = []
        regressions = []
        for metric, (direction, band) in sorted(tolerances.items()):
            if metric not in base["metrics"] or \
                    metric not in newest["metrics"]:
                continue
            b, n = base["metrics"][metric], newest["metrics"][metric]
            ok, bound = _judge(direction, band, b, n)
            row = {"metric": metric, "direction": direction,
                   "band": band, "baseline": b, "newest": n,
                   "bound": bound, "ok": ok}
            comparisons.append(row)
            if not ok:
                regressions.append(row)
                _profiler.runtime_metrics.inc("bench.regressions")
        report["benches"][bench] = {
            "runs": len(runs),
            "baseline_time_unix": base["time_unix"],
            "newest_time_unix": newest["time_unix"],
            "comparisons": comparisons,
            "regressions": regressions,
        }
        if regressions:
            report["ok"] = False
    return report
