"""Observability spine: structured tracing, flight recorder, Prometheus.

Three layers over the existing ``profiler.RuntimeMetrics`` counters:

- :mod:`paddle_tpu.obs.trace` — Dapper-style spans with contextvar
  nesting and cross-process trace-context propagation, recorded into a
  bounded ring (``PADDLE_TPU_TRACE``), exported as Chrome trace-event
  JSON (``/trace``, ``paddle_tpu trace dump``).
- :mod:`paddle_tpu.obs.flight` — post-mortem dumps of the span tail +
  metrics snapshot on crash / graceful shutdown / chaos kill
  (``PADDLE_TPU_POSTMORTEM``).
- :mod:`paddle_tpu.obs.prom` — Prometheus text exposition of the
  runtime metrics (``/metrics``, ``paddle_tpu stats --prom``).

See ``docs/observability.md`` for the span API, the trace-context
headers, the post-mortem file format, and the metric-name registry.
"""

from __future__ import annotations

from paddle_tpu.obs import trace
from paddle_tpu.obs import flight
from paddle_tpu.obs import prom
from paddle_tpu.obs.trace import (span, record_span, trace_context,
                                  current_trace_id, new_trace_id,
                                  chrome_trace, dump_chrome_trace)
from paddle_tpu.obs.flight import write_postmortem, read_postmortem
from paddle_tpu.obs.prom import render_prometheus

__all__ = ["trace", "flight", "prom", "span", "record_span",
           "trace_context", "current_trace_id", "new_trace_id",
           "chrome_trace", "dump_chrome_trace", "write_postmortem",
           "read_postmortem", "render_prometheus"]

# arm the uncaught-exception post-mortem hook iff the operator asked
# for one (PADDLE_TPU_POSTMORTEM); unarmed this changes nothing
flight.install_from_env()
