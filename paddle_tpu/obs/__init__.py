"""Observability spine: structured tracing, flight recorder, Prometheus.

Three layers over the existing ``profiler.RuntimeMetrics`` counters:

- :mod:`paddle_tpu.obs.trace` — Dapper-style spans with contextvar
  nesting and cross-process trace-context propagation, recorded into a
  bounded ring (``PADDLE_TPU_TRACE``), exported as Chrome trace-event
  JSON (``/trace``, ``paddle_tpu trace dump``).
- :mod:`paddle_tpu.obs.flight` — post-mortem dumps of the span tail +
  metrics snapshot on crash / graceful shutdown / chaos kill
  (``PADDLE_TPU_POSTMORTEM``).
- :mod:`paddle_tpu.obs.prom` — Prometheus text exposition of the
  runtime metrics (``/metrics``, ``paddle_tpu stats --prom``).

Three FLEET-level layers on top (the multi-process plane):

- :mod:`paddle_tpu.obs.aggregate` — metrics federation (one exposition
  over every replica's registry, ``replica=`` labels + rollups) and
  cross-process trace assembly (clock-skew-normalized merged Chrome
  timelines), served by the fleet router (``/metrics?fleet=1``,
  ``/trace?fleet=1``) and ``paddle_tpu fleet-stats``.
- :mod:`paddle_tpu.obs.slo` — declarative SLO specs
  (``PADDLE_TPU_SLO``) evaluated on a sliding window over the runtime
  metrics, with breach counters, a structured breach log, and a
  flight-recorder post-mortem on sustained breach.
- :mod:`paddle_tpu.obs.bench_history` — the bench trajectory
  (``BENCH_TRAJECTORY.json``): bench scripts append headline metrics,
  ``paddle_tpu bench check`` fails on regression past per-metric
  tolerance bands.

And the TRAINING-health plane:

- :mod:`paddle_tpu.obs.ledger` — the persistent run ledger: an
  append-only, schema-validated JSONL step series (loss, grad/param
  norms, MFU, tokens/s, datapipe stall, HBM headroom) with atomic
  segment rotation, exactly-once resume through the checkpoint
  sidecar, drift alerts, and the ``paddle_tpu runs tail|show|compare``
  CLI family.
- :mod:`paddle_tpu.obs.numerics` — per-op tensor-stat probes (the
  ``paddle_tpu replay --localize`` fault localizer) and the fused
  param/grad-norm health reduction the sentinel runs per guarded step.

And the DEVICE-side plane:

- :mod:`paddle_tpu.obs.perf` — XLA cost/memory attribution per jit key
  (captured on every jit-cache miss), trace/lower/compile phase times,
  a live ``train.mfu`` / ``gen.decode_mfu`` gauge, the ``hbm.*``
  live-buffer census with collection attribution and a high watermark,
  and the pre-run projected-footprint headroom check — surfaced by the
  ``paddle_tpu profile compile|memory|step`` CLI family.

See ``docs/observability.md`` for the span API, the trace-context
headers, the post-mortem file format, and the metric-name registry.
"""

from __future__ import annotations

from paddle_tpu.obs import trace
from paddle_tpu.obs import flight
from paddle_tpu.obs import prom
from paddle_tpu.obs import aggregate
from paddle_tpu.obs import bench_history
from paddle_tpu.obs import perf
from paddle_tpu.obs import slo
from paddle_tpu.obs import ledger
from paddle_tpu.obs import numerics
from paddle_tpu.obs.trace import (span, record_span, trace_context,
                                  current_trace_id, new_trace_id,
                                  chrome_trace, dump_chrome_trace,
                                  set_process_name, snapshot_payload)
from paddle_tpu.obs.flight import write_postmortem, read_postmortem
from paddle_tpu.obs.prom import render_prometheus
from paddle_tpu.obs.aggregate import (FleetScraper, assemble_fleet_trace,
                                      render_federated)
from paddle_tpu.obs.slo import SLOWatchdog, load_spec, validate_spec

__all__ = ["trace", "flight", "prom", "aggregate", "bench_history",
           "perf", "slo", "ledger", "numerics",
           "span", "record_span", "trace_context",
           "current_trace_id", "new_trace_id", "chrome_trace",
           "dump_chrome_trace", "set_process_name", "snapshot_payload",
           "write_postmortem", "read_postmortem", "render_prometheus",
           "FleetScraper", "assemble_fleet_trace", "render_federated",
           "SLOWatchdog", "load_spec", "validate_spec"]

# arm the uncaught-exception post-mortem hook iff the operator asked
# for one (PADDLE_TPU_POSTMORTEM); unarmed this changes nothing
flight.install_from_env()
