"""Numerics observatory: per-op tensor-stat probes + fused training-health
norms (docs/observability.md "Run ledger & numerics").

Two halves, both cheap when disarmed:

* **Op probes** — the reference's ``FLAGS_check_nan_inf`` walks every
  output tensor after each op kernel and names the first non-finite
  one; our compiled path fuses the whole step into one XLA executable,
  so the walk only exists in interpreted op-by-op execution.  Arming a
  :class:`ProbeCollector` (``with numerics.probe(collector): ...``)
  forces interpret mode — exactly like op profiling — and the executor
  calls :func:`record_op` after each lowered op with its concrete
  outputs.  The collector keeps cheap host-side stats (finite fraction,
  absmax, zero fraction, mean/std) for a bounded trail of recent ops
  and captures the FIRST op producing a non-finite output together
  with its ``creation_site`` and the stats of its *inputs* at that
  moment.  :func:`localize_bundle` wires this into sentinel quarantine
  bundles: ``paddle_tpu replay <bundle> --localize`` re-executes the
  quarantined step on CPU op-by-op and the report names the poisoned
  op.  The disarmed hot path is one module-global ``is None`` check.

* **Health norms** — :func:`fused_check_fn` builds the jitted reduction
  the sentinel runs on guarded steps: the existing all-finite check
  PLUS the global parameter norm and update norm of the step, fused
  into ONE device computation (a guarded step still pays exactly one
  device sync).  :func:`set_health_gauges` publishes them as the
  ``train.param_norm`` / ``train.grad_norm`` / ``train.update_ratio``
  gauges (``train.grad_norm`` is the l2 norm of the applied parameter
  update — the optimizer-scaled gradient step, the quantity that
  explodes when gradients do), which the run ledger snapshots per step
  and the fleet scraper federates.
"""

from __future__ import annotations

import collections
import contextlib
import logging

import numpy as np

__all__ = ["ProbeCollector", "probe", "probing_enabled", "record_op",
           "tensor_stats", "localize_bundle", "fused_check_fn",
           "set_health_gauges"]

logger = logging.getLogger(__name__)

# the armed probe collector; the disarmed per-op cost is this one read
_PROBE = None


def probing_enabled():
    """True while a probe collector is armed (forces interpret mode,
    like ``profiler.op_profiling_enabled`` — the per-op hook only
    exists in op-by-op execution)."""
    return _PROBE is not None


def tensor_stats(value):
    """Cheap host-side stats of one tensor: finite fraction, absmax,
    zero fraction, mean/std (over finite entries), dtype and shape.
    Never raises — un-statable values degrade to their type name."""
    try:
        arr = np.asarray(value)
    except Exception:
        return {"kind": type(value).__name__}
    kind = getattr(arr.dtype, "kind", None)
    if kind in ("O", "S", "U", "M", "m"):
        return {"kind": type(value).__name__}
    out = {"dtype": str(arr.dtype), "shape": list(arr.shape)}
    if arr.size == 0:
        out.update(finite_frac=1.0, absmax=0.0, zero_frac=0.0,
                   mean=0.0, std=0.0)
        return out
    # ml_dtypes low-precision floats (bfloat16, float8) register as
    # structured kind "V" but cast cleanly to float32
    if kind == "V" and "float" in str(arr.dtype):
        kind = "f"
        arr = arr.astype("float32")
    if kind not in ("f", "c"):
        if kind in ("i", "u", "b"):
            a = arr.astype("float64")
            out.update(finite_frac=1.0,
                       absmax=float(np.abs(a).max()),
                       zero_frac=float((a == 0).mean()),
                       mean=float(a.mean()), std=float(a.std()))
        return out
    # float32 covers ml_dtypes (bfloat16) numpy can't reduce natively
    a = arr.astype("float32", copy=False)
    finite = np.isfinite(a)
    n_finite = int(finite.sum())
    out["finite_frac"] = n_finite / a.size
    out["zero_frac"] = float((a == 0).mean())
    if n_finite:
        fin = a[finite] if n_finite != a.size else a
        out["absmax"] = float(np.abs(fin).max())
        out["mean"] = float(fin.mean())
        out["std"] = float(fin.std())
    else:
        out.update(absmax=None, mean=None, std=None)
    return out


def _non_finite(stats):
    frac = stats.get("finite_frac")
    return frac is not None and frac < 1.0


class ProbeCollector:
    """Per-op stat collector for one interpreted execution.

    ``trail`` bounds the rolling window of recent op stat rows;
    ``poison_var`` (used by :func:`localize_bundle` on
    ``sentinel.nan``-injected bundles) NaNs that variable at its
    producing op, so the op-level poison lands exactly where the
    sentinel's post-step poison *would have* originated and the drill
    exercises the same localization machinery an organic fault does."""

    def __init__(self, trail=16, poison_var=None):
        self.trail = collections.deque(maxlen=max(1, int(trail)))
        self.poison_var = poison_var
        self.poisoned = False
        self.first_bad = None
        self.ops_probed = 0

    def record_op(self, op, outputs, env):
        from paddle_tpu.profiler import runtime_metrics
        self.ops_probed += 1
        runtime_metrics.inc("numerics.ops_probed")
        if self.poison_var is not None and not self.poisoned \
                and self.poison_var in outputs:
            v = np.asarray(env[self.poison_var])
            if getattr(v.dtype, "kind", None) == "f":
                env[self.poison_var] = np.full_like(v, np.nan)
                self.poisoned = True
        out_stats = {n: tensor_stats(env.get(n))
                     for n in op.output_arg_names if n}
        row = {"index": self.ops_probed - 1, "type": op.type,
               "outputs": out_stats}
        self.trail.append(row)
        if self.first_bad is None and \
                any(_non_finite(s) for s in out_stats.values()):
            runtime_metrics.inc("numerics.non_finite_ops")
            in_stats = {n: tensor_stats(env.get(n))
                        for n in op.input_arg_names
                        if n and env.get(n) is not None}
            self.first_bad = {
                "index": self.ops_probed - 1,
                "type": op.type,
                "creation_site": list(getattr(op, "creation_site", None)
                                      or ()) or None,
                "outputs": out_stats,
                "inputs": in_stats,
                "trail": [dict(r) for r in self.trail],
            }


@contextlib.contextmanager
def probe(collector):
    """Arm ``collector`` as the process-global probe for the body."""
    global _PROBE
    prev = _PROBE
    _PROBE = collector
    try:
        yield collector
    finally:
        _PROBE = prev


def record_op(op, outputs, env):
    """Executor hook: called after each lowered op in interpret mode
    while a probe is armed (``lower_block``)."""
    p = _PROBE
    if p is not None:
        p.record_op(op, outputs, env)


# ---------------------------------------------------------------------------
# op-level fault localization (`paddle_tpu replay <bundle> --localize`)
# ---------------------------------------------------------------------------

def _poison_target(program, fetch_names, loss_name=None):
    """The variable an injected bundle's op-level poison lands on: the
    recorded loss fetch when its producing op is in the program, else
    the first fetch produced by any op."""
    block = program.global_block()
    produced = set()
    for op in block.ops:
        produced.update(n for n in op.output_arg_names if n)
    if loss_name and loss_name in produced:
        return loss_name
    for name in fetch_names:
        if name in produced:
            return name
    return None


def localize_bundle(path, trail=16):
    """Re-execute a quarantine bundle op-by-op on CPU with probes armed
    and name the first op producing a non-finite output.

    Returns ``{"localized": bool, "first_bad_op": {...} | None,
    "step", "reason", "injected", "ops_probed", "bad", "health"}`` —
    ``first_bad_op`` carries the op type, its ``creation_site``
    (file, line of the user code that appended it), per-output stats,
    the stats of its inputs at that moment, and the trailing stat rows
    leading into it.  Bundles whose fault was manufactured by the
    ``sentinel.nan`` failpoint poison the loss-producing op during the
    re-execution (the sentinel's poison is post-step, so no op would
    organically produce the NaN), exercising the same probe machinery.
    Malformed / unreplayable bundles raise ``ValueError`` (the CLI's
    exit 2), mirroring :func:`paddle_tpu.fault.sentinel.replay_bundle`.
    """
    from paddle_tpu.executor import Executor
    from paddle_tpu.fault.sentinel import load_bundle
    from paddle_tpu.framework import Program
    from paddle_tpu.place import CPUPlace
    from paddle_tpu.scope import Scope

    bundle = load_bundle(path)
    repro = bundle.get("repro")
    if not repro:
        raise ValueError(f"{path}: bundle carries no repro payload")
    try:
        program = Program.from_dict(repro["program"])
        program.random_seed = repro.get("random_seed")
        scope = Scope()
        for name, value in (repro.get("state") or {}).items():
            scope.set_var(name, value)
        run_counter = int(repro.get("run_counter", 1)) - 1
        feed = dict(repro["feed"])
        fetch_names = list(repro["fetch_names"])
    except Exception as e:
        raise ValueError(
            f"{path}: cannot rebuild repro payload: {e}") from e
    exe = Executor(CPUPlace())
    exe._run_counter = run_counter
    det = bundle.get("detector") or {}
    collector = ProbeCollector(trail=trail)
    if bundle.get("injected"):
        collector.poison_var = _poison_target(
            program, fetch_names, loss_name=det.get("loss_name"))
    try:
        with probe(collector):
            exe.run(program, feed=feed, fetch_list=fetch_names,
                    scope=scope)
    except Exception as e:
        raise ValueError(
            f"{path}: bundle does not re-execute: {e}") from e
    return {
        "localized": collector.first_bad is not None,
        "first_bad_op": collector.first_bad,
        "step": bundle.get("step"),
        "reason": bundle.get("reason"),
        "injected": bool(bundle.get("injected")),
        "ops_probed": collector.ops_probed,
        "bad": list(bundle.get("bad") or []),
        "health": bundle.get("health"),
    }


# ---------------------------------------------------------------------------
# fused training-health norms (the sentinel's guarded-step reduction)
# ---------------------------------------------------------------------------

def fused_check_fn():
    """Build the jitted fused guarded-step reduction: all-finite over
    every floating check tensor PLUS the global parameter/update norms,
    one device computation (jit retraces per pytree structure and is
    cached thereafter — the sentinel holds one instance).

    Signature: ``fn(arrs, new_params, old_params) -> (all_finite,
    norms)`` where ``norms`` is ``[param_norm, update_norm]`` (empty
    when no parameter pairs were passed)."""
    import jax
    import jax.numpy as jnp

    def _ssq(xs):
        total = jnp.zeros((), jnp.float32)
        for x in xs:
            total = total + jnp.sum(
                jnp.square(x.astype(jnp.float32)))
        return total

    def _fused(arrs, new_params, old_params):
        if arrs:
            finite = jnp.all(jnp.stack(
                [jnp.isfinite(a).all() for a in arrs]))
        else:
            finite = jnp.asarray(True)
        if new_params:
            p = jnp.sqrt(_ssq(new_params))
            u = jnp.sqrt(_ssq([n - o for n, o in
                               zip(new_params, old_params)]))
            norms = jnp.stack([p, u])
        else:
            norms = jnp.zeros((0,), jnp.float32)
        return finite, norms

    return jax.jit(_fused)


def health_from_norms(norms):
    """``(param_norm, update_norm)`` host floats -> the health dict the
    sentinel stashes in its escalation context (quarantine bundles,
    rollback post-mortems).  ``update_ratio`` is update/param — the
    step-size signal that precedes most divergences."""
    if norms is None or len(norms) < 2:
        return None
    param_norm = float(norms[0])
    grad_norm = float(norms[1])
    ratio = grad_norm / (param_norm + 1e-12)
    return {"param_norm": param_norm, "grad_norm": grad_norm,
            "update_ratio": ratio}


def set_health_gauges(metrics, health):
    """Publish the health dict as gauges.  Disabled path (no health
    computed this step) is the ``None`` check — nothing else runs."""
    if metrics is None or health is None:
        return
    metrics.set_gauge("train.param_norm", health["param_norm"])
    metrics.set_gauge("train.grad_norm", health["grad_norm"])
    metrics.set_gauge("train.update_ratio", health["update_ratio"])
