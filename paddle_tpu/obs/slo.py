"""SLO watchdog: declarative service-level objectives evaluated on a
sliding window over :class:`profiler.RuntimeMetrics`.

An SLO spec is a small JSON document (``PADDLE_TPU_SLO=/path/spec.json``
arms it; ``paddle_tpu selfcheck`` validates its schema statically):

.. code-block:: json

    {
      "version": 1,
      "interval_seconds": 5.0,
      "sustained_breaches": 3,
      "objectives": [
        {"name": "request-latency", "kind": "quantile",
         "series": "fleet.request_seconds", "quantile": "p99",
         "max": 0.5},
        {"name": "error-rate", "kind": "error_rate",
         "ok": ["fleet.requests_ok"], "errors": ["fleet.shed"],
         "max_ratio": 0.01},
        {"name": "ttft", "kind": "quantile",
         "series": "gen.ttft_seconds", "quantile": "p99", "max": 0.3},
        {"name": "tokens-floor", "kind": "rate_floor",
         "counter": "gen.tokens", "min_rate": 50.0}
      ]
    }

Three objective kinds cover the serving SLOs that matter:

- ``quantile`` — a windowed latency percentile (the bounded reservoir
  :meth:`RuntimeMetrics.percentiles` keeps) must stay under ``max``
  seconds.  No samples in the window = nothing to judge (skipped, not
  breached).
- ``error_rate`` — errors / (ok + errors) over the counter DELTAS since
  the previous evaluation must stay under ``max_ratio``.  A window with
  zero traffic is skipped.
- ``rate_floor`` — a counter's rate (delta / elapsed) must stay at or
  above ``min_rate``.  By default an idle window (zero delta) is
  skipped — a tokens/s floor means "when generating, generate this
  fast", not "never be idle"; set ``"idle_ok": false`` for a liveness
  floor that breaches on silence.

The :class:`SLOWatchdog` emits ``slo.evaluations`` / ``slo.breach``
counters and the ``slo.breaching`` gauge, keeps a bounded structured
``breach_log``, logs every breach, and — after ``sustained_breaches``
CONSECUTIVE breaches of one objective — writes a flight-recorder
post-mortem (``slo.postmortems``) so the span ring and metrics at the
moment the SLO went red are preserved.  The episode re-arms after the
objective recovers: a flapping SLO produces one post-mortem per
sustained episode, not one per evaluation.

Hot-path contract: :func:`tick` is the only thing schedulers/routers
call per iteration — a ``None`` watchdog costs one identity check, an
armed-but-not-due one costs a single monotonic clock read (guarded by
``tests/test_obs_overhead.py``).
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time

logger = logging.getLogger(__name__)

__all__ = ["SLOSpec", "SLOWatchdog", "load_spec", "validate_spec",
           "watchdog_from_env", "tick", "SLO_ENV", "EXAMPLE_SPEC"]

SLO_ENV = "PADDLE_TPU_SLO"
SPEC_VERSION = 1
_KINDS = ("quantile", "error_rate", "rate_floor")
_QUANTILES = ("p50", "p95", "p99")

# the documented spec shape — selfcheck validates this constant so the
# schema validator itself is exercised even when no spec file is armed
EXAMPLE_SPEC = {
    "version": 1,
    "interval_seconds": 5.0,
    "sustained_breaches": 3,
    "objectives": [
        {"name": "request-latency-p99", "kind": "quantile",
         "series": "fleet.request_seconds", "quantile": "p99",
         "max": 0.5},
        {"name": "error-rate", "kind": "error_rate",
         "ok": ["fleet.requests_ok"], "errors": ["fleet.shed"],
         "max_ratio": 0.01},
        {"name": "ttft-p99", "kind": "quantile",
         "series": "gen.ttft_seconds", "quantile": "p99", "max": 0.3},
        {"name": "tokens-per-sec-floor", "kind": "rate_floor",
         "counter": "gen.tokens", "min_rate": 50.0},
    ],
}


def _is_number(v):
    return isinstance(v, (int, float)) and not isinstance(v, bool) \
        and v == v and abs(v) != float("inf")


def validate_spec(obj):
    """Schema problems of an SLO spec dict, as a list of strings (empty
    = valid).  Never raises — selfcheck renders the list."""
    problems = []
    if not isinstance(obj, dict):
        return [f"spec must be a JSON object, got {type(obj).__name__}"]
    if obj.get("version") != SPEC_VERSION:
        problems.append(f"version must be {SPEC_VERSION}, "
                        f"got {obj.get('version')!r}")
    for key in ("interval_seconds",):
        if key in obj and (not _is_number(obj[key]) or obj[key] <= 0):
            problems.append(f"{key} must be a positive number")
    if "sustained_breaches" in obj and (
            not isinstance(obj["sustained_breaches"], int)
            or isinstance(obj["sustained_breaches"], bool)
            or obj["sustained_breaches"] < 1):
        problems.append("sustained_breaches must be an integer >= 1")
    objectives = obj.get("objectives")
    if not isinstance(objectives, list) or not objectives:
        problems.append("objectives must be a non-empty list")
        objectives = []
    seen = set()
    for i, o in enumerate(objectives):
        where = f"objectives[{i}]"
        if not isinstance(o, dict):
            problems.append(f"{where}: must be an object")
            continue
        name = o.get("name")
        if not isinstance(name, str) or not name:
            problems.append(f"{where}: needs a non-empty string name")
        elif name in seen:
            problems.append(f"{where}: duplicate name {name!r}")
        else:
            seen.add(name)
        kind = o.get("kind")
        if kind not in _KINDS:
            problems.append(f"{where}: kind must be one of {_KINDS}, "
                            f"got {kind!r}")
            continue
        if kind == "quantile":
            if not isinstance(o.get("series"), str) or not o.get("series"):
                problems.append(f"{where}: quantile needs a series name")
            if o.get("quantile", "p99") not in _QUANTILES:
                problems.append(f"{where}: quantile must be one of "
                                f"{_QUANTILES}, "
                                f"got {o.get('quantile')!r}")
            if not _is_number(o.get("max")) or o.get("max") <= 0:
                problems.append(f"{where}: needs max > 0 (seconds)")
        elif kind == "error_rate":
            for key in ("ok", "errors"):
                v = o.get(key)
                if not isinstance(v, list) or not v or \
                        not all(isinstance(c, str) and c for c in v):
                    problems.append(f"{where}: {key} must be a non-empty "
                                    f"list of counter names")
            r = o.get("max_ratio")
            if not _is_number(r) or not (0 <= r <= 1):
                problems.append(f"{where}: max_ratio must be in [0, 1]")
        elif kind == "rate_floor":
            if not isinstance(o.get("counter"), str) or \
                    not o.get("counter"):
                problems.append(f"{where}: rate_floor needs a counter "
                                f"name")
            if not _is_number(o.get("min_rate")) or o["min_rate"] < 0:
                problems.append(f"{where}: needs min_rate >= 0")
            if "idle_ok" in o and not isinstance(o["idle_ok"], bool):
                problems.append(f"{where}: idle_ok must be a boolean")
        unknown = set(o) - {"name", "kind", "series", "quantile", "max",
                            "ok", "errors", "max_ratio", "counter",
                            "min_rate", "idle_ok", "description"}
        if unknown:
            problems.append(f"{where}: unknown keys {sorted(unknown)}")
    return problems


class SLOSpec:
    """A validated SLO spec; construct via :func:`load_spec`."""

    def __init__(self, obj, source=None):
        problems = validate_spec(obj)
        if problems:
            raise ValueError(
                "invalid SLO spec" + (f" ({source})" if source else "")
                + ":\n  " + "\n  ".join(problems))
        self.source = source
        self.interval = float(obj.get("interval_seconds", 5.0))
        self.sustained = int(obj.get("sustained_breaches", 3))
        self.objectives = [dict(o) for o in obj["objectives"]]

    def to_dict(self):
        return {"version": SPEC_VERSION,
                "interval_seconds": self.interval,
                "sustained_breaches": self.sustained,
                "objectives": [dict(o) for o in self.objectives]}


def load_spec(spec):
    """Coerce a path / dict / SLOSpec into an :class:`SLOSpec`; raises
    ``ValueError`` naming every schema problem."""
    if isinstance(spec, SLOSpec):
        return spec
    if isinstance(spec, dict):
        return SLOSpec(spec)
    with open(spec) as f:
        try:
            obj = json.load(f)
        except ValueError as e:
            raise ValueError(f"invalid SLO spec ({spec}): not JSON: {e}")
    return SLOSpec(obj, source=str(spec))


class SLOWatchdog:
    """Evaluate an :class:`SLOSpec` against a metrics registry.

    Two wirings (both used in-tree): the router runs :meth:`start` for
    a background evaluation thread; the :class:`gen.GenScheduler` calls
    :func:`tick` from its decode loop so evaluation piggybacks on the
    thread that produces the metrics being judged."""

    def __init__(self, spec, metrics=None, log_size=256):
        self.spec = load_spec(spec)
        if metrics is None:
            from paddle_tpu.profiler import runtime_metrics
            metrics = runtime_metrics
        self._metrics = metrics
        self._lock = threading.Lock()
        self._last_eval = None           # monotonic of last evaluate()
        self._prev = None                # (monotonic, {counter: value})
        self._consecutive = collections.Counter()
        self._postmortem_armed = {o["name"]: True
                                  for o in self.spec.objectives}
        self.breach_log = collections.deque(maxlen=log_size)
        self.breaches_total = 0
        self.evaluations = 0
        self._last_values = []        # per-objective verdicts, last pass
        self._thread = None
        self._stop = threading.Event()

    # -- evaluation --------------------------------------------------------
    def _counters_for_prev(self):
        names = set()
        for o in self.spec.objectives:
            if o["kind"] == "error_rate":
                names.update(o["ok"])
                names.update(o["errors"])
            elif o["kind"] == "rate_floor":
                names.add(o["counter"])
        return {n: self._metrics.counter(n) for n in names}

    def evaluate(self):
        """One evaluation pass over every objective; returns the list
        of breach dicts found this pass.  All shared state
        (``_consecutive``, ``_postmortem_armed``, ``breach_log``) is
        mutated under the watchdog lock — :meth:`state` reads the same
        structures from HTTP handler threads, and a dict/deque resized
        mid-iteration would 500 the /stats probe at exactly breach
        onset."""
        now = time.monotonic()
        with self._lock:
            self._last_eval = now
            prev = self._prev
            counters = self._counters_for_prev()
            self._prev = (now, counters)
        elapsed = (now - prev[0]) if prev else None
        breaches = []
        breaching = 0
        last_values = []
        for o in self.spec.objectives:
            self._metrics.inc("slo.evaluations")
            verdict = self._judge(o, prev, counters, elapsed)
            name = o["name"]
            last_values.append({
                "objective": name, "kind": o["kind"],
                "value": None if verdict is None else verdict[0],
                "threshold": None if verdict is None else verdict[1],
                "breached": bool(verdict and verdict[2])})
            dump = False
            with self._lock:
                self.evaluations += 1
                if verdict is None:      # nothing to judge this window
                    self._consecutive[name] = 0
                    self._postmortem_armed[name] = True
                    continue
                value, threshold, breached = verdict
                if not breached:
                    self._consecutive[name] = 0
                    self._postmortem_armed[name] = True
                    continue
                breaching += 1
                self._consecutive[name] += 1
                breach = {"time_unix": time.time(),
                          "objective": name, "kind": o["kind"],
                          "value": value, "threshold": threshold,
                          "consecutive": self._consecutive[name]}
                self.breach_log.append(breach)
                log_tail = list(self.breach_log)[-32:]
                self.breaches_total += 1
                if self._consecutive[name] >= self.spec.sustained and \
                        self._postmortem_armed[name]:
                    # one post-mortem per sustained episode: re-arms
                    # only after the objective recovers (or goes idle)
                    self._postmortem_armed[name] = False
                    dump = True
            breaches.append(breach)
            self._metrics.inc("slo.breach")
            logger.warning("slo.breach %s", json.dumps(breach))
            if dump:
                self._metrics.inc("slo.postmortems")
                from paddle_tpu.obs import flight
                flight.write_postmortem(
                    reason=f"sustained SLO breach: {name} "
                           f"({breach['consecutive']} consecutive)",
                    extra={"slo_breach": breach,
                           "breach_log": log_tail,
                           "spec": self.spec.to_dict()})
        with self._lock:
            self._last_values = last_values
        self._metrics.set_gauge("slo.breaching", breaching)
        return breaches

    def last_values(self):
        """Per-objective verdicts of the most recent :meth:`evaluate`
        pass: ``[{objective, kind, value, threshold, breached}]``
        (``value``/``threshold`` None when that window had nothing to
        judge).  This is the fleet controller's PRESSURE signal — it
        acts on value-vs-threshold *margins* before a breach, not only
        on the binary breach log."""
        with self._lock:
            return [dict(v) for v in self._last_values]

    def _judge(self, o, prev, counters, elapsed):
        """(value, threshold, breached) for one objective, or None when
        this window has nothing to judge."""
        kind = o["kind"]
        if kind == "quantile":
            q = o.get("quantile", "p99")
            value = self._metrics.percentiles(o["series"], (int(q[1:]),)) \
                .get(q)
            if value is None:
                return None
            return value, o["max"], value > o["max"]
        if prev is None or not elapsed or elapsed <= 0:
            return None                 # rate kinds need two passes
        if kind == "error_rate":
            ok = sum(counters[c] - prev[1].get(c, 0) for c in o["ok"])
            err = sum(counters[c] - prev[1].get(c, 0)
                      for c in o["errors"])
            total = ok + err
            if total <= 0:
                return None
            ratio = err / total
            return ratio, o["max_ratio"], ratio > o["max_ratio"]
        if kind == "rate_floor":
            delta = counters[o["counter"]] - \
                prev[1].get(o["counter"], 0)
            if delta == 0 and o.get("idle_ok", True):
                return None
            rate = delta / elapsed
            return rate, o["min_rate"], rate < o["min_rate"]
        return None  # pragma: no cover - validate_spec rejects

    def maybe_evaluate(self):
        """Evaluate iff the spec's interval has elapsed — the cheap
        call hot loops make every iteration."""
        last = self._last_eval
        if last is not None and \
                time.monotonic() - last < self.spec.interval:
            return None
        return self.evaluate()

    # -- state / lifecycle -------------------------------------------------
    def state(self):
        """JSON-able summary for /stats (shared structures copied
        under the watchdog lock — the evaluation thread mutates them
        concurrently)."""
        with self._lock:
            breaching = {name: n for name, n
                         in self._consecutive.items() if n}
            log_tail = list(self.breach_log)[-16:]
            evaluations = self.evaluations
            breaches_total = self.breaches_total
        return {"source": self.spec.source,
                "interval_seconds": self.spec.interval,
                "sustained_breaches": self.spec.sustained,
                "objectives": [o["name"] for o in self.spec.objectives],
                "evaluations": evaluations,
                "breaches_total": breaches_total,
                "breaching": breaching,
                "breach_log": log_tail}

    def start(self, interval=None):
        """Background evaluation thread (the router wiring); idempotent."""
        if self._thread is not None:
            return self._thread
        period = float(interval if interval is not None
                       else self.spec.interval)

        def loop():
            while not self._stop.wait(period):
                try:
                    self.evaluate()
                except Exception:  # pragma: no cover - must never die
                    logger.exception("slo watchdog evaluation failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="paddle-tpu-slo-watchdog")
        self._thread.start()
        return self._thread

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def watchdog_from_env(metrics=None):
    """An armed :class:`SLOWatchdog` from ``PADDLE_TPU_SLO``, or None
    when the env var is unset.  A malformed file WARNS and disarms —
    an observability knob must never veto serving (selfcheck is the
    static gate that fails it loudly)."""
    path = os.environ.get(SLO_ENV, "").strip()
    if not path:
        return None
    try:
        return SLOWatchdog(path, metrics=metrics)
    except (OSError, ValueError) as e:
        import warnings
        warnings.warn(f"{SLO_ENV}={path!r} did not load — SLO watchdog "
                      f"disarmed: {e}")
        return None


def tick(watchdog):
    """The per-iteration hot-path hook: no-op when no watchdog is
    armed, one clock read when armed but not due."""
    if watchdog is not None:
        watchdog.maybe_evaluate()
