"""Prometheus text-exposition rendering of the runtime metrics registry.

The reference exposed its pserver/master counters through Go Prometheus
handlers (``go/pserver/service.go``); here one renderer maps the
process-wide :class:`profiler.RuntimeMetrics` snapshot onto the v0.0.4
text format, served by the inference server's ``/metrics`` endpoint and
``paddle_tpu stats --prom``:

==============  =========================================================
registry kind   exposition mapping
==============  =========================================================
counters        ``paddle_tpu_<name>_total`` (counter)
gauges          ``paddle_tpu_<name>`` (gauge)
series          summary: ``{quantile="0.5|0.95|0.99"}`` + ``_sum`` /
                ``_count`` (window percentiles over the bounded
                reservoir; sum/count are lifetime aggregates)
histograms      histogram: cumulative ``_bucket{le="..."}`` + ``_sum`` /
                ``_count`` (discrete occupancy values as bucket edges)
==============  =========================================================

Dots and other non-metric characters in registry names become ``_``
(``serving.request_seconds`` -> ``paddle_tpu_serving_request_seconds``).
"""

from __future__ import annotations

import re

__all__ = ["render_prometheus", "sanitize_name", "CONTENT_TYPE", "PREFIX"]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
PREFIX = "paddle_tpu_"

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_LEADING_BAD = re.compile(r"^[^a-zA-Z_:]")


def sanitize_name(name):
    """Registry name -> legal Prometheus metric name (prefixed)."""
    out = _NAME_BAD.sub("_", str(name))
    if _LEADING_BAD.match(out):
        out = "_" + out
    return PREFIX + out


def _fmt(value):
    if value is None:
        return "NaN"
    f = float(value)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _esc_label(value):
    return str(value).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _labelset(labels, **inline):
    """``{a="1",b="2"}`` rendering of fixed labels + per-sample ones
    (``quantile``/``le``); empty when there are none."""
    pairs = list((labels or {}).items()) + list(inline.items())
    if not pairs:
        return ""
    return "{" + ",".join(f'{k}="{_esc_label(v)}"' for k, v in pairs) + "}"


def render_prometheus(snapshot=None, labels=None, emit_meta=True):
    """Render a ``RuntimeMetrics.snapshot()`` (or the live process
    registry when None) as Prometheus text exposition format.

    ``labels`` attaches a fixed label set to EVERY sample (the fleet
    federation path renders each replica's snapshot under its
    ``replica="host:port"`` identity); ``emit_meta=False`` suppresses
    the ``# HELP``/``# TYPE`` comments so a federated exposition can
    declare each family once and append the per-replica sample blocks
    after it.

    Conformance contract (locked by tests/test_obs_prom.py): histogram
    buckets render in ascending ``le`` order with cumulative counts, a
    terminal ``+Inf`` bucket, and ``_count`` equal to the ``+Inf``
    bucket; summaries carry ascending ``quantile`` in [0, 1] plus
    ``_sum``/``_count``; counters end in ``_total``."""
    if snapshot is None:
        from paddle_tpu.profiler import runtime_metrics
        snapshot = runtime_metrics.snapshot()
    lines = []

    def meta(metric, name, kind):
        if emit_meta:
            lines.append(f"# HELP {metric} {name} ({kind})")
            lines.append(f"# TYPE {metric} {kind.split()[-1]}")

    for name, value in sorted((snapshot.get("counters") or {}).items()):
        metric = sanitize_name(name) + "_total"
        meta(metric, name, "counter")
        lines.append(f"{metric}{_labelset(labels)} {_fmt(value)}")

    for name, value in sorted((snapshot.get("gauges") or {}).items()):
        metric = sanitize_name(name)
        meta(metric, name, "gauge")
        lines.append(f"{metric}{_labelset(labels)} {_fmt(value)}")

    for name, s in sorted((snapshot.get("series") or {}).items()):
        metric = sanitize_name(name)
        meta(metric, name, "windowed summary")
        for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            v = s.get(key)
            if v is not None:
                lines.append(f"{metric}{_labelset(labels, quantile=q)} "
                             f"{_fmt(v)}")
        lines.append(f"{metric}_sum{_labelset(labels)} "
                     f"{_fmt(s.get('total', 0.0))}")
        lines.append(f"{metric}_count{_labelset(labels)} "
                     f"{_fmt(s.get('count', 0))}")

    for name, hist in sorted((snapshot.get("histograms") or {}).items()):
        metric = sanitize_name(name)
        meta(metric, name, "histogram")
        total = 0
        weighted = 0.0
        # discrete observed values become cumulative le edges, emitted
        # strictly ascending (numeric sort, not lexicographic)
        for key, count in sorted(hist.items(), key=lambda kv: float(kv[0])):
            total += int(count)
            weighted += float(key) * int(count)
            lines.append(f"{metric}_bucket{_labelset(labels, le=key)} "
                         f"{_fmt(total)}")
        lines.append(f'{metric}_bucket{_labelset(labels, le="+Inf")} '
                     f"{_fmt(total)}")
        lines.append(f"{metric}_sum{_labelset(labels)} {_fmt(weighted)}")
        lines.append(f"{metric}_count{_labelset(labels)} {_fmt(total)}")

    return "\n".join(lines) + "\n"
