"""Persistent run ledger: append-only, schema-validated JSONL step
series (docs/observability.md "Run ledger & numerics").

Every step-series training-health signal the process already computes —
loss, grad/param norms, update ratio, MFU, tokens/s, datapipe stall,
HBM headroom — previously evaporated at process exit.  The ledger
persists them as a directory of JSONL segments:

* ``seg-000000.jsonl`` … sealed, immutable segments;
* ``seg-00000N.jsonl.open`` … the single active segment.

Each segment's first line is a header (``{"ledger_format": 1,
"segment": N, "rows_before": M}``); every following line is one step
row (``validate_row``).  Rotation is atomic: flush + fsync + ``os
.replace`` of the ``.open`` name to the sealed name, then a fresh
active segment.  Appends are buffered (``flush_every``) so an armed
ledger stays inside the tests/test_obs_overhead.py <5% budget; the
disarmed path in ``Executor.run_pipeline`` is a ``None`` check.

**Exactly-once resume.** ``state_dict()`` (flush + fsync, then
``{"format", "rows_total", "last_step"}``) rides the checkpoint
sidecar exactly like datapipe iterator state: `run_pipeline` appends
the step row BEFORE ``on_step`` runs the checkpoint hook, so a
snapshot's ``rows_total`` includes its own step; on restore,
``load_state_dict`` truncates the ledger back to ``rows_total`` rows —
rows from steps after the restored checkpoint (which will be re-run)
are dropped, rows up to it are never duplicated.  Because ``note_step``
self-numbers rows (``last_step + 1``), the series stays monotonic even
though ``run_pipeline`` restarts its local step counter at 0.

**Drift alerts.** An optional drift spec (same problems-list
``validate_spec`` idiom as ``obs/slo.py``; see
``EXAMPLE_DRIFT_SPEC``) evaluates rules per appended row — ``spike``
(value > EMA × factor after a warmup), ``ceiling`` (value > max),
``floor`` (value < min).  Breaches increment ``ledger.drift_breaches``;
``sustained`` consecutive breaches of one rule write a flight-recorder
post-mortem (``ledger.drift_postmortems``) and the episode re-arms only
after the rule recovers, so a flapping signal yields one post-mortem
per episode.

``paddle_tpu runs tail|show|compare`` reads ledger directories offline;
:func:`active_tail` gives the flight recorder the last-N in-memory rows
so crash dumps show the loss/grad trajectory into the fault.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import re
import time
import weakref

logger = logging.getLogger(__name__)

__all__ = ["RunLedger", "DriftWatch", "validate_spec", "validate_row",
           "validate_header", "read_rows", "tail_rows", "summarize",
           "compare", "active_tail", "LEDGER_FORMAT", "ROW_FIELDS",
           "EXAMPLE_DRIFT_SPEC"]

LEDGER_FORMAT = 1

# every optional per-step field (number or null); "step"/"time_unix"
# are the two required keys of a row
ROW_FIELDS = ("loss", "grad_norm", "param_norm", "update_ratio", "mfu",
              "tokens_per_sec", "datapipe_stall_seconds",
              "hbm_headroom_bytes")

# gauges note_step snapshots into the row (reads, not emissions — the
# writers own the registry entries)
_GAUGE_FIELDS = (("grad_norm", "train.grad_norm"),
                 ("param_norm", "train.param_norm"),
                 ("update_ratio", "train.update_ratio"),
                 ("mfu", "train.mfu"),
                 ("tokens_per_sec", "train.tokens_per_sec"),
                 ("hbm_headroom_bytes", "hbm.headroom_bytes"))

_SEG_RE = re.compile(r"^seg-(\d{6})\.jsonl(\.open)?$")

DRIFT_KINDS = ("spike", "ceiling", "floor")

# the documented drift-spec shape — selfcheck validates this constant
# so the validator is exercised even when no spec is armed
EXAMPLE_DRIFT_SPEC = {
    "version": 1,
    "sustained": 2,
    "rules": [
        {"name": "loss-spike", "kind": "spike", "field": "loss",
         "factor": 10.0, "warmup": 8, "ema_beta": 0.9},
        {"name": "grad-norm-explosion", "kind": "ceiling",
         "field": "grad_norm", "max": 1e3},
    ],
}


def _is_number(v):
    return isinstance(v, (int, float)) and not isinstance(v, bool) \
        and v == v and abs(v) != float("inf")


# ---------------------------------------------------------------------------
# schema
# ---------------------------------------------------------------------------

def validate_header(obj):
    """Problems of a segment header line (empty list = valid)."""
    problems = []
    if not isinstance(obj, dict):
        return [f"header must be an object, got {type(obj).__name__}"]
    if obj.get("ledger_format") != LEDGER_FORMAT:
        problems.append(f"ledger_format must be {LEDGER_FORMAT}, "
                        f"got {obj.get('ledger_format')!r}")
    for key in ("segment", "rows_before"):
        v = obj.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            problems.append(f"{key} must be an integer >= 0")
    return problems


def validate_row(obj):
    """Problems of one step row (empty list = valid).  Unknown keys are
    rejected — the schema is the compatibility contract ``runs
    compare`` and ``bench check`` rely on."""
    problems = []
    if not isinstance(obj, dict):
        return [f"row must be an object, got {type(obj).__name__}"]
    step = obj.get("step")
    if not isinstance(step, int) or isinstance(step, bool) or step < 0:
        problems.append("step must be an integer >= 0")
    if not _is_number(obj.get("time_unix")):
        problems.append("time_unix must be a finite number")
    allowed = {"step", "time_unix", *ROW_FIELDS}
    for key in obj:
        if key not in allowed:
            problems.append(f"unknown field {key!r}")
    for key in ROW_FIELDS:
        if key in obj and obj[key] is not None \
                and not _is_number(obj[key]):
            problems.append(f"{key} must be a finite number or null")
    return problems


def validate_spec(obj):
    """Schema problems of a drift spec dict, as a list of strings
    (empty = valid).  Never raises — selfcheck renders the list."""
    problems = []
    if not isinstance(obj, dict):
        return [f"spec must be a JSON object, got {type(obj).__name__}"]
    if obj.get("version") != LEDGER_FORMAT:
        problems.append(f"version must be {LEDGER_FORMAT}, "
                        f"got {obj.get('version')!r}")
    if "sustained" in obj and (not isinstance(obj["sustained"], int)
                               or isinstance(obj["sustained"], bool)
                               or obj["sustained"] < 1):
        problems.append("sustained must be an integer >= 1")
    rules = obj.get("rules")
    if not isinstance(rules, list) or not rules:
        problems.append("rules must be a non-empty list")
        return problems
    seen = set()
    for i, rule in enumerate(rules):
        where = f"rules[{i}]"
        if not isinstance(rule, dict):
            problems.append(f"{where} must be an object")
            continue
        name = rule.get("name")
        if not isinstance(name, str) or not name:
            problems.append(f"{where}.name must be a non-empty string")
        elif name in seen:
            problems.append(f"{where}.name {name!r} is duplicated")
        else:
            seen.add(name)
        kind = rule.get("kind")
        if kind not in DRIFT_KINDS:
            problems.append(
                f"{where}.kind must be one of {DRIFT_KINDS}, "
                f"got {kind!r}")
            continue
        if rule.get("field") not in ROW_FIELDS:
            problems.append(
                f"{where}.field must be one of {ROW_FIELDS}, "
                f"got {rule.get('field')!r}")
        if kind == "spike":
            if not _is_number(rule.get("factor")) \
                    or rule.get("factor") <= 1:
                problems.append(f"{where}.factor must be a number > 1")
            if "warmup" in rule and (
                    not isinstance(rule["warmup"], int)
                    or isinstance(rule["warmup"], bool)
                    or rule["warmup"] < 1):
                problems.append(f"{where}.warmup must be an "
                                "integer >= 1")
            if "ema_beta" in rule and (
                    not _is_number(rule["ema_beta"])
                    or not 0 < rule["ema_beta"] < 1):
                problems.append(f"{where}.ema_beta must be in (0, 1)")
        elif kind == "ceiling":
            if not _is_number(rule.get("max")):
                problems.append(f"{where}.max must be a finite number")
        elif kind == "floor":
            if not _is_number(rule.get("min")):
                problems.append(f"{where}.min must be a finite number")
    return problems


class DriftWatch:
    """Evaluate drift rules against each appended row.

    Mirrors ``SLOWatchdog``'s episode semantics at row granularity:
    ``sustained`` CONSECUTIVE breaches of one rule emit a single
    flight-recorder post-mortem, re-armed after the rule recovers."""

    def __init__(self, spec, metrics=None, log_limit=64):
        problems = validate_spec(spec)
        if problems:
            raise ValueError("invalid drift spec: " +
                             "; ".join(problems))
        self.spec = spec
        self.sustained = int(spec.get("sustained", 3))
        self._metrics = metrics
        self.breach_log = collections.deque(maxlen=log_limit)
        self._state = {r["name"]: {"ema": None, "n": 0, "consec": 0,
                                   "fired": False}
                       for r in spec["rules"]}

    def _judge(self, rule, value, st):
        kind = rule["kind"]
        if kind == "ceiling":
            return value > rule["max"]
        if kind == "floor":
            return value < rule["min"]
        # spike: against the EMA of previously seen values
        beta = rule.get("ema_beta", 0.9)
        warmup = rule.get("warmup", 8)
        breached = (st["n"] >= warmup and st["ema"] is not None
                    and abs(value) > abs(st["ema"]) * rule["factor"])
        if not breached:  # a spike must not drag the baseline up
            st["ema"] = value if st["ema"] is None else \
                beta * st["ema"] + (1 - beta) * value
            st["n"] += 1
        return breached

    def evaluate(self, row):
        """Judge one row; returns the list of rule names that breached."""
        breached_names = []
        for rule in self.spec["rules"]:
            value = row.get(rule["field"])
            if value is None:
                continue
            st = self._state[rule["name"]]
            if not self._judge(rule, value, st):
                st["consec"] = 0
                st["fired"] = False
                continue
            breached_names.append(rule["name"])
            st["consec"] += 1
            entry = {"rule": rule["name"], "kind": rule["kind"],
                     "field": rule["field"], "value": value,
                     "step": row.get("step"),
                     "consecutive": st["consec"]}
            self.breach_log.append(entry)
            if self._metrics is not None:
                self._metrics.inc("ledger.drift_breaches")
            logger.warning("ledger drift breach: %s", entry)
            if st["consec"] >= self.sustained and not st["fired"]:
                st["fired"] = True
                if self._metrics is not None:
                    self._metrics.inc("ledger.drift_postmortems")
                try:
                    from paddle_tpu.obs import flight
                    flight.write_postmortem(
                        reason=f"ledger drift sustained: "
                               f"{rule['name']}",
                        extra={"breach": entry,
                               "row": dict(row),
                               "rule": dict(rule)})
                except Exception:  # pragma: no cover - best effort
                    pass
        return breached_names


# ---------------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------------

# the most recently installed ledger (weakref), for flight post-mortems
_ACTIVE = None


def active_tail(n=32):
    """Last ``n`` in-memory rows of the installed ledger (``[]`` when
    none) — embedded into flight-recorder post-mortems."""
    ref = _ACTIVE
    ledger = ref() if ref is not None else None
    if ledger is None:
        return []
    return ledger.tail(n)


class RunLedger:
    """Append-only JSONL step series over a directory of segments."""

    def __init__(self, dirname, rotate_rows=4096, flush_every=32,
                 drift_spec=None, metrics=None, install=True):
        if metrics is None:
            from paddle_tpu.profiler import runtime_metrics
            metrics = runtime_metrics
        self.dirname = str(dirname)
        self.rotate_rows = max(1, int(rotate_rows))
        self.flush_every = max(1, int(flush_every))
        self._metrics = metrics
        self.drift = DriftWatch(drift_spec, metrics=metrics) \
            if drift_spec else None
        self._buf = []
        self._tail = collections.deque(maxlen=64)
        self._fh = None
        self._recover()
        if install:
            global _ACTIVE
            _ACTIVE = weakref.ref(self)

    # -- segment bookkeeping -------------------------------------------

    def _seg_path(self, index, open_=False):
        name = f"seg-{index:06d}.jsonl"
        return os.path.join(self.dirname,
                            name + (".open" if open_ else ""))

    def _list_segments(self):
        """Sorted ``(index, path, is_open)`` of every segment file."""
        out = []
        for name in os.listdir(self.dirname):
            m = _SEG_RE.match(name)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.dirname, name),
                            bool(m.group(2))))
        out.sort()
        return out

    def _recover(self):
        os.makedirs(self.dirname, exist_ok=True)
        segs = self._list_segments()
        opens = [s for s in segs if s[2]]
        if len(opens) > 1:  # torn rotation: seal all but the newest
            for index, path, _ in opens[:-1]:
                os.replace(path, self._seg_path(index))
            segs = self._list_segments()
            opens = opens[-1:]
        if not segs:
            self._start_segment(0, 0, last_step=-1)
            return
        if opens:
            index, path, _ = opens[0]
            header, rows = _scan_segment(path, truncate_torn=True)
            if header is None:  # torn before the header landed
                rows_before = self._rows_before_from_sealed(segs, index)
                os.remove(path)
                self._start_segment(index, rows_before,
                                    last_step=self._last_sealed_step(
                                        segs, index))
                return
            self._seg_index = index
            self._seg_rows = len(rows)
            self._rows_total = header["rows_before"] + len(rows)
            self._last_step = rows[-1]["step"] if rows else \
                self._last_sealed_step(segs, index)
            self._tail.extend(rows[-self._tail.maxlen:])
            self._fh = open(path, "ab")
        else:  # sealed-only directory (clean kill after rotation)
            index = segs[-1][0] + 1
            header, rows = _scan_segment(segs[-1][1])
            rows_before = (header["rows_before"] if header else 0) \
                + len(rows)
            self._start_segment(
                index, rows_before,
                last_step=rows[-1]["step"] if rows else -1)
            self._tail.extend(rows[-self._tail.maxlen:])

    def _rows_before_from_sealed(self, segs, before_index):
        sealed = [s for s in segs if not s[2] and s[0] < before_index]
        if not sealed:
            return 0
        header, rows = _scan_segment(sealed[-1][1])
        return (header["rows_before"] if header else 0) + len(rows)

    def _last_sealed_step(self, segs, before_index):
        sealed = [s for s in segs if not s[2] and s[0] < before_index]
        if not sealed:
            return -1
        _, rows = _scan_segment(sealed[-1][1])
        return rows[-1]["step"] if rows else -1

    def _start_segment(self, index, rows_before, last_step=None):
        path = self._seg_path(index, open_=True)
        header = {"ledger_format": LEDGER_FORMAT, "segment": index,
                  "rows_before": rows_before}
        with open(path, "wb") as f:
            f.write(json.dumps(header).encode() + b"\n")
            f.flush()
            os.fsync(f.fileno())
        self._fh = open(path, "ab")
        self._seg_index = index
        self._seg_rows = 0
        self._rows_total = rows_before
        if last_step is not None:
            self._last_step = last_step

    def _flush(self, fsync=False):
        if self._buf:
            self._fh.write(b"".join(self._buf))
            self._buf = []
        self._fh.flush()
        if fsync:
            os.fsync(self._fh.fileno())

    def _rotate(self):
        self._flush(fsync=True)
        self._fh.close()
        os.replace(self._seg_path(self._seg_index, open_=True),
                   self._seg_path(self._seg_index))
        self._metrics.inc("ledger.rotations")
        self._start_segment(self._seg_index + 1, self._rows_total)

    # -- appends -------------------------------------------------------

    def append(self, row):
        """Validate + append one row dict (non-finite values sanitized
        to null first).  Returns the row as written."""
        row = dict(row)
        for key in ROW_FIELDS:
            v = row.get(key)
            if v is not None and not _is_number(v):
                row[key] = None
        problems = validate_row(row)
        if problems:
            raise ValueError("invalid ledger row: " +
                             "; ".join(problems))
        self._buf.append(json.dumps(row, allow_nan=False).encode()
                         + b"\n")
        self._seg_rows += 1
        self._rows_total += 1
        self._last_step = row["step"]
        self._tail.append(row)
        self._metrics.inc("ledger.rows")
        if self.drift is not None:
            self.drift.evaluate(row)
        if self._seg_rows >= self.rotate_rows:
            self._rotate()
        elif len(self._buf) >= self.flush_every:
            self._flush()
        return row

    def note_step(self, step=None, fetch_names=(), fetches=(),
                  stall_seconds=None, loss=None):
        """Build and append one step row from the training loop's
        fetches plus the gauges the process already maintains.

        ``step=None`` self-numbers (``last_step + 1``): `run_pipeline`
        restarts its local counter at 0 on every call, but the ledger
        series must stay monotonic across resumes."""
        if step is None:
            step = self._last_step + 1
        if loss is None:
            loss = _first_scalar(fetch_names, fetches)
        row = {"step": int(step), "time_unix": time.time(),
               "loss": loss}
        for field, gauge in _GAUGE_FIELDS:
            v = self._metrics.gauge(gauge)
            if v is not None:
                row[field] = v
        if stall_seconds is not None:
            row["datapipe_stall_seconds"] = float(stall_seconds)
        return self.append(row)

    # -- resume (checkpoint sidecar) -----------------------------------

    def state_dict(self):
        """Durable resume cursor (flushes + fsyncs first, so a crash
        can never leave fewer rows on disk than a saved sidecar
        claims)."""
        self._flush(fsync=True)
        return {"format": LEDGER_FORMAT,
                "rows_total": self._rows_total,
                "last_step": self._last_step}

    def load_state_dict(self, state):
        """Rewind the ledger to exactly ``state["rows_total"]`` rows
        (the restore half of exactly-once resume).  Raises
        ``ValueError`` when the sidecar is malformed or claims more
        rows than exist."""
        if not isinstance(state, dict) \
                or state.get("format") != LEDGER_FORMAT:
            raise ValueError(
                f"ledger sidecar format mismatch: expected "
                f"{LEDGER_FORMAT}, got "
                f"{state.get('format') if isinstance(state, dict) else state!r}")
        target = state.get("rows_total")
        if not isinstance(target, int) or isinstance(target, bool) \
                or target < 0:
            raise ValueError("ledger sidecar rows_total must be an "
                             "integer >= 0")
        self._flush(fsync=True)
        if target > self._rows_total:
            raise ValueError(
                f"ledger sidecar claims {target} rows but only "
                f"{self._rows_total} exist (history lost?)")
        if target == self._rows_total:
            return
        removed = self._rows_total - target
        self._fh.close()
        self._fh = None
        segs = self._list_segments()
        kept_last_step = -1
        boundary = None
        for index, path, is_open in segs:
            header, rows = _scan_segment(path)
            rows_before = header["rows_before"] if header else 0
            if boundary is not None or rows_before + len(rows) > target:
                if boundary is None:
                    boundary = index
                    keep = rows[:target - rows_before]
                    tmp = path + ".tmp"
                    with open(tmp, "wb") as f:
                        f.write(json.dumps(
                            {"ledger_format": LEDGER_FORMAT,
                             "segment": index,
                             "rows_before": rows_before}).encode()
                            + b"\n")
                        for r in keep:
                            f.write(json.dumps(r).encode() + b"\n")
                        f.flush()
                        os.fsync(f.fileno())
                    os.replace(tmp, self._seg_path(index, open_=True))
                    if not is_open:
                        os.remove(path)
                    if keep:
                        kept_last_step = keep[-1]["step"]
                else:
                    os.remove(path)
            else:
                if rows:
                    kept_last_step = rows[-1]["step"]
        self._fh = open(self._seg_path(boundary, open_=True), "ab")
        self._seg_index = boundary
        header, rows = _scan_segment(
            self._seg_path(boundary, open_=True))
        self._seg_rows = len(rows)
        self._rows_total = target
        self._last_step = kept_last_step
        self._tail.clear()
        self._tail.extend(rows[-self._tail.maxlen:])
        self._metrics.inc("ledger.rewound_rows", removed)
        logger.info("ledger rewound %d rows to %d (step %d)",
                    removed, target, self._last_step)

    # -- readers -------------------------------------------------------

    def tail(self, n=32):
        n = max(0, int(n))
        rows = list(self._tail)
        return rows[len(rows) - n:] if n else []

    @property
    def rows_total(self):
        return self._rows_total

    @property
    def last_step(self):
        return self._last_step

    def flush(self):
        self._flush(fsync=True)

    def close(self):
        if self._fh is not None:
            self._flush(fsync=True)
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# offline readers (`paddle_tpu runs ...`)
# ---------------------------------------------------------------------------

def _scan_segment(path, truncate_torn=False):
    """``(header, rows)`` of one segment file; a torn tail (partial or
    invalid trailing line after a kill) is ignored — and physically
    truncated away when ``truncate_torn`` (recovery of the active
    segment, so the append handle starts at a clean line boundary)."""
    header = None
    rows = []
    with open(path, "rb") as f:
        data = f.read()
    good = 0
    pos = 0
    while pos < len(data):
        nl = data.find(b"\n", pos)
        if nl < 0:
            break  # no trailing newline: torn mid-line
        line = data[pos:nl]
        pos = nl + 1
        if not line.strip():
            good = pos
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            break
        if header is None:
            if validate_header(obj):
                break
            header = obj
        else:
            if validate_row(obj):
                break
            rows.append(obj)
        good = pos
    if truncate_torn and good < len(data):
        with open(path, "r+b") as f:
            f.truncate(good)
    return header, rows


def read_rows(dirname):
    """All rows of a ledger directory, in order.  Raises ``ValueError``
    on an unreadable directory."""
    ledger_dir = str(dirname)
    if not os.path.isdir(ledger_dir):
        raise ValueError(f"not a ledger directory: {ledger_dir}")
    segs = []
    for name in os.listdir(ledger_dir):
        m = _SEG_RE.match(name)
        if m:
            segs.append((int(m.group(1)),
                         os.path.join(ledger_dir, name)))
    if not segs:
        raise ValueError(f"no ledger segments in {ledger_dir}")
    segs.sort()
    rows = []
    for _, path in segs:
        _, seg_rows = _scan_segment(path)
        rows.extend(seg_rows)
    return rows


def tail_rows(dirname, n=10):
    rows = read_rows(dirname)
    return rows[max(0, len(rows) - max(0, int(n))):]


def _series_summary(rows, field):
    values = [r[field] for r in rows
              if r.get(field) is not None]
    if not values:
        return None
    return {"first": values[0], "last": values[-1],
            "min": min(values), "max": max(values),
            "samples": len(values)}


def summarize(dirname):
    """The ``runs show`` body: row/segment counts, step range, and a
    first/last/min/max digest per field."""
    rows = read_rows(dirname)
    segs = sum(1 for name in os.listdir(str(dirname))
               if _SEG_RE.match(name))
    out = {"dir": str(dirname), "rows": len(rows), "segments": segs,
           "first_step": rows[0]["step"] if rows else None,
           "last_step": rows[-1]["step"] if rows else None,
           "fields": {}}
    for field in ROW_FIELDS:
        s = _series_summary(rows, field)
        if s is not None:
            out["fields"][field] = s
    return out


def compare(dir_a, dir_b):
    """The ``runs compare`` body: per-field digests of both runs plus
    the last-value delta on the steps both ledgers cover."""
    a, b = summarize(dir_a), summarize(dir_b)
    deltas = {}
    for field in ROW_FIELDS:
        sa, sb = a["fields"].get(field), b["fields"].get(field)
        if sa is None or sb is None:
            continue
        deltas[field] = {"a_last": sa["last"], "b_last": sb["last"],
                         "delta_last": sb["last"] - sa["last"]}
    return {"a": a, "b": b, "deltas": deltas}


def _first_scalar(fetch_names, fetches):
    """The loss heuristic: the first fetched value that collapses to a
    finite scalar float (training loops fetch loss first)."""
    import numpy as np
    for _, value in zip(fetch_names, fetches):
        try:
            arr = np.asarray(value)
        except Exception:
            continue
        if arr.size == 1 and getattr(arr.dtype, "kind", None) == "f":
            v = float(arr.reshape(()))
            return v if v == v and abs(v) != float("inf") else None
    return None
