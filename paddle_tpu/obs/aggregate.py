"""Fleet-wide observability aggregation: metrics federation and
cross-process trace assembly.

PR 4's spans, flight recorder, and Prometheus exposition are strictly
per-process; the serving fleet (router + master + N replicas) needs the
two multi-process shapes of the related work:

- **Metrics federation** (Monarch/Prometheus-federation shape):
  :class:`FleetScraper` pulls every replica's ``/stats`` snapshot and
  renders ONE fleet-level exposition — each replica's full registry
  under a ``replica="host:port"`` label, per-replica
  ``fleet_replica_up`` liveness, and computed rollups (aggregate RPS
  and tokens/s from counter deltas between scrapes, fleet-level
  latency/TTFT percentiles merged from the per-replica summaries).  A
  dead replica marks its sample block STALE (``up 0``, ``stale="1"``)
  instead of failing the scrape: the fleet view stays servable through
  churn.

- **Cross-process trace assembly** (Dapper stitching shape): trace ids
  already flow through ``X-Request-Id`` headers and master RPC frames;
  :func:`assemble_fleet_trace` fetches each process's span ring
  (``/spans``), normalizes clock skew NTP-style against the scraper's
  send/receive envelope (offset = remote ``now_unix`` minus the
  envelope midpoint), and merges everything into one Chrome-trace
  timeline with a distinct ``pid`` row group per process — a
  failed-over request's router -> dead-replica -> surviving-replica
  story becomes one artifact.

Merged-percentile caveat: ``/stats`` summaries carry window
percentiles, not raw samples, so the fleet p99 is the COUNT-WEIGHTED
mean of per-replica p99s — an approximation (exact only when replicas
see identical distributions), clearly better than "one process's p99"
and cheap enough to compute on every scrape.  The per-replica labelled
series remain in the exposition for exact per-process values.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

from paddle_tpu.obs import trace as _trace
from paddle_tpu.obs.prom import _fmt, _labelset, render_prometheus, \
    sanitize_name

__all__ = ["FleetScraper", "fetch_stats", "fetch_spans",
           "fetch_spans_many", "merged_quantile", "render_federated",
           "replica_perf", "assemble_fleet_trace", "CONTENT_TYPE",
           "PERF_GAUGES"]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# counter families summed into the fleet rollup rates
_REQUEST_COUNTERS = ("serving.requests_ok", "gen.requests_ok")
_TOKEN_COUNTERS = ("gen.tokens",)
# series whose per-replica summaries merge into fleet percentiles
_MERGED_SERIES = ("serving.request_seconds", "gen.ttft_seconds",
                  "gen.intertoken_seconds", "gen.decode_step_seconds")
# device-performance gauges federated per replica (obs.perf): each
# replica's value rides its labelled registry block; these also feed
# the fleet_mfu_mean / fleet_hbm_headroom_min rollups and the router's
# /stats `fleet_perf` summary
PERF_GAUGES = ("train.mfu", "gen.decode_mfu", "hbm.headroom_bytes",
               "hbm.total_bytes", "hbm.high_watermark_bytes",
               # training-health gauges (obs.numerics fused norms):
               # federated per replica and rolled up as
               # fleet_grad_norm_max — the exploding replica pages you
               "train.grad_norm", "train.param_norm",
               "train.update_ratio")


def replica_perf(scrapes):
    """Per-replica device-performance summary of a federation pass:
    ``{addr: {"id": ..., <gauge>: value}}`` over the :data:`PERF_GAUGES`
    a replica reports (replicas running no perf-instrumented work are
    omitted; stale replicas never appear)."""
    out = {}
    for s in scrapes:
        if not s.get("ok"):
            continue
        gauges = (s["stats"].get("gauges") or {})
        vals = {g: gauges[g] for g in PERF_GAUGES if g in gauges}
        if vals:
            out[s["addr"]] = dict(vals, id=s.get("id") or s["addr"])
    return out


def _get_json(addr, path, timeout):
    with urllib.request.urlopen(f"http://{addr}{path}",
                                timeout=timeout) as r:
        return json.loads(r.read())


def fetch_stats(addr, timeout=5.0):
    """One replica's ``/stats`` snapshot (raises on unreachable)."""
    return _get_json(addr, "/stats", timeout)


def fetch_spans(addr, timeout=5.0):
    """One process's ``/spans`` payload plus the scraper-side send/recv
    envelope ``(t_send_unix, t_recv_unix)`` used for clock-skew
    normalization."""
    t_send = time.time()
    payload = _get_json(addr, "/spans", timeout)
    t_recv = time.time()
    return payload, (t_send, t_recv)


def fetch_spans_many(addrs, timeout=5.0, max_concurrency=8):
    """Concurrent ``/spans`` scrape of many processes: a list of
    :func:`assemble_fleet_trace` source dicts, one per address —
    unreachable processes come back as ``{"source", "error"}`` entries
    (reported in the assembly sidecar, never fatal), and N hung
    replicas cost one timeout per pass, not N."""
    from concurrent.futures import ThreadPoolExecutor

    def one(addr):
        try:
            payload, envelope = fetch_spans(addr, timeout=timeout)
        except Exception as e:
            return {"source": addr, "error": f"{type(e).__name__}: {e}"}
        return {"source": addr, "payload": payload,
                "envelope": envelope}

    addrs = list(addrs)
    if not addrs:
        return []
    with ThreadPoolExecutor(
            max_workers=min(max(1, int(max_concurrency)),
                            len(addrs))) as pool:
        return list(pool.map(one, addrs))


def merged_quantile(scrapes, series, q="p99"):
    """Count-weighted merge of one series' per-replica window
    percentile across live scrapes; None when no replica has samples."""
    weighted = 0.0
    total = 0
    for s in scrapes:
        if not s.get("ok"):
            continue
        entry = ((s["stats"].get("series") or {}).get(series)) or {}
        count, value = entry.get("count") or 0, entry.get(q)
        if count and value is not None:
            weighted += value * count
            total += count
    return (weighted / total) if total else None


class FleetScraper:
    """Pull-based federation over a replica table.

    ``targets_fn`` returns the current scrape targets as ``[(addr,
    replica_id)]`` (the router passes a closure over its routing table
    — including cooling-down replicas: the scrape itself decides
    staleness by failing).  Rollup RATES come from counter deltas
    between consecutive scrapes, so the first federation pass renders
    totals but no rates."""

    def __init__(self, targets_fn, timeout=2.0, metrics=None,
                 max_concurrency=8):
        self._targets_fn = targets_fn
        self._timeout = float(timeout)
        self._max_concurrency = max(1, int(max_concurrency))
        if metrics is None:
            from paddle_tpu.profiler import runtime_metrics
            metrics = runtime_metrics
        self._metrics = metrics
        self._lock = threading.Lock()
        self._prev = None  # (monotonic, {addr: (requests, tokens)})
        self._last_perf = {}  # replica_perf() of the latest pass
        self._last_ok = set()  # addrs that answered the latest pass

    def _scrape_one(self, target):
        addr, replica_id = target
        one = {"addr": addr, "id": replica_id, "ok": False,
               "stats": None, "error": None, "rtt_s": None}
        t_req = time.perf_counter()
        try:
            one["stats"] = fetch_stats(addr, timeout=self._timeout)
            one["ok"] = True
            self._metrics.inc("fleet.scrape.ok")
        except Exception as e:
            one["error"] = f"{type(e).__name__}: {e}"
            self._metrics.inc("fleet.scrape.errors")
        one["rtt_s"] = time.perf_counter() - t_req
        return one

    def scrape(self):
        """One federation pass: ``[{addr, id, ok, stats|error,
        rtt_s}]`` — unreachable replicas come back ``ok=False`` (stale)
        instead of raising.  Targets are scraped CONCURRENTLY, so N
        partitioned replicas (connect hangs, not refuses) cost one
        scrape timeout per pass, not N — a Prometheus pull of the
        router must not go dark because a replica did."""
        from concurrent.futures import ThreadPoolExecutor
        t0 = time.perf_counter()
        targets = list(self._targets_fn())
        if not targets:
            scrapes = []
        else:
            with ThreadPoolExecutor(
                    max_workers=min(self._max_concurrency,
                                    len(targets))) as pool:
                scrapes = list(pool.map(self._scrape_one, targets))
        self._metrics.observe("fleet.scrape_seconds",
                              time.perf_counter() - t0)
        self._metrics.set_gauge("fleet.replicas_stale",
                                sum(1 for s in scrapes if not s["ok"]))
        with self._lock:
            self._last_perf = replica_perf(scrapes)
            self._last_ok = {s["addr"] for s in scrapes if s["ok"]}
        return scrapes

    def last_perf(self):
        """Per-replica MFU / HBM summary of the most recent federation
        pass (the router's ``/stats`` ``fleet_perf`` body; empty before
        the first scrape — ``/stats`` must never block on a fleet
        pull)."""
        with self._lock:
            return {addr: dict(vals)
                    for addr, vals in self._last_perf.items()}

    def last_ok(self):
        """Addresses that answered the most recent federation pass —
        the router's *scrape evidence* when deciding whether a sibling
        replica plausibly has headroom (empty before the first scrape:
        no evidence, make no headroom claims)."""
        with self._lock:
            return set(self._last_ok)

    def rates(self, scrapes):
        """Public face of the rollup rates: ``(rps, tokens_per_sec)``
        vs the previous pass — the controller's demand signal."""
        return self._rates(scrapes)

    def _rates(self, scrapes):
        """(rps, tokens_per_sec) vs the previous scrape; None on the
        first pass or when time stood still.  Deltas are PER REPLICA —
        summed only over replicas present in both passes, each clamped
        at 0 — so a replica dying (its counters leaving the sum) or
        restarting (its counters resetting) between scrapes does not
        zero the survivors' reported rate."""
        now = time.monotonic()
        per = {}
        for s in scrapes:
            if not s["ok"]:
                continue
            counters = s["stats"].get("counters") or {}
            per[s["addr"]] = (
                sum(counters.get(c, 0) for c in _REQUEST_COUNTERS),
                sum(counters.get(c, 0) for c in _TOKEN_COUNTERS))
        with self._lock:
            prev, self._prev = self._prev, (now, per)
        if prev is None or now <= prev[0]:
            return None, None
        dt = now - prev[0]
        requests = tokens = 0
        for addr, (r, t) in per.items():
            if addr not in prev[1]:
                continue  # newly seen: contributes from the next pass
            pr, pt = prev[1][addr]
            requests += max(0, r - pr)
            tokens += max(0, t - pt)
        return requests / dt, tokens / dt

    def federate(self):
        """Scrape + render: the fleet ``/metrics`` body.  Returns
        ``(text, scrapes)``."""
        scrapes = self.scrape()
        rps, tps = self._rates(scrapes)
        return render_federated(scrapes, rps=rps, tokens_per_sec=tps), \
            scrapes


def render_federated(scrapes, rps=None, tokens_per_sec=None):
    """Render a federation pass as one Prometheus exposition: fleet
    rollups first, per-replica liveness, then every replica's registry
    under its ``replica=`` label (one TYPE declaration per family)."""
    live = [s for s in scrapes if s["ok"]]
    lines = []
    declared = set()

    def rollup(metric, value, help_text):
        if value is None:
            return
        lines.append(f"# HELP {metric} {help_text}")
        lines.append(f"# TYPE {metric} gauge")
        declared.add(metric)
        lines.append(f"{metric} {_fmt(value)}")

    rollup("paddle_tpu_fleet_replicas_scraped", len(scrapes),
           "replicas in the federation scrape set")
    rollup("paddle_tpu_fleet_replicas_stale",
           len(scrapes) - len(live),
           "replicas unreachable in this pass (marked stale)")
    rollup("paddle_tpu_fleet_rps", rps,
           "aggregate completed requests/sec across live replicas "
           "(counter delta between scrapes)")
    rollup("paddle_tpu_fleet_tokens_per_sec", tokens_per_sec,
           "aggregate generated tokens/sec across live replicas "
           "(counter delta between scrapes)")
    for series in _MERGED_SERIES:
        for q in ("p50", "p99"):
            rollup(f"{sanitize_name(series)}_fleet_{q}",
                   merged_quantile(scrapes, series, q),
                   f"{series} {q} merged across replicas "
                   f"(count-weighted)")
    # device-performance rollups: fleet-mean MFU (a replica compiling
    # or idling drags it visibly) and the TIGHTEST HBM headroom — the
    # replica closest to OOM is the one that pages you, so min, not
    # mean.  Per-replica exact values ride the labelled registries.
    perf = replica_perf(scrapes)
    mfus = [v for p in perf.values()
            for v in [p.get("train.mfu", p.get("gen.decode_mfu"))]
            if v is not None]
    rollup("paddle_tpu_fleet_mfu_mean",
           (sum(mfus) / len(mfus)) if mfus else None,
           "mean live MFU across replicas reporting one "
           "(train.mfu, else gen.decode_mfu)")
    heads = [p["hbm.headroom_bytes"] for p in perf.values()
             if p.get("hbm.headroom_bytes") is not None]
    rollup("paddle_tpu_fleet_hbm_headroom_min_bytes",
           min(heads) if heads else None,
           "tightest device-memory headroom across replicas")
    grads = [p["train.grad_norm"] for p in perf.values()
             if p.get("train.grad_norm") is not None]
    rollup("paddle_tpu_fleet_grad_norm_max",
           max(grads) if grads else None,
           "largest per-step update norm across training replicas "
           "(the diverging replica surfaces here first)")

    lines.append("# HELP paddle_tpu_fleet_replica_up replica scrape "
                 "health (0 = unreachable/stale)")
    lines.append("# TYPE paddle_tpu_fleet_replica_up gauge")
    declared.add("paddle_tpu_fleet_replica_up")
    for s in scrapes:
        labels = {"replica": s["addr"], "id": s["id"] or s["addr"],
                  "stale": "0" if s["ok"] else "1"}
        lines.append(f"paddle_tpu_fleet_replica_up{_labelset(labels)} "
                     f"{1 if s['ok'] else 0}")

    # per-replica registries: declare each family once, then append
    # every live replica's labelled samples for it
    kinds = (("counters", "counter", "_total"),
             ("gauges", "gauge", ""),
             ("series", "summary", ""),
             ("histograms", "histogram", ""))
    for key, kind, suffix in kinds:
        names = sorted({name for s in live
                        for name in (s["stats"].get(key) or {})})
        for name in names:
            metric = sanitize_name(name) + suffix
            if metric not in declared:
                # a fleet rollup may share a family name with a
                # replica-registry gauge (an in-process fleet scrapes
                # its own fleet.* gauges back): one TYPE per family,
                # labelled samples join it
                lines.append(f"# HELP {metric} {name} "
                             f"(per-replica {kind})")
                lines.append(f"# TYPE {metric} {kind}")
                declared.add(metric)
            for s in live:
                value = (s["stats"].get(key) or {}).get(name)
                if value is None:
                    continue
                block = render_prometheus(
                    {key: {name: value}},
                    labels={"replica": s["addr"]}, emit_meta=False)
                lines.extend(block.splitlines())
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# cross-process trace assembly
# ---------------------------------------------------------------------------

def _normalize(payload, envelope, zero_unix):
    """One process's spans shifted onto the assembler's clock: span
    ``ts`` becomes seconds since ``zero_unix`` IN THE ASSEMBLER'S
    wall clock, using offset = remote now_unix - envelope midpoint."""
    offset = 0.0
    if envelope is not None:
        offset = payload["now_unix"] - (envelope[0] + envelope[1]) / 2.0
    base = payload["epoch_unix"] - offset - zero_unix
    out = []
    for sp in payload["spans"]:
        d = dict(sp)
        d["ts"] = base + sp["ts"]
        d.setdefault("pid", payload.get("pid"))
        if not d.get("proc"):
            d["proc"] = payload.get("process_name")
        out.append(d)
    return out, offset


def assemble_fleet_trace(sources, zero_unix=None):
    """Merge span payloads from several processes into one Chrome-trace
    timeline.

    ``sources`` is a list of ``{"source": label, "payload": <the
    /spans body>, "envelope": (t_send, t_recv) | None}`` dicts — the
    assembler's own ring goes in with ``envelope=None`` (no skew by
    definition); unreachable processes go in as ``{"source": label,
    "error": str}`` and are reported, not fatal.

    Process identity is ``(pid, process_name)``, NOT the raw OS pid:
    span ids are per-process counters, and containerized replicas
    routinely all run as pid 1, so keying on pid alone would silently
    drop every process after the first AND fold them onto one timeline
    row.  Spans dedupe by ``(identity, span_id)`` — so an in-process
    fleet (every replica serving the same ring under the same
    identity) assembles without duplicate events — and identities
    whose raw pids collide get a remapped DISPLAY pid so each process
    keeps its own labelled row.  The result is a Perfetto-loadable
    trace object with a ``fleetAssembly`` sidecar describing
    per-process display pids, clock offsets, and failures."""
    if zero_unix is None:
        zero_unix = _trace.epoch_unix()
    merged = []
    seen = set()
    processes = []
    failures = []
    display = {}       # identity -> display pid
    used_pids = set()
    for src in sources:
        if src.get("error") is not None or src.get("payload") is None:
            failures.append({"source": src.get("source"),
                             "error": src.get("error") or "no payload"})
            continue
        payload = src["payload"]
        identity = (payload.get("pid"), payload.get("process_name"))
        if identity not in display:
            pid = payload.get("pid") or 1
            while pid in used_pids:   # another process owns this pid
                pid += 1
            used_pids.add(pid)
            display[identity] = pid
        disp_pid = display[identity]
        spans, offset = _normalize(payload, src.get("envelope"),
                                   zero_unix)
        fresh = []
        for sp in spans:
            key = (identity, sp.get("span_id"))
            if key in seen:
                continue
            seen.add(key)
            sp["pid"] = disp_pid
            fresh.append(sp)
        merged.extend(fresh)
        processes.append({"source": src.get("source"),
                          "pid": disp_pid,
                          "os_pid": payload.get("pid"),
                          "process_name": payload.get("process_name"),
                          "clock_offset_s": offset,
                          "spans": len(fresh)})
    merged.sort(key=lambda sp: sp["ts"])
    obj = _trace.chrome_trace(merged)
    obj["fleetAssembly"] = {"zero_unix": zero_unix,
                            "processes": processes,
                            "failures": failures}
    return obj
