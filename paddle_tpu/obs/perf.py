"""Device-performance observability: XLA cost/memory attribution, HBM
accounting, live MFU.

The host-side plane (spans, federated metrics, SLOs) sees everything the
PROCESS does; this module lights up the DEVICE:

- **Compile capture** — on every jit-cache miss the executor routes the
  fresh ``jax.jit`` through :func:`instrument_jit`: the first call runs
  the AOT pipeline (``trace -> lower -> compile``) with the real
  arguments, records per-phase wall time, the compiled executable's XLA
  ``cost_analysis()`` (FLOPs, bytes accessed) and ``memory_analysis()``
  (argument/output/temp/generated-code bytes) keyed by jit key, and
  keeps serving the AOT executable (same donation semantics as the jit
  call path; a signature mismatch falls back to the original jit
  function).  Records surface through :func:`records` /
  :func:`compile_report` and the ``paddle_tpu profile compile`` CLI.

- **Live MFU** — :func:`note_step` divides a record's cost-analysis
  FLOPs by the measured step seconds and the chip's peak
  (:func:`peak_flops_per_chip`, moved here from ``bench.py`` so the
  library and the bench share one table) into the ``train.mfu`` gauge
  (or ``gen.decode_mfu`` for a decode program).  The measured step
  time covers the whole step — feed staging to the host
  materialization of the fetches, the point that BLOCKS on the
  device — so it is an honest
  (slightly conservative: host conversion included) wall time; paths
  that hand back async device arrays (``return_numpy=False``) derive
  no gauge, because their submit time would overstate MFU by the
  async-dispatch factor.

- **HBM census** — :func:`hbm_census` walks ``jax.live_arrays()`` and
  attributes bytes to collections: scope params vs optimizer state
  (accumulator-name conventions from ``optimizer.py``), KV-cache slots
  (``GenPredictor`` registers a provider), datapipe prefetch buffers
  (``DevicePrefetch`` registers one), everything else ``other`` — as
  ``hbm.*`` gauges with a process-lifetime high watermark.  Armed on a
  cadence via ``PADDLE_TPU_HBM_CENSUS=<seconds>`` the executor's
  per-step :func:`census_tick` costs a None check unarmed and one clock
  read armed-but-not-due (guarded in ``tests/test_obs_overhead.py``).

- **Headroom check** — when a compile's ``memory_analysis`` lands, the
  projected footprint (temp + output + generated code) is compared
  against the device limit minus the live set; a program that will not
  fit warns (``hbm.headroom_warnings``) BEFORE it runs, and the
  ``hbm.limit_bytes`` / ``hbm.headroom_bytes`` gauges track the margin.

See ``docs/performance.md`` ("Device performance") for the CLI family
and the MFU derivation, and ``docs/observability.md`` for the metric
registry rows.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time

__all__ = ["peak_flops_per_chip", "peak_flops_info", "MFU_BASES",
           "instrument_jit",
           "capture_enabled", "note_step", "records", "compile_report",
           "validate_report", "reset_records", "hbm_census",
           "register_hbm_provider", "unregister_hbm_provider",
           "hbm_limit_bytes", "census_tick", "arm_census",
           "enable_step_phases", "disable_step_phases",
           "step_phases_enabled", "WarmupReport"]

logger = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# peak FLOPs (moved from bench.py — the library and the bench must never
# disagree on the denominator MFU claims rest on)
# ---------------------------------------------------------------------------

#: best-effort peak bf16 FLOP/s per chip by device-kind substring
PEAK_FLOPS_TABLE = {
    "v5e": 197e12, "v5litepod": 197e12, "v5p": 459e12,
    "v4": 275e12, "v3": 123e12, "v2": 45e12, "v6e": 918e12,
}

#: the finite-but-meaningless CPU fallback (tagged, never compared
#: against tpu-peak records — see bench_history's mfu_basis refusal)
CPU_FALLBACK_PEAK = 1e12

#: every legal MFU basis tag — the ONE definition ``peak_flops_info``
#: emits from, ``validate_report`` checks against, and
#: ``bench_history`` re-exports for trajectory validation
MFU_BASES = ("tpu-peak", "cpu-fallback")

_peak_cache = None  # (value, basis)


def peak_flops_info():
    """``(peak_flops, basis)`` for the local accelerator; ``basis`` is
    ``"tpu-peak"`` when the device kind matched the table (or is a TPU
    of unknown generation) and ``"cpu-fallback"`` otherwise — every MFU
    number carries its basis so a CPU smoke run can never be compared
    against a real-chip trajectory."""
    global _peak_cache
    if _peak_cache is not None:
        return _peak_cache
    import jax
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "cpu").lower()
    value, basis = None, None
    for k, v in PEAK_FLOPS_TABLE.items():
        if k in kind:
            value, basis = v, "tpu-peak"
            break
    if value is None:
        if "tpu" in kind or "axon" in kind:
            value, basis = 197e12, "tpu-peak"
        else:
            value, basis = CPU_FALLBACK_PEAK, "cpu-fallback"
    _peak_cache = (value, basis)
    return _peak_cache


def peak_flops_per_chip():
    """Best-effort peak (bf16) FLOP/s for the local accelerator (the
    ``bench.py`` function, now library API)."""
    return peak_flops_info()[0]


# ---------------------------------------------------------------------------
# compile capture
# ---------------------------------------------------------------------------

_records_lock = threading.Lock()
_records = collections.OrderedDict()   # key -> record dict
_RECORDS_MAX = 256
_key_counter = [0]

REPORT_FORMAT = 1

#: keys every compile record carries (``validate_report`` and the
#: selfcheck ``perf`` section hold the ``profile compile --json`` schema
#: to this)
RECORD_KEYS = ("key", "label", "created_unix", "flops", "bytes_accessed",
               "memory", "phases", "steps", "last_step_seconds", "mfu")
MEMORY_KEYS = ("argument_bytes", "output_bytes", "temp_bytes",
               "alias_bytes", "generated_code_bytes")
PHASE_KEYS = ("trace_seconds", "lower_seconds", "backend_seconds")


def capture_enabled():
    """Compile capture is on by default; ``PADDLE_TPU_PERF=0`` disables
    it (the executor then jits exactly as before this module existed)."""
    return os.environ.get("PADDLE_TPU_PERF", "1").strip().lower() \
        not in ("0", "false", "off", "no")


def _metrics():
    from paddle_tpu.profiler import runtime_metrics
    return runtime_metrics


def _cost_summary(compiled):
    """(flops, bytes_accessed) from ``cost_analysis()`` — a list of
    per-computation dicts on this jax, a dict on others, possibly
    unavailable on exotic backends."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None, None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return None, None

    def _clean(v):
        # XLA reports -1 for costs it cannot model (some convolutions,
        # custom calls) — that is "unknown", not a number to divide by
        if v is None or float(v) < 0:
            return None
        return float(v)

    return _clean(ca.get("flops")), _clean(ca.get("bytes accessed"))


def _memory_summary(compiled):
    """The device-memory breakdown of ``memory_analysis()`` as a plain
    dict (None when the backend does not report one)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out = {}
    for key, attr in (("argument_bytes", "argument_size_in_bytes"),
                      ("output_bytes", "output_size_in_bytes"),
                      ("temp_bytes", "temp_size_in_bytes"),
                      ("alias_bytes", "alias_size_in_bytes"),
                      ("generated_code_bytes",
                       "generated_code_size_in_bytes")):
        v = getattr(ma, attr, None)
        if v is None:
            return None
        out[key] = int(v)
    return out


def jit_label(feed_arrays, fetch_names, tag=""):
    """Human-readable jit-key label for the profile tables: the sorted
    feed name:shape pairs (truncated) — recognizable without leaking a
    whole signature tuple into a table column."""
    parts = []
    for n in sorted(feed_arrays):
        a = feed_arrays[n]
        shape = "x".join(str(d) for d in getattr(a, "shape", ())) or "()"
        parts.append(f"{n}:{shape}")
    label = (f"{tag}:" if tag else "") + ",".join(parts)
    if len(label) > 96:
        label = label[:93] + "..."
    return label or "(no feeds)"


def _insert_record(record):
    with _records_lock:
        while len(_records) >= _RECORDS_MAX:
            _records.popitem(last=False)
        _records[record["key"]] = record


def instrument_jit(jitted, label="", metrics=None):
    """Wrap a fresh ``jax.jit`` callable so its FIRST call compiles via
    the AOT pipeline and captures a compile record; later calls run the
    AOT executable directly.

    Degradation contract: any capture failure (backend without AOT,
    analysis unavailable, tracing quirk) falls back to calling
    ``jitted`` unchanged and bumps ``compile.capture_failures``; a
    post-capture signature mismatch (``TypeError`` from the AOT
    executable's argument check — raised before execution, so donation
    never half-happens) re-dispatches through ``jitted`` and bumps
    ``compile.aot_fallbacks``.  The wrapper exposes ``.perf`` (the
    holder dict whose ``"record"`` the executor reads for MFU)."""
    m = metrics or _metrics()
    holder = {"exec": None, "record": None, "failed": False,
              "label": label}

    def call(*args):
        if holder["exec"] is None and not holder["failed"]:
            try:
                _capture(jitted, args, holder, m)
            except Exception:
                holder["failed"] = True
                m.inc("compile.capture_failures")
                logger.debug("compile capture failed for %r; running "
                             "the plain jit path", label, exc_info=True)
        if holder["exec"] is not None:
            try:
                return holder["exec"](*args)
            except TypeError:
                # argument signature drifted from the captured one
                # (checked before execution — donation is safe); the
                # plain jit path recompiles and keeps serving
                m.inc("compile.aot_fallbacks")
                return jitted(*args)
        return jitted(*args)

    call.perf = holder
    return call


def _capture(jitted, args, holder, m):
    t0 = time.perf_counter()
    traced = jitted.trace(*args)
    t1 = time.perf_counter()
    lowered = traced.lower()
    t2 = time.perf_counter()
    compiled = lowered.compile()
    t3 = time.perf_counter()

    _key_counter[0] += 1
    key = f"jit-{_key_counter[0]:04d}"
    flops, bytes_accessed = _cost_summary(compiled)
    memory = _memory_summary(compiled)
    record = {
        "key": key,
        "label": holder["label"] or key,
        "created_unix": time.time(),
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "memory": memory,
        "phases": {"trace_seconds": t1 - t0,
                   "lower_seconds": t2 - t1,
                   "backend_seconds": t3 - t2},
        "steps": 0,
        "last_step_seconds": None,
        "mfu": None,
    }
    _insert_record(record)
    holder["exec"] = compiled
    holder["record"] = record

    m.inc("compile.captures")
    m.observe("compile.phase_trace_seconds", t1 - t0)
    m.observe("compile.phase_lower_seconds", t2 - t1)
    m.observe("compile.phase_backend_seconds", t3 - t2)
    if flops is not None:
        m.observe("compile.cost_flops", flops)
    if bytes_accessed is not None:
        m.observe("compile.cost_bytes", bytes_accessed)
    if memory is not None:
        m.observe("compile.memory_temp_bytes", memory["temp_bytes"])
        _headroom_check(record, m)
    return record


def records():
    """Snapshot of the captured compile records, oldest first."""
    with _records_lock:
        return [dict(r, phases=dict(r["phases"]),
                     memory=(dict(r["memory"]) if r["memory"] else None))
                for r in _records.values()]


def reset_records():
    """Drop captured records (tests)."""
    with _records_lock:
        _records.clear()


def total_compile_seconds():
    """Summed trace+lower+backend wall time across captured records —
    the compile cost a cold process paid (what ``bench check`` guards
    via the ``compile_seconds`` trajectory row)."""
    total = 0.0
    for r in records():
        total += sum(r["phases"].values())
    return total


def compile_report():
    """The ``profile compile --json`` body (schema held stable by
    :func:`validate_report` and the selfcheck ``perf`` section)."""
    import jax
    try:
        backend = jax.default_backend()
    except Exception:
        backend = "unavailable"
    peak, basis = peak_flops_info()
    return {"format": REPORT_FORMAT, "backend": backend,
            "peak_flops_per_chip": peak, "mfu_basis": basis,
            "records": records()}


def validate_report(obj):
    """Schema problems of a :func:`compile_report` body as a list of
    strings (empty = valid)."""
    problems = []
    if not isinstance(obj, dict):
        return [f"report must be an object, got {type(obj).__name__}"]
    if obj.get("format") != REPORT_FORMAT:
        problems.append(f"format must be {REPORT_FORMAT}, "
                        f"got {obj.get('format')!r}")
    if obj.get("mfu_basis") not in MFU_BASES:
        problems.append(f"mfu_basis must be one of {MFU_BASES}, "
                        f"got {obj.get('mfu_basis')!r}")
    recs = obj.get("records")
    if not isinstance(recs, list):
        return problems + ["records must be a list"]
    for i, r in enumerate(recs):
        where = f"records[{i}]"
        if not isinstance(r, dict):
            problems.append(f"{where}: must be an object")
            continue
        for k in RECORD_KEYS:
            if k not in r:
                problems.append(f"{where}: missing key {k!r}")
        phases = r.get("phases")
        if not isinstance(phases, dict) or \
                any(k not in phases for k in PHASE_KEYS):
            problems.append(f"{where}: phases needs {PHASE_KEYS}")
        mem = r.get("memory")
        if mem is not None and (not isinstance(mem, dict) or
                                any(k not in mem for k in MEMORY_KEYS)):
            problems.append(f"{where}: memory needs {MEMORY_KEYS}")
        for k in ("flops", "bytes_accessed"):
            v = r.get(k)
            if v is not None and (not isinstance(v, (int, float))
                                  or isinstance(v, bool) or v < 0):
                problems.append(f"{where}: {k} must be a non-negative "
                                f"number or null")
    return problems


# ---------------------------------------------------------------------------
# live MFU
# ---------------------------------------------------------------------------

def note_step(record, seconds, gauge="train.mfu", devices=1,
              flops_scale=1, metrics=None):
    """Per-step MFU hook (called by the executor after every dispatch):
    with a captured record carrying cost-analysis FLOPs, derive
    ``flops * flops_scale / seconds / (peak * devices)`` into
    ``gauge``.  Without one (capture disabled/failed, interpret mode)
    this is a None check — the hot path stays inside the <5% overhead
    guard.  ``flops_scale`` exists for the ``run_steps`` scan path:
    XLA's cost analysis counts a loop body ONCE regardless of trip
    count, so the executor passes ``steps`` there."""
    if record is None or not seconds or seconds <= 0:
        return None
    flops = record.get("flops")
    if not flops:
        return None
    peak, _basis = peak_flops_info()
    mfu = flops * flops_scale / seconds / (peak * max(int(devices), 1))
    record["steps"] += 1
    record["last_step_seconds"] = seconds
    record["mfu"] = mfu
    (metrics or _metrics()).set_gauge(gauge, mfu)
    return mfu


# ---------------------------------------------------------------------------
# HBM census
# ---------------------------------------------------------------------------

#: scope-variable name prefixes that mark optimizer accumulator state
#: (``optimizer.py`` names accumulators ``<slot>.<param>_N`` via
#: ``unique_name(".".join([name, param.name]))``)
OPTIMIZER_STATE_PREFIXES = (
    "moment", "velocity", "beta1_pow", "beta2_pow", "inf_norm",
    "avg_squared", "mean_square", "squared_accumulator",
    "linear_accumulator",
)

#: census collections, in attribution priority order; provider-backed
#: collections claim their buffers before the scope walk (``kv_pages``:
#: a paged gen bundle's page pool + its host-side page tables)
HBM_COLLECTIONS = ("kv_cache", "kv_pages", "prefetch", "embedding",
                   "optimizer", "params")

_hbm_lock = threading.Lock()
_hbm_providers = {}     # collection -> {token: callable}
_hbm_token = [0]
_hbm_high_watermark = [0.0]


def register_hbm_provider(collection, fn):
    """Register ``fn`` (no args -> iterable of device arrays) as a
    source of buffers for ``collection`` (``kv_cache`` / ``prefetch`` /
    custom).  Returns a token for :func:`unregister_hbm_provider`.
    Providers that raise are skipped, never fatal — the census is a
    diagnostic, not a dependency."""
    with _hbm_lock:
        _hbm_token[0] += 1
        token = _hbm_token[0]
        _hbm_providers.setdefault(collection, {})[token] = fn
    return token


def unregister_hbm_provider(token):
    with _hbm_lock:
        for fns in _hbm_providers.values():
            fns.pop(token, None)


def _provider_arrays(collection):
    with _hbm_lock:
        fns = list(_hbm_providers.get(collection, {}).values())
    out = []
    for fn in fns:
        try:
            out.extend(fn() or ())
        except Exception:
            logger.debug("hbm provider for %r raised; skipped",
                         collection, exc_info=True)
    return out


def _is_optimizer_state(name):
    base = name.rsplit("/", 1)[-1]
    return any(base.startswith(p) for p in OPTIMIZER_STATE_PREFIXES)


_limit_cache = [False, None]   # [resolved, value]


def hbm_limit_bytes():
    """Device memory limit for headroom accounting:
    ``PADDLE_TPU_HBM_LIMIT_BYTES`` wins (operators and tests), else the
    backend's ``memory_stats()['bytes_limit']`` (TPU/GPU report it, CPU
    does not), else None — the headroom check then stands down."""
    raw = os.environ.get("PADDLE_TPU_HBM_LIMIT_BYTES", "").strip()
    if raw:
        try:
            return int(float(raw))
        except ValueError:
            logger.warning("bad PADDLE_TPU_HBM_LIMIT_BYTES=%r; ignored",
                           raw)
    if _limit_cache[0]:
        return _limit_cache[1]
    limit = None
    try:
        import jax
        d = jax.devices()[0]
        stats = d.memory_stats() if hasattr(d, "memory_stats") else None
        if stats:
            limit = int(stats.get("bytes_limit") or 0) or None
    except Exception:
        limit = None
    _limit_cache[0], _limit_cache[1] = True, limit
    return limit


def live_device_bytes():
    """Total bytes of every live jax array in the process (the census
    denominator; best-effort — aliased views may double-count)."""
    import jax
    total = 0
    for a in jax.live_arrays():
        total += int(getattr(a, "nbytes", 0) or 0)
    return total


def hbm_census(scope=None, metrics=None):
    """One live-buffer walk attributed to collections, exported as the
    ``hbm.*`` gauges.  ``scope`` defaults to the ambient global scope;
    its device arrays split into ``params`` vs ``optimizer`` by the
    accumulator naming convention, provider-backed collections
    (``kv_cache``, ``prefetch``) claim their buffers first, and
    everything unattributed lands in ``other``.  Returns the census
    dict.  Cost is O(live arrays) — run it on the
    ``PADDLE_TPU_HBM_CENSUS`` cadence or from ``profile memory``, not
    per step."""
    import jax
    m = metrics or _metrics()
    counted = set()
    census = {c: 0 for c in HBM_COLLECTIONS}

    def claim(collection, arrays):
        for a in arrays:
            nbytes = getattr(a, "nbytes", None)
            if nbytes is None or not hasattr(a, "dtype"):
                continue
            i = id(a)
            if i in counted:
                continue
            counted.add(i)
            census[collection] += int(nbytes)

    claim("kv_cache", _provider_arrays("kv_cache"))
    claim("kv_pages", _provider_arrays("kv_pages"))
    claim("prefetch", _provider_arrays("prefetch"))
    claim("embedding", _provider_arrays("embedding"))

    if scope is None:
        from paddle_tpu.scope import global_scope
        scope = global_scope()
    # embedding tables are params by structure but their own memory
    # story (the axis the CTR workload scales along) — attribute them
    # by the table registry, ahead of the params split
    from paddle_tpu.embedding import is_table as _is_table
    emb_arrays, opt_arrays, param_arrays = [], [], []
    s = scope
    while s is not None:
        for name, v in s.items():
            if not hasattr(v, "nbytes") or not hasattr(v, "dtype"):
                continue  # readers, lod metadata, host objects
            if _is_table(name):
                emb_arrays.append(v)
            elif _is_optimizer_state(name):
                opt_arrays.append(v)
            else:
                param_arrays.append(v)
        s = s.parent
    claim("embedding", emb_arrays)
    claim("optimizer", opt_arrays)
    claim("params", param_arrays)

    total = 0
    attributed = 0
    for a in jax.live_arrays():
        nbytes = int(getattr(a, "nbytes", 0) or 0)
        total += nbytes
        if id(a) in counted:
            attributed += nbytes
    census["other"] = max(0, total - attributed)
    census["total"] = total
    if total > _hbm_high_watermark[0]:
        _hbm_high_watermark[0] = float(total)
    census["high_watermark"] = _hbm_high_watermark[0]

    m.inc("hbm.census_runs")
    m.set_gauge("hbm.params_bytes", census["params"])
    m.set_gauge("hbm.optimizer_bytes", census["optimizer"])
    m.set_gauge("hbm.kv_cache_bytes", census["kv_cache"])
    m.set_gauge("hbm.kv_pages_bytes", census["kv_pages"])
    m.set_gauge("hbm.prefetch_bytes", census["prefetch"])
    m.set_gauge("hbm.embedding_bytes", census["embedding"])
    m.set_gauge("hbm.other_bytes", census["other"])
    m.set_gauge("hbm.total_bytes", census["total"])
    m.set_gauge("hbm.high_watermark_bytes", census["high_watermark"])
    limit = hbm_limit_bytes()
    if limit is not None:
        census["limit"] = limit
        census["headroom"] = limit - total
        m.set_gauge("hbm.limit_bytes", limit)
        m.set_gauge("hbm.headroom_bytes", limit - total)
    return census


def _headroom_check(record, m):
    """Projected-footprint check for a freshly compiled program: its
    temp + output + generated-code bytes must fit beside the CURRENT
    live set (arguments are already live).  Warns — counter plus a log
    line naming the program — before the program ever runs."""
    limit = hbm_limit_bytes()
    mem = record.get("memory")
    if limit is None or mem is None:
        return
    live = live_device_bytes()
    projected = (mem["temp_bytes"] + mem["output_bytes"]
                 + mem["generated_code_bytes"])
    headroom = limit - live
    m.set_gauge("hbm.limit_bytes", limit)
    m.set_gauge("hbm.headroom_bytes", headroom)
    if projected > headroom:
        m.inc("hbm.headroom_warnings")
        logger.warning(
            "projected footprint of %s (%s) is %.1f MB but only %.1f MB "
            "of device memory remains beside the %.1f MB live set — the "
            "next dispatch may OOM",
            record["key"], record["label"], projected / 1e6,
            headroom / 1e6, live / 1e6)


# ---------------------------------------------------------------------------
# census cadence (the executor's per-step hook)
# ---------------------------------------------------------------------------

_census_interval = None
_census_due = 0.0


def arm_census(interval_seconds):
    """Arm (or, with None/0, disarm) the per-step census cadence.
    Re-arming at the SAME interval keeps the current due time — every
    ``Executor.__init__`` re-reads the env, and each construction must
    not force an immediate off-cadence census."""
    global _census_interval, _census_due
    if not interval_seconds:
        _census_interval = None
        return
    interval = float(interval_seconds)
    if _census_interval == interval:
        return
    _census_interval = interval
    _census_due = 0.0


def arm_census_from_env():
    """``PADDLE_TPU_HBM_CENSUS=<seconds>`` arms the cadence (called by
    ``Executor.__init__`` — idempotent, env wins over a previous
    programmatic arm only when set)."""
    raw = os.environ.get("PADDLE_TPU_HBM_CENSUS", "").strip()
    if not raw:
        return
    try:
        arm_census(float(raw))
    except ValueError:
        logger.warning("bad PADDLE_TPU_HBM_CENSUS=%r; census not armed",
                       raw)


def census_tick(scope=None):
    """The executor's per-step hook: a None check unarmed, one clock
    read armed-but-not-due, a full census when the interval elapsed."""
    global _census_due
    if _census_interval is None:
        return
    now = time.monotonic()
    if now < _census_due:
        return
    _census_due = now + _census_interval
    try:
        hbm_census(scope)
    except Exception:
        logger.warning("hbm census failed", exc_info=True)


# ---------------------------------------------------------------------------
# step-phase breakdown (paddle_tpu profile step)
# ---------------------------------------------------------------------------

_step_phases = False


def enable_step_phases():
    """Arm the executor's per-step feed/dispatch/device-wait/fetch
    series (``perf.step.*``) — adds one device sync per step, so this
    is a profiling mode (``paddle_tpu profile step``), not a
    steady-state default."""
    global _step_phases
    _step_phases = True


def disable_step_phases():
    global _step_phases
    _step_phases = False


def step_phases_enabled():
    return _step_phases


# ---------------------------------------------------------------------------
# warmup report
# ---------------------------------------------------------------------------

class WarmupReport(int):
    """``Executor.warmup``'s return value: still the fresh-compile count
    (int subclass — every existing caller keeps working), plus a
    per-bucket ``buckets`` list: ``{"signature": {name: shape},
    "compiles": n, "seconds": s, "cache": "cold" | "persistent-hit" |
    "warm"}`` — the observable form of a rolling restart's "warm via
    compile cache" claim, surfaced per bucket in serving ``/stats``."""

    def __new__(cls, compiles, buckets=()):
        obj = super().__new__(cls, int(compiles))
        obj.buckets = list(buckets)
        return obj

    @staticmethod
    def merge(*reports, **tags):
        """Concatenate reports; keyword tags are stamped onto every
        bucket of the matching positional report by index name
        (``merge(pre, dec, prefill=0, decode=1)`` is NOT the API —
        pass ``labels=("prefill", "decode")`` instead)."""
        labels = tags.pop("labels", None)
        buckets = []
        for i, rep in enumerate(reports):
            for b in getattr(rep, "buckets", ()):
                b = dict(b)
                if labels is not None:
                    b["program"] = labels[i]
                buckets.append(b)
        return WarmupReport(sum(int(r) for r in reports), buckets)
