"""Bounded retry with exponential backoff + jitter.

The reference's Go clients retry master/pserver RPCs in ad-hoc loops
(``go/master/client.go`` reconnects on lease loss; ``go/pserver/client``
re-dials).  Here the policy is one reusable object so every networked
path — :class:`MasterClient`, the serving client, checkpoint IO — shares
the same knobs: max attempts, exponential backoff with full jitter, and
an overall deadline.
"""

from __future__ import annotations

import random
import time

__all__ = ["RetryPolicy", "RetryError", "retrying", "DEFAULT_RPC_POLICY",
           "parse_hostport", "parse_deadline_ms", "parse_retry_after"]


def parse_hostport(addr):
    """``(host, port)`` from a tuple or a ``"host:port"`` string — the
    shared address convention of the networked clients (master RPC,
    serving HTTP)."""
    if isinstance(addr, tuple):
        host, port = addr
    else:
        host, _, port = addr.rpartition(":")
    return host, int(port)


def parse_deadline_ms(value):
    """Seconds of budget from an ``X-Deadline-Ms`` header value, or
    None when absent/blank — the shared deadline convention of the
    serving/fleet HTTP surface.  Raises ValueError on anything
    non-finite: nan compares False everywhere and inf breaks int()
    downstream, so both must be rejected at the edge, identically by
    every consumer."""
    import math
    value = (value or "").strip()
    if not value:
        return None
    budget = float(value) / 1000.0   # ValueError on garbage propagates
    if not math.isfinite(budget):
        raise ValueError(f"non-finite deadline {value!r}")
    return budget


def parse_retry_after(value):
    """Seconds from a ``Retry-After`` header value, or None when
    absent/unparseable.  Only the delta-seconds form is supported (the
    fleet's own sheds emit it; HTTP-date senders fall back to the
    default backoff) and negative/non-finite values are rejected as
    None — a malformed hint must degrade to the policy's own backoff,
    never produce a negative sleep."""
    import math
    if not isinstance(value, str):
        return None
    value = value.strip()
    if not value:
        return None
    try:
        secs = float(value)
    except ValueError:
        return None
    if not math.isfinite(secs) or secs < 0:
        return None
    return secs


class RetryError(RuntimeError):
    """All attempts exhausted (or deadline hit); ``.last`` is the final
    underlying exception, also chained as ``__cause__``.  ``.history``
    is the per-attempt context trail (e.g. the replica each attempt hit,
    attached by failover callers like ``FleetRouter``/``ServingClient``)
    — empty when the caller recorded none."""

    def __init__(self, message, last, history=None):
        super().__init__(message)
        self.last = last
        self.history = list(history) if history else []


class RetryPolicy:
    """``cap(n) = min(max_delay, base_delay * multiplier**n)``, jittered;
    give up after ``max_attempts`` tries or when the remaining
    ``deadline`` budget is smaller than the next backoff (the policy
    raises :class:`RetryError` immediately rather than sleeping through
    — or past — the budget).

    ``jitter`` is either a float ``j`` (equal-style: the cap scaled by
    ``1 ± j``) or the string ``"full"`` (AWS full jitter:
    ``uniform(0, cap)`` — the decorrelated choice for thundering-herd
    retry storms, where every client re-dialing a restarted master at
    the same instant is exactly the failure mode).
    """

    def __init__(self, max_attempts=5, base_delay=0.05, max_delay=2.0,
                 multiplier=2.0, jitter=0.5, deadline=None,
                 retryable=(ConnectionError, TimeoutError, OSError)):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if jitter != "full":
            try:
                # coerce on store: a numeric string must not survive
                # construction only to blow up inside backoff() mid-retry
                jitter = None if jitter is None else float(jitter)
                valid = jitter is None or 0 <= jitter <= 1
            except (TypeError, ValueError):
                valid = False
            if not valid:
                raise ValueError(
                    'jitter must be "full", None, or a float in [0, 1]')
        self.max_attempts = max_attempts
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.multiplier = multiplier
        self.jitter = jitter
        self.deadline = deadline
        self.retryable = tuple(retryable)

    def backoff(self, attempt):
        """Sleep before retry number ``attempt`` (1-based)."""
        delay = min(self.max_delay,
                    self.base_delay * self.multiplier ** (attempt - 1))
        if self.jitter == "full":
            return random.uniform(0.0, delay)
        if self.jitter:
            delay *= 1.0 + self.jitter * (2.0 * random.random() - 1.0)
        return max(0.0, delay)

    def hinted_delay(self, hint):
        """Sleep for a server-supplied ``Retry-After`` hint: the hint
        capped at ``max_delay``, under the policy's own jitter mode —
        with full jitter the N clients a shedding server just bounced
        drain back spread over the hinted window instead of returning
        in one synchronized wave."""
        base = min(max(0.0, float(hint)), self.max_delay)
        if self.jitter == "full":
            return random.uniform(0.0, base)
        if self.jitter:
            base *= 1.0 + self.jitter * (2.0 * random.random() - 1.0)
        return max(0.0, base)

    def call(self, fn, *args, on_retry=None, deadline=None, **kwargs):
        """Run ``fn(*args, **kwargs)``, retrying on ``self.retryable``.

        ``on_retry(attempt, exc, delay)`` is invoked before each sleep
        (logging / reconnect hooks).  ``deadline`` overrides the
        policy's budget for this one call (seconds from the first
        attempt) — when the remaining budget is smaller than the next
        backoff, :class:`RetryError` is raised immediately instead of
        sleeping.  Non-retryable exceptions propagate immediately;
        exhausted attempts raise :class:`RetryError`.  (``deadline`` is
        consumed by the policy, never forwarded to ``fn``.)
        """
        deadline = self.deadline if deadline is None else deadline
        start = time.monotonic()
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn(*args, **kwargs)
            except self.retryable as e:
                if attempt >= self.max_attempts:
                    raise RetryError(
                        f"gave up after {attempt} attempts: {e}", e) from e
                hint = getattr(e, "retry_after", None)
                if hint is not None:
                    # server-paced backoff: sleep the Retry-After hint
                    # (jittered, capped) clamped to the remaining budget
                    # — a shedding server's hint should never make us
                    # abandon a request the deadline still allows
                    delay = self.hinted_delay(hint)
                    if deadline is not None:
                        remaining = deadline - (time.monotonic() - start)
                        if remaining <= 0.01:
                            raise RetryError(
                                f"deadline {deadline}s exceeded after "
                                f"{attempt} attempts (Retry-After "
                                f"{hint}s hinted, {remaining:.3f}s "
                                f"remaining): {e}", e) from e
                        delay = min(delay, max(0.0, remaining - 0.005))
                else:
                    delay = self.backoff(attempt)
                    if deadline is not None:
                        remaining = deadline - (time.monotonic() - start)
                        if delay > remaining:
                            raise RetryError(
                                f"deadline {deadline}s exceeded after "
                                f"{attempt} attempts ({remaining:.3f}s "
                                f"remaining < next backoff {delay:.3f}s): "
                                f"{e}", e) from e
                if on_retry is not None:
                    on_retry(attempt, e, delay)
                time.sleep(delay)


def retrying(policy=None, **kwargs):
    """Decorator form: ``@retrying(RetryPolicy(...))`` or
    ``@retrying(max_attempts=3)``."""
    policy = policy or RetryPolicy(**kwargs)

    def wrap(fn):
        def wrapped(*args, **kw):
            return policy.call(fn, *args, **kw)
        wrapped.__name__ = getattr(fn, "__name__", "retrying")
        wrapped.__doc__ = fn.__doc__
        wrapped.retry_policy = policy
        return wrapped

    return wrap


# trainer-facing RPC default: ~6s worst-case total sleep, enough to ride
# out a master restart without stalling a trainer for minutes
DEFAULT_RPC_POLICY = RetryPolicy(max_attempts=6, base_delay=0.05,
                                 max_delay=2.0, deadline=30.0)
