"""Elastic fault-tolerance runtime.

The reference's fault-tolerance story is split across the Go master
(lease-based task requeue, ``go/master/service.go``) and the Go pserver
(CRC'd checkpoints, ``go/pserver/service.go:346``).  This package is the
runtime that ties our ports of those pieces into something that actually
survives failure:

- :mod:`paddle_tpu.fault.checkpoint` — crash-consistent checkpoint
  commits (temp dir -> fsync -> atomic rename -> checksummed manifest)
  and a :class:`CheckpointManager` with keep-N GC and a
  ``restore_latest()`` that quarantines torn/corrupt checkpoints.
- :mod:`paddle_tpu.fault.retry` — :class:`RetryPolicy` (bounded
  attempts, exponential backoff + jitter, deadline) for RPC and IO
  paths.
- :mod:`paddle_tpu.fault.chaos` — named failpoints armed by tests or the
  ``PADDLE_TPU_CHAOS`` env var; product code calls ``chaos.fire(name)``
  at checkpoint/RPC/step boundaries, a disarmed failpoint costs one dict
  lookup.
- :mod:`paddle_tpu.fault.lifecycle` — :class:`GracefulShutdown`:
  SIGTERM/SIGINT-aware stop flag so a preempted trainer finishes the
  current step, commits a checkpoint, and exits cleanly.
- :mod:`paddle_tpu.fault.sentinel` — :class:`Sentinel`: numerical-fault
  detection (fused device-side finite checks + EMA loss-spike detector)
  with an escalation ladder — skip-step, quarantine (pickled repro
  bundles replayable via ``paddle_tpu replay``), and automatic rollback
  to the last known-good checkpoint
  (``CheckpointManager.mark_good()/restore_last_good()``).
- :mod:`paddle_tpu.fault.shard_ckpt` — the elastic per-shard checkpoint
  format: concurrent one-file-per-mesh-shard writes inside the atomic
  commit, a manifest topology record, and the statically-verified
  restore planner that maps a dp4 checkpoint onto a dp2 (or dp8) mesh.
"""

from __future__ import annotations

from paddle_tpu.fault import chaos
from paddle_tpu.fault.chaos import FaultInjected, fire, inject
from paddle_tpu.fault.checkpoint import (CheckpointManager, CorruptCheckpoint,
                                         manager_from_env, verify_checkpoint)
from paddle_tpu.fault.lifecycle import GracefulShutdown, graceful_shutdown
from paddle_tpu.fault.retry import RetryError, RetryPolicy, retrying
from paddle_tpu.fault.sentinel import (NumericalFault, Sentinel,
                                       replay_bundle, sentinel_from_env)
from paddle_tpu.fault.shard_ckpt import ReshardError

__all__ = [
    "chaos", "FaultInjected", "fire", "inject",
    "CheckpointManager", "CorruptCheckpoint", "manager_from_env",
    "verify_checkpoint", "ReshardError",
    "GracefulShutdown", "graceful_shutdown",
    "RetryError", "RetryPolicy", "retrying",
    "NumericalFault", "Sentinel", "replay_bundle", "sentinel_from_env",
]

# parse PADDLE_TPU_CHAOS eagerly so a malformed spec fails fast at
# import, not from inside an arbitrary failpoint site mid-training
chaos._load_env()
