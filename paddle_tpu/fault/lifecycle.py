"""Preemption-aware lifecycle: finish the step, checkpoint, exit clean.

TPU pods preempt with a SIGTERM and a grace window; the reference
trainer just died and leaned on the master's lease timeout to requeue
its work.  :class:`GracefulShutdown` converts SIGTERM/SIGINT into a stop
flag the training loop polls between steps, so the trainer commits a
final checkpoint instead of losing the tail of its progress — the
TF-style preemption-safe checkpointing discipline.
"""

from __future__ import annotations

import signal
import threading

__all__ = ["GracefulShutdown", "graceful_shutdown"]


class GracefulShutdown:
    """Context manager: trap termination signals into a flag.

    ::

        with GracefulShutdown() as stop:
            for step in range(start, num_steps):
                if stop.should_stop():
                    break           # fall through to the final commit
                run_one_step()
                manager.save(step)

    Signal handlers are only installable from the main thread; elsewhere
    the guard still works as a manual flag (``stop.request()``).  The
    previous handlers are restored on exit.  ``on_shutdown`` (if given)
    runs inside the handler — keep it async-signal-light (set flags,
    don't checkpoint there; checkpoint from the loop).
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT),
                 on_shutdown=None):
        self.signals = tuple(signals)
        self.on_shutdown = on_shutdown
        self._event = threading.Event()
        self._previous = {}
        self.received = None      # signum of the first trapped signal

    # -- flag --------------------------------------------------------------
    def should_stop(self):
        return self._event.is_set()

    def wait(self, timeout=None):
        return self._event.wait(timeout)

    def request(self, signum=None):
        """Trip the flag programmatically (tests, cluster RPCs)."""
        if self.received is None:
            self.received = signum
        self._event.set()
        if self.on_shutdown is not None:
            self.on_shutdown(signum)

    # -- context -----------------------------------------------------------
    def _handler(self, signum, frame):
        self.request(signum)

    def __enter__(self):
        for sig in self.signals:
            try:
                self._previous[sig] = signal.signal(sig, self._handler)
            except ValueError:      # not the main thread: manual-flag mode
                break
        return self

    def __exit__(self, *exc):
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous.clear()
        return False


def graceful_shutdown(**kwargs):
    """Convenience alias: ``with graceful_shutdown() as stop: ...``"""
    return GracefulShutdown(**kwargs)
