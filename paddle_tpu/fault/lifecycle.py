"""Preemption-aware lifecycle: finish the step, checkpoint, exit clean.

TPU pods preempt with a SIGTERM and a grace window; the reference
trainer just died and leaned on the master's lease timeout to requeue
its work.  :class:`GracefulShutdown` converts SIGTERM/SIGINT into a stop
flag the training loop polls between steps, so the trainer commits a
final checkpoint instead of losing the tail of its progress — the
TF-style preemption-safe checkpointing discipline.
"""

from __future__ import annotations

import signal
import threading

__all__ = ["GracefulShutdown", "graceful_shutdown"]


class GracefulShutdown:
    """Context manager: trap termination signals into a flag.

    ::

        with GracefulShutdown() as stop:
            for step in range(start, num_steps):
                if stop.should_stop():
                    break           # fall through to the final commit
                run_one_step()
                manager.save(step)

    Signal handlers are only installable from the main thread; elsewhere
    the guard still works as a manual flag (``stop.request()``).  The
    previous handlers are restored on exit.  ``on_shutdown`` (if given)
    runs inside the handler — keep it async-signal-light (set flags,
    don't checkpoint there; checkpoint from the loop).
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT),
                 on_shutdown=None):
        self.signals = tuple(signals)
        self.on_shutdown = on_shutdown
        self._event = threading.Event()
        self._previous = {}
        self.received = None      # signum of the first trapped signal

    # -- flag --------------------------------------------------------------
    def should_stop(self):
        return self._event.is_set()

    def wait(self, timeout=None):
        return self._event.wait(timeout)

    def request(self, signum=None):
        """Trip the flag programmatically (tests, cluster RPCs)."""
        if self.received is None:
            self.received = signum
        self._event.set()
        # flight-recorder snapshot at the moment of preemption (no-op
        # unless PADDLE_TPU_POSTMORTEM is armed) — if the grace window
        # runs out mid-commit, this is what survives.  MUST NOT run in
        # this frame: request() is called from the signal handler, i.e.
        # on the main thread, which may be interrupted INSIDE a
        # runtime-metrics lock — snapshotting here would deadlock.  A
        # daemon thread acquires that lock normally once the handler
        # returns and the main thread releases it; __exit__ writes a
        # final synchronous dump as the deterministic backstop.
        try:
            self._dump_async(signum)
        except Exception:
            pass
        if self.on_shutdown is not None:
            self.on_shutdown(signum)

    def _dump_async(self, signum):
        from paddle_tpu.obs import flight
        if flight.postmortem_path() is None:
            return
        reason = f"graceful shutdown (signal {signum})"
        threading.Thread(target=flight.write_postmortem, daemon=True,
                         kwargs={"reason": reason},
                         name="paddle-tpu-postmortem").start()

    # -- context -----------------------------------------------------------
    def _handler(self, signum, frame):
        self.request(signum)

    def __enter__(self):
        for sig in self.signals:
            try:
                self._previous[sig] = signal.signal(sig, self._handler)
            except ValueError:      # not the main thread: manual-flag mode
                break
        return self

    def __exit__(self, *exc):
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous.clear()
        if self.received is not None:
            # deterministic final dump from loop context (the async
            # handler-side dump is best-effort; the write is atomic and
            # idempotent, so doubling up is safe)
            try:
                from paddle_tpu.obs import flight
                flight.write_postmortem(
                    reason=f"graceful shutdown (signal {self.received})")
            except Exception:
                pass
        return False


def graceful_shutdown(**kwargs):
    """Convenience alias: ``with graceful_shutdown() as stop: ...``"""
    return GracefulShutdown(**kwargs)
