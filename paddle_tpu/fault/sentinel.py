"""Training sentinel: numerical-fault detection, batch quarantine,
automatic rollback, and deterministic replay.

The elastic runtime (checkpoint.py, retry.py, lifecycle.py) survives
*process* deaths; at scale the more common killer is *state* corruption
— one NaN/Inf loss or gradient silently poisons the parameters and
every step after it is wasted (the MegaScale-class failure mode).  The
:class:`Sentinel` is the layer that composes the existing machinery
into an automatic recovery loop:

1. **detect** — a cheap device-side finite check (fused ``jnp.isfinite``
   all-reduced to ONE scalar over loss/params/updated state, so a check
   step pays exactly one host sync) plus an EMA-based loss-spike
   detector, at a configurable cadence (``PADDLE_TPU_SENTINEL``);
2. **skip-step** — on a trip, ``Executor.run`` discards the update
   (the scope keeps the pre-step state; buffer donation is disabled
   while a sentinel guards the program, which is what makes the discard
   possible) and raises :class:`NumericalFault`;
3. **quarantine** — the faulty step is dumped as a pickled repro bundle
   (program, pre-step state, batch, RNG coordinates, trace id) for
   offline forensics: ``paddle_tpu replay <bundle>`` re-executes it
   under ``JAX_PLATFORMS=cpu`` and reports whether the non-finite
   reproduces;
4. **rollback** — after K consecutive strikes,
   ``CheckpointManager.restore_last_good()`` rewinds params AND the
   datapipe iterator to the last *verified-good* checkpoint (marked by
   :meth:`Sentinel.note_checkpoint` after N clean checks; the GC never
   collects it) and training resumes, emitting a flight-recorder
   post-mortem.

The ``sentinel.nan`` chaos failpoint injects NaNs into the loss and the
updated state exactly where a real numerical fault would appear, so the
full ladder can be drilled end to end (``tests/test_sentinel.py``).

With no sentinel attached, ``Executor.run`` is byte-for-byte the
donating fast path — no extra device sync, no host transfer (the
structural guarantee ``tests/test_sentinel.py`` locks).

Caveat: in interpret (host-op) mode persistables write through the
scope *during* the step, so skip-step cannot fully discard a poisoned
update there — detection still works and rollback is the recovery.
"""

from __future__ import annotations

import logging
import os
import pickle
import time

from paddle_tpu.fault import chaos
from paddle_tpu.obs.trace import span as _span, current_trace_id

__all__ = ["Sentinel", "NumericalFault", "sentinel_from_env",
           "replay_bundle", "load_bundle", "BUNDLE_FORMAT"]

logger = logging.getLogger(__name__)

BUNDLE_FORMAT = 1


class NumericalFault(RuntimeError):
    """A sentinel check tripped: non-finite values or a loss spike.

    Raised by ``Executor.run`` BEFORE the poisoned update reaches the
    scope (the step is skipped).  ``reason`` is ``"non_finite"`` or
    ``"loss_spike"``; ``bad`` names the offending tensors; ``repro`` is
    the self-contained replay payload (see :func:`replay_bundle`);
    ``injected`` marks faults manufactured by the ``sentinel.nan``
    failpoint; ``health`` is the fused norm digest (param/grad norm,
    update ratio) of the tripping step, when the check computed one.
    """

    def __init__(self, message, step=None, reason=None, bad=None,
                 repro=None, injected=False, health=None):
        super().__init__(message)
        self.step = step
        self.reason = reason
        self.bad = list(bad or [])
        self.repro = repro
        self.injected = injected
        self.health = health


def _metrics():
    from paddle_tpu.profiler import runtime_metrics
    return runtime_metrics


class _NullMetrics:
    """Sink for sentinels that must not touch the process-global
    counters — the replay guard, which would otherwise inflate the
    production ``sentinel.*`` fault metrics of a process that also
    trains or serves ``/stats``."""

    def inc(self, name, n=1):
        pass

    def observe(self, name, value):
        pass

    def set_gauge(self, name, value):
        pass


_NULL_METRICS = _NullMetrics()


class Sentinel:
    """Numerical-fault guard for ``Executor.run`` / ``run_pipeline``.

    ::

        sentinel = Sentinel(manager=mgr, cadence=1, strikes=3)
        exe.run_pipeline(main, pipe, fetch_list=[loss],
                         sentinel=sentinel,
                         on_step=lambda s, _: (mgr.save(s),
                                               sentinel.note_checkpoint(s)))

    Parameters
    ----------
    manager : CheckpointManager, optional
        Rollback target provider.  Without one, the ladder ends at
        quarantine and the K-th strike re-raises the fault.
    cadence : int
        Check every ``cadence``-th step (1 = every step).  Each check
        costs one device sync; off-cadence steps pay nothing.
    strikes : int
        Consecutive faulty checks before rollback.  A clean check
        resets the count.
    spike_factor : float or None
        Trip when ``|loss - ema| > spike_factor * (|ema| + 1e-9)`` after
        ``spike_warmup`` observations.  None disables the detector.
    ema_beta : float
        EMA smoothing for the loss baseline.
    loss_name : str, optional
        Fetch name of the loss; default: the first scalar float fetch.
    quarantine_dir : str, optional
        Where repro bundles land (default:
        ``<manager.dirname>/quarantine`` or ``./sentinel_quarantine``).
    mark_good_after : int
        Clean checks after ``note_checkpoint(step)`` before the
        checkpoint is promoted to known-good.
    max_rollbacks : int
        Rollbacks allowed without forward progress (a successful
        mark-good resets the budget); exceeding it re-raises the fault
        instead of looping on a poisoned known-good.
    """

    def __init__(self, manager=None, cadence=1, strikes=3,
                 spike_factor=10.0, ema_beta=0.9, spike_warmup=5,
                 loss_name=None, quarantine_dir=None, mark_good_after=1,
                 max_rollbacks=3):
        self.manager = manager
        self.cadence = max(1, int(cadence))
        self.strikes = max(1, int(strikes))
        self.spike_factor = None if spike_factor is None \
            else float(spike_factor)
        self.ema_beta = float(ema_beta)
        self.spike_warmup = max(1, int(spike_warmup))
        self.loss_name = loss_name
        self.mark_good_after = max(0, int(mark_good_after))
        self.max_rollbacks = max(0, int(max_rollbacks))
        if quarantine_dir is None and manager is not None:
            quarantine_dir = os.path.join(manager.dirname, "quarantine")
        self.quarantine_dir = quarantine_dir or "sentinel_quarantine"
        self._tick = 0            # steps seen
        self._strikes = 0         # consecutive faulty checks
        self._rollbacks = 0       # rollbacks since last forward progress
        self._ema = None
        self._ema_n = 0
        self._pending_good = []   # [step, clean checks still needed]
        self._bundle_seq = 0
        self._check_fn = None     # lazily-jitted fused finite check
        self._metrics_enabled = True   # replay guards flip this off
        self._warned_loss_name = False
        self.last_health = None   # fused norm digest of the last check

    def _m(self):
        return _metrics() if self._metrics_enabled else _NULL_METRICS

    # -- detection (called by Executor.run on guarded steps) ------------

    def after_step(self, fetch_names, fetches, new_state, repro=None,
                   prev_state=None, param_names=()):
        """Inspect one step's results BEFORE scope write-back.

        Applies the ``sentinel.nan`` poison when that failpoint fires,
        then — on cadence steps — runs the fused device-side finite
        check and the EMA spike detector.  Returns the (possibly
        poisoned) ``(fetches, new_state)`` for write-back; raises
        :class:`NumericalFault` on a trip, in which case the executor
        discards the update.

        ``prev_state``/``param_names`` (the executor's pre-step inout
        state and which of those names are Parameters) extend the fused
        reduction with the global param/update norms — still ONE device
        computation and one host sync per guarded step — published as
        the ``train.param_norm`` / ``train.grad_norm`` /
        ``train.update_ratio`` gauges and carried into the escalation
        context (quarantine bundles, rollback post-mortems)."""
        self._tick += 1
        if self._tick % self.cadence:
            return fetches, new_state
        injected = False
        # the failpoint fires only on CHECKED steps: an off-cadence
        # poison would be committed unseen and the next check would
        # quarantine an innocent batch — injection means "poison the
        # next step the sentinel actually inspects"
        if chaos.armed("sentinel.nan"):
            try:
                chaos.fire("sentinel.nan", step=self._tick)
            except chaos.FaultInjected:
                injected = True
                fetches, new_state = self._poison(fetch_names, fetches,
                                                  new_state)
        t0 = time.perf_counter()
        try:
            with _span("sentinel.check", step=self._tick):
                self._inspect(fetch_names, fetches, new_state, repro,
                              injected, prev_state, param_names)
        finally:
            # a tripped check raises out of _inspect — exactly the
            # expensive case (it pays the host-side culprit sweep), so
            # the latency series the docs tune cadence against must
            # still record it
            self._m().observe("sentinel.check_seconds",
                              time.perf_counter() - t0)
        return fetches, new_state

    def _inspect(self, fetch_names, fetches, new_state, repro, injected,
                 prev_state=None, param_names=()):
        m = self._m()
        m.inc("sentinel.checks")
        named = list(zip(fetch_names, fetches))
        named += list(new_state.items())
        finite, health = self._device_check([v for _, v in named],
                                            new_state, prev_state,
                                            param_names)
        self.last_health = health
        if health is not None:
            from paddle_tpu.obs import numerics as _numerics
            _numerics.set_health_gauges(m, health)
        if not finite:
            bad = [n for n, v in named if not _host_finite(v)]
            m.inc("sentinel.non_finite")
            self._trip("non_finite", bad, repro, injected,
                       f"non-finite values in {bad[:4]} at guarded "
                       f"step {self._tick}")
        loss = self._loss_value(fetch_names, fetches)
        if loss is not None and self.spike_factor is not None:
            if self._ema_n >= self.spike_warmup and \
                    abs(loss - self._ema) > \
                    self.spike_factor * (abs(self._ema) + 1e-9):
                m.inc("sentinel.loss_spikes")
                self._trip("loss_spike", [], repro, injected,
                           f"loss {loss:g} spiked against EMA "
                           f"{self._ema:g} at guarded step {self._tick}")
            beta = self.ema_beta
            self._ema = loss if self._ema is None \
                else beta * self._ema + (1.0 - beta) * loss
            self._ema_n += 1
        # clean check: strikes reset, pending checkpoints age toward good
        self._strikes = 0
        self._advance_good()

    def _trip(self, reason, bad, repro, injected, message):
        self._m().inc("sentinel.skipped_steps")
        payload = None
        if repro is not None:
            try:
                payload = repro() if callable(repro) else repro
            except Exception:
                logger.warning("sentinel: repro payload capture failed",
                               exc_info=True)
        raise NumericalFault(message, step=self._tick, reason=reason,
                             bad=bad, repro=payload, injected=injected,
                             health=self.last_health)

    def _device_check(self, values, new_state, prev_state, param_names):
        """Fused ``jnp.isfinite(...).all()`` over every floating tensor
        PLUS the global param/update norms (obs/numerics.py), all in ONE
        device computation — the single host sync a check step pays.
        Culprit naming (rare) happens host-side after.  Returns
        ``(all_finite, health_dict_or_None)``."""
        import jax.numpy as jnp
        from paddle_tpu.obs import numerics as _numerics
        leaves = [jnp.asarray(v) for v in values
                  if hasattr(v, "dtype") or _is_arraylike(v)]
        leaves = [l for l in leaves
                  if jnp.issubdtype(l.dtype, jnp.floating)]
        new_params, old_params = [], []
        if prev_state is not None:
            for n in param_names:
                nv = new_state.get(n)
                ov = prev_state.get(n)
                if nv is None or ov is None:
                    continue
                a, b = jnp.asarray(nv), jnp.asarray(ov)
                if jnp.issubdtype(a.dtype, jnp.floating) and \
                        a.shape == b.shape:
                    new_params.append(a)
                    old_params.append(b)
        if not leaves and not new_params:
            return True, None
        if self._check_fn is None:
            self._check_fn = _numerics.fused_check_fn()
        finite, norms = self._check_fn(leaves, new_params, old_params)
        import numpy as np
        health = _numerics.health_from_norms(np.asarray(norms)) \
            if norms.shape[0] else None
        return bool(finite), health

    def _loss_value(self, fetch_names, fetches):
        idx = self._loss_index(fetch_names, fetches)
        if idx is None:
            return None
        import numpy as np
        try:
            return float(np.asarray(fetches[idx],
                                    dtype="float32").reshape(-1)[0])
        except (TypeError, ValueError, IndexError):
            return None

    def _loss_index(self, fetch_names, fetches):
        if self.loss_name is not None:
            try:
                return list(fetch_names).index(self.loss_name)
            except ValueError:
                if not self._warned_loss_name:
                    # a typo'd loss= must not SILENTLY disable the spike
                    # detector the operator believes is active
                    self._warned_loss_name = True
                    logger.warning(
                        "sentinel: configured loss_name %r is not among "
                        "the fetches %s — the loss-spike detector is "
                        "inactive until it matches",
                        self.loss_name, list(fetch_names))
                return None
        import numpy as np
        for i, v in enumerate(fetches):
            if not _is_arraylike(v):
                continue
            a = np.asarray(v) if not hasattr(v, "dtype") else v
            try:
                floating = np.issubdtype(np.dtype(str(a.dtype)),
                                         np.floating)
            except TypeError:
                floating = "float" in str(a.dtype)
            if floating and _size_of(a) == 1:
                return i
        return None

    def _poison(self, fetch_names, fetches, new_state):
        """``sentinel.nan`` failpoint action: NaN out the loss fetch and
        every floating tensor of the updated state — the shape of a real
        numerical blow-up (bad loss + poisoned params)."""
        import jax.numpy as jnp
        fetches = list(fetches)
        idx = self._loss_index(fetch_names, fetches)
        if idx is not None:
            fetches[idx] = jnp.full_like(jnp.asarray(fetches[idx]),
                                         jnp.nan)
        poisoned = {}
        for n, v in new_state.items():
            a = jnp.asarray(v)
            if jnp.issubdtype(a.dtype, jnp.floating):
                poisoned[n] = a * jnp.nan
            else:
                poisoned[n] = v
        return fetches, poisoned

    # -- escalation ladder (called by Executor.run_pipeline) -------------

    def handle_fault(self, fault, step=None):
        """Quarantine the faulty step and count the strike; after K
        consecutive strikes roll back to the last known-good checkpoint.
        Returns the restored step on rollback, else None (the caller
        skips the batch and continues).  Re-raises when unrecoverable
        (no manager, nothing restorable, rollback budget exhausted)."""
        self._strikes += 1
        # a fault invalidates the clean-streak countdown of every save
        # not yet promoted — a poisoned step may already be inside them
        self._pending_good.clear()
        try:
            self.quarantine(fault, step=step)
        except Exception:
            logger.warning("sentinel: quarantine dump failed",
                           exc_info=True)
        if self._strikes >= self.strikes:
            return self.rollback(fault)
        return None

    def quarantine(self, fault, step=None):
        """Dump the fault as a pickled bundle under ``quarantine_dir``
        (atomic tmp+rename); returns the path.  A fault whose repro
        capture failed still records the event (step, reason, culprits,
        trace id) — such a bundle cannot replay (``paddle_tpu replay``
        exits 2 on it) but keeps the forensic trail."""
        os.makedirs(self.quarantine_dir, exist_ok=True)
        self._bundle_seq += 1
        name = (f"quarantine-step{step if step is not None else fault.step}"
                f"-{os.getpid()}-{self._bundle_seq}.pkl")
        path = os.path.join(self.quarantine_dir, name)
        bundle = {
            "format": BUNDLE_FORMAT,
            "step": step if step is not None else fault.step,
            "reason": fault.reason,
            "bad": fault.bad,
            "injected": bool(fault.injected),
            "trace_id": current_trace_id(),
            "time_unix": time.time(),
            # detector state at the trip: replaying a loss-spike bundle
            # needs the EMA baseline the loss spiked AGAINST
            "detector": {"ema": self._ema, "ema_n": self._ema_n,
                         "spike_factor": self.spike_factor,
                         "ema_beta": self.ema_beta,
                         "loss_name": self.loss_name},
            # fused norm digest of the tripping step — forensics can
            # tell "params were already huge" from "one bad batch"
            "health": getattr(fault, "health", None),
            "repro": fault.repro,
        }
        with _span("sentinel.quarantine", step=bundle["step"]):
            from paddle_tpu.io import atomic_write
            atomic_write(path, pickle.dumps(bundle, protocol=4))
        self._m().inc("sentinel.quarantined")
        logger.warning("sentinel: quarantined step %s (%s) -> %s",
                       bundle["step"], fault.reason, path)
        return path

    def rollback(self, fault=None):
        """Restore the last known-good checkpoint (params + datapipe
        position) through the attached manager and reset the detector
        state.  Emits a flight-recorder post-mortem (no-op unless
        ``PADDLE_TPU_POSTMORTEM`` is armed).  Returns the restored
        step."""
        err = fault or NumericalFault("sentinel rollback requested",
                                      reason="manual")
        if self.manager is None:
            raise err
        if self._rollbacks >= self.max_rollbacks:
            logger.error("sentinel: rollback budget (%d) exhausted with "
                         "no forward progress — giving up",
                         self.max_rollbacks)
            raise err
        self._rollbacks += 1
        with _span("sentinel.rollback", strikes=self._strikes):
            restored = self.manager.restore_last_good()
        if restored is None:
            raise err
        self._strikes = 0
        self._ema = None
        self._ema_n = 0
        self._pending_good.clear()
        self._m().inc("sentinel.rollbacks")
        try:
            from paddle_tpu.obs import flight
            flight.write_postmortem(
                reason=f"sentinel rollback to step {restored}",
                extra={"restored_step": int(restored),
                       "fault": str(fault) if fault else None,
                       "health": self.last_health,
                       "quarantine_dir": self.quarantine_dir})
        except Exception:
            pass
        logger.warning("sentinel: rolled back to known-good step %s",
                       restored)
        return restored

    # -- known-good promotion --------------------------------------------

    def note_checkpoint(self, step):
        """Register a freshly-saved checkpoint; after ``mark_good_after``
        clean checks it is promoted via ``manager.mark_good(step)``."""
        if self.manager is None:
            return
        if self.mark_good_after <= 0:
            self._promote(int(step))
        else:
            self._pending_good.append([int(step), self.mark_good_after])

    def _advance_good(self):
        if not self._pending_good:
            return
        promoted = None
        for entry in self._pending_good:
            entry[1] -= 1
            if entry[1] <= 0:
                promoted = entry[0]   # newest eligible wins
        if promoted is not None:
            self._pending_good = [e for e in self._pending_good
                                  if e[1] > 0]
            self._promote(promoted)

    def _promote(self, step):
        try:
            got = self.manager.mark_good(step)
        except Exception:
            logger.warning("sentinel: mark_good(%s) failed", step,
                           exc_info=True)
            return
        if got is None:
            # the checkpoint was rotated away before its promotion
            # caught up: no new anchor, no forward progress — the
            # rollback budget must NOT refill on a phantom promotion
            logger.warning("sentinel: checkpoint %s vanished before "
                           "promotion (keep-N rotation outran the "
                           "clean-check lag)", step)
            return
        self._rollbacks = 0   # forward progress: refill rollback budget


def sentinel_from_env(manager=None, spec=None, **overrides):
    """Build a :class:`Sentinel` from ``PADDLE_TPU_SENTINEL`` (or an
    explicit ``spec``); returns None when unset/disabled — training
    scripts guard only when the operator asked.

    Grammar (``;`` or ``,`` separated)::

        PADDLE_TPU_SENTINEL="1"                              # defaults
        PADDLE_TPU_SENTINEL="cadence=4;strikes=3;spike=10"
        PADDLE_TPU_SENTINEL="cadence=1;spike=off;quarantine=/tmp/q"

    Keys: ``cadence``, ``strikes``, ``spike`` (factor, or ``off``),
    ``ema``, ``warmup``, ``good_after``, ``max_rollbacks``,
    ``quarantine`` (dir), ``loss`` (fetch name)."""
    spec = spec if spec is not None \
        else os.environ.get("PADDLE_TPU_SENTINEL", "")
    spec = spec.strip()
    if not spec or spec.lower() in ("0", "false", "off", "no"):
        return None
    kwargs = {}
    if spec.lower() not in ("1", "true", "on", "yes"):
        keymap = {"cadence": ("cadence", int),
                  "strikes": ("strikes", int),
                  "spike": ("spike_factor",
                            lambda v: None if v.lower() in ("off", "none")
                            else float(v)),
                  "ema": ("ema_beta", float),
                  "warmup": ("spike_warmup", int),
                  "good_after": ("mark_good_after", int),
                  "max_rollbacks": ("max_rollbacks", int),
                  "quarantine": ("quarantine_dir", str),
                  "loss": ("loss_name", str)}
        for clause in spec.replace(",", ";").split(";"):
            clause = clause.strip()
            if not clause:
                continue
            key, _, value = clause.partition("=")
            key = key.strip().lower()
            if key not in keymap:
                raise ValueError(
                    f"PADDLE_TPU_SENTINEL: unknown key {key!r} in "
                    f"{clause!r} (want {sorted(keymap)})")
            dest, conv = keymap[key]
            kwargs[dest] = conv(value.strip())
    kwargs.update(overrides)
    return Sentinel(manager=manager, **kwargs)


# ---------------------------------------------------------------------------
# offline replay (`paddle_tpu replay <bundle>`)
# ---------------------------------------------------------------------------

def load_bundle(path):
    """Unpickle + sanity-check a quarantine bundle (shared by
    :func:`replay_bundle` and ``numerics.localize_bundle``); a
    malformed bundle raises ``ValueError`` (the CLI's exit 2)."""
    try:
        with open(path, "rb") as f:
            bundle = pickle.load(f)
        if not isinstance(bundle, dict):
            raise ValueError("not a bundle dict")
    except OSError:
        raise
    except Exception as e:
        # pickle raises a zoo on truncated/corrupt input
        # (UnpicklingError, EOFError, AttributeError, ...): normalize to
        # the CLI's "malformed bundle" verdict (exit 2) — never the
        # "replayed clean" one
        raise ValueError(f"{path}: malformed bundle: {e}") from e
    return bundle


def replay_bundle(path):
    """Re-execute a quarantined step from its repro bundle and report
    whether the numerical fault reproduces.

    Rebuilds the program, pre-step state, batch, and RNG coordinates
    recorded at quarantine time, runs ONE step under a detect-only
    sentinel, and returns ``{"reproduced": bool, "reason", "bad",
    "step", "injected"}``.  Bundles whose fault was manufactured by the
    ``sentinel.nan`` failpoint re-arm it for one fire, so injected
    drills replay deterministically too.  Run under
    ``JAX_PLATFORMS=cpu`` (the CLI does this) to debug a TPU fault on a
    workstation."""
    bundle = load_bundle(path)
    repro = bundle.get("repro")
    if not repro:
        raise ValueError(f"{path}: bundle carries no repro payload")
    from paddle_tpu.executor import Executor
    from paddle_tpu.framework import Program
    from paddle_tpu.place import CPUPlace
    from paddle_tpu.scope import Scope

    try:
        program = Program.from_dict(repro["program"])
        program.random_seed = repro.get("random_seed")
        scope = Scope()
        for name, value in (repro.get("state") or {}).items():
            scope.set_var(name, value)
        # the step's PRNGKey is (seed * 1000003 + run_counter); rewind
        # the counter so the replayed step folds in the exact same key
        run_counter = int(repro.get("run_counter", 1)) - 1
    except Exception as e:
        # a bundle that unpickles but whose payload cannot rebuild
        # (version skew, corrupt arrays) is still "malformed" (exit 2),
        # never "replayed clean"
        raise ValueError(
            f"{path}: cannot rebuild repro payload: {e}") from e
    exe = Executor(CPUPlace())
    exe._run_counter = run_counter
    det = bundle.get("detector") or {}
    if bundle.get("reason") == "loss_spike" and \
            det.get("spike_factor") is not None and \
            det.get("ema") is not None:
        # re-arm the spike detector against the recorded EMA baseline —
        # a deterministic spike (bad batch) reproduces, a transient one
        # replays clean
        guard = Sentinel(cadence=1, strikes=1 << 30,
                         spike_factor=det["spike_factor"],
                         ema_beta=det.get("ema_beta", 0.9),
                         spike_warmup=1,
                         loss_name=det.get("loss_name"))
        guard._ema = det.get("ema")
        guard._ema_n = max(int(det.get("ema_n") or 1), 1)
    else:
        guard = Sentinel(cadence=1, strikes=1 << 30, spike_factor=None)
    # the replay guard must not inflate the process-global sentinel.*
    # fault counters (an in-process replay is forensics, not a fault)
    guard._metrics_enabled = False
    prev_nan_fp = None
    if bundle.get("injected"):
        # swap, don't inject+clear: an in-process caller may have a live
        # drill armed on sentinel.nan (e.g. PADDLE_TPU_CHAOS
        # "sentinel.nan=error@100*3" waiting for step 100) — the replay
        # must not clobber it on the way in or disarm it on the way out
        prev_nan_fp = chaos.swap("sentinel.nan", None)
        chaos.inject("sentinel.nan", times=1)
    report = {"reproduced": False, "reason": None, "bad": [],
              "step": bundle.get("step"),
              "injected": bool(bundle.get("injected"))}
    try:
        try:
            exe.run(program, feed=dict(repro["feed"]),
                    fetch_list=list(repro["fetch_names"]), scope=scope,
                    sentinel=guard)
        except NumericalFault as f:
            report.update(reproduced=True, reason=f.reason, bad=f.bad)
        except Exception as e:
            # a step that cannot re-execute at all (version skew hitting
            # jit tracing, an XLA runtime error) is "unreplayable" (the
            # CLI's exit 2) — it must never fall through to exit 1, the
            # "replayed CLEAN, suspect hardware" verdict automated
            # triage trusts
            raise ValueError(
                f"{path}: bundle does not re-execute: {e}") from e
    finally:
        if bundle.get("injected"):
            chaos.swap("sentinel.nan", prev_nan_fp)
    return report


def _is_arraylike(v):
    return hasattr(v, "shape") or hasattr(v, "dtype")


def _size_of(a):
    try:
        return int(a.size)
    except (AttributeError, TypeError):
        return None


def _host_finite(v):
    import numpy as np
    try:
        a = np.asarray(v)
    except TypeError:
        return True
    if getattr(a.dtype, "kind", None) in ("i", "u", "b"):
        return True
    try:
        # cast through float32: covers ml_dtypes (bfloat16) too
        return bool(np.isfinite(a.astype("float32", copy=False)).all())
    except (TypeError, ValueError):
        return True
