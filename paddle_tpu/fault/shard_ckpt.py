"""Per-shard parallel checkpoint format + mesh-elastic restore planning.

The orbax whole-state path (``io.save_checkpoint``'s default) writes one
opaque blob per checkpoint; this module is the *elastic* format: every
persistable tensor is written as one file per owned mesh shard
(``shards/<var>/shard-<k>-of-<N>``), concurrently, and the manifest
gains a **topology record** — mesh shape, axis names, and a per-var
shard→rank map — so a later boot can read the checkpoint's geometry
without loading a single tensor, prove a restore plan against a
*different* mesh (dp4 → dp2, or dp2 → dp8), and only then touch data.

The commit discipline is unchanged: shards land in the ``.tmp-`` dir
and ride the existing manifest → fsync → rename atomic commit
(``fault.checkpoint.commit_checkpoint``), with each shard file
individually SHA-256'd in the manifest.  The ``ckpt.shard.write``
failpoint fires before every shard write — a kill there leaves only the
temp dir, so the previous committed checkpoint stays the restore
target.  ``ckpt.reshard`` fires at the head of restore *planning* — an
error there surfaces as a clean, retryable :class:`ReshardError` before
the scope is touched.
"""

from __future__ import annotations

import json
import os
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from paddle_tpu.fault import chaos

__all__ = ["ReshardError", "SHARD_DIR", "TOPOLOGY_FORMAT",
           "build_topology", "write_state", "read_state", "plan_restore",
           "validate_topology", "shard_relpath", "owner_process",
           "read_manifest"]

SHARD_DIR = "shards"
TOPOLOGY_FORMAT = 1


class ReshardError(RuntimeError):
    """A restore plan cannot map the saved topology onto the target
    mesh.  Raised during *planning*, before any tensor is read or any
    scope entry mutated — the failure is clean and retryable (fix the
    mesh, or restore onto the saved geometry)."""

    retryable = True


def _quote(name):
    return name.replace("/", "%2F")


def shard_relpath(name, k, n):
    """Checkpoint-relative path of shard ``k`` of ``n`` of ``name``."""
    return os.path.join(SHARD_DIR, _quote(name), f"shard-{k}-of-{n}")


def owner_process(rank, num_shards, processes):
    """Host owning dp rank ``rank``'s shard: ranks are block-assigned to
    processes (contiguous device blocks per host on TPU meshes)."""
    return rank * processes // num_shards


def _shard_axis(spec):
    """(axis_index, axis_name) of the first sharded dim, or (None, None)
    for a replicated placement."""
    for d, ax in enumerate(spec or ()):
        if ax is not None:
            return d, ax
    return None, None


def build_topology(mesh, state, shard_specs=None):
    """The manifest topology record for ``state`` (name -> host array)
    saved on ``mesh``.  ``shard_specs`` maps names to placement tuples
    (e.g. a :meth:`ZeroPlan.checkpoint_specs` dict); unlisted vars are
    recorded replicated (one shard)."""
    import jax
    shard_specs = shard_specs or {}
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    shards = {}
    for name in sorted(state):
        value = state[name]
        # shape/dtype only — materializing the tensor here would pull
        # the whole payload device->host a second time (write_state
        # does the one real copy)
        shape = tuple(int(d) for d in value.shape)
        spec = tuple(shard_specs.get(name) or ())
        axis, axis_name = _shard_axis(spec)
        n = int(axis_sizes.get(axis_name, 1)) if axis_name else 1
        if axis is None or n <= 1 or len(shape) <= axis or \
                shape[axis] % n != 0:
            spec, axis, n = (), None, 1
        shards[name] = {
            "shape": list(shape),
            "dtype": str(value.dtype),
            "spec": [a if a is None else str(a) for a in spec],
            "axis": axis,
            "num_shards": n,
            "shard_ranks": list(range(n)),
        }
    return {
        "format": TOPOLOGY_FORMAT,
        "mesh_shape": [int(d) for d in mesh.devices.shape],
        "axis_names": [str(a) for a in mesh.axis_names],
        "processes": int(jax.process_count()),
        "shards": shards,
    }


def _write_one(path, piece, name, k, n, step):
    chaos.fire("ckpt.shard.write", var=name, shard=k, step=step)
    from paddle_tpu import profiler as _profiler
    t0 = time.perf_counter()
    with open(path, "wb") as f:
        np.save(f, piece, allow_pickle=False)
        f.flush()
        os.fsync(f.fileno())
    dt = time.perf_counter() - t0
    _profiler.runtime_metrics.inc("ckpt.shard.writes")
    _profiler.runtime_metrics.inc("ckpt.shard.bytes", piece.nbytes)
    _profiler.runtime_metrics.observe("ckpt.shard.write_seconds", dt)
    from paddle_tpu.obs.trace import record_span
    record_span("ckpt.shard.write", t0, dt, var=name, shard=k, of=n)


def write_state(tmp_path, state, topology, step=None, max_workers=None):
    """Write this host's owned shards of ``state`` under
    ``tmp_path/shards/`` — one file per shard, written concurrently.
    Replicated vars are written by the coordinator host only."""
    import jax
    proc, procs = jax.process_index(), int(topology["processes"])
    jobs = []
    for name, rec in topology["shards"].items():
        arr = np.asarray(state[name])
        n, axis = rec["num_shards"], rec["axis"]
        for k in rec["shard_ranks"]:
            if owner_process(k, max(n, 1), procs) != proc:
                continue
            if axis is None:
                piece = arr
            else:
                size = arr.shape[axis] // n
                sl = [slice(None)] * arr.ndim
                sl[axis] = slice(k * size, (k + 1) * size)
                piece = arr[tuple(sl)]
            path = os.path.join(tmp_path, shard_relpath(name, k, n))
            os.makedirs(os.path.dirname(path), exist_ok=True)
            jobs.append((path, piece, name, k, n))
    if not jobs:
        return 0
    workers = max_workers or min(8, len(jobs), os.cpu_count() or 1)
    if workers <= 1:
        for path, piece, name, k, n in jobs:
            _write_one(path, piece, name, k, n, step)
    else:
        with ThreadPoolExecutor(max_workers=workers) as pool:
            futs = [pool.submit(_write_one, path, piece, name, k, n,
                                step)
                    for path, piece, name, k, n in jobs]
            for f in futs:
                f.result()   # surface the first failure (incl. chaos)
    return len(jobs)


def read_state(path, topology, names=None):
    """Reassemble host arrays from a committed shard checkpoint:
    shards of each var concatenated along its saved axis.  Reads only —
    callers commit to the scope after EVERY var loaded cleanly."""
    out = {}
    for name, rec in topology["shards"].items():
        if names is not None and name not in names:
            continue
        n, axis = rec["num_shards"], rec["axis"]
        pieces = [np.load(os.path.join(path, shard_relpath(name, k, n)),
                          allow_pickle=False)
                  for k in rec["shard_ranks"]]
        arr = pieces[0] if axis is None else np.concatenate(pieces,
                                                            axis=axis)
        want = tuple(rec["shape"])
        if arr.shape != want:
            raise ReshardError(
                f"checkpoint var `{name}` reassembles to {arr.shape} "
                f"but the topology declares {want}")
        out[name] = arr
    return out


def validate_topology(manifest):
    """Self-consistency problems of a manifest's topology record, as a
    list of strings (empty = consistent).  Cross-checks the record
    against the manifest's own file table: every declared shard file
    must be checksummed, shard counts must match the saved mesh axis
    they ride, and shapes must slice evenly."""
    problems = []
    topo = manifest.get("topology")
    if not isinstance(topo, dict):
        return ["manifest has no topology record"]
    if topo.get("format") != TOPOLOGY_FORMAT:
        problems.append(f"topology format must be {TOPOLOGY_FORMAT}, "
                        f"got {topo.get('format')!r}")
    mesh_shape = topo.get("mesh_shape")
    axis_names = topo.get("axis_names")
    if not isinstance(mesh_shape, list) or not mesh_shape or \
            not all(isinstance(d, int) and d > 0 for d in mesh_shape):
        problems.append(f"mesh_shape must be positive ints, "
                        f"got {mesh_shape!r}")
        mesh_shape = []
    if not isinstance(axis_names, list) or \
            len(axis_names) != len(mesh_shape):
        problems.append(f"axis_names {axis_names!r} do not label "
                        f"mesh_shape {mesh_shape!r}")
        axis_names = []
    axis_sizes = dict(zip(axis_names, mesh_shape))
    files = manifest.get("files", {})
    shards = topo.get("shards")
    if not isinstance(shards, dict):
        return problems + ["topology.shards must be an object"]
    for name, rec in sorted(shards.items()):
        where = f"shards[{name!r}]"
        n = rec.get("num_shards")
        axis = rec.get("axis")
        shape = rec.get("shape") or []
        spec = rec.get("spec") or []
        if not isinstance(n, int) or n < 1:
            problems.append(f"{where}: bad num_shards {n!r}")
            continue
        if rec.get("shard_ranks") != list(range(n)):
            problems.append(f"{where}: shard_ranks must be "
                            f"0..{n - 1}, got {rec.get('shard_ranks')!r}")
        if axis is not None:
            if not isinstance(axis, int) or not \
                    (0 <= axis < len(shape)):
                problems.append(f"{where}: axis {axis!r} out of range "
                                f"for shape {shape}")
            elif shape[axis] % n != 0:
                problems.append(f"{where}: dim {axis} of {shape[axis]} "
                                f"does not slice into {n} shards")
            _, axis_name = _shard_axis(spec)
            if axis_name is not None and \
                    axis_sizes.get(axis_name) not in (None, n):
                problems.append(
                    f"{where}: {n} shards ride mesh axis "
                    f"`{axis_name}` of size {axis_sizes[axis_name]}")
        elif n != 1:
            problems.append(f"{where}: replicated var with {n} shards")
        for k in range(n):
            rel = shard_relpath(name, k, n)
            if rel not in files:
                problems.append(f"{where}: shard file {rel!r} missing "
                                f"from the manifest file table")
    # the reverse direction: a shard file the topology does not declare
    declared = {shard_relpath(name, k, rec["num_shards"])
                for name, rec in shards.items()
                if isinstance(rec.get("num_shards"), int)
                for k in range(max(rec["num_shards"], 0))}
    for rel in files:
        if rel.startswith(SHARD_DIR + os.sep) and rel not in declared:
            problems.append(f"undeclared shard file {rel!r}")
    return problems


def plan_restore(topology, mesh):
    """Map a saved topology onto ``mesh``: the *restore plan* — name ->
    target placement tuple — statically verified against the new mesh
    (axis exists, dims divide) BEFORE any shard is read or any device
    allocated.  Raises :class:`ReshardError` with every violation when
    the plan is unprovable; the scope is untouched.

    The verification rides the same facts the PTA016 pass checks
    (``analysis.distributed._validate_spec``): an elastic restore is a
    sharding plan like any other, and it gets the same static proof.
    """
    chaos.fire("ckpt.reshard", mesh_shape=list(mesh.devices.shape))
    from paddle_tpu.analysis.distributed import _validate_spec
    axis_sizes = {str(a): int(s) for a, s in
                  zip(mesh.axis_names, mesh.devices.shape)}
    plan = {}
    diags = []
    resliced = 0
    for name, rec in sorted(topology["shards"].items()):
        spec = tuple(a if a is None else str(a)
                     for a in rec.get("spec") or ())
        shape = tuple(int(d) for d in rec.get("shape") or ())
        _validate_spec(name, spec, shape, axis_sizes, diags,
                       program="restore-plan")
        plan[name] = spec
        _, axis_name = _shard_axis(spec)
        if axis_name is not None and \
                axis_sizes.get(axis_name) != rec.get("num_shards"):
            resliced += 1
    if diags:
        raise ReshardError(
            "restore plan does not map the saved topology "
            f"(mesh {topology.get('mesh_shape')} "
            f"{topology.get('axis_names')}) onto the target mesh "
            f"({list(mesh.devices.shape)} {list(mesh.axis_names)}):\n"
            + "\n".join(d.format() for d in diags))
    from paddle_tpu import profiler as _profiler
    _profiler.runtime_metrics.inc("reshard.plans")
    _profiler.runtime_metrics.inc("reshard.vars", resliced)
    return plan


def read_manifest(path):
    """The committed manifest of checkpoint dir ``path`` (or None when
    absent/unreadable) — the cheap format probe restore uses to pick
    the shard path over the orbax path."""
    from paddle_tpu.fault.checkpoint import MANIFEST_NAME
    try:
        with open(os.path.join(path, MANIFEST_NAME)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
