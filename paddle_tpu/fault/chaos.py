"""Failpoint-based fault injection.

Product code marks failure boundaries with ``chaos.fire("name")``; tests
(or an operator, via ``PADDLE_TPU_CHAOS``) arm those names to raise,
delay, or hard-kill the process exactly there.  Modeled on the
freebsd/etcd ``failpoint`` idiom: a disarmed failpoint is one dict
lookup on an (almost always) empty dict, so instrumentation can stay in
hot-ish paths like the reader pump and the RPC client.

The authoritative list of failpoint names wired through the codebase is
the registry table in ``docs/fault_tolerance.md`` — it is
scanner-enforced (``tests/test_chaos_failpoint_registry.py`` fails when
a ``chaos.fire(...)`` site is missing from it), so unlike a docstring
copy it cannot drift.

Env grammar (``;`` or ``,`` separated)::

    PADDLE_TPU_CHAOS="train.step=kill@4;master.rpc=error*2;reader.pump=delay:0.5"

``action`` is ``error`` (raise :class:`FaultInjected`), ``kill``
(``os._exit(137)``), or ``delay:SECONDS``.  ``@N`` skips the first N
fires; ``*N`` triggers at most N times (default: unlimited).
"""

from __future__ import annotations

import os
import random
import re
import threading
import time

__all__ = ["FaultInjected", "inject", "fire", "clear", "armed",
           "failpoints", "scoped", "swap", "arm_from_env",
           "KILL_EXIT_CODE"]

KILL_EXIT_CODE = 137


class FaultInjected(RuntimeError):
    """Raised by an armed ``error`` failpoint."""

    def __init__(self, failpoint, message=None):
        super().__init__(message or f"fault injected at {failpoint!r}")
        self.failpoint = failpoint


class _Failpoint:
    def __init__(self, name, error=None, kill=False, delay=None,
                 times=None, after=0, probability=1.0):
        self.name = name
        self.error = error
        self.kill = kill
        self.delay = delay
        self.times = times        # remaining triggers; None = unlimited
        self.after = after        # skip this many fires first
        self.probability = probability
        self.fired = 0            # total fire() calls seen
        self.triggered = 0        # times the action actually ran


_lock = threading.Lock()
_registry: dict[str, _Failpoint] = {}
_env_loaded = False


def inject(name, error=None, kill=False, delay=None, times=None, after=0,
           probability=1.0):
    """Arm failpoint ``name``.

    ``error``: exception instance/class to raise (``True`` or ``None``
    with no other action means a :class:`FaultInjected`); ``kill``:
    ``os._exit(137)`` — a crash no ``finally`` can intercept; ``delay``:
    sleep seconds (combinable with error/kill); ``times``: max triggers
    before auto-disarm; ``after``: let this many fires pass first;
    ``probability``: trigger chance per eligible fire.
    """
    fp = _Failpoint(name, error=error, kill=kill, delay=delay, times=times,
                    after=after, probability=probability)
    with _lock:
        _registry[name] = fp
    return fp


def clear(name=None):
    """Disarm one failpoint (or all, when ``name`` is None)."""
    with _lock:
        if name is None:
            _registry.clear()
        else:
            _registry.pop(name, None)


def armed(name):
    return name in _registry


def swap(name, fp):
    """Install failpoint object ``fp`` under ``name`` (remove it when
    ``fp`` is None) and return the previously armed object, if any —
    the save/restore idiom for code that must re-arm a failpoint
    briefly without clobbering an operator's live spec (``scoped``
    only disarms on exit; it cannot restore a prior arm)."""
    with _lock:
        prev = _registry.pop(name, None)
        if fp is not None:
            _registry[name] = fp
        return prev


def failpoints():
    """Snapshot of armed failpoints: name -> (fired, triggered)."""
    with _lock:
        return {n: (fp.fired, fp.triggered) for n, fp in _registry.items()}


def fire(name, **context):
    """Evaluate failpoint ``name``; no-op unless armed.

    Called from product code at failure boundaries.  ``context`` is
    carried into the :class:`FaultInjected` message for debuggability.
    """
    if not _env_loaded:
        _load_env()
    if not _registry:          # fast path: nothing armed
        return
    with _lock:
        fp = _registry.get(name)
        if fp is None:
            return
        fp.fired += 1
        if fp.fired <= fp.after:
            return
        if fp.times is not None and fp.triggered >= fp.times:
            return
        if fp.probability < 1.0 and random.random() >= fp.probability:
            return
        fp.triggered += 1
        error, kill, delay = fp.error, fp.kill, fp.delay
    if delay:
        time.sleep(delay)
    if kill:
        # last act before the hard exit: flight-recorder dump (no-op
        # unless PADDLE_TPU_POSTMORTEM is set; write_postmortem never
        # raises).  A chaos kill is the drill for a real crash — the
        # post-mortem is the artifact the drill validates.
        try:
            from paddle_tpu.obs import flight
            flight.write_postmortem(
                reason=f"chaos kill at failpoint {name!r}",
                extra={"failpoint": name, "context": repr(context)})
        except Exception:
            pass
        os._exit(KILL_EXIT_CODE)   # hard crash: no atexit, no finally
    if error is not None or delay is None:
        detail = f" ({context})" if context else ""
        if isinstance(error, BaseException):
            raise error
        if isinstance(error, type) and issubclass(error, BaseException):
            raise error(f"fault injected at {name!r}{detail}")
        raise FaultInjected(name, f"fault injected at {name!r}{detail}")


class scoped:
    """``with chaos.scoped("master.rpc", error=...):`` — auto-disarm."""

    def __init__(self, name, **kwargs):
        self.name = name
        self.kwargs = kwargs

    def __enter__(self):
        return inject(self.name, **self.kwargs)

    def __exit__(self, *exc):
        clear(self.name)
        return False


def arm_from_env(spec=None):
    """Parse ``PADDLE_TPU_CHAOS`` (or an explicit ``spec``) and arm the
    failpoints it names.  Returns the list of armed names."""
    spec = spec if spec is not None else os.environ.get("PADDLE_TPU_CHAOS", "")
    names = []
    for clause in spec.replace(",", ";").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        name, _, action = clause.partition("=")
        name, action = name.strip(), (action.strip() or "error")
        after, times = 0, None
        # the @N / *N modifiers compose in either order (error*2@3 ==
        # error@3*2): peel them off the tail one at a time
        while True:
            m = re.search(r"([*@])(\d+)$", action)
            if m is None:
                break
            if m.group(1) == "*":
                times = int(m.group(2))
            else:
                after = int(m.group(2))
            action = action[:m.start()]
        kwargs = dict(after=after, times=times)
        if action == "kill":
            kwargs["kill"] = True
        elif action == "delay" or action.startswith("delay:"):
            kwargs["delay"] = float(action.partition(":")[2] or 0.1)
        elif action != "error":
            raise ValueError(
                f"PADDLE_TPU_CHAOS: unknown action {action!r} in "
                f"{clause!r} (want error|kill|delay:SECS)")
        inject(name, **kwargs)
        names.append(name)
    return names


def _load_env():
    global _env_loaded
    with _lock:
        if _env_loaded:
            return
        _env_loaded = True
    if os.environ.get("PADDLE_TPU_CHAOS"):
        arm_from_env()
