"""Crash-consistent checkpoint commits + managed retention/recovery.

The reference pserver checkpoints with a CRC32 over the serialized state
(``go/pserver/service.go:346``) and recovers by validating it on load.
Same discipline here, at directory granularity: a checkpoint is written
to a temp dir, a ``MANIFEST.json`` with per-file SHA-256 checksums is
added, everything is fsynced, and only then is the dir atomically
renamed to its final ``ckpt-<step>`` name.  A crash at ANY point leaves
either the previous committed checkpoint or a ``.tmp-``/renamed-away dir
that :meth:`CheckpointManager.restore_latest` ignores or quarantines —
never a half-written checkpoint that loads garbage.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import shutil

from paddle_tpu.fault import chaos
from paddle_tpu.obs.trace import span as _span

__all__ = ["CheckpointManager", "CorruptCheckpoint", "MANIFEST_NAME",
           "DATAPIPE_STATE_NAME", "LEDGER_STATE_NAME", "write_manifest",
           "verify_checkpoint", "commit_checkpoint"]

MANIFEST_NAME = "MANIFEST.json"
DATAPIPE_STATE_NAME = "datapipe_state.pkl"
LEDGER_STATE_NAME = "ledger_state.pkl"
GOOD_POINTER_NAME = "last_good"


def _datapipe_state_name(rank=None, processes=None):
    """Per-host sidecar name: each trainer's iterator position is
    host-local state (its own input shard), so multi-host runs save one
    file per process; single-host keeps the unsuffixed legacy name.
    ``rank``/``processes`` override the live process coordinates (the
    topology-changed restore derives rank 0's saved name through the
    same formatter that wrote it)."""
    import jax
    if processes is None:
        processes = jax.process_count()
    if rank is None:
        rank = jax.process_index()
    if processes == 1:
        return DATAPIPE_STATE_NAME
    return f"datapipe_state.{rank}.pkl"
MANIFEST_FORMAT = 1
_TMP_PREFIX = ".tmp-"
_QUARANTINE_SUFFIX = ".corrupt"


class CorruptCheckpoint(RuntimeError):
    """A checkpoint failed manifest/checksum verification."""


def _sha256(path, chunk=1 << 20):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(chunk), b""):
            h.update(block)
    return h.hexdigest()


def _walk_files(root):
    for dirpath, _, names in os.walk(root):
        for n in sorted(names):
            p = os.path.join(dirpath, n)
            yield os.path.relpath(p, root), p


def _fsync_dir(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def write_manifest(path, step=None, extra=None):
    """Checksum every file under ``path`` into ``MANIFEST.json``
    (fsynced).  ``extra``: additional top-level manifest entries — the
    shard checkpoint writer records its mesh ``topology`` here, inside
    the same fsynced commit as the checksums."""
    files = {}
    for rel, abs_p in _walk_files(path):
        if rel == MANIFEST_NAME:
            continue
        files[rel] = {"sha256": _sha256(abs_p),
                      "size": os.path.getsize(abs_p)}
    manifest = {"format": MANIFEST_FORMAT, "step": step, "files": files}
    for key, value in (extra or {}).items():
        manifest[key] = value
    mpath = os.path.join(path, MANIFEST_NAME)
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    return manifest


def verify_checkpoint(path):
    """Validate ``path`` against its manifest.

    Returns the manifest dict; raises :class:`CorruptCheckpoint` on a
    missing/unreadable manifest, a missing file, a size mismatch, or a
    checksum mismatch.  (Pre-manifest legacy checkpoints fail here — the
    manager treats only manifested dirs as verifiable and leaves legacy
    dirs to explicit ``load_checkpoint`` calls.)
    """
    mpath = os.path.join(path, MANIFEST_NAME)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CorruptCheckpoint(
            f"{path}: unreadable manifest ({e})") from e
    for rel, want in manifest.get("files", {}).items():
        abs_p = os.path.join(path, rel)
        if not os.path.exists(abs_p):
            raise CorruptCheckpoint(f"{path}: missing file {rel!r}")
        size = os.path.getsize(abs_p)
        if size != want["size"]:
            raise CorruptCheckpoint(
                f"{path}: {rel!r} size {size} != manifest {want['size']}")
        if _sha256(abs_p) != want["sha256"]:
            raise CorruptCheckpoint(f"{path}: {rel!r} checksum mismatch")
    if "topology" in manifest:
        # shard-format checkpoint: the topology record must also be
        # SELF-consistent (every declared shard file checksummed, shard
        # counts matching the saved mesh axis, shapes slicing evenly) —
        # per-file hashes prove bytes, this proves the geometry
        from paddle_tpu.fault import shard_ckpt
        problems = shard_ckpt.validate_topology(manifest)
        if problems:
            raise CorruptCheckpoint(
                f"{path}: inconsistent topology record: "
                + "; ".join(problems))
    return manifest


def commit_checkpoint(tmp_path, final_path, step=None, extra=None):
    """Manifest + fsync + atomic rename: the commit point of a save.

    The ``ckpt.commit`` failpoint sits after the full temp write and
    before the rename — a kill there must leave the previous committed
    checkpoint as the restore target.
    """
    with _span("ckpt.manifest", step=step):
        write_manifest(tmp_path, step=step, extra=extra)
        _fsync_dir(tmp_path)
    chaos.fire("ckpt.commit", step=step)
    with _span("ckpt.rename", step=step):
        displaced = None
        if os.path.exists(final_path):
            # overwriting a committed step (rollback + retrain): displace
            # it by ATOMIC rename rather than rmtree, so a crash in this
            # window still leaves a complete dir on disk (restore falls
            # back to an earlier step; the displaced dir is swept by the
            # next GC)
            displaced = os.path.join(
                os.path.dirname(final_path),
                _TMP_PREFIX + "old-" + os.path.basename(final_path))
            if os.path.exists(displaced):
                shutil.rmtree(displaced)
            os.rename(final_path, displaced)
        os.rename(tmp_path, final_path)
        _fsync_dir(os.path.dirname(final_path) or ".")
        if displaced is not None:
            shutil.rmtree(displaced, ignore_errors=True)
    return final_path


def manager_from_env(executor=None, main_program=None, scope=None):
    """Build a :class:`CheckpointManager` from the ``PADDLE_TPU_CKPT_DIR``
    / ``PADDLE_TPU_CKPT_KEEP`` env vars (exported by ``paddle_tpu train
    --checkpoint-dir``); returns None when unset — training scripts call
    this once and checkpoint/resume only when the operator asked for it."""
    dirname = os.environ.get("PADDLE_TPU_CKPT_DIR")
    if not dirname:
        return None
    keep = int(os.environ.get("PADDLE_TPU_CKPT_KEEP", "5"))
    return CheckpointManager(dirname, keep=keep, executor=executor,
                             main_program=main_program, scope=scope)


class CheckpointManager:
    """Keep-N managed checkpoints over ``io.save_checkpoint`` /
    ``io.load_checkpoint`` with corruption-tolerant recovery.

    ``save(step)`` commits crash-consistently (the io layer routes
    through :func:`commit_checkpoint`) and garbage-collects all but the
    newest ``keep`` committed steps.  ``restore_latest()`` walks
    committed steps newest-first, verifies each manifest, quarantines
    (renames to ``ckpt-N.corrupt``) anything torn or corrupt, and
    restores the newest checkpoint that passes — returning its step, or
    None when nothing is restorable.

    ``datapipe``: an optional ``datapipe`` pipeline (any stage with
    ``state_dict``/``load_state_dict``).  Its iterator position is
    serialized into every checkpoint (same atomic commit as the
    tensors) and restored alongside them, so a killed trainer resumes
    mid-epoch with the exact sample sequence it would have seen.

    ``mesh`` + ``shard_specs``: switch saves to the ELASTIC per-shard
    format (``fault.shard_ckpt``) — each var one file per mesh shard,
    written concurrently, topology recorded in the manifest — and let
    every restore accept a ``mesh=`` that *differs* from the one that
    saved (dp4 → dp2 and back), with the restore plan statically
    verified before any device allocation.  ``save_async`` moves the
    whole write+commit off the step path: the state snapshot is taken
    synchronously (jax arrays are immutable), the serialization, shard
    writes, and atomic commit run on a background thread.
    """

    def __init__(self, dirname, keep=5, executor=None, main_program=None,
                 scope=None, datapipe=None, mesh=None, shard_specs=None,
                 ledger=None):
        self.dirname = str(dirname)
        self.keep = keep
        self.executor = executor
        self.main_program = main_program
        self.scope = scope
        self.datapipe = datapipe
        # optional obs.ledger.RunLedger: its resume cursor rides every
        # checkpoint (same atomic commit) exactly like datapipe state,
        # and every restore rewinds it — no duplicated/missing step rows
        # across kill→restore or sentinel rollback
        self.ledger = ledger
        self.mesh = mesh
        self.shard_specs = dict(shard_specs or {})
        self._async_pool = None       # lazily-built single writer thread
        self._pending = None          # in-flight async save future
        self._committed = set()       # every step saved by this process
        self._verified = set()        # steps read-verified this process
        self._verify_failed = set()   # ...and ones that failed, so a
        # corrupt newer checkpoint is not re-hashed on EVERY save's GC
        # pin scan for as long as it stays in the directory
        self.last_committed_step = None   # most recent save() by THIS
        # process — unlike latest_step() it ignores other runs' leftovers
        # in the directory, so a restarted trainer renumbering from 0
        # still observes its own commits (and reading it costs no I/O)
        self.last_restore_rewound = False   # last restore moved the pipe
        os.makedirs(self.dirname, exist_ok=True)

    # -- introspection -----------------------------------------------------
    def steps(self):
        """Committed (fully renamed) checkpoint steps, ascending."""
        steps = []
        for name in os.listdir(self.dirname):
            if not name.startswith("ckpt-") or name.endswith(
                    _QUARANTINE_SUFFIX):
                continue
            suffix = name[len("ckpt-"):]
            if suffix.isdigit():
                steps.append(int(suffix))
        return sorted(steps)

    def latest_step(self):
        steps = self.steps()
        return steps[-1] if steps else None

    def quarantined(self):
        return sorted(n for n in os.listdir(self.dirname)
                      if n.endswith(_QUARANTINE_SUFFIX))

    def path(self, step):
        return os.path.join(self.dirname, f"ckpt-{int(step)}")

    # -- save --------------------------------------------------------------
    def save(self, step):
        """Commit the current training state as ``ckpt-<step>`` (plus the
        datapipe iterator position, when a pipeline is attached)."""
        self.wait_pending()   # one writer: never overlap an async save
        state, extras = self._snapshot()
        return self._save_committed(step, state, extras)

    def save_async(self, step):
        """Commit ``ckpt-<step>`` OFF the step path: the state is
        snapshotted to HOST now (the next guarded step would otherwise
        donate the device buffers out from under the writer — host
        materialization is the one synchronous cost, the standard async
        checkpoint split; the datapipe position is captured at the same
        point), then serialization, shard writes, and the atomic commit
        run on a background writer thread.  Returns a
        ``concurrent.futures.Future`` resolving to the committed path;
        saves are serialized on one writer thread (single-writer
        directory protocol), and a pending save is drained before any
        synchronous :meth:`save`, any restore, or
        :meth:`wait_pending`."""
        from concurrent.futures import ThreadPoolExecutor
        self.wait_pending()
        state, extras = self._snapshot(materialize=True)
        if self._async_pool is None:
            self._async_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt-async")
        self._pending = self._async_pool.submit(
            self._save_committed, step, state, extras)
        return self._pending

    def wait_pending(self):
        """Block until the in-flight async save (if any) committed;
        re-raises its failure.  Returns the committed path or None."""
        pending, self._pending = self._pending, None
        if pending is None:
            return None
        return pending.result()

    def _snapshot(self, materialize=False):
        import numpy as np
        from paddle_tpu import io
        state = io.snapshot_state(self.main_program, self.scope)
        if materialize:
            # host copies: donation on the next step may delete the
            # device buffers this snapshot references
            state = {n: np.asarray(v) for n, v in state.items()}
        extras = {}
        if self.datapipe is not None:
            extras[_datapipe_state_name()] = pickle.dumps(
                self.datapipe.state_dict(), protocol=4)
        if self.ledger is not None:
            extras[LEDGER_STATE_NAME] = pickle.dumps(
                self.ledger.state_dict(), protocol=4)
        return state, extras or None

    def _save_committed(self, step, state, extras):
        from paddle_tpu import io
        import jax
        if jax.process_index() == 0 and \
                self.last_good_step() == int(step):
            # re-saving the anchor's step displaces the PROMOTED state
            # with one that has not yet earned its clean checks (the
            # restart-renumbering pattern) — drop the pointer before
            # the overwrite; the sentinel re-promotes after N clean
            # checks.  Before, not after: a crash mid-commit must not
            # leave the pointer naming an unpromoted checkpoint.
            try:
                os.remove(self._good_pointer())
            except OSError:
                pass
        with _span("ckpt.save", step=step):
            path = io.save_checkpoint(self.executor, self.dirname,
                                      main_program=self.main_program,
                                      step=step, scope=self.scope,
                                      extras=extras, mesh=self.mesh,
                                      shard_specs=self.shard_specs,
                                      state=state)
            self._committed.add(int(step))
            self._verified.discard(int(step))   # content just changed
            self._verify_failed.discard(int(step))
            self.last_committed_step = int(step)
            with _span("ckpt.gc", step=step):
                self._gc(fresh=int(step))
        return path

    def _gc(self, fresh=None):
        # GC mirrors the commit protocol: only the coordinator host
        # mutates the shared directory (non-coordinators would otherwise
        # sweep .tmp-ckpt-<step> out from under process 0's in-flight
        # manifest/rename)
        import jax
        if jax.process_index() != 0:
            return
        steps = self.steps()
        victims = steps[:-self.keep] if self.keep else []
        if victims:
            # rotation must never leave only corrupt checkpoints behind:
            # pin the newest step that actually verifies (plus the
            # known-good pointer target), regardless of keep-N.  `fresh`
            # names the step this very save just committed — trusted
            # without a re-hash; everything else re-verifies, so
            # externally-torn newer checkpoints cannot shadow the one
            # restorable copy out of existence.
            protect = set()
            if fresh is not None:
                # the step this save just committed: under restart
                # renumbering it can sort BELOW older checkpoints and
                # land in the victim window while "latest" names it
                protect.add(fresh)
            good = self.last_good_step()
            if good is not None:
                protect.add(good)
            pinned = self._newest_verified(steps, fresh=fresh)
            if pinned is not None:
                protect.add(pinned)
            for step in victims:
                if step in protect:
                    continue
                shutil.rmtree(self.path(step), ignore_errors=True)
        # stale temp dirs from crashed saves are torn garbage by
        # definition — sweep them too.  (A checkpoint dir has ONE
        # writer: the trainer committing steps.  Concurrent savers into
        # the same dir already race the final rename and are
        # unsupported; multi-host saves share one coordinator-committed
        # dir, see io.save_checkpoint.)
        for name in os.listdir(self.dirname):
            if name.startswith(_TMP_PREFIX):
                shutil.rmtree(os.path.join(self.dirname, name),
                              ignore_errors=True)

    def _newest_verified(self, steps, fresh=None):
        """Newest committed step that passes manifest verification
        (``fresh`` — the step committed microseconds ago by this very
        save — is trusted without a re-hash).  Returns None when nothing
        verifies.  Cost: verifies newest-first until one passes, so a
        healthy directory pays at most one full verify per GC."""
        for step in reversed(steps):
            if fresh is not None and step == fresh:
                return step
            if step in self._verified:
                # read-verified earlier by this process: don't re-hash
                # the same foreign newest on EVERY save (restart
                # renumbering keeps it newest for a long time).
                # NOTE: _committed is deliberately NOT trusted here —
                # the pin exists to catch post-commit external
                # corruption of exactly those steps.
                return step
            if step in self._verify_failed:
                continue
            try:
                verify_checkpoint(self.path(step))
            except CorruptCheckpoint:
                # remember the failure: a torn multi-GB checkpoint must
                # not add a full re-hash to every subsequent save until
                # it rotates out (save() clears this if rewritten)
                self._verify_failed.add(step)
                continue
            self._verified.add(step)
            return step
        return None

    # -- known-good promotion (the sentinel's rollback anchor) -------------
    def _good_pointer(self):
        return os.path.join(self.dirname, GOOD_POINTER_NAME)

    def last_good_step(self):
        """Step named by the ``last_good`` pointer, or None when the
        pointer is absent/unreadable or its checkpoint dir is gone."""
        try:
            with open(self._good_pointer()) as f:
                step = int(f.read().strip())
        except (OSError, ValueError):
            return None
        if not os.path.isdir(self.path(step)):
            return None
        return step

    def mark_good(self, step=None, verify=True):
        """Promote ``ckpt-<step>`` (default: newest committed) to
        *known-good* — the sentinel's rollback target.  The pointer
        write is atomic (tmp + rename) and ``_gc`` never collects the
        step it names.  ``verify=True`` re-checks the manifest first so
        a torn checkpoint can never become the rollback anchor; raises
        :class:`CorruptCheckpoint` on failure.  Returns the step, or
        None when there is nothing committed."""
        # promoting the step an in-flight save_async is still writing
        # must wait for its commit — otherwise the dir does not exist
        # yet and the promotion silently returns None
        self.wait_pending()
        if step is None:
            step = self.latest_step()
        if step is None:
            return None
        step = int(step)
        if not os.path.isdir(self.path(step)):
            # keep-N rotation got there before the promotion did (the
            # clean-check lag): nothing to anchor — the caller must NOT
            # treat this as forward progress
            return None
        if verify and step not in self._committed:
            # steps this process committed were hashed at write time;
            # anything else (resume after restart) re-verifies — a torn
            # checkpoint must never become the rollback anchor.  (The
            # rollback itself re-verifies in restore_last_good either
            # way.)
            verify_checkpoint(self.path(step))
        import jax
        if jax.process_index() == 0:
            from paddle_tpu.io import atomic_write
            atomic_write(self._good_pointer(), str(step))
        from paddle_tpu import profiler as _profiler
        _profiler.runtime_metrics.inc("ckpt.marked_good")
        return step

    def restore_last_good(self, shardings=None, mesh=None):
        """Restore the last known-good checkpoint (params + datapipe
        position) — the rollback rung of the sentinel's escalation
        ladder.  A corrupt/vanished known-good is quarantined and the
        restore falls back to :meth:`restore_latest` (newest verifiable
        wins).  Returns the restored step or None.

        ``mesh``: the mesh the restoring run trains on — it may DIFFER
        from the mesh that saved (elastic resume): shard-format
        checkpoints are re-sliced onto it after the restore plan
        verifies statically, and the datapipe position is repositioned
        consistently with the new sharding degree (the stride sources
        remap their saved offsets; see ``datapipe.sources.Source``).
        Defaults to the manager's own ``mesh``."""
        from paddle_tpu import io
        self.wait_pending()
        mesh = mesh if mesh is not None else self.mesh
        step = self.last_good_step()
        if step is not None:
            path = self.path(step)
            try:
                verify_checkpoint(path)
            except CorruptCheckpoint:
                self._quarantine(path)
                step = None
        if step is None:
            try:
                os.remove(self._good_pointer())
            except OSError:
                pass
            return self.restore_latest(shardings=shardings, mesh=mesh)
        got = io.load_checkpoint(self.executor, self.dirname,
                                 main_program=self.main_program,
                                 step=step, scope=self.scope,
                                 shardings=shardings, mesh=mesh)
        io._write_latest(self.dirname, step)
        self._restore_datapipe(step)
        self._restore_ledger(step)
        return got

    # -- restore -----------------------------------------------------------
    def verify(self, step):
        return verify_checkpoint(self.path(step))

    def restore(self, step, shardings=None, mesh=None):
        """Verify + restore one specific step (no fallback); ``mesh``
        as in :meth:`restore_last_good` (elastic resume)."""
        from paddle_tpu import io
        self.wait_pending()
        verify_checkpoint(self.path(step))
        got = io.load_checkpoint(self.executor, self.dirname,
                                 main_program=self.main_program, step=step,
                                 scope=self.scope, shardings=shardings,
                                 mesh=mesh if mesh is not None
                                 else self.mesh)
        self._restore_datapipe(step)
        self._restore_ledger(step)
        return got

    def _restore_datapipe(self, step):
        """Load the iterator position saved next to ``ckpt-<step>`` into
        the attached pipeline (no-op without one; a checkpoint written
        before a pipeline existed leaves the pipeline untouched).
        ``last_restore_rewound`` records the outcome so a caller acting
        on a restore (the sentinel rollback) can tell a rewound stream
        from a params-only restore."""
        self.last_restore_rewound = False
        if self.datapipe is None:
            return False
        p = os.path.join(self.path(step), _datapipe_state_name())
        if not os.path.exists(p):
            # topology-changed fallback: a shrink/grow restore may not
            # find this host's own sidecar — rank 0's position (all
            # ranks checkpoint at the same step boundary, so their
            # strides agree) or the unsuffixed single-host legacy name
            # still repositions exactly; the stride sources remap the
            # offsets to the restoring degree on load
            for cand in (_datapipe_state_name(rank=0, processes=1),
                         _datapipe_state_name(rank=0, processes=2)):
                p = os.path.join(self.path(step), cand)
                if os.path.exists(p):
                    break
            else:
                return False
        with open(p, "rb") as f:
            self.datapipe.load_state_dict(pickle.load(f))
        self.last_restore_rewound = True
        return True

    def _restore_ledger(self, step):
        """Rewind the attached run ledger to the cursor saved next to
        ``ckpt-<step>`` (no-op without a ledger or for checkpoints
        written before one was attached — those rows simply stay)."""
        if self.ledger is None:
            return False
        p = os.path.join(self.path(step), LEDGER_STATE_NAME)
        if not os.path.exists(p):
            return False
        with open(p, "rb") as f:
            self.ledger.load_state_dict(pickle.load(f))
        return True

    def restore_latest(self, shardings=None, mesh=None):
        """Restore the newest restorable checkpoint; returns its step or
        None.  Corrupt/partial candidates are quarantined and skipped.
        ``mesh`` as in :meth:`restore_last_good` (elastic resume)."""
        from paddle_tpu import io
        self.wait_pending()
        mesh = mesh if mesh is not None else self.mesh
        for step in reversed(self.steps()):
            path = self.path(step)
            if os.path.exists(os.path.join(path, MANIFEST_NAME)):
                try:
                    verify_checkpoint(path)
                except CorruptCheckpoint:
                    self._quarantine(path)
                    continue
                # checksums passed: a load failure now is environmental
                # (bad shardings arg, FS flake, OOM) — propagate it
                # rather than quarantining a valid checkpoint
            else:
                # pre-manifest legacy checkpoint: unverifiable but very
                # possibly valid — try it, and on failure SKIP without
                # quarantining (the dir stays for explicit
                # load_checkpoint / forensics)
                try:
                    got = io.load_checkpoint(
                        self.executor, self.dirname,
                        main_program=self.main_program, step=step,
                        scope=self.scope, shardings=shardings,
                        mesh=mesh)
                except Exception:
                    continue
                io._write_latest(self.dirname, step)
                self._restore_datapipe(step)
                self._restore_ledger(step)
                return got
            got = io.load_checkpoint(
                self.executor, self.dirname,
                main_program=self.main_program, step=step,
                scope=self.scope, shardings=shardings, mesh=mesh)
            # re-point ``latest`` in case it referenced a checkpoint we
            # just quarantined (load_checkpoint(step=None) keeps working)
            io._write_latest(self.dirname, step)
            self._restore_datapipe(step)
            self._restore_ledger(step)
            return got
        # nothing restorable: drop a ``latest`` pointer that would now
        # name a quarantined dir (load_checkpoint(step=None) then fails
        # with a clear missing-pointer error, not a phantom ckpt path)
        try:
            os.remove(os.path.join(self.dirname, "latest"))
        except OSError:
            pass
        return None

    def _quarantine(self, path):
        target = path + _QUARANTINE_SUFFIX
        if os.path.exists(target):
            shutil.rmtree(target, ignore_errors=True)
        try:
            os.rename(path, target)
        except OSError:
            shutil.rmtree(path, ignore_errors=True)
