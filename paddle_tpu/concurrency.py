"""CSP concurrency DSL: Go, Select, make_channel, channel_send/recv/close.

Reference: ``python/paddle/fluid/concurrency.py`` (451 LoC) over the go/
select/channel ops; execution semantics in ``paddle_tpu/ops/csp_ops.py``
(host-side Python threads, Go-style channels from ``paddle_tpu/channel.py``).
"""

from __future__ import annotations

from paddle_tpu.framework import (default_main_program, default_startup_program,
                                  unique_name)
from paddle_tpu.layer_helper import LayerHelper

__all__ = ["Go", "make_channel", "channel_send", "channel_recv",
           "channel_close", "Select"]


def _external_reads(sub_block):
    produced = set()
    reads = []
    for op in sub_block.ops:
        for n in op.input_arg_names:
            if n and n not in produced and n not in reads:
                reads.append(n)
        for n in op.output_arg_names:
            produced.add(n)
    return [n for n in reads if not sub_block.has_var_local(n)]


class Go:
    """``with fluid.Go():`` — run the body as a goroutine
    (reference ``concurrency.py:27``)."""

    def __init__(self, name=None):
        self.helper = LayerHelper("go", name=name)

    def __enter__(self):
        self._program = self.helper.main_program
        self._parent = self._program.current_block()
        self._sub = self._program.create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        self._program.rollback()
        ext = _external_reads(self._sub)
        self._parent.append_op(
            type="go", inputs={"X": ext}, outputs={},
            attrs={"sub_block": self._sub})
        return True


def make_channel(dtype=None, capacity=0):
    """Create a channel variable (reference ``concurrency.py:279`` —
    channel_create op; capacity 0 = unbuffered rendezvous)."""
    helper = LayerHelper("channel_create")
    main_block = default_main_program().current_block()
    ch = main_block.create_var(name=unique_name("channel"))
    ch.persistable = True
    ch.stop_gradient = True
    main_block.append_op(
        type="channel_create", inputs={}, outputs={"Out": [ch]},
        attrs={"capacity": int(capacity),
               "data_type": str(dtype) if dtype is not None else None})
    return ch


def channel_send(channel, value, is_copy=False):
    """Send ``value`` into ``channel``; returns a bool status variable
    (reference ``concurrency.py:335``)."""
    helper = LayerHelper("channel_send")
    x = value
    if is_copy:
        copied = helper.create_tmp_variable(dtype=value.dtype)
        helper.append_op(type="assign", inputs={"X": [value]},
                         outputs={"Out": [copied]})
        x = copied
    status = helper.create_tmp_variable(dtype="bool", stop_gradient=True)
    helper.append_op(type="channel_send",
                     inputs={"Channel": [channel], "X": [x]},
                     outputs={"Status": [status]})
    return status


def channel_recv(channel, return_value):
    """Receive into ``return_value``; returns (value, status)
    (reference ``concurrency.py:385``)."""
    helper = LayerHelper("channel_recv")
    status = helper.create_tmp_variable(dtype="bool", stop_gradient=True)
    helper.append_op(type="channel_recv",
                     inputs={"Channel": [channel]},
                     outputs={"Out": [return_value], "Status": [status]})
    return return_value, status


def channel_close(channel):
    """Close the channel (reference ``concurrency.py:429``)."""
    helper = LayerHelper("channel_close")
    helper.append_op(type="channel_close", inputs={"Channel": [channel]},
                     outputs={})


class Select:
    """``with fluid.Select() as select:`` + ``select.case(...)`` /
    ``select.default()`` (reference ``concurrency.py:79,193``).

    Each case body is captured into its own sub-block
    (``case_block_<i>`` attr); the select op probes the cases and runs
    exactly one body (csp_ops.select_lower)."""

    DEFAULT, SEND, RECEIVE = 0, 1, 2

    def __init__(self, name=None):
        self.helper = LayerHelper("select", name=name)
        self._cases = []        # serialized "idx,action,ch,val"
        self._case_blocks = {}  # idx -> Block
        self._channels = []
        self._values = []

    def __enter__(self):
        self._program = self.helper.main_program
        self._parent = self._program.current_block()
        return self

    def _case_guard(self, action, channel=None, value=None):
        select = self
        idx = len(select._cases)

        class _CaseGuard:
            def __enter__(self_):
                self_._sub = select._program.create_block()
                return self_

            def __exit__(self_, exc_type, exc_val, exc_tb):
                if exc_type is not None:
                    return False
                select._program.rollback()
                ch_name = channel.name if channel is not None else ""
                val_name = value.name if value is not None else ""
                select._cases.append(f"{idx},{action},{ch_name},{val_name}")
                select._case_blocks[idx] = self_._sub
                if channel is not None:
                    select._channels.append(channel)
                if value is not None:
                    select._values.append(value)
                return True

        return _CaseGuard()

    def case(self, channel_action_fn, channel, value, is_copy=False):
        if channel_action_fn is channel_send:
            x = value
            if is_copy:
                copied = self.helper.create_tmp_variable(dtype=value.dtype)
                self.helper.append_op(type="assign", inputs={"X": [value]},
                                      outputs={"Out": [copied]})
                x = copied
            return self._case_guard(self.SEND, channel, x)
        if channel_action_fn is channel_recv:
            return self._case_guard(self.RECEIVE, channel, value)
        raise ValueError("case() needs channel_send or channel_recv")

    def default(self):
        return self._case_guard(self.DEFAULT)

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        # inputs: channels + sent values + everything the case bodies read
        ext = set()
        for blk in self._case_blocks.values():
            ext.update(_external_reads(blk))
        outs = set()
        for blk in self._case_blocks.values():
            for op in blk.ops:
                outs.update(n for n in op.output_arg_names
                            if self._parent.has_var(n))
        # recv targets are written by the select op itself
        for c in self._cases:
            parts = c.split(",")
            if int(parts[1]) == self.RECEIVE and parts[3]:
                outs.add(parts[3])
        attrs = {"cases": list(self._cases)}
        for idx, blk in self._case_blocks.items():
            attrs[f"case_block_{idx}"] = blk
        self._parent.append_op(
            type="select",
            inputs={"X": sorted(ext),
                    "case_to_execute": []},
            outputs={"Out": sorted(outs)},
            attrs=attrs)
        return True
