"""Control-flow layers (reference ``python/paddle/fluid/layers/control_flow.py``).

``While`` (:608), ``StaticRNN`` (:383), ``DynamicRNN`` (:1354),
``IfElse`` (:1252), ``Switch`` (:1163), plus the array/rank-table helpers.
Sub-blocks are real IR blocks; lowering turns them into
``lax.while_loop`` / ``lax.scan`` / ``lax.cond`` (see
``paddle_tpu/ops/control_flow_ops.py``).
"""

from __future__ import annotations

import contextlib

import numpy as np

from paddle_tpu import framework
from paddle_tpu.framework import Variable, unique_name, default_main_program
from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.layers import tensor as tensor_layers

__all__ = [
    "While", "StaticRNN", "DynamicRNN", "IfElse", "Switch",
    "ConditionalBlock", "lod_rank_table", "max_sequence_len",
    "lod_tensor_to_array", "array_to_lod_tensor", "array_read",
    "array_write", "array_length", "create_array", "increment",
    "less_than", "equal", "not_equal", "greater_than", "greater_equal",
    "less_equal", "shrink_memory", "reorder_lod_tensor_by_rank",
    "is_empty", "Print",
]


# ---------------------------------------------------------------------------
# comparisons / counters (thin wrappers over registered ops)
# ---------------------------------------------------------------------------

def _compare(op_type, x, y, cond=None):
    helper = LayerHelper(op_type)
    if cond is None:
        cond = helper.create_tmp_variable(dtype="bool")
        cond.stop_gradient = True
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [cond]})
    return cond


def less_than(x, y, cond=None):
    return _compare("less_than", x, y, cond)


def less_equal(x, y, cond=None):
    return _compare("less_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _compare("greater_than", x, y, cond)


def greater_equal(x, y, cond=None):
    return _compare("greater_equal", x, y, cond)


def equal(x, y, cond=None):
    return _compare("equal", x, y, cond)


def not_equal(x, y, cond=None):
    return _compare("not_equal", x, y, cond)


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(type="increment", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"step": float(value)})
    return out


def is_empty(x, cond=None):
    helper = LayerHelper("is_empty")
    if cond is None:
        cond = helper.create_tmp_variable(dtype="bool")
        cond.stop_gradient = True
    helper.append_op(type="is_empty", inputs={"X": [x]},
                     outputs={"Out": [cond]})
    return cond


def Print(input, first_n=-1, message=None, summarize=-1, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True,
          print_tensor_lod=True, print_phase="both"):
    helper = LayerHelper("print")
    out = helper.create_tmp_variable(dtype=input.dtype)
    helper.append_op(type="print", inputs={"In": [input]},
                     outputs={"Out": [out]},
                     attrs={"first_n": first_n, "message": message or "",
                            "summarize": summarize,
                            "print_phase": print_phase})
    return out


# ---------------------------------------------------------------------------
# tensor arrays
# ---------------------------------------------------------------------------

def create_array(dtype):
    helper = LayerHelper("create_array")
    return helper.main_program.current_block().create_var(
        name=unique_name("array"), dtype=dtype, type="tensor_array")


def array_write(x, i, array=None, capacity=None):
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype)
    attrs = {}
    if capacity is not None:
        attrs["capacity"] = int(capacity)
    helper.append_op(type="write_to_array",
                     inputs={"X": [x], "I": [i]},
                     outputs={"Out": [array]}, attrs=attrs)
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_tmp_variable(dtype=array.dtype)
    helper.append_op(type="read_from_array",
                     inputs={"X": [array], "I": [i]},
                     outputs={"Out": [out]})
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_tmp_variable(dtype="int32")
    out.stop_gradient = True
    helper.append_op(type="lod_array_length", inputs={"X": [array]},
                     outputs={"Out": [out]})
    return out


# ---------------------------------------------------------------------------
# rank table / lod<->array
# ---------------------------------------------------------------------------

def lod_rank_table(x, level=0):
    helper = LayerHelper("lod_rank_table")
    table = helper.main_program.current_block().create_var(
        name=unique_name("lod_rank_table"), dtype=x.dtype,
        type="lod_rank_table")
    helper.append_op(type="lod_rank_table", inputs={"X": [x]},
                     outputs={"Out": [table]}, attrs={"level": level})
    return table


def max_sequence_len(rank_table):
    helper = LayerHelper("max_sequence_len")
    out = helper.create_tmp_variable(dtype="int32")
    out.stop_gradient = True
    helper.append_op(type="max_sequence_len",
                     inputs={"RankTable": [rank_table]},
                     outputs={"Out": [out]})
    return out


def lod_tensor_to_array(x, table):
    helper = LayerHelper("lod_tensor_to_array")
    array = helper.main_program.current_block().create_var(
        name=unique_name("lod_tensor_to_array"), dtype=x.dtype,
        type="tensor_array")
    helper.append_op(type="lod_tensor_to_array",
                     inputs={"X": [x], "RankTable": [table]},
                     outputs={"Out": [array]})
    return array


def array_to_lod_tensor(x, table):
    helper = LayerHelper("array_to_lod_tensor")
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(type="array_to_lod_tensor",
                     inputs={"X": [x], "RankTable": [table]},
                     outputs={"Out": [out]})
    return out


def shrink_memory(x, i, table):
    helper = LayerHelper("shrink_memory")
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(type="shrink_rnn_memory",
                     inputs={"X": [x], "I": [i], "RankTable": [table]},
                     outputs={"Out": [out]})
    return out


def reorder_lod_tensor_by_rank(x, rank_table):
    helper = LayerHelper("reorder_lod_tensor_by_rank")
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(type="reorder_lod_tensor_by_rank",
                     inputs={"X": [x], "RankTable": [rank_table]},
                     outputs={"Out": [out]})
    return out


# ---------------------------------------------------------------------------
# While
# ---------------------------------------------------------------------------

class While:
    """``while cond: body`` over a sub-block (reference control_flow.py:608).

    Usage::
        cond = layers.less_than(i, n)
        w = While(cond)
        with w.block():
            ...build body...
            layers.increment(i)
            layers.less_than(i, n, cond=cond)   # recompute condition
    """

    def __init__(self, cond, name=None):
        if cond.dtype != "bool":
            raise TypeError("While condition must be a bool variable")
        self.cond_var = cond
        self.helper = LayerHelper("while", name=name)

    @contextlib.contextmanager
    def block(self):
        program = self.helper.main_program
        parent = program.current_block()
        sub = program.create_block()
        yield
        program.rollback()
        written = _written_vars(sub)
        # external data deps must be declared so IR autodiff can route
        # gradients into the loop (the reference computes the same set in
        # while_op.cc by scanning the sub-block)
        ext = _external_reads(sub, parent)
        ext = [n for n in ext if n != self.cond_var.name]
        parent.append_op(
            type="while",
            inputs={"Condition": [self.cond_var], "X": ext},
            outputs={"Out": written, "StepScopes": []},
            attrs={"sub_block": sub})


def _written_vars(block):
    from paddle_tpu.ops.control_flow_ops import _collect_written
    return _collect_written(block)


def _external_reads(block, parent):
    """Names read by ``block`` (recursively) that it does not produce
    itself and that resolve in an ancestor block."""
    produced = set()
    ext = []
    def walk(b):
        for op in b.ops:
            for n in op.input_arg_names:
                if n and n not in produced and not b.has_var_local(n):
                    if parent.has_var(n) and n not in ext:
                        ext.append(n)
            for n in op.output_arg_names:
                produced.add(n)
            for a in op.attrs.values():
                if hasattr(a, "ops"):
                    walk(a)
    walk(block)
    # rank tables are static metadata, not runtime arrays
    out = []
    for n in ext:
        try:
            v = parent.var(n)
        except KeyError:
            continue
        if getattr(v, "type", "") != "lod_rank_table":
            out.append(n)
    return out


# ---------------------------------------------------------------------------
# StaticRNN
# ---------------------------------------------------------------------------

class StaticRNN:
    """Unrolled-over-time RNN builder lowered to ONE ``lax.scan``
    (reference control_flow.py:383; C++ recurrent_op.cc:222).

    Step inputs are [B, T, D] (batch-major); ``step_input`` exposes the
    per-step [B, D] slice inside the block.
    """

    BEFORE_RNN_BLOCK = 0
    IN_RNN_BLOCK = 1
    AFTER_RNN_BLOCK = 2

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self.status = StaticRNN.BEFORE_RNN_BLOCK
        self.sub_block = None
        self.seq_len = None
        self.step_inputs = {}    # outer name -> step var name
        self.memories = []       # {pre, mem, init}
        self.step_outputs = {}   # step var name -> outer name
        self._outer_outputs = []

    @contextlib.contextmanager
    def step(self):
        program = self.helper.main_program
        self.status = StaticRNN.IN_RNN_BLOCK
        self.sub_block = program.create_block()
        yield
        program.rollback()
        self.status = StaticRNN.AFTER_RNN_BLOCK
        self._complete_op()

    def _assert_in_rnn_block(self, method):
        if self.status != StaticRNN.IN_RNN_BLOCK:
            raise ValueError(f"StaticRNN.{method} must be called "
                             f"inside rnn.step()")

    def step_input(self, x):
        self._assert_in_rnn_block("step_input")
        if x.shape is None or len(x.shape) < 2:
            raise ValueError("StaticRNN step input needs [B, T, ...] shape")
        if self.seq_len is None:
            self.seq_len = x.shape[1]
        ipt = self.sub_block.create_var(
            name=unique_name(x.name + "@step"), shape=(x.shape[0],) + tuple(
                x.shape[2:]), dtype=x.dtype)
        self.step_inputs[x.name] = ipt.name
        return ipt

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0, init_batch_dim_idx=0, ref_batch_dim_idx=1):
        self._assert_in_rnn_block("memory")
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory needs init or (shape, batch_ref)")
            parent = self.sub_block.parent_block
            cur = self.helper.main_program._current_block_idx
            self.helper.main_program._current_block_idx = parent.idx
            try:
                init = tensor_layers.fill_constant_batch_size_like(
                    input=batch_ref, shape=[batch_ref.shape[0]] + list(shape),
                    dtype="float32", value=init_value)
            finally:
                self.helper.main_program._current_block_idx = cur
        pre = self.sub_block.create_var(
            name=unique_name(init.name + "@pre"), shape=init.shape,
            dtype=init.dtype)
        self.memories.append({"pre": pre.name, "mem": None,
                              "init": init.name})
        return pre

    def update_memory(self, mem, var):
        self._assert_in_rnn_block("update_memory")
        for m in self.memories:
            if m["pre"] == mem.name:
                m["mem"] = var.name
                return
        raise ValueError("update_memory on an unknown memory")

    def step_output(self, o):
        self._assert_in_rnn_block("step_output")
        outer = self.sub_block.parent_block.create_var(
            name=unique_name(o.name + "@stacked"), dtype=o.dtype,
            shape=None if o.shape is None else
            (o.shape[0], self.seq_len) + tuple(o.shape[1:]))
        self.step_outputs[o.name] = outer.name
        self._outer_outputs.append(outer)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _complete_op(self):
        for m in self.memories:
            if m["mem"] is None:
                raise ValueError("every StaticRNN memory needs "
                                 "update_memory")
        parent = self.sub_block.parent_block
        ext = _external_reads(self.sub_block, parent)
        inner = set(self.step_inputs.values()) | {
            m["pre"] for m in self.memories}
        ext = [n for n in ext
               if n not in inner and n not in self.step_inputs
               and n not in {m["init"] for m in self.memories}]
        parent.append_op(
            type="recurrent",
            inputs={"X": list(self.step_inputs),
                    "InitStates": [m["init"] for m in self.memories],
                    "Params": ext},
            outputs={"Out": list(self.step_outputs.values())},
            attrs={"sub_block": self.sub_block,
                   "step_inputs": dict(self.step_inputs),
                   "memories": [dict(m) for m in self.memories],
                   "step_outputs": dict(self.step_outputs)})

    def __call__(self):
        if self.status != StaticRNN.AFTER_RNN_BLOCK:
            raise ValueError("StaticRNN outputs read after step block")
        if len(self._outer_outputs) == 1:
            return self._outer_outputs[0]
        return self._outer_outputs


# ---------------------------------------------------------------------------
# DynamicRNN — ragged batch over the same scan machinery
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def _block_guard(program, block_idx):
    """Temporarily switch the program's current block (used to emit prep
    ops into the parent block while building a loop body)."""
    prev = program._current_block_idx
    program._current_block_idx = block_idx
    try:
        yield
    finally:
        program._current_block_idx = prev


class DynamicRNN:
    """Variable-length RNN (reference control_flow.py:1354).

    TPU re-design: the ragged LoD input becomes a time-major padded
    TensorArray once (lod_tensor_to_array); the body is ONE lax.while_loop
    over full-batch masked steps; outputs restore to ragged form
    (array_to_lod_tensor).  Usage mirrors the reference::

        drnn = DynamicRNN()
        with drnn.block():
            word = drnn.step_input(sentence)
            prev = drnn.memory(shape=[H])
            hidden = fc(input=[word, prev], size=H, act='relu')
            drnn.update_memory(prev, hidden)
            drnn.output(hidden)
        out = drnn()
    """

    BEFORE_RNN = 0
    IN_RNN = 1
    AFTER_RNN = 2

    def __init__(self, name=None):
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self.status = DynamicRNN.BEFORE_RNN
        self.lod_rank_table = None
        self.max_seq_len = None
        self.step_idx = None
        self.input_array = []
        self.mem_link = []
        self.output_array = []
        self.cond = self.helper.create_tmp_variable(dtype="bool")
        self.cond.stop_gradient = True
        self.while_op = While(self.cond)

    def _parent_block(self):
        program = self.helper.main_program
        return program.block(program.current_block().parent_idx)

    @contextlib.contextmanager
    def block(self):
        if self.status != DynamicRNN.BEFORE_RNN:
            raise ValueError("rnn.block() can only be invoked once")
        self.step_idx = tensor_layers.fill_constant(
            shape=[1], dtype="int32", value=0)
        self.step_idx.stop_gradient = False
        self.status = DynamicRNN.IN_RNN
        with self.while_op.block():
            yield
            increment(x=self.step_idx, value=1.0, in_place=True)
            for new_mem, mem_array in self.mem_link:
                array_write(x=new_mem, i=self.step_idx, array=mem_array)
            less_than(x=self.step_idx, y=self.max_seq_len, cond=self.cond)
        self.status = DynamicRNN.AFTER_RNN

    def step_input(self, x):
        if self.status != DynamicRNN.IN_RNN:
            raise ValueError("step_input() must be inside rnn.block()")
        program = self.helper.main_program
        parent = self._parent_block()
        with _block_guard(program, parent.idx):
            if self.lod_rank_table is None:
                self.lod_rank_table = lod_rank_table(x)
                self.max_seq_len = max_sequence_len(self.lod_rank_table)
                less_than(x=self.step_idx, y=self.max_seq_len,
                          cond=self.cond)
            array = lod_tensor_to_array(x, self.lod_rank_table)
        self.input_array.append(array)
        return array_read(array=array, i=self.step_idx)

    def static_input(self, x):
        if self.lod_rank_table is None:
            raise ValueError("static_input() must follow step_input()")
        program = self.helper.main_program
        with _block_guard(program, self._parent_block().idx):
            return reorder_lod_tensor_by_rank(x, self.lod_rank_table)

    def memory(self, init=None, shape=None, value=0.0, dtype="float32"):
        if self.status != DynamicRNN.IN_RNN:
            raise ValueError("memory() must be inside rnn.block()")
        if self.lod_rank_table is None:
            raise ValueError("memory() must follow step_input()")
        program = self.helper.main_program
        parent = self._parent_block()
        with _block_guard(program, parent.idx):
            if init is not None:
                mem = reorder_lod_tensor_by_rank(init, self.lod_rank_table)
            else:
                first = array_read(array=self.input_array[0],
                                   i=tensor_layers.fill_constant(
                                       shape=[1], dtype="int32", value=0))
                mem = tensor_layers.fill_constant_batch_size_like(
                    input=first, shape=[-1] + list(shape), dtype=dtype,
                    value=value)
            arr = array_write(x=mem, i=tensor_layers.fill_constant(
                shape=[1], dtype="int32", value=0), array=None)
        return array_read(array=arr, i=self.step_idx)

    def update_memory(self, ex_mem, new_mem):
        if self.status != DynamicRNN.IN_RNN:
            raise ValueError("update_memory() must be inside rnn.block()")
        read_op = ex_mem.op
        arr_name = read_op.input("X")[0]
        arr = ex_mem.block.var(arr_name)
        self.mem_link.append((new_mem, arr))

    def output(self, *outputs):
        if self.status != DynamicRNN.IN_RNN:
            raise ValueError("output() must be inside rnn.block()")
        for each in outputs:
            outside_array = array_write(x=each, i=self.step_idx, array=None)
            self.output_array.append(outside_array)

    def __call__(self, *args, **kwargs):
        if self.status != DynamicRNN.AFTER_RNN:
            raise ValueError("rnn() read before block() completes")
        result = [array_to_lod_tensor(a, self.lod_rank_table)
                  for a in self.output_array]
        return result[0] if len(result) == 1 else result


# ---------------------------------------------------------------------------
# ConditionalBlock / IfElse / Switch
# ---------------------------------------------------------------------------

class ConditionalBlock:
    def __init__(self, inputs, is_scalar_condition=False, name=None):
        for each in inputs:
            assert isinstance(each, Variable)
        self.inputs = inputs
        self.is_scalar_condition = is_scalar_condition
        self.helper = LayerHelper("conditional_block", name=name)

    @contextlib.contextmanager
    def block(self):
        program = self.helper.main_program
        parent = program.current_block()
        sub = program.create_block()
        yield
        program.rollback()
        written = _written_vars(sub)
        parent.append_op(
            type="conditional_block",
            inputs={"Cond": [v.name for v in self.inputs]},
            outputs={"Out": written, "Scope": []},
            attrs={"sub_block": sub,
                   "is_scalar_condition": self.is_scalar_condition})


class IfElse:
    """Batch-row routed if/else (reference control_flow.py:1252).

    TPU semantics: both branches compute over the FULL batch; ``true_block``
    rows and ``false_block`` rows are merged per row by the boolean
    condition (merge_lod_tensor = where(mask)).
    """

    OUT_IF_ELSE_BLOCKS = 0
    IN_IF_ELSE_TRUE_BLOCKS = 1
    IN_IF_ELSE_FALSE_BLOCKS = 2

    def __init__(self, cond, name=None):
        self.helper = LayerHelper("ifelse", name=name)
        self.cond = cond
        self.input_table = {}
        self.status = IfElse.OUT_IF_ELSE_BLOCKS
        self.output_table = [[], []]  # [false_outs, true_outs]

    def input(self, x):
        if self.status == IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError("input() must be inside a branch block")
        # both branches see the full batch
        return x

    @contextlib.contextmanager
    def _block(self, status):
        if self.status != IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError("nested IfElse branch")
        self.status = status
        yield
        self.status = IfElse.OUT_IF_ELSE_BLOCKS

    def true_block(self):
        return self._block(IfElse.IN_IF_ELSE_TRUE_BLOCKS)

    def false_block(self):
        return self._block(IfElse.IN_IF_ELSE_FALSE_BLOCKS)

    def output(self, *outs):
        if self.status == IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError("output() must be inside a branch block")
        is_true = self.status == IfElse.IN_IF_ELSE_TRUE_BLOCKS
        self.output_table[1 if is_true else 0].extend(outs)

    def __call__(self):
        if self.status != IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError("IfElse::__call__ outside blocks")
        false_outs, true_outs = self.output_table
        if len(false_outs) != len(true_outs):
            raise ValueError("true/false blocks must emit matching outputs")
        helper = LayerHelper("merge_lod_tensor")
        rets = []
        for t, f in zip(true_outs, false_outs):
            out = helper.create_tmp_variable(dtype=t.dtype)
            helper.append_op(
                type="merge_lod_tensor",
                inputs={"Mask": [self.cond], "InTrue": [t],
                        "InFalse": [f], "X": [t]},
                outputs={"Out": [out]}, attrs={"level": 0})
            rets.append(out)
        return rets[0] if len(rets) == 1 else rets


class Switch:
    """Scalar multi-way branch (reference control_flow.py:1163): a chain of
    scalar conditional_blocks; exactly the first true case runs."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self.inside_scope = False
        self.pre_not_conditions = []

    @contextlib.contextmanager
    def case(self, condition):
        if not self.inside_scope:
            raise ValueError("case() outside with switch.block()")
        from paddle_tpu.layers import nn as nn_layers
        if len(self.pre_not_conditions) == 0:
            cond_block = ConditionalBlock([condition],
                                          is_scalar_condition=True)
            not_cond = _logical_not(condition)
            self.pre_not_conditions.append(not_cond)
        else:
            pre = self.pre_not_conditions[-1]
            new_cond = _logical_and(pre, condition)
            not_cond = _logical_and(pre, _logical_not(condition))
            self.pre_not_conditions.append(not_cond)
            cond_block = ConditionalBlock([new_cond],
                                          is_scalar_condition=True)
        with cond_block.block():
            yield

    @contextlib.contextmanager
    def default(self):
        if len(self.pre_not_conditions) == 0:
            raise ValueError("default() requires at least one case")
        cond_block = ConditionalBlock([self.pre_not_conditions[-1]],
                                      is_scalar_condition=True)
        with cond_block.block():
            yield

    @contextlib.contextmanager
    def block(self):
        self.inside_scope = True
        yield
        self.inside_scope = False


def _logical_not(x):
    helper = LayerHelper("logical_not")
    out = helper.create_tmp_variable(dtype="bool")
    out.stop_gradient = True
    helper.append_op(type="logical_not", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def _logical_and(x, y):
    helper = LayerHelper("logical_and")
    out = helper.create_tmp_variable(dtype="bool")
    out.stop_gradient = True
    helper.append_op(type="logical_and", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out
