"""LR-decay schedules as graph ops (reference
``python/paddle/fluid/layers/learning_rate_scheduler.py``: the schedule is
part of the program, driven by the global step counter)."""

from __future__ import annotations

import math

from paddle_tpu.layers import nn, tensor
from paddle_tpu.layer_helper import LayerHelper

__all__ = ["exponential_decay", "natural_exp_decay", "inverse_time_decay",
           "polynomial_decay", "piecewise_decay", "noam_decay"]


def _decay_step_counter(begin=0):
    global_step = nn.autoincreased_step_counter(
        counter_name="@LR_DECAY_COUNTER@", begin=begin, step=1)
    return nn.cast(global_step, "float32")


def noam_decay(d_model, warmup_steps):
    """lr = d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)
    (reference learning_rate_scheduler.py noam_decay)."""
    global_step = _decay_step_counter(1)
    a = global_step ** -0.5
    b = (warmup_steps ** -1.5) * global_step
    lr_value = (d_model ** -0.5) * nn.elementwise_min(a, b)
    return lr_value


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    global_step = _decay_step_counter()
    div_res = global_step / decay_steps
    if staircase:
        from paddle_tpu.layers import ops
        div_res = ops.floor(div_res)
    return learning_rate * (decay_rate ** div_res)


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    from paddle_tpu.layers import ops
    global_step = _decay_step_counter()
    div_res = global_step / decay_steps
    if staircase:
        div_res = ops.floor(div_res)
    return learning_rate * ops.exp(-1.0 * decay_rate * div_res)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    from paddle_tpu.layers import ops
    global_step = _decay_step_counter()
    div_res = global_step / decay_steps
    if staircase:
        div_res = ops.floor(div_res)
    return learning_rate / (1.0 + decay_rate * div_res)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    from paddle_tpu.layers import ops
    global_step = _decay_step_counter()
    if cycle:
        div_res = ops.ceil(global_step / decay_steps)
        # avoid zero division on step 0: ceil(0/n)=0 -> use max(div,1)
        div_res = nn.elementwise_max(
            div_res, tensor.fill_constant([1], "float32", 1.0))
        decay_steps_var = div_res * float(decay_steps)
        frac = global_step / decay_steps_var
    else:
        capped = nn.elementwise_min(
            global_step,
            tensor.fill_constant([1], "float32", float(decay_steps)))
        frac = capped / float(decay_steps)
    return (learning_rate - end_learning_rate) * \
        ((1.0 - frac) ** power) + end_learning_rate


def piecewise_decay(boundaries, values):
    """Piecewise-constant schedule via nested comparisons."""
    if len(values) - len(boundaries) != 1:
        raise ValueError("len(values) must be len(boundaries) + 1")
    global_step = _decay_step_counter()
    # fold from the right: lr = step < b_i ? values[i] : lr
    lr = tensor.fill_constant([1], "float32", float(values[-1]))
    for i in range(len(boundaries) - 1, -1, -1):
        b = tensor.fill_constant([1], "float32", float(boundaries[i]))
        cond = nn.cast(global_step < b, "float32")
        lr = lr * (1.0 - cond) + cond * float(values[i])
    return lr
