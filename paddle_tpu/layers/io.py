"""Data-input layers (reference ``python/paddle/fluid/layers/io.py``:
``data:28``, ``open_recordio_file:281``, ``open_files:353``, the decorated
readers and ``read_file`` — the distributed Send/ListenAndServ surface
lives in ``paddle_tpu.parallel``)."""

from __future__ import annotations

from paddle_tpu.framework import (default_main_program,
                                  default_startup_program, unique_name)
from paddle_tpu.layer_helper import LayerHelper

__all__ = ["data", "open_recordio_file", "open_files",
           "random_data_generator", "shuffle", "batch", "double_buffer",
           "multi_pass", "parallel", "read_file"]


def data(name, shape, append_batch_size=True, dtype="float32", lod_level=0,
         type=None, stop_gradient=True):
    """Declare an input variable (reference ``layers/io.py:28``)."""
    helper_block = default_main_program().global_block()
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    var = helper_block.create_var(name=name, shape=shape, dtype=dtype,
                                  lod_level=lod_level, is_data=True)
    var.stop_gradient = stop_gradient
    # mirror into startup program for parity with reference behavior
    sb = default_startup_program().global_block()
    if not sb.has_var_local(name):
        sv = sb.create_var(name=name, shape=shape, dtype=dtype,
                           lod_level=lod_level, is_data=True)
        sv.stop_gradient = stop_gradient
    return var


# ---------------------------------------------------------------------------
# reader layers (reference layers/io.py:281-500); reader execution model is
# documented in paddle_tpu/ops/reader_ops.py
# ---------------------------------------------------------------------------

def _monkey_patch_reader_methods(reader_var):
    from paddle_tpu.scope import global_scope

    def _reader():
        # the executor pre-pass pins the runtime reader on the variable
        # (works for any scope); global scope is the fallback
        r = getattr(reader_var, "_reader_runtime", None)
        if r is None:
            r = global_scope().find_var(reader_var.name)
        if r is None:
            raise RuntimeError(
                f"reader {reader_var.name!r} not created yet — run the "
                f"program once (or the startup program) first")
        return r

    reader_var.reset = lambda: _reader().reset()
    reader_var.stop_gradient = True
    reader_var.persistable = True
    return reader_var


def _concat_shapes(shapes):
    shape_concat, ranks = [], []
    for shape in shapes:
        shape_concat.extend(int(d) for d in shape)
        ranks.append(len(shape))
    return shape_concat, ranks


def _create_reader(op_type, attrs, shapes=None, dtypes=None, lod_levels=None,
                   startup=True, underlying=None):
    var_name = unique_name(op_type)
    if shapes is not None:
        shape_concat, ranks = _concat_shapes(shapes)
        attrs = dict(attrs, shape_concat=shape_concat, ranks=ranks,
                     dtypes=[str(d) for d in (dtypes or [])],
                     lod_levels=list(lod_levels or []))
    blocks = []
    if startup:
        blocks.append(default_startup_program().current_block())
    blocks.append(default_main_program().current_block())
    var = None
    for blk in blocks:
        var = blk.create_var(name=var_name)
        var.persistable = True
        inputs = {}
        if underlying is not None:
            if not blk.has_var(underlying.name):
                uv = blk.create_var(name=underlying.name)
                uv.persistable = True
            inputs["UnderlyingReader"] = [underlying.name]
        blk.append_op(type=op_type, inputs=inputs,
                      outputs={"Out": [var_name]}, attrs=attrs)
    # carry the slot metadata on the python Variable (the reference stores
    # it in the reader VarDesc)
    main_var = default_main_program().current_block().var(var_name)
    src = underlying if shapes is None else None
    main_var._reader_shapes = (list(shapes) if shapes is not None
                               else list(src._reader_shapes))
    main_var._reader_dtypes = ([str(d) for d in dtypes] if shapes is not None
                               else list(src._reader_dtypes))
    main_var._reader_lod_levels = (list(lod_levels or [])
                                   if shapes is not None
                                   else list(src._reader_lod_levels))
    main_var._reader_batched = False if shapes is not None \
        else getattr(src, "_reader_batched", False)
    main_var._reader_batch_size = -1 if shapes is not None \
        else getattr(src, "_reader_batch_size", -1)
    return _monkey_patch_reader_methods(main_var)


def open_recordio_file(filename, shapes, lod_levels, dtypes, pass_num=1,
                       for_parallel=False):
    """Reader over one recordio file (reference ``layers/io.py:281``)."""
    reader = _create_reader("create_recordio_file_reader",
                            {"filename": filename},
                            shapes=shapes, dtypes=dtypes,
                            lod_levels=lod_levels)
    if pass_num > 1:
        reader = multi_pass(reader=reader, pass_num=pass_num)
    if for_parallel:
        reader = parallel(reader=reader)
    return reader


def open_files(filenames, shapes, lod_levels, dtypes, thread_num=2,
               buffer_size=None, pass_num=1, for_parallel=False):
    """Multi-file threaded reader (reference ``layers/io.py:353``)."""
    if isinstance(filenames, str):
        filenames = [filenames]
    reader = _create_reader(
        "open_files",
        {"file_names": list(filenames), "thread_num": thread_num,
         "buffer_size": buffer_size or thread_num * 32},
        shapes=shapes, dtypes=dtypes, lod_levels=lod_levels)
    if pass_num > 1:
        reader = multi_pass(reader=reader, pass_num=pass_num)
    if for_parallel:
        reader = parallel(reader=reader)
    return reader


def random_data_generator(low, high, shapes, lod_levels, seed=0):
    """Endless uniform-random reader for tests/benchmarks (reference
    ``create_random_data_generator_op.cc``)."""
    return _create_reader("create_random_data_generator",
                          {"min": float(low), "max": float(high),
                           "seed": int(seed)},
                          shapes=shapes,
                          dtypes=["float32"] * len(shapes),
                          lod_levels=lod_levels)


def shuffle(reader, buffer_size, seed=0):
    return _create_reader("create_shuffle_reader",
                          {"buffer_size": int(buffer_size),
                           "seed": int(seed)},
                          startup=False, underlying=reader)


def batch(reader, batch_size):
    out = _create_reader("create_batch_reader",
                         {"batch_size": int(batch_size)},
                         startup=False, underlying=reader)
    out._reader_batched = True
    out._reader_batch_size = int(batch_size)
    return out


def double_buffer(reader, place=None, capacity=4):
    """Background-thread prefetch + host→device copy overlap; ``capacity``
    sizes the prefetch queue (>= the run_steps step count lets a whole
    device-loop's batches decode during the previous dispatch)."""
    return _create_reader("create_double_buffer_reader",
                          {"capacity": int(capacity)},
                          startup=False, underlying=reader)


def multi_pass(reader, pass_num):
    return _create_reader("create_multi_pass_reader",
                          {"pass_num": int(pass_num)},
                          startup=False, underlying=reader)


def parallel(reader):
    return _create_reader("create_threaded_reader", {},
                          startup=False, underlying=reader)


def read_file(file_obj):
    """Pop one batch from a reader into data variables (reference
    ``layers/io.py:489``; executed by the Executor's reader pre-pass)."""
    helper = LayerHelper("read_file")
    shapes = getattr(file_obj, "_reader_shapes", None)
    dtypes = getattr(file_obj, "_reader_dtypes", None)
    if shapes is None:
        raise ValueError("read_file: argument is not a reader variable")
    batched = getattr(file_obj, "_reader_batched", False)
    bs = getattr(file_obj, "_reader_batch_size", -1)
    out = []
    for shape, dtype in zip(shapes, dtypes):
        v = helper.create_tmp_variable(dtype=dtype, stop_gradient=True)
        v.shape = ((bs,) + tuple(shape)) if batched else tuple(shape)
        v.is_data = True
        out.append(v)
    helper.append_op(type="read", inputs={"Reader": [file_obj]},
                     outputs={"Out": out})
    return out[0] if len(out) == 1 else out
