"""Data-input layers (reference ``python/paddle/fluid/layers/io.py``:
``data:28`` plus the reader/Send/ListenAndServ surface — the distributed
pieces live in ``paddle_tpu.parallel``)."""

from __future__ import annotations

from paddle_tpu.framework import default_main_program, default_startup_program

__all__ = ["data"]


def data(name, shape, append_batch_size=True, dtype="float32", lod_level=0,
         type=None, stop_gradient=True):
    """Declare an input variable (reference ``layers/io.py:28``)."""
    helper_block = default_main_program().global_block()
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    var = helper_block.create_var(name=name, shape=shape, dtype=dtype,
                                  lod_level=lod_level, is_data=True)
    var.stop_gradient = stop_gradient
    # mirror into startup program for parity with reference behavior
    sb = default_startup_program().global_block()
    if not sb.has_var_local(name):
        sv = sb.create_var(name=name, shape=shape, dtype=dtype,
                           lod_level=lod_level, is_data=True)
        sv.stop_gradient = stop_gradient
    return var
