"""Layers DSL (reference ``python/paddle/fluid/layers/``)."""

from paddle_tpu.layers import math_op_patch  # applies Variable overloading
from paddle_tpu.layers.nn import *  # noqa: F401,F403
from paddle_tpu.layers.tensor import *  # noqa: F401,F403
from paddle_tpu.layers.ops import *  # noqa: F401,F403
from paddle_tpu.layers.io import *  # noqa: F401,F403
from paddle_tpu.layers.control_flow import *  # noqa: F401,F403
from paddle_tpu.layers import control_flow  # noqa: F401
from paddle_tpu.layers.sequence import *  # noqa: F401,F403
from paddle_tpu.layers import sequence  # noqa: F401
from paddle_tpu.layers import learning_rate_scheduler  # noqa: F401
from paddle_tpu.layers.learning_rate_scheduler import *  # noqa: F401,F403

from paddle_tpu.layers.detection import *  # noqa: F401,F403
from paddle_tpu.layers import detection  # noqa: F401
from paddle_tpu.layers import nn  # noqa: F401
from paddle_tpu.layers import tensor  # noqa: F401
from paddle_tpu.layers import ops  # noqa: F401
from paddle_tpu.layers import io  # noqa: F401
