"""User-facing NN layers (reference ``python/paddle/fluid/layers/nn.py``,
3,680 LoC; the `fc:83` pattern: create params via LayerHelper, append op(s),
return the output Variable).
"""

from __future__ import annotations

import numpy as np

from paddle_tpu import framework
from paddle_tpu.framework import Variable
from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu import initializer as init_mod
from paddle_tpu.param_attr import ParamAttr

__all__ = [
    "fc", "embedding", "conv2d", "conv2d_transpose", "pool2d", "batch_norm",
    "layer_norm", "dropout", "softmax", "cross_entropy",
    "softmax_with_cross_entropy", "accuracy", "auc", "square_error_cost",
    "chunk_eval", "linear_chain_crf", "crf_decoding",
    "rank_loss", "huber_loss",
    "lrn", "l2_normalize", "matmul", "topk", "relu", "one_hot",
    "sigmoid_cross_entropy_with_logits", "smooth_l1", "label_smooth",
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "elementwise_pow", "clip", "clip_by_norm", "mean", "mul", "scale",
    "reduce_sum", "reduce_mean", "reduce_max", "reduce_min", "reduce_prod",
    "concat", "cast", "split", "reshape", "transpose", "expand", "pad",
    "squeeze", "unsqueeze", "gather", "scatter", "slice", "shape",
    "prelu", "maxout", "nce", "im2sequence", "multiplex", "row_conv",
    "conv_shift", "pool3d", "unpool", "spp", "pool2d_with_index",
    "fused_attention",
    "autoincreased_step_counter", "cos_sim", "dot_product_attention",
    "beam_search", "beam_search_decode", "ring_attention",
    "conv3d", "conv3d_transpose", "warpctc", "ctc_greedy_decoder",
    "image_resize",
]


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       use_mkldnn=False, act=None, is_test=False, name=None):
    """Fully-connected layer (reference ``nn.py:83``)."""
    helper = LayerHelper("fc", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = helper.input_dtype()
    mul_results = []
    for input_var, p_attr in helper.iter_inputs_and_params():
        input_shape = input_var.shape
        param_shape = [
            int(np.prod(input_shape[num_flatten_dims:]))
        ] + [size]
        w = helper.create_parameter(p_attr, shape=param_shape, dtype=dtype)
        tmp = helper.create_tmp_variable(dtype)
        helper.append_op(
            type="mul", inputs={"X": [input_var], "Y": [w]},
            outputs={"Out": [tmp]},
            attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1})
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_tmp_variable(dtype)
        helper.append_op(type="sum", inputs={"X": mul_results},
                         outputs={"Out": [pre_bias]})
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """Embedding lookup (reference ``nn.py`` embedding; the sparse
    SelectedRows grad path maps to XLA scatter-add)."""
    helper = LayerHelper("embedding", param_attr=param_attr)
    w = helper.create_parameter(helper.param_attr, shape=list(size),
                                dtype=dtype, is_bias=False)
    from paddle_tpu.embedding import register_table
    register_table(w.name, vocab=size[0], dim=size[1])
    tmp = helper.create_tmp_variable(dtype)
    padding_idx = -1 if padding_idx is None else \
        (padding_idx if padding_idx >= 0 else size[0] + padding_idx)
    helper.append_op(
        type="lookup_table", inputs={"W": [w], "Ids": [input]},
        outputs={"Out": [tmp]},
        attrs={"is_sparse": is_sparse, "is_distributed": is_distributed,
               "padding_idx": padding_idx})
    return tmp


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           use_mkldnn=False, act=None, name=None):
    """2-D convolution, NCHW (reference ``nn.py`` conv2d)."""
    helper = LayerHelper("conv2d", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    num_channels = input.shape[1]
    groups = groups or 1
    if num_channels % groups != 0:
        raise ValueError("num_channels must be divisible by groups")

    def _pair(x):
        return [x, x] if isinstance(x, int) else list(x)

    filter_size = _pair(filter_size)
    stride = _pair(stride)
    padding = _pair(padding)
    dilation = _pair(dilation)
    filter_shape = [num_filters, num_channels // groups] + filter_size
    std = (2.0 / (filter_size[0] * filter_size[1] * num_channels)) ** 0.5
    w = helper.create_parameter(
        helper.param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=init_mod.Normal(0.0, std))
    pre_bias = helper.create_tmp_variable(dtype)
    op_type = "depthwise_conv2d" if (groups == num_channels and
                                     num_filters == num_channels and
                                     groups > 1) else "conv2d"
    helper.append_op(
        type=op_type, inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={"strides": stride, "paddings": padding,
               "dilations": dilation, "groups": groups})
    pre_act = _append_channel_bias(helper, pre_bias)
    return helper.append_activation(pre_act)


def _append_channel_bias(helper, pre_bias):
    bias_attr = helper.kwargs.get("bias_attr")
    if bias_attr is False:
        return pre_bias
    attr = helper.bias_attr
    num_out = pre_bias.shape[1]
    b = helper.create_parameter(attr, shape=[num_out],
                                dtype=pre_bias.dtype, is_bias=True)
    tmp = helper.create_tmp_variable(pre_bias.dtype)
    helper.append_op(type="elementwise_add",
                     inputs={"X": [pre_bias], "Y": [b]},
                     outputs={"Out": [tmp]}, attrs={"axis": 1})
    return tmp


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    helper = LayerHelper("conv2d_transpose", input=input,
                         param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    dtype = input.dtype
    num_channels = input.shape[1]
    groups = groups or 1

    def _pair(x):
        return [x, x] if isinstance(x, int) else list(x)

    stride, padding, dilation = _pair(stride), _pair(padding), _pair(dilation)
    if filter_size is None:
        if output_size is None:
            raise ValueError("output_size or filter_size required")
        output_size = _pair(output_size)
        h, w = input.shape[2], input.shape[3]
        filter_size = [
            (output_size[0] - (h - 1) * stride[0] + 2 * padding[0] - 1)
            // dilation[0] + 1,
            (output_size[1] - (w - 1) * stride[1] + 2 * padding[1] - 1)
            // dilation[1] + 1]
    else:
        filter_size = _pair(filter_size)
    filter_shape = [num_channels, num_filters // groups] + filter_size
    w = helper.create_parameter(helper.param_attr, shape=filter_shape,
                                dtype=dtype)
    pre_bias = helper.create_tmp_variable(dtype)
    helper.append_op(
        type="conv2d_transpose", inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={"strides": stride, "paddings": padding,
               "dilations": dilation, "groups": groups})
    pre_act = _append_channel_bias(helper, pre_bias)
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, use_mkldnn=False, name=None):
    if pool_type not in ("max", "avg"):
        raise ValueError("pool_type must be 'max' or 'avg'")
    helper = LayerHelper("pool2d", input=input, name=name)

    def _pair(x):
        return [x, x] if isinstance(x, int) else list(x)

    pool_size = _pair(pool_size)
    pool_stride = _pair(pool_stride)
    pool_padding = _pair(pool_padding)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(
        type="pool2d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": pool_size,
               "strides": pool_stride, "paddings": pool_padding,
               "global_pooling": global_pooling, "ceil_mode": ceil_mode})
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, use_mkldnn=False, name=None,
               moving_mean_name=None, moving_variance_name=None,
               do_model_average_for_mean_and_var=False):
    """Batch normalization (reference ``nn.py`` batch_norm)."""
    helper = LayerHelper("batch_norm", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    input_shape = input.shape
    if data_layout == "NCHW":
        channel_num = input_shape[1] if len(input_shape) > 1 else \
            input_shape[0]
    else:
        channel_num = input_shape[-1]
    param_shape = [channel_num]

    scale = helper.create_parameter(
        helper.param_attr, shape=param_shape, dtype=dtype,
        default_initializer=init_mod.Constant(1.0))
    bias = helper.create_parameter(helper.bias_attr, shape=param_shape,
                                   dtype=dtype, is_bias=True)

    mean = helper.create_global_variable(
        name=moving_mean_name or framework.unique_name(
            ".".join([helper.name, "mean"])),
        dtype=dtype, shape=param_shape, persistable=True)
    mean.stop_gradient = True
    helper.set_variable_initializer(mean, init_mod.Constant(0.0))
    variance = helper.create_global_variable(
        name=moving_variance_name or framework.unique_name(
            ".".join([helper.name, "variance"])),
        dtype=dtype, shape=param_shape, persistable=True)
    variance.stop_gradient = True
    helper.set_variable_initializer(variance, init_mod.Constant(1.0))

    saved_mean = helper.create_tmp_variable(dtype, stop_gradient=True)
    saved_variance = helper.create_tmp_variable(dtype, stop_gradient=True)
    out = helper.create_tmp_variable(dtype)

    helper.append_op(
        type="batch_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias],
                "Mean": [mean], "Variance": [variance]},
        outputs={"Y": [out], "MeanOut": [mean], "VarianceOut": [variance],
                 "SavedMean": [saved_mean],
                 "SavedVariance": [saved_variance]},
        attrs={"momentum": momentum, "epsilon": epsilon,
               "is_test": is_test, "data_layout": data_layout})
    return helper.append_activation(out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    helper = LayerHelper("layer_norm", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    input_shape = input.shape
    param_shape = [int(np.prod(input_shape[begin_norm_axis:]))]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(
            helper.param_attr, shape=param_shape, dtype=dtype,
            default_initializer=init_mod.Constant(1.0))
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(helper.bias_attr, shape=param_shape,
                                    dtype=dtype, is_bias=True)
        inputs["Bias"] = [b]
    mean_out = helper.create_tmp_variable(dtype, stop_gradient=True)
    variance_out = helper.create_tmp_variable(dtype, stop_gradient=True)
    out = helper.create_tmp_variable(dtype)
    helper.append_op(
        type="layer_norm", inputs=inputs,
        outputs={"Y": [out], "Mean": [mean_out], "Variance": [variance_out]},
        attrs={"epsilon": epsilon, "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(out)


def dropout(x, dropout_prob, is_test=False, seed=None, name=None):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_tmp_variable(x.dtype)
    mask = helper.create_tmp_variable(x.dtype, stop_gradient=True)
    helper.append_op(
        type="dropout", inputs={"X": [x]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={"dropout_prob": dropout_prob, "is_test": is_test,
               "fix_seed": seed is not None, "seed": seed or 0})
    return out


def softmax(input, use_cudnn=True, name=None, bias=None):
    """Last-axis softmax; ``bias`` optionally fuses an additive mask
    (broadcastable, e.g. [B,1,1,S] padding / [1,1,S,S] causal) into the
    op so attention scores need not materialize in f32 (see
    ops/nn_ops.py softmax_lower)."""
    helper = LayerHelper("softmax", name=name)
    out = helper.create_tmp_variable(input.dtype)
    inputs = {"X": [input]}
    if bias is not None:
        inputs["Bias"] = [bias]
    helper.append_op(type="softmax", inputs=inputs,
                     outputs={"Out": [out]})
    return out


def relu(x, name=None):
    helper = LayerHelper("relu", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="relu", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def cross_entropy(input, label, soft_label=False):
    helper = LayerHelper("cross_entropy")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="cross_entropy",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out], "Out": [out]},
                     attrs={"soft_label": soft_label})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax_out = helper.create_tmp_variable(logits.dtype)
    loss = helper.create_tmp_variable(logits.dtype)
    helper.append_op(type="softmax_with_cross_entropy",
                     inputs={"Logits": [logits], "Label": [label]},
                     outputs={"Softmax": [softmax_out], "Loss": [loss]},
                     attrs={"soft_label": soft_label})
    return loss


def sigmoid_cross_entropy_with_logits(x, label, name=None):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="sigmoid_cross_entropy_with_logits",
                     inputs={"X": [x], "Label": [label]},
                     outputs={"Out": [out]})
    return out


def square_error_cost(input, label):
    """(input - label)^2 elementwise (reference layers)."""
    helper = LayerHelper("square_error_cost")
    minus_out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="elementwise_sub",
                     inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [minus_out]})
    square_out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="square", inputs={"X": [minus_out]},
                     outputs={"Out": [square_out]})
    return square_out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss")
    diff = helper.create_tmp_variable(x.dtype)
    loss = helper.create_tmp_variable(x.dtype)
    inputs = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight]
    helper.append_op(type="smooth_l1_loss", inputs=inputs,
                     outputs={"Diff": [diff], "Out": [loss]},
                     attrs={"sigma": sigma or 1.0})
    return loss


def accuracy(input, label, k=1, correct=None, total=None):
    """Top-k accuracy (reference ``layers/metric.py`` accuracy)."""
    helper = LayerHelper("accuracy")
    topk_out = helper.create_tmp_variable(dtype=input.dtype)
    topk_indices = helper.create_tmp_variable(dtype="int64")
    helper.append_op(type="top_k", inputs={"X": [input]},
                     outputs={"Out": [topk_out], "Indices": [topk_indices]},
                     attrs={"k": k})
    acc_out = helper.create_tmp_variable(dtype="float32")
    correct = correct or helper.create_tmp_variable(dtype="int64")
    total = total or helper.create_tmp_variable(dtype="int64")
    helper.append_op(
        type="accuracy",
        inputs={"Out": [topk_out], "Indices": [topk_indices],
                "Label": [label]},
        outputs={"Accuracy": [acc_out], "Correct": [correct],
                 "Total": [total]})
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=200):
    helper = LayerHelper("auc")
    auc_out = helper.create_tmp_variable(dtype="float32")
    stat_pos = helper.create_global_variable(
        persistable=True, dtype="int64", shape=[num_thresholds + 1])
    helper.set_variable_initializer(stat_pos, init_mod.Constant(0.0))
    stat_neg = helper.create_global_variable(
        persistable=True, dtype="int64", shape=[num_thresholds + 1])
    helper.set_variable_initializer(stat_neg, init_mod.Constant(0.0))
    helper.append_op(
        type="auc",
        inputs={"Predict": [input], "Label": [label],
                "StatPos": [stat_pos], "StatNeg": [stat_neg]},
        outputs={"AUC": [auc_out], "StatPosOut": [stat_pos],
                 "StatNegOut": [stat_neg]},
        attrs={"curve": curve, "num_thresholds": num_thresholds})
    return auc_out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_tmp_variable(input.dtype)
    mid = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    helper.append_op(type="lrn", inputs={"X": [input]},
                     outputs={"Out": [out], "MidOut": [mid]},
                     attrs={"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_tmp_variable(x.dtype)
    norm = helper.create_tmp_variable(x.dtype, stop_gradient=True)
    helper.append_op(type="norm", inputs={"X": [x]},
                     outputs={"Out": [out], "Norm": [norm]},
                     attrs={"axis": 1 if axis is None else axis,
                            "epsilon": epsilon})
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="matmul", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"transpose_X": transpose_x,
                            "transpose_Y": transpose_y, "alpha": alpha})
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_tmp_variable(input.dtype)
    indices = helper.create_tmp_variable("int64")
    helper.append_op(type="top_k", inputs={"X": [input]},
                     outputs={"Out": [values], "Indices": [indices]},
                     attrs={"k": k})
    return values, indices


def one_hot(input, depth):
    helper = LayerHelper("one_hot")
    out = helper.create_tmp_variable("float32")
    helper.append_op(type="one_hot", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"depth": depth})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    helper = LayerHelper("label_smooth", name=name)
    inputs = {"X": [label]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist]
    out = helper.create_tmp_variable(dtype)
    helper.append_op(type="label_smooth", inputs=inputs,
                     outputs={"Out": [out]}, attrs={"epsilon": epsilon})
    return out


def prelu(x, mode="all", param_attr=None, name=None):
    helper = LayerHelper("prelu", param_attr=param_attr, name=name)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [x.shape[1]]
    else:
        alpha_shape = list(x.shape[1:])
    alpha = helper.create_parameter(
        helper.param_attr, shape=alpha_shape, dtype=x.dtype,
        default_initializer=init_mod.Constant(0.25))
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="prelu", inputs={"X": [x], "Alpha": [alpha]},
                     outputs={"Out": [out]}, attrs={"mode": mode})
    return out


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="maxout", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"groups": groups})
    return out


def multiplex(inputs, index):
    helper = LayerHelper("multiplex")
    out = helper.create_tmp_variable(inputs[0].dtype)
    helper.append_op(type="multiplex",
                     inputs={"X": inputs, "Ids": [index]},
                     outputs={"Out": [out]})
    return out


def cos_sim(X, Y):
    helper = LayerHelper("cos_sim")
    out = helper.create_tmp_variable(X.dtype)
    xnorm = helper.create_tmp_variable(X.dtype, stop_gradient=True)
    ynorm = helper.create_tmp_variable(X.dtype, stop_gradient=True)
    helper.append_op(type="cos_sim", inputs={"X": [X], "Y": [Y]},
                     outputs={"Out": [out], "XNorm": [xnorm],
                              "YNorm": [ynorm]})
    return out


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None):
    """Noise-contrastive estimation loss (reference ``nn.py`` nce over
    ``operators/nce_op.h``); returns per-example cost / (num_neg + 1)."""
    helper = LayerHelper("nce", param_attr=param_attr, bias_attr=bias_attr)
    dim = input.shape[1]
    w = helper.create_parameter(attr=helper.param_attr,
                                shape=[num_total_classes, dim],
                                is_bias=False, dtype=input.dtype)
    bias_attr_ = helper.bias_attr
    b = None if bias_attr_ is None else helper.create_parameter(
        attr=bias_attr_, shape=[num_total_classes, 1], is_bias=True,
        dtype=input.dtype)
    cost = helper.create_tmp_variable(dtype=input.dtype)
    sample_logits = helper.create_tmp_variable(dtype=input.dtype,
                                               stop_gradient=True)
    sample_labels = helper.create_tmp_variable(dtype="int64",
                                               stop_gradient=True)
    num_neg_samples = 10 if num_neg_samples is None else int(num_neg_samples)
    inputs = {"Input": input, "Label": label, "Weight": w}
    if b is not None:
        inputs["Bias"] = b
    if sample_weight is not None:
        inputs["SampleWeight"] = sample_weight
    helper.append_op(
        type="nce", inputs=inputs,
        outputs={"Cost": cost, "SampleLogits": sample_logits,
                 "SampleLabels": sample_labels},
        attrs={"num_total_classes": int(num_total_classes),
               "num_neg_samples": num_neg_samples})
    return cost / (num_neg_samples + 1)


def rank_loss(left, right, label, name=None):
    """Pairwise rank loss (reference ``rank_loss_op.cc``)."""
    helper = LayerHelper("rank_loss", name=name)
    out = helper.create_tmp_variable(left.dtype)
    helper.append_op(type="rank_loss",
                     inputs={"Left": [left], "Right": [right],
                             "Label": [label]},
                     outputs={"Out": [out]})
    return out


def huber_loss(input, label, delta=1.0, name=None):
    """Huber regression loss (reference ``huber_loss_op.cc``)."""
    helper = LayerHelper("huber_loss", name=name)
    out = helper.create_tmp_variable(input.dtype)
    residual = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    helper.append_op(type="huber_loss",
                     inputs={"X": [input], "Y": [label]},
                     outputs={"Out": [out], "Residual": [residual]},
                     attrs={"delta": delta})
    return out


def linear_chain_crf(input, label, param_attr=None):
    """Linear-chain CRF negative log-likelihood (reference ``nn.py``
    linear_chain_crf over ``linear_chain_crf_op.cc``); creates the
    [K+2, K] transition parameter (rows 0/1 = start/stop)."""
    helper = LayerHelper("linear_chain_crf", param_attr=param_attr)
    size = input.shape[-1]
    transition = helper.create_parameter(
        attr=helper.param_attr, shape=[size + 2, size], dtype=input.dtype)
    log_likelihood = helper.create_tmp_variable(dtype=input.dtype)
    alpha = helper.create_tmp_variable(dtype=input.dtype,
                                       stop_gradient=True)
    emission_exps = helper.create_tmp_variable(dtype=input.dtype,
                                               stop_gradient=True)
    transition_exps = helper.create_tmp_variable(dtype=input.dtype,
                                                 stop_gradient=True)
    helper.append_op(
        type="linear_chain_crf",
        inputs={"Emission": [input], "Transition": [transition],
                "Label": [label]},
        outputs={"LogLikelihood": [log_likelihood], "Alpha": [alpha],
                 "EmissionExps": [emission_exps],
                 "TransitionExps": [transition_exps]})
    return log_likelihood


def crf_decoding(input, param_attr=None, label=None):
    """Viterbi decode with the CRF transition parameter (reference
    ``nn.py`` crf_decoding over ``crf_decoding_op.cc``)."""
    helper = LayerHelper("crf_decoding", param_attr=param_attr)
    transition = helper.param_attr
    # reuse the trained transition parameter by name
    from paddle_tpu.framework import default_main_program
    block = default_main_program().global_block()
    trans_var = block.var(transition.name) if transition and \
        transition.name and block.has_var(transition.name) else None
    if trans_var is None:
        size = input.shape[-1]
        trans_var = helper.create_parameter(
            attr=helper.param_attr, shape=[size + 2, size],
            dtype=input.dtype)
    viterbi_path = helper.create_tmp_variable(dtype="int32",
                                              stop_gradient=True)
    inputs = {"Emission": [input], "Transition": [trans_var]}
    if label is not None:
        inputs["Label"] = [label]
    helper.append_op(type="crf_decoding", inputs=inputs,
                     outputs={"ViterbiPath": [viterbi_path]})
    return viterbi_path


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None):
    """NER chunk precision/recall/F1 (reference ``nn.py:1049`` over
    ``chunk_eval_op.h``); returns (precision, recall, f1, #infer, #label,
    #correct)."""
    helper = LayerHelper("chunk_eval")
    precision = helper.create_tmp_variable(dtype="float32")
    recall = helper.create_tmp_variable(dtype="float32")
    f1_score = helper.create_tmp_variable(dtype="float32")
    num_infer_chunks = helper.create_tmp_variable(dtype="int64")
    num_label_chunks = helper.create_tmp_variable(dtype="int64")
    num_correct_chunks = helper.create_tmp_variable(dtype="int64")
    helper.append_op(
        type="chunk_eval",
        inputs={"Inference": [input], "Label": [label]},
        outputs={"Precision": [precision], "Recall": [recall],
                 "F1-Score": [f1_score],
                 "NumInferChunks": [num_infer_chunks],
                 "NumLabelChunks": [num_label_chunks],
                 "NumCorrectChunks": [num_correct_chunks]},
        attrs={"num_chunk_types": num_chunk_types,
               "chunk_scheme": chunk_scheme,
               "excluded_chunk_types": excluded_chunk_types or []})
    return (precision, recall, f1_score, num_infer_chunks, num_label_chunks,
            num_correct_chunks)


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    """Extract image patches as a LoD sequence (reference ``nn.py``
    im2sequence over ``im2sequence_op.h``)."""
    def _quad(v):
        if isinstance(v, int):
            return [v, v, v, v]
        if len(v) == 2:
            return [v[0], v[1], v[0], v[1]]
        return list(v)

    def _pair(v):
        return [v, v] if isinstance(v, int) else list(v)

    helper = LayerHelper("im2sequence", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="im2sequence", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"kernels": _pair(filter_size),
                            "strides": _pair(stride),
                            "paddings": _quad(padding)})
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (reference ``nn.py`` row_conv over
    ``row_conv_op.cc``; DeepSpeech2-style streaming context)."""
    helper = LayerHelper("row_conv", param_attr=param_attr, act=act)
    dtype = input.dtype
    filter_shape = [future_context_size + 1, input.shape[1]]
    filter_param = helper.create_parameter(helper.param_attr,
                                           shape=filter_shape, dtype=dtype)
    out = helper.create_tmp_variable(dtype)
    helper.append_op(type="row_conv",
                     inputs={"X": [input], "Filter": [filter_param]},
                     outputs={"Out": [out]})
    return helper.append_activation(out)


def pool2d_with_index(input, pool_size, pool_stride=1, pool_padding=0,
                      global_pooling=False, name=None):
    """Max pooling that also returns the argmax mask (reference
    ``pool_with_index_op.cc``); the mask feeds ``unpool``."""
    def _pair(v):
        return [v, v] if isinstance(v, int) else list(v)

    helper = LayerHelper("pool2d_with_index", name=name)
    out = helper.create_tmp_variable(input.dtype)
    mask = helper.create_tmp_variable("int64", stop_gradient=True)
    helper.append_op(type="pool2d_with_index", inputs={"X": [input]},
                     outputs={"Out": [out], "Mask": [mask]},
                     attrs={"ksize": _pair(pool_size),
                            "strides": _pair(pool_stride),
                            "paddings": _pair(pool_padding),
                            "global_pooling": global_pooling})
    return out, mask


def conv_shift(x, y, name=None):
    """Circular correlation (reference ``conv_shift_op.cc``; NTM
    addressing)."""
    helper = LayerHelper("conv_shift", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="conv_shift", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def pool3d(input, pool_size, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, ceil_mode=False, name=None):
    """3-D pooling over NCDHW input (reference ``pool_op.cc`` pool3d)."""
    def _triple(v):
        return [v, v, v] if isinstance(v, int) else list(v)

    helper = LayerHelper("pool3d", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="pool3d", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"pooling_type": pool_type,
                            "ksize": _triple(pool_size),
                            "strides": _triple(pool_stride),
                            "paddings": _triple(pool_padding),
                            "global_pooling": global_pooling,
                            "ceil_mode": ceil_mode})
    return out


def unpool(input, indices, unpool_size, unpool_stride=None,
           unpool_padding=0, name=None):
    """Max unpooling from pool_with_index indices (reference
    ``unpool_op.cc``)."""
    def _pair(v):
        return [v, v] if isinstance(v, int) else list(v)

    helper = LayerHelper("unpool", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="unpool",
                     inputs={"X": [input], "Indices": [indices]},
                     outputs={"Out": [out]},
                     attrs={"ksize": _pair(unpool_size),
                            "strides": _pair(unpool_stride or unpool_size),
                            "paddings": _pair(unpool_padding)})
    return out


def spp(input, pyramid_height, pool_type="max", name=None):
    """Spatial pyramid pooling (reference ``spp_op.h``)."""
    helper = LayerHelper("spp", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="spp", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"pyramid_height": pyramid_height,
                            "pooling_type": pool_type})
    return out


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, name=None):
    """One beam-search pruning step over dense ``[B, K]`` beams (reference
    ``layers`` beam_search -> ``beam_search_op.cc``; see
    ops/beam_search_ops.py for the static-shape re-design).

    Returns (selected_ids, selected_scores, parent_idx), each [B, K].
    ``level`` is accepted for API parity (the LoD level has no dense
    equivalent).
    """
    helper = LayerHelper("beam_search", name=name)
    sel_ids = helper.create_tmp_variable("int64")
    sel_scores = helper.create_tmp_variable("float32")
    parent = helper.create_tmp_variable("int64")
    helper.append_op(
        type="beam_search",
        inputs={"pre_ids": [pre_ids], "pre_scores": [pre_scores],
                "ids": [ids], "scores": [scores]},
        outputs={"selected_ids": [sel_ids],
                 "selected_scores": [sel_scores],
                 "parent_idx": [parent]},
        attrs={"beam_size": int(beam_size), "end_id": int(end_id),
               "level": int(level)})
    return sel_ids, sel_scores, parent


def beam_search_decode(ids, parent_idx, scores, max_len=None, name=None):
    """Backtrack per-step (ids, parent) TensorArrays into full hypotheses
    (reference ``beam_search_decode_op.cc``).  Returns
    (sentence_ids [B, K, T], sentence_scores [B, K])."""
    helper = LayerHelper("beam_search_decode", name=name)
    sent_ids = helper.create_tmp_variable("int64")
    sent_scores = helper.create_tmp_variable("float32")
    attrs = {}
    if max_len is not None:
        attrs["max_len"] = int(max_len)
    helper.append_op(
        type="beam_search_decode",
        inputs={"Ids": [ids], "ParentIdx": [parent_idx],
                "Scores": [scores]},
        outputs={"SentenceIds": [sent_ids],
                 "SentenceScores": [sent_scores]},
        attrs=attrs)
    return sent_ids, sent_scores


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Global step counter variable (reference nn.py)."""
    helper = LayerHelper("global_step_counter")
    counter_name = counter_name or "@STEP_COUNTER@"
    counter = helper.create_global_variable(
        name=counter_name, dtype="int64", shape=[1], persistable=True)
    counter.stop_gradient = True
    helper.set_variable_initializer(
        counter, init_mod.Constant(float(begin - step)))
    helper.append_op(type="increment", inputs={"X": [counter]},
                     outputs={"Out": [counter]}, attrs={"step": float(step)})
    return counter


def dot_product_attention(querys, keys, values):
    """Plain dot-product attention over dense tensors (reference
    ``nets.py`` scaled_dot_product_attention is the richer variant)."""
    product = matmul(x=querys, y=keys, transpose_y=True)
    weights = softmax(product)
    return matmul(weights, values), weights


# re-exported thin wrappers built on ops --------------------------------------

def _unary_layer(op_type):
    def layer(x, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_tmp_variable(x.dtype)
        helper.append_op(type=op_type, inputs={"X": [x]},
                         outputs={"Out": [out]})
        return out
    layer.__name__ = op_type
    return layer


def _binary_layer(op_type):
    def layer(x, y, axis=-1, act=None, name=None):
        helper = LayerHelper(op_type, act=act, name=name)
        out = helper.create_tmp_variable(x.dtype)
        helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                         outputs={"Out": [out]}, attrs={"axis": axis})
        return helper.append_activation(out)
    layer.__name__ = op_type
    return layer


elementwise_add = _binary_layer("elementwise_add")
elementwise_sub = _binary_layer("elementwise_sub")
elementwise_mul = _binary_layer("elementwise_mul")
elementwise_div = _binary_layer("elementwise_div")
elementwise_max = _binary_layer("elementwise_max")
elementwise_min = _binary_layer("elementwise_min")
elementwise_pow = _binary_layer("elementwise_pow")


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="mean", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="mul", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"x_num_col_dims": x_num_col_dims,
                            "y_num_col_dims": y_num_col_dims})
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None,
          name=None):
    helper = LayerHelper("scale", act=act, name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="scale", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"scale": float(scale), "bias": float(bias),
                            "bias_after_scale": bias_after_scale})
    return helper.append_activation(out)


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="clip", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"min": min, "max": max})
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="clip_by_norm", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"max_norm": max_norm})
    return out


def _reduce_layer(op_type):
    def layer(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_tmp_variable(input.dtype)
        if dim is None:
            attrs = {"dim": [0], "keep_dim": keep_dim, "reduce_all": True}
        else:
            attrs = {"dim": dim if isinstance(dim, (list, tuple)) else [dim],
                     "keep_dim": keep_dim, "reduce_all": False}
        helper.append_op(type=op_type, inputs={"X": [input]},
                         outputs={"Out": [out]}, attrs=attrs)
        return out
    layer.__name__ = op_type
    return layer


reduce_sum = _reduce_layer("reduce_sum")
reduce_mean = _reduce_layer("reduce_mean")
reduce_max = _reduce_layer("reduce_max")
reduce_min = _reduce_layer("reduce_min")
reduce_prod = _reduce_layer("reduce_prod")


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_tmp_variable(input[0].dtype)
    helper.append_op(type="concat", inputs={"X": input},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def cast(x, dtype):
    helper = LayerHelper("cast")
    out = helper.create_tmp_variable(dtype)
    helper.append_op(type="cast", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"in_dtype": x.dtype, "out_dtype":
                            framework.convert_np_dtype(dtype)})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    dim = dim if dim >= 0 else dim + len(input.shape)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = []
        n_out = num
    else:
        num = 0
        sections = list(num_or_sections)
        n_out = len(sections)
    outs = [helper.create_tmp_variable(input.dtype) for _ in range(n_out)]
    helper.append_op(type="split", inputs={"X": [input]},
                     outputs={"Out": outs},
                     attrs={"num": num, "sections": sections, "axis": dim})
    return outs


def reshape(x, shape, actual_shape=None, act=None, inplace=True, name=None):
    helper = LayerHelper("reshape", act=act, name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="reshape", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"shape": list(shape)})
    return helper.append_activation(out)


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="transpose", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"axis": list(perm)})
    return out


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="expand", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"expand_times": list(expand_times)})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="pad", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"paddings": list(paddings),
                            "pad_value": float(pad_value)})
    return out


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="squeeze", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"axes": list(axes)})
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="unsqueeze", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"axes": list(axes)})
    return out


def gather(input, index):
    helper = LayerHelper("gather")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="gather", inputs={"X": [input], "Index": [index]},
                     outputs={"Out": [out]})
    return out


def scatter(input, index, updates, name=None):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(
        type="scatter",
        inputs={"X": [input], "Ids": [index], "Updates": [updates]},
        outputs={"Out": [out]})
    return out


def slice(input, axes, starts, ends, name=None):
    helper = LayerHelper("slice", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="slice", inputs={"Input": [input]},
                     outputs={"Out": [out]},
                     attrs={"axes": list(axes), "starts": list(starts),
                            "ends": list(ends)})
    return out


def shape(input, name=None):
    helper = LayerHelper("shape", name=name)
    out = helper.create_tmp_variable("int64")
    helper.append_op(type="shape", inputs={"Input": [input]},
                     outputs={"Out": [out]})
    return out


def ring_attention(q, k, v, causal=False, scale=None, seq_axis="seq",
                   name=None):
    """Sequence-parallel exact attention over [B, H, S, D] with S sharded
    over the mesh's ``seq_axis`` (ops/attention_ops.py ring_attention;
    single-device fallback when no sequence axis is populated)."""
    helper = LayerHelper("ring_attention", name=name)
    out = helper.create_tmp_variable(q.dtype)
    helper.append_op(type="ring_attention",
                     inputs={"Q": [q], "K": [k], "V": [v]},
                     outputs={"Out": [out]},
                     attrs={"causal": causal, "scale": scale,
                            "seq_axis": seq_axis})
    return out


def fused_attention(q, k, v, k_mask=None, causal=False, scale=1.0,
                    use_flash=True, name=None):
    """Fused scaled-dot-product attention over [B, H, S, D] tensors
    (Pallas flash kernel on TPU; see ops/attention_ops.py).  ``k_mask`` is
    [B, S_k] with 1 = attend."""
    helper = LayerHelper("scaled_dot_product_attention", name=name)
    out = helper.create_tmp_variable(q.dtype)
    # Lse: softmax log-normalizer residual saved by the flash kernel so the
    # backward op reuses it instead of re-running the forward
    lse = helper.create_tmp_variable("float32")
    lse.stop_gradient = True
    inputs = {"Q": [q], "K": [k], "V": [v]}
    if k_mask is not None:
        inputs["KMask"] = [k_mask]
    helper.append_op(type="scaled_dot_product_attention", inputs=inputs,
                     outputs={"Out": [out], "Lse": [lse]},
                     attrs={"causal": causal, "scale": float(scale),
                            "use_flash": use_flash})
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=None, param_attr=None, bias_attr=None,
           act=None, name=None):
    """3-D convolution, NCDHW (reference ``nn.py`` conv3d over
    ``conv3d_op``; same MXU lowering family as conv2d)."""
    helper = LayerHelper("conv3d", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    num_channels = input.shape[1]
    groups = groups or 1

    def _triple(x):
        return [x, x, x] if isinstance(x, int) else list(x)

    filter_size = _triple(filter_size)
    stride = _triple(stride)
    padding = _triple(padding)
    dilation = _triple(dilation)
    filter_shape = [num_filters, num_channels // groups] + filter_size
    fan_in = num_channels * int(np.prod(filter_size))
    w = helper.create_parameter(
        helper.param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=init_mod.Normal(0.0, (2.0 / fan_in) ** 0.5))
    pre_bias = helper.create_tmp_variable(dtype)
    helper.append_op(
        type="conv3d", inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={"strides": stride, "paddings": padding,
               "dilations": dilation, "groups": groups})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    """Transposed 3-D convolution, NCDHW (reference ``nn.py``
    conv3d_transpose over ``conv_transpose_op.cc:314``); filter layout
    (C_in, C_out/groups, kd, kh, kw) like conv2d_transpose."""
    helper = LayerHelper("conv3d_transpose", input=input,
                         param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    dtype = input.dtype
    num_channels = input.shape[1]
    groups = groups or 1

    def _triple(x):
        return [x, x, x] if isinstance(x, int) else list(x)

    stride, padding, dilation = (_triple(stride), _triple(padding),
                                 _triple(dilation))
    if filter_size is None:
        if output_size is None:
            raise ValueError("output_size or filter_size required")
        output_size = _triple(output_size)
        filter_size = [
            (output_size[i] - (input.shape[2 + i] - 1) * stride[i]
             + 2 * padding[i] - 1) // dilation[i] + 1
            for i in range(3)]
    else:
        filter_size = _triple(filter_size)
    filter_shape = [num_channels, num_filters // groups] + filter_size
    w = helper.create_parameter(helper.param_attr, shape=filter_shape,
                                dtype=dtype)
    pre_bias = helper.create_tmp_variable(dtype)
    helper.append_op(
        type="conv3d_transpose", inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={"strides": stride, "paddings": padding,
               "dilations": dilation, "groups": groups})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def warpctc(input, label, blank=0, norm_by_times=False):
    """CTC loss over ragged logits/labels (reference ``nn.py`` warpctc
    over ``warpctc_op.cc``); returns [B, 1] per-sequence losses."""
    helper = LayerHelper("warpctc")
    loss = helper.create_tmp_variable(dtype=input.dtype)
    helper.append_op(
        type="warpctc",
        inputs={"Logits": [input], "Label": [label]},
        outputs={"Loss": [loss]},
        attrs={"blank": blank, "norm_by_times": norm_by_times})
    return loss


def ctc_greedy_decoder(input, blank=0):
    """Greedy CTC decode: per-row argmax, merge repeats, drop blanks
    (reference ``nn.py`` ctc_greedy_decoder over ``ctc_align_op``)."""
    from paddle_tpu.layers.tensor import argmax
    helper = LayerHelper("ctc_align")
    ids = argmax(input, axis=-1)
    out = helper.create_tmp_variable(dtype="int32", stop_gradient=True)
    helper.append_op(type="ctc_align", inputs={"Input": [ids]},
                     outputs={"Output": [out]}, attrs={"blank": blank})
    return out


def image_resize(input, out_shape, method="bilinear", name=None,
                 align_corners=True):
    """Resize NCHW feature maps to ``out_shape`` = (H, W) by bilinear or
    nearest interpolation (reference gserver BilinearInterpLayer.cpp /
    UpsampleLayer.cpp). For bilinear, ``align_corners=True`` (the
    default) matches the reference's ``(in-1)/(out-1)`` sampling ratios
    and ``False`` uses the half-pixel convention of jax.image.resize;
    nearest always uses half-pixel (identical to the reference's
    pixel-duplication for integer upsample factors)."""
    helper = LayerHelper("image_resize", name=name)
    out = helper.create_tmp_variable(dtype=input.dtype)
    helper.append_op(
        type="image_resize", inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"out_h": int(out_shape[0]), "out_w": int(out_shape[1]),
               "method": method, "align_corners": bool(align_corners)})
    return out
