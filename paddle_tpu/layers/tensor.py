"""Tensor-creation layers (reference ``python/paddle/fluid/layers/tensor.py``)."""

from __future__ import annotations

import numpy as np

from paddle_tpu import framework
from paddle_tpu.framework import Variable, convert_np_dtype
from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "create_tensor", "create_parameter", "create_global_var", "fill_constant",
    "fill_constant_batch_size_like", "ones", "zeros", "assign", "cast",
    "concat", "sums", "argmin", "argmax", "zeros_like",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(name=helper.name, dtype=dtype,
                                  persistable=persistable)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from paddle_tpu.param_attr import ParamAttr
    helper = LayerHelper("create_parameter", name=name)
    attr = ParamAttr.to_attr(attr)
    if name is not None and attr.name is None:
        attr.name = name
    return helper.create_parameter(attr, shape, dtype, is_bias,
                                   default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    from paddle_tpu import initializer as init_mod
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(dtype=dtype, shape=shape,
                                        persistable=persistable, name=name)
    helper.set_variable_initializer(var, init_mod.Constant(value))
    return var


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    if out is None:
        out = helper.create_tmp_variable(dtype=dtype)
    helper.append_op(
        type="fill_constant", outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": convert_np_dtype(dtype),
               "value": float(value)})
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_tmp_variable(dtype=dtype)
    helper.append_op(
        type="fill_constant_batch_size_like", inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": convert_np_dtype(dtype),
               "value": float(value), "input_dim_idx": input_dim_idx,
               "output_dim_idx": output_dim_idx})
    out.stop_gradient = True
    return out


def ones(shape, dtype, force_cpu=False):
    return fill_constant(value=1.0, shape=shape, dtype=dtype)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(value=0.0, shape=shape, dtype=dtype)


def zeros_like(x, out=None):
    helper = LayerHelper("zeros_like")
    if out is None:
        out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(type="fill_zeros_like", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if output is None:
        if isinstance(input, Variable):
            out_dtype = input.dtype
        elif isinstance(input, np.ndarray):
            out_dtype = convert_np_dtype(input.dtype)
        else:
            out_dtype = "float32"
        output = helper.create_tmp_variable(dtype=out_dtype)
    if isinstance(input, Variable):
        helper.append_op(type="assign", inputs={"X": [input]},
                         outputs={"Out": [output]})
    elif isinstance(input, np.ndarray):
        dtype = convert_np_dtype(input.dtype)
        if dtype in ("float32", "float64"):
            values = [float(v) for v in input.flat]
            value_name = "fp32_values"
        else:
            values = [int(v) for v in input.flat]
            value_name = "int32_values"
        helper.append_op(
            type="assign_value", outputs={"Out": [output]},
            attrs={"dtype": dtype, "shape": list(input.shape),
                   value_name: values})
    else:
        raise TypeError("assign expects Variable or numpy.ndarray")
    return output


from paddle_tpu.layers.nn import cast, concat  # noqa: E402,F401  (re-export)


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_tmp_variable(dtype=input[0].dtype)
    helper.append_op(type="sum", inputs={"X": input}, outputs={"Out": [out]})
    return out


def argmin(x, axis=0):
    helper = LayerHelper("arg_min")
    out = helper.create_tmp_variable("int64")
    helper.append_op(type="arg_min", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def argmax(x, axis=0):
    helper = LayerHelper("arg_max")
    out = helper.create_tmp_variable("int64")
    helper.append_op(type="arg_max", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out
