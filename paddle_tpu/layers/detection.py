"""Detection layers — SSD pipeline wrappers.

Reference: ``python/paddle/fluid/layers/detection.py`` (843 LoC).  Same API
surface, re-expressed over the TPU-native detection op group
(``paddle_tpu/ops/detection_ops.py``).
"""

from __future__ import annotations

from functools import reduce

from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.layers import nn

__all__ = [
    "prior_box", "multi_box_head", "bipartite_match", "target_assign",
    "detection_output", "ssd_loss", "detection_map", "iou_similarity",
    "box_coder", "roi_pool", "scale_sub_region",
]


def iou_similarity(x, y, name=None):
    """IoU matrix between row boxes of ``x`` [N,4] and ``y`` [M,4]."""
    helper = LayerHelper("iou_similarity", **locals())
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(type="iou_similarity", inputs={"X": x, "Y": y},
                     outputs={"Out": out})
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", name=None):
    """Encode/decode target boxes against prior boxes
    (reference ``box_coder_op.h``)."""
    helper = LayerHelper("box_coder", **locals())
    out = helper.create_tmp_variable(dtype=target_box.dtype)
    inputs = {"PriorBox": prior_box, "TargetBox": target_box}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = prior_box_var
    helper.append_op(type="box_coder", inputs=inputs,
                     outputs={"OutputBox": out},
                     attrs={"code_type": code_type})
    return out


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=None,
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              step_w=0.0, step_h=0.0, offset=0.5, name=None):
    """SSD prior boxes for one feature map (reference ``prior_box_op.h``)."""
    helper = LayerHelper("prior_box", **locals())
    dtype = input.dtype
    attrs = {
        "min_sizes": list(min_sizes),
        "aspect_ratios": list(aspect_ratios or []),
        "variances": list(variance),
        "flip": flip,
        "clip": clip,
        "step_w": step_w,
        "step_h": step_h,
        "offset": offset,
    }
    if max_sizes:
        attrs["max_sizes"] = list(max_sizes)
    box = helper.create_tmp_variable(dtype)
    var = helper.create_tmp_variable(dtype)
    helper.append_op(type="prior_box",
                     inputs={"Input": input, "Image": image},
                     outputs={"Boxes": box, "Variances": var}, attrs=attrs)
    box.stop_gradient = True
    var.stop_gradient = True
    return box, var


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    """Greedy bipartite matching (reference ``bipartite_match_op.cc``)."""
    helper = LayerHelper("bipartite_match", **locals())
    match_indices = helper.create_tmp_variable(dtype="int32")
    match_distance = helper.create_tmp_variable(dtype=dist_matrix.dtype)
    helper.append_op(
        type="bipartite_match",
        inputs={"DistMat": dist_matrix},
        attrs={"match_type": match_type, "dist_threshold": dist_threshold},
        outputs={"ColToRowMatchIndices": match_indices,
                 "ColToRowMatchDist": match_distance})
    return match_indices, match_distance


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    """Assign per-prediction targets/weights via match indices
    (reference ``target_assign_op.h``)."""
    helper = LayerHelper("target_assign", **locals())
    out = helper.create_tmp_variable(dtype=input.dtype)
    out_weight = helper.create_tmp_variable(dtype="float32")
    inputs = {"X": input, "MatchIndices": matched_indices}
    if negative_indices is not None:
        inputs["NegIndices"] = negative_indices
    helper.append_op(type="target_assign", inputs=inputs,
                     outputs={"Out": out, "OutWeight": out_weight},
                     attrs={"mismatch_value": mismatch_value})
    return out, out_weight


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """Decode predictions + multi-class NMS (reference
    ``layers/detection.py:45``).  Output is a LoD tensor [No, 6] of
    [label, confidence, xmin, ymin, xmax, ymax] rows."""
    helper = LayerHelper("detection_output", **locals())
    decoded_box = box_coder(prior_box=prior_box, prior_box_var=prior_box_var,
                            target_box=loc, code_type="decode_center_size")
    old_shape = scores.shape
    scores2 = nn.reshape(x=scores, shape=(-1, old_shape[-1]))
    scores2 = nn.softmax(scores2)
    scores2 = nn.reshape(x=scores2, shape=old_shape)
    scores2 = nn.transpose(scores2, perm=[0, 2, 1])
    scores2.stop_gradient = True
    nmsed_outs = helper.create_tmp_variable(dtype=decoded_box.dtype)
    helper.append_op(
        type="multiclass_nms",
        inputs={"Scores": scores2, "BBoxes": decoded_box},
        outputs={"Out": nmsed_outs},
        attrs={
            "background_label": background_label,
            "nms_threshold": nms_threshold,
            "nms_top_k": nms_top_k,
            "keep_top_k": keep_top_k,
            "score_threshold": score_threshold,
            "nms_eta": nms_eta,
        })
    nmsed_outs.stop_gradient = True
    return nmsed_outs


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.3, evaluate_difficult=True,
                  has_state=None, input_states=None, out_states=None,
                  ap_version="integral"):
    """Streaming VOC mAP (reference ``layers/detection.py:156``)."""
    helper = LayerHelper("detection_map", **locals())

    def _var(dtype):
        return helper.create_tmp_variable(dtype=dtype)

    map_out = _var("float32")
    accum_pos_count_out = out_states[0] if out_states else _var("int32")
    accum_true_pos_out = out_states[1] if out_states else _var("float32")
    accum_false_pos_out = out_states[2] if out_states else _var("float32")

    pos_count = input_states[0] if input_states else None
    true_pos = input_states[1] if input_states else None
    false_pos = input_states[2] if input_states else None

    inputs = {"Label": label, "DetectRes": detect_res}
    for slot, v in (("HasState", has_state), ("PosCount", pos_count),
                    ("TruePos", true_pos), ("FalsePos", false_pos)):
        if v is not None:
            inputs[slot] = v
    helper.append_op(
        type="detection_map", inputs=inputs,
        outputs={
            "MAP": map_out,
            "AccumPosCount": accum_pos_count_out,
            "AccumTruePos": accum_true_pos_out,
            "AccumFalsePos": accum_false_pos_out,
        },
        attrs={
            "overlap_threshold": overlap_threshold,
            "evaluate_difficult": evaluate_difficult,
            "ap_type": ap_version,
            "class_num": class_num,
            "background_label": background_label,
        })
    return map_out


def roi_pool(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0):
    """Max-pool features inside each ROI (reference ``roi_pool_op.h``)."""
    helper = LayerHelper("roi_pool", **locals())
    out = helper.create_tmp_variable(dtype=input.dtype)
    argmax = helper.create_tmp_variable(dtype="int64")
    helper.append_op(type="roi_pool",
                     inputs={"X": input, "ROIs": rois},
                     outputs={"Out": out, "Argmax": argmax},
                     attrs={"pooled_height": pooled_height,
                            "pooled_width": pooled_width,
                            "spatial_scale": spatial_scale})
    return out


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True, sample_size=None):
    """SSD multibox loss (reference ``layers/detection.py:349``): match,
    mine hard negatives, assign targets, weighted conf+loc loss."""
    helper = LayerHelper("ssd_loss", **locals())
    if mining_type not in ("max_negative", "hard_example"):
        raise ValueError("mining_type must be max_negative or hard_example")

    num, num_prior, num_class = confidence.shape

    def _to_2d(var):
        return nn.reshape(x=var, shape=[-1, var.shape[-1]])

    # 1. match priors to ground truth
    iou = iou_similarity(x=gt_box, y=prior_box)
    matched_indices, matched_dist = bipartite_match(iou, match_type,
                                                    overlap_threshold)

    # 2. confidence loss for mining
    gt_label_r = nn.reshape(x=gt_label, shape=tuple(gt_label.shape) + (1,))
    gt_label_r.stop_gradient = True
    target_label, _ = target_assign(gt_label_r, matched_indices,
                                    mismatch_value=background_label)
    confidence2 = _to_2d(confidence)
    target_label2 = nn.cast(x=_to_2d(target_label), dtype="int64")
    target_label2.stop_gradient = True
    conf_loss = nn.softmax_with_cross_entropy(confidence2, target_label2)

    # 3. mine hard examples
    conf_loss = nn.reshape(x=conf_loss, shape=(num, num_prior))
    conf_loss.stop_gradient = True
    neg_indices = helper.create_tmp_variable(dtype="int32")
    updated_matched_indices = helper.create_tmp_variable(dtype="int32")
    helper.append_op(
        type="mine_hard_examples",
        inputs={"ClsLoss": conf_loss, "MatchIndices": matched_indices,
                "MatchDist": matched_dist},
        outputs={"NegIndices": neg_indices,
                 "UpdatedMatchIndices": updated_matched_indices},
        attrs={
            "neg_pos_ratio": neg_pos_ratio,
            "neg_dist_threshold": neg_overlap,
            "mining_type": mining_type,
            "sample_size": sample_size,
        })
    neg_indices.stop_gradient = True
    updated_matched_indices.stop_gradient = True

    # 4. assign regression + classification targets
    encoded_bbox = box_coder(prior_box=prior_box,
                             prior_box_var=prior_box_var,
                             target_box=gt_box,
                             code_type="encode_center_size")
    target_bbox, target_loc_weight = target_assign(
        encoded_bbox, updated_matched_indices,
        mismatch_value=background_label)
    target_label, target_conf_weight = target_assign(
        gt_label_r, updated_matched_indices, negative_indices=neg_indices,
        mismatch_value=background_label)

    # 5. weighted losses
    target_label = nn.cast(x=_to_2d(target_label), dtype="int64")
    target_label.stop_gradient = True
    conf_loss = nn.softmax_with_cross_entropy(confidence2, target_label)
    target_conf_weight = _to_2d(target_conf_weight)
    target_conf_weight.stop_gradient = True
    conf_loss = conf_loss * target_conf_weight

    location2 = _to_2d(location)
    target_bbox = _to_2d(target_bbox)
    target_bbox.stop_gradient = True
    loc_loss = nn.smooth_l1(location2, target_bbox)
    target_loc_weight2 = _to_2d(target_loc_weight)
    target_loc_weight2.stop_gradient = True
    loc_loss = loc_loss * target_loc_weight2

    loss = conf_loss * conf_loss_weight + loc_loss * loc_loss_weight
    loss = nn.reshape(x=loss, shape=(-1, num_prior))
    loss = nn.reduce_sum(loss, dim=1, keep_dim=True)
    if normalize:
        normalizer = nn.reduce_sum(target_loc_weight)
        loss = loss / normalizer
    return loss


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None):
    """SSD multi-box head over a list of feature maps (reference
    ``layers/detection.py:567``): per-map prior boxes + conv loc/conf
    predictions, concatenated."""
    helper = LayerHelper("multi_box_head", **locals())

    def _is_seq(v):
        return isinstance(v, (list, tuple))

    num_layer = len(inputs)
    if min_sizes is None:
        # derive sizes from min/max ratio (reference behavior)
        assert num_layer >= 3, "multi_box_head needs min_sizes for <3 inputs"
        min_sizes = []
        max_sizes = []
        step = int((max_ratio - min_ratio) / (num_layer - 2))
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes
        max_sizes = [base_size * 0.2] + max_sizes

    if steps is not None:
        step_w = step_h = steps
    step_w = step_w or [0.0] * num_layer
    step_h = step_h or [0.0] * num_layer

    locs, confs, boxes_list, vars_list = [], [], [], []
    for i, inp in enumerate(inputs):
        min_size = min_sizes[i]
        max_size = max_sizes[i] if max_sizes else None
        if not _is_seq(min_size):
            min_size = [min_size]
        if max_size is not None and not _is_seq(max_size):
            max_size = [max_size]
        ar = aspect_ratios[i]
        if not _is_seq(ar):
            ar = [ar]
        box, var = prior_box(inp, image, min_size, max_size, ar,
                             list(variance), flip, clip,
                             float(step_w[i]), float(step_h[i]), offset)
        boxes_list.append(box)
        vars_list.append(var)
        num_boxes = box.shape[2]

        # location predictions: conv -> [N, H*W*priors, 4]
        mbox_loc = nn.conv2d(input=inp, num_filters=num_boxes * 4,
                             filter_size=kernel_size, padding=pad,
                             stride=stride)
        mbox_loc = nn.transpose(mbox_loc, perm=[0, 2, 3, 1])
        n = mbox_loc.shape[0]
        flat = reduce(lambda a, b: a * b, mbox_loc.shape[1:])
        mbox_loc = nn.reshape(x=mbox_loc, shape=[n, flat // 4, 4])
        locs.append(mbox_loc)

        # confidence predictions: conv -> [N, H*W*priors, C]
        conf = nn.conv2d(input=inp, num_filters=num_boxes * num_classes,
                         filter_size=kernel_size, padding=pad, stride=stride)
        conf = nn.transpose(conf, perm=[0, 2, 3, 1])
        flat = reduce(lambda a, b: a * b, conf.shape[1:])
        conf = nn.reshape(x=conf, shape=[n, flat // num_classes, num_classes])
        confs.append(conf)

    mbox_locs = nn.concat(locs, axis=1)
    mbox_confs = nn.concat(confs, axis=1)
    boxes2 = [nn.reshape(x=b, shape=[-1, 4]) for b in boxes_list]
    vars2 = [nn.reshape(x=v, shape=[-1, 4]) for v in vars_list]
    box = nn.concat(boxes2)
    var = nn.concat(vars2)
    box.stop_gradient = True
    var.stop_gradient = True
    return mbox_locs, mbox_confs, box, var


def scale_sub_region(x, indices, value=1.0):
    """Scale a per-sample [C,H,W] sub-region of ``x`` [N,C,H,W] by
    ``value``; ``indices`` [N, 6] holds one-based inclusive
    (c0, c1, h0, h1, w0, w1) ranges (reference
    ``gserver/layers/ScaleSubRegionLayer.cpp:1``)."""
    helper = LayerHelper("scale_sub_region", **locals())
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(type="scale_sub_region",
                     inputs={"X": x, "Indices": indices},
                     outputs={"Out": out},
                     attrs={"value": float(value)})
    return out
