"""Operator overloading on Variable (reference
``python/paddle/fluid/layers/math_op_patch.py``): ``a + b`` appends an
elementwise_add op, scalars become fill_constant, etc."""

from __future__ import annotations

from paddle_tpu.framework import Variable, unique_name

__all__ = ["monkey_patch_variable"]


def monkey_patch_variable():
    def unique_tmp_name():
        return unique_name("tmp")

    def safe_get_dtype(var):
        return var.dtype

    def emit_block(var):
        """Emit into the program's CURRENT block, not the variable's
        owner block: an expression on an outer-block var inside a
        While/cond body must compute INSIDE the body — emitting into
        the owner block silently hoists it out of the loop, so the loop
        re-reads a stale pre-loop value (r5 bug: ``acc = acc + 1``
        in a While body incremented exactly once)."""
        try:
            return var.block.program.current_block()
        except Exception:
            return var.block

    def create_tensor(block, value, dtype, shape):
        value = float(value)
        tmp_name = unique_tmp_name()
        var = block.create_var(name=tmp_name, shape=shape, dtype=dtype)
        block.append_op(type="fill_constant", outputs={"Out": [var.name]},
                        attrs={"dtype": var.dtype, "shape": shape,
                               "value": value})
        var.stop_gradient = True
        return var

    def create_scalar(block, value, dtype):
        return create_tensor(block, value, dtype, shape=[1])

    def create_tensor_with_batchsize(ref_var, value, dtype):
        assert isinstance(ref_var, Variable)
        value = float(value)
        tmp_name = unique_tmp_name()
        blk = emit_block(ref_var)
        var = blk.create_var(name=tmp_name, dtype=dtype,
                             shape=ref_var.shape)
        var.stop_gradient = True
        blk.append_op(
            type="fill_constant_batch_size_like",
            outputs={"Out": [var.name]}, inputs={"Input": [ref_var.name]},
            attrs={"dtype": var.dtype, "shape": list(ref_var.shape),
                   "value": value})
        return var

    def astype(self, dtype):
        block = emit_block(self)
        out = block.create_var(name=unique_tmp_name(), dtype=dtype,
                               shape=self.shape)
        block.append_op(type="cast", inputs={"X": [self.name]},
                        outputs={"Out": [out.name]},
                        attrs={"in_dtype": self.dtype, "out_dtype": dtype})
        return out

    def _elemwise_method_creator_(method_name, op_type, reverse=False):
        def __impl__(self, other_var):
            block = emit_block(self)
            lhs_dtype = safe_get_dtype(self)
            if not isinstance(other_var, Variable):
                if reverse:
                    has_batch_size = any(d == -1 for d in (self.shape or ()))
                    if not has_batch_size:
                        other_var = create_tensor(block, other_var,
                                                  dtype=lhs_dtype,
                                                  shape=list(self.shape))
                    else:
                        other_var = create_tensor_with_batchsize(
                            self, other_var, lhs_dtype)
                else:
                    other_var = create_scalar(block, value=other_var,
                                              dtype=lhs_dtype)

            if reverse:
                tmp = self
                self, other_var = other_var, tmp

            out = block.create_var(name=unique_tmp_name(), dtype=lhs_dtype,
                                   shape=self.shape)
            block.append_op(type=op_type,
                            inputs={"X": [self.name],
                                    "Y": [other_var.name]},
                            outputs={"Out": [out.name]},
                            attrs={"axis": -1})
            return out

        __impl__.__name__ = method_name
        return __impl__

    Variable.astype = astype
    for method, op_type, reverse in (
            ("__add__", "elementwise_add", False),
            ("__radd__", "elementwise_add", True),
            ("__sub__", "elementwise_sub", False),
            ("__rsub__", "elementwise_sub", True),
            ("__mul__", "elementwise_mul", False),
            ("__rmul__", "elementwise_mul", True),
            ("__truediv__", "elementwise_div", False),
            ("__rtruediv__", "elementwise_div", True),
            ("__div__", "elementwise_div", False),
            ("__rdiv__", "elementwise_div", True),
            ("__pow__", "elementwise_pow", False),
            ("__rpow__", "elementwise_pow", True),
            ("__mod__", "elementwise_mod", False),
            ("__floordiv__", "elementwise_floordiv", False)):
        setattr(Variable, method, _elemwise_method_creator_(method, op_type,
                                                            reverse))

    def _cmp_method_creator_(method_name, op_type):
        def __impl__(self, other_var):
            block = emit_block(self)
            if not isinstance(other_var, Variable):
                other_var = create_scalar(block, other_var,
                                          safe_get_dtype(self))
            out = block.create_var(name=unique_tmp_name(), dtype="bool",
                                   shape=self.shape)
            block.append_op(type=op_type,
                            inputs={"X": [self.name],
                                    "Y": [other_var.name]},
                            outputs={"Out": [out.name]})
            return out
        __impl__.__name__ = method_name
        return __impl__

    for method, op_type in (("__lt__", "less_than"),
                            ("__le__", "less_equal"),
                            ("__gt__", "greater_than"),
                            ("__ge__", "greater_equal")):
        setattr(Variable, method, _cmp_method_creator_(method, op_type))

    def __neg__(self):
        block = emit_block(self)
        out = block.create_var(name=unique_tmp_name(), dtype=self.dtype,
                               shape=self.shape)
        block.append_op(type="scale", inputs={"X": [self.name]},
                        outputs={"Out": [out.name]},
                        attrs={"scale": -1.0, "bias": 0.0,
                               "bias_after_scale": True})
        return out

    Variable.__neg__ = __neg__


monkey_patch_variable()
