"""Sequence & recurrent layers (reference ``layers/nn.py`` dynamic_lstm,
dynamic_gru, sequence_* wrappers)."""

from __future__ import annotations

import copy

from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.param_attr import ParamAttr

__all__ = [
    "dynamic_lstm", "dynamic_lstmp", "dynamic_gru", "gru_unit",
    "sequence_conv", "sequence_pool", "sequence_softmax",
    "sequence_first_step", "sequence_last_step", "sequence_expand",
    "sequence_reshape", "sequence_concat", "lod_reset",
    "sequence_reverse", "sequence_slice", "sequence_erase",
]


def dynamic_lstm(input, size, param_attr=None, bias_attr=None,
                 use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    """LSTM over a ragged batch; ``input`` is [N, 4H] pre-projected
    (reference ``layers/nn.py`` dynamic_lstm -> lstm_op.cc)."""
    helper = LayerHelper("lstm", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    size = size // 4
    weight = helper.create_parameter(helper.param_attr,
                                     shape=[size, 4 * size], dtype=dtype)
    bias_size = [1, 7 * size] if use_peepholes else [1, 4 * size]
    bias = helper.create_parameter(helper.bias_attr, shape=bias_size,
                                   dtype=dtype, is_bias=True)
    hidden = helper.create_tmp_variable(dtype)
    cell = helper.create_tmp_variable(dtype)
    helper.append_op(
        type="lstm",
        inputs={"Input": [input], "Weight": [weight], "Bias": [bias]},
        outputs={"Hidden": [hidden], "Cell": [cell]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation})
    return hidden, cell


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None):
    """LSTM with recurrent projection (reference ``nn.py`` dynamic_lstmp
    over ``lstmp_op.h``); ``input`` is [N, 4H] pre-projected, returns
    (projection [N, P], cell [N, H])."""
    helper = LayerHelper("lstmp", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    size = size // 4
    weight = helper.create_parameter(helper.param_attr,
                                     shape=[proj_size, 4 * size],
                                     dtype=dtype)
    proj_weight = helper.create_parameter(
        copy.deepcopy(helper.param_attr) if helper.param_attr else None,
        shape=[size, proj_size], dtype=dtype)
    bias_size = [1, 7 * size] if use_peepholes else [1, 4 * size]
    bias = helper.create_parameter(helper.bias_attr, shape=bias_size,
                                   dtype=dtype, is_bias=True)
    projection = helper.create_tmp_variable(dtype)
    cell = helper.create_tmp_variable(dtype)
    helper.append_op(
        type="lstmp",
        inputs={"Input": [input], "Weight": [weight],
                "ProjWeight": [proj_weight], "Bias": [bias]},
        outputs={"Projection": [projection], "Cell": [cell]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation,
               "proj_activation": proj_activation})
    return projection, cell


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, dtype="float32"):
    helper = LayerHelper("gru", param_attr=param_attr, bias_attr=bias_attr)
    weight = helper.create_parameter(helper.param_attr,
                                     shape=[size, 3 * size], dtype=dtype)
    bias = helper.create_parameter(helper.bias_attr, shape=[1, 3 * size],
                                   dtype=dtype, is_bias=True)
    hidden = helper.create_tmp_variable(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    helper.append_op(
        type="gru", inputs=inputs, outputs={"Hidden": [hidden]},
        attrs={"is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "activation": candidate_activation})
    return hidden


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid"):
    """Single GRU step (reference ``layers/nn.py`` gru_unit)."""
    helper = LayerHelper("gru_unit", param_attr=param_attr,
                         bias_attr=bias_attr)
    size = size // 3
    weight = helper.create_parameter(helper.param_attr,
                                     shape=[size, 3 * size], dtype=input.dtype)
    bias = helper.create_parameter(helper.bias_attr, shape=[1, 3 * size],
                                   dtype=input.dtype, is_bias=True)
    gate = helper.create_tmp_variable(input.dtype)
    reset_hidden_pre = helper.create_tmp_variable(input.dtype)
    updated_hidden = helper.create_tmp_variable(input.dtype)
    helper.append_op(
        type="gru_unit",
        inputs={"Input": [input], "HiddenPrev": [hidden],
                "Weight": [weight], "Bias": [bias]},
        outputs={"Gate": [gate], "ResetHiddenPrev": [reset_hidden_pre],
                 "Hidden": [updated_hidden]},
        attrs={"activation": activation,
               "gate_activation": gate_activation})
    return updated_hidden, reset_hidden_pre, gate


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, bias_attr=None, param_attr=None, act=None):
    helper = LayerHelper("sequence_conv", param_attr=param_attr,
                         bias_attr=bias_attr, act=act)
    filter_shape = [filter_size * input.shape[1], num_filters]
    filter_param = helper.create_parameter(helper.param_attr,
                                           shape=filter_shape,
                                           dtype=input.dtype)
    pre_bias = helper.create_tmp_variable(input.dtype)
    helper.append_op(
        type="sequence_conv",
        inputs={"X": [input], "Filter": [filter_param]},
        outputs={"Out": [pre_bias]},
        attrs={"contextStride": filter_stride,
               "contextStart": -int(filter_size // 2),
               "contextLength": filter_size})
    pre_act = helper.append_bias_op(pre_bias)
    return helper.append_activation(pre_act)


def sequence_pool(input, pool_type):
    helper = LayerHelper("sequence_pool")
    pool_out = helper.create_tmp_variable(input.dtype)
    max_index = helper.create_tmp_variable("int32")
    helper.append_op(
        type="sequence_pool", inputs={"X": [input]},
        outputs={"Out": [pool_out], "MaxIndex": [max_index]},
        attrs={"pooltype": pool_type.upper()})
    if pool_type == "max":
        max_index.stop_gradient = True
    return pool_out


def sequence_first_step(input):
    return sequence_pool(input=input, pool_type="first")


def sequence_last_step(input):
    return sequence_pool(input=input, pool_type="last")


def sequence_softmax(input, use_cudnn=False, name=None):
    helper = LayerHelper("sequence_softmax", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="sequence_softmax", inputs={"X": [input]},
                     outputs={"Out": [out]})
    return out


def sequence_expand(x, y, ref_level=-1, name=None):
    helper = LayerHelper("sequence_expand", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="sequence_expand",
                     inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"ref_level": ref_level})
    return out


def sequence_reshape(input, new_dim):
    helper = LayerHelper("sequence_reshape")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="sequence_reshape", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"new_dim": new_dim})
    return out


def sequence_concat(input, name=None):
    helper = LayerHelper("sequence_concat", name=name)
    out = helper.create_tmp_variable(input[0].dtype)
    helper.append_op(type="sequence_concat",
                     inputs={"X": [v for v in input]},
                     outputs={"Out": [out]})
    return out


def lod_reset(x, y=None, target_lod=None):
    helper = LayerHelper("lod_reset")
    out = helper.create_tmp_variable(x.dtype)
    if y is not None:
        helper.append_op(type="lod_reset", inputs={"X": [x], "Y": [y]},
                         outputs={"Out": [out]})
    elif target_lod is not None:
        helper.append_op(type="lod_reset", inputs={"X": [x]},
                         outputs={"Out": [out]},
                         attrs={"target_lod": [int(v) for v in target_lod]})
    else:
        raise ValueError("lod_reset needs y or target_lod")
    return out


def sequence_reverse(x, name=None):
    """Reverse rows within each sequence (reference
    ``sequence_reverse_op.h``); LoD is preserved."""
    helper = LayerHelper("sequence_reverse", name=name)
    out = helper.create_tmp_variable(dtype=x.dtype)
    helper.append_op(type="sequence_reverse", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def sequence_slice(input, offset, length, name=None):
    """Per-sequence subsequence extraction (reference
    ``sequence_slice_op.cc``): ``offset``/``length`` are [B]-shaped."""
    helper = LayerHelper("sequence_slice", name=name)
    out = helper.create_tmp_variable(dtype=input.dtype)
    helper.append_op(type="sequence_slice",
                     inputs={"X": [input], "Offset": [offset],
                             "Length": [length]},
                     outputs={"Out": [out]})
    return out


def sequence_erase(input, tokens, name=None):
    """Remove the listed token ids from each sequence (reference
    ``sequence_erase_op.cc``)."""
    helper = LayerHelper("sequence_erase", name=name)
    out = helper.create_tmp_variable(dtype=input.dtype)
    helper.append_op(type="sequence_erase", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"tokens": list(tokens)})
    return out
