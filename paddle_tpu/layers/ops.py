"""Auto-generated thin layer wrappers over registered ops (reference
``python/paddle/fluid/layers/ops.py:76`` generates these from OpProtos)."""

from __future__ import annotations

from paddle_tpu.layer_helper import LayerHelper

__all__ = []

_ACTIVATIONS = [
    "sigmoid", "logsigmoid", "exp", "relu", "tanh", "tanh_shrink",
    "softshrink", "sqrt", "abs", "ceil", "floor", "round", "reciprocal",
    "log", "square", "softplus", "softsign", "brelu", "leaky_relu",
    "soft_relu", "elu", "relu6", "pow", "stanh", "hard_sigmoid", "swish",
    "hard_shrink", "thresholded_relu", "gelu", "sin", "cos",
]

# NOTE: softmax is NOT generated here — layers/nn.py defines the real
# wrapper (optional fused Bias input); generating it too would shadow
# that one through the star-import order in layers/__init__.py
_UNARY_OPS = _ACTIVATIONS + ["sign", "cumsum", "log_softmax"]


def _make_wrapper(op_type):
    def layer(x, name=None, **attrs):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_tmp_variable(x.dtype)
        helper.append_op(type=op_type, inputs={"X": [x]},
                         outputs={"Out": [out]}, attrs=attrs)
        return out
    layer.__name__ = op_type
    layer.__doc__ = f"Elementwise `{op_type}` op wrapper."
    return layer


for _op in _UNARY_OPS:
    globals()[_op] = _make_wrapper(_op)
    __all__.append(_op)


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="clip_by_norm", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"max_norm": max_norm})
    return out


__all__.append("clip_by_norm")


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_tmp_variable(dtype)
    helper.append_op(type="uniform_random", outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "min": min, "max": max, "seed": seed})
    return out


__all__.append("uniform_random")


def gaussian_random(shape, dtype="float32", mean=0.0, std=1.0, seed=0):
    helper = LayerHelper("gaussian_random")
    out = helper.create_tmp_variable(dtype)
    helper.append_op(type="gaussian_random", outputs={"Out": [out]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "mean": mean, "std": std, "seed": seed})
    return out


__all__.append("gaussian_random")
