"""Evaluators: IR-state streaming metrics (reference
``python/paddle/fluid/evaluator.py``, 382 LoC).

An Evaluator owns persistable state variables that graph ops update every
minibatch; ``eval()`` combines the accumulated state, ``reset()`` zeroes it
(the reference builds a reset program of fill_constant ops; same here)."""

from __future__ import annotations

import numpy as np

from paddle_tpu import framework
from paddle_tpu import layers
from paddle_tpu.framework import Program, program_guard, unique_name
from paddle_tpu.layer_helper import LayerHelper

__all__ = ["Evaluator", "ChunkEvaluator", "EditDistance", "DetectionMAP",
           "CTCErrorEvaluator"]


def _clone_var(block, var):
    return block.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                            persistable=True)


class Evaluator:
    """Base (reference ``evaluator.py:43``): subclasses create state vars
    with ``create_state`` and append update ops in ``__init__``."""

    def __init__(self, name, **kwargs):
        self.states = []
        self.metrics = []
        self.helper = LayerHelper(name, **kwargs)

    def reset(self, executor, reset_program=None):
        """Zero the accumulator state (reference ``evaluator.py:70``)."""
        if reset_program is None:
            reset_program = Program()
        with program_guard(main_program=reset_program):
            for var in self.states:
                g_var = _clone_var(reset_program.current_block(), var)
                layers.fill_constant(shape=g_var.shape, value=0.0,
                                     dtype=g_var.dtype, out=g_var)
        executor.run(reset_program)

    def eval(self, executor, eval_program=None):
        raise NotImplementedError

    def create_state(self, suffix, dtype, shape):
        state = self.helper.main_program.current_block().create_var(
            name=unique_name(self.helper.name + "_" + suffix),
            shape=list(shape), dtype=dtype)
        state.persistable = True
        self.states.append(state)
        return state


class ChunkEvaluator(Evaluator):
    """Streaming chunk F1 (reference ``evaluator.py:115``): accumulates
    infer/label/correct chunk counts across minibatches."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None):
        super().__init__("chunk_eval")
        main_program = self.helper.main_program
        if main_program.current_block().idx != 0:
            raise ValueError("You can only invoke Evaluator in root block")

        self.num_infer_chunks = self.create_state(
            "num_infer_chunks", "int64", (1,))
        self.num_label_chunks = self.create_state(
            "num_label_chunks", "int64", (1,))
        self.num_correct_chunks = self.create_state(
            "num_correct_chunks", "int64", (1,))
        (precision, recall, f1, num_infer, num_label, num_correct) = \
            layers.chunk_eval(input=input, label=label,
                              chunk_scheme=chunk_scheme,
                              num_chunk_types=num_chunk_types,
                              excluded_chunk_types=excluded_chunk_types)
        layers.sums(input=[self.num_infer_chunks, num_infer],
                    out=self.num_infer_chunks)
        layers.sums(input=[self.num_label_chunks, num_label],
                    out=self.num_label_chunks)
        layers.sums(input=[self.num_correct_chunks, num_correct],
                    out=self.num_correct_chunks)
        self.metrics.extend([precision, recall, f1])

    def eval(self, executor, eval_program=None):
        from paddle_tpu.scope import global_scope
        scope = global_scope()
        num_infer = float(np.asarray(scope.find_var(
            self.num_infer_chunks.name)).reshape(-1)[0])
        num_label = float(np.asarray(scope.find_var(
            self.num_label_chunks.name)).reshape(-1)[0])
        num_correct = float(np.asarray(scope.find_var(
            self.num_correct_chunks.name)).reshape(-1)[0])
        precision = num_correct / num_infer if num_infer else 0.0
        recall = num_correct / num_label if num_label else 0.0
        f1 = (2 * precision * recall / (precision + recall)
              if num_correct else 0.0)
        return np.array([precision]), np.array([recall]), np.array([f1])


class EditDistance(Evaluator):
    """Streaming average edit distance + exact-match rate (reference
    ``evaluator.py:180``)."""

    def __init__(self, input, label, ignored_tokens=None,
                 normalized=False, name="edit_distance"):
        super().__init__(name)
        self.total_distance = self.create_state(
            "total_distance", "float32", (1,))
        self.seq_num = self.create_state("seq_num", "int64", (1,))
        self.instance_error = self.create_state(
            "instance_error", "int64", (1,))
        helper = self.helper
        dist = helper.create_tmp_variable("float32")
        seq_num = helper.create_tmp_variable("int64")
        helper.append_op(type="edit_distance",
                         inputs={"Hyps": [input], "Refs": [label]},
                         outputs={"Out": [dist], "SequenceNum": [seq_num]},
                         attrs={"normalized": normalized})
        zero = layers.fill_constant(shape=[1], value=0.0, dtype="float32")
        erroneous = helper.create_tmp_variable("int64")
        helper.append_op(type="greater_than",
                         inputs={"X": [dist], "Y": [zero]},
                         outputs={"Out": [erroneous]})
        err_count = layers.reduce_sum(layers.cast(erroneous, "int64"))
        batch_dist = layers.reduce_sum(dist)
        layers.sums(input=[self.total_distance, batch_dist],
                    out=self.total_distance)
        layers.sums(input=[self.seq_num, seq_num], out=self.seq_num)
        layers.sums(input=[self.instance_error, err_count],
                    out=self.instance_error)
        self.metrics.append(batch_dist)

    def eval(self, executor, eval_program=None):
        from paddle_tpu.scope import global_scope
        scope = global_scope()
        total = float(np.asarray(scope.find_var(
            self.total_distance.name)).reshape(-1)[0])
        n = float(np.asarray(scope.find_var(
            self.seq_num.name)).reshape(-1)[0])
        err = float(np.asarray(scope.find_var(
            self.instance_error.name)).reshape(-1)[0])
        avg = total / n if n else 0.0
        return np.array([avg]), np.array([err / n if n else 0.0])


class DetectionMAP(Evaluator):
    """Streaming VOC mAP over the detection_map op's accumulator state
    (reference ``evaluator.py:258``)."""

    def __init__(self, input, gt_label, gt_box, gt_difficult=None,
                 class_num=None, background_label=0, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="integral"):
        super().__init__("map_eval")
        from paddle_tpu.layers import detection
        if gt_difficult is not None:
            label = layers.concat([gt_label, gt_difficult, gt_box], axis=1)
        else:
            label = layers.concat([gt_label, gt_box], axis=1)
        # batch mAP (stateless)
        map_out = detection.detection_map(
            input, label, class_num, background_label,
            overlap_threshold=overlap_threshold,
            evaluate_difficult=evaluate_difficult, ap_version=ap_version)
        self.cur_map = map_out
        # streaming mAP through carried accumulators
        self.has_state = self.helper.main_program.current_block().create_var(
            name=unique_name("map_eval_has_state"), dtype="int32",
            shape=(1,))
        self.has_state.persistable = True
        self.states = [self.has_state]
        pos_count = self.create_state("pos_count", "int32", (class_num, 1))
        true_pos = self.create_state("true_pos", "float32", (0, 2))
        false_pos = self.create_state("false_pos", "float32", (0, 2))
        self.accum_map = detection.detection_map(
            input, label, class_num, background_label,
            overlap_threshold=overlap_threshold,
            evaluate_difficult=evaluate_difficult,
            has_state=self.has_state,
            input_states=[pos_count, true_pos, false_pos],
            out_states=[pos_count, true_pos, false_pos],
            ap_version=ap_version)
        layers.fill_constant(shape=[1], value=1, dtype="int32",
                             out=self.has_state)
        self.metrics.extend([self.cur_map, self.accum_map])

    def get_map_var(self):
        return self.cur_map, self.accum_map

    def reset(self, executor, reset_program=None):
        from paddle_tpu.scope import global_scope
        scope = global_scope()
        scope.set_var(self.has_state.name, np.zeros((1,), np.int32))
        for var in self.states[1:]:
            shape = [0 if d is None else max(d, 0) for d in var.shape]
            scope.set_var(var.name, np.zeros(shape, var.dtype))


class CTCErrorEvaluator(EditDistance):
    """Streaming CTC sequence error rate: ctc_align the network output,
    then LENGTH-NORMALIZED edit distance against the label (reference
    ``gserver/evaluators/CTCErrorEvaluator.cpp`` accumulates
    distance/len per sequence) — composed from EditDistance."""

    def __init__(self, input, label, blank=0):
        helper = LayerHelper("ctc_error")
        aligned = helper.create_tmp_variable("int64")
        helper.append_op(type="ctc_align", inputs={"Input": [input]},
                         outputs={"Output": [aligned]},
                         attrs={"blank": blank, "merge_repeated": True})
        super().__init__(aligned, label, normalized=True, name="ctc_error")

    def eval(self, executor, eval_program=None):
        avg_rate, _ = super().eval(executor, eval_program)
        return avg_rate
