"""Device identity (reference ``paddle/fluid/platform/place.h:25-49``).

The reference's CPUPlace/CUDAPlace/CUDAPinnedPlace variant becomes
CPUPlace/TPUPlace; pinned host memory has no user-visible analog (XLA's
runtime owns transfer staging).
"""

from __future__ import annotations

import jax

__all__ = ["CPUPlace", "TPUPlace", "CUDAPlace", "core_devices", "is_tpu_available"]


class Place:
    def __eq__(self, other):
        return type(self) is type(other) and getattr(self, "device_id", 0) == \
            getattr(other, "device_id", 0)

    def __hash__(self):
        return hash((type(self).__name__, getattr(self, "device_id", 0)))


class CPUPlace(Place):
    def __repr__(self):
        return "CPUPlace"

    def jax_device(self):
        cpus = [d for d in jax.devices() if d.platform == "cpu"]
        return cpus[0] if cpus else jax.devices()[0]


class TPUPlace(Place):
    def __init__(self, device_id=0):
        self.device_id = device_id

    def __repr__(self):
        return f"TPUPlace({self.device_id})"

    def jax_device(self):
        devs = [d for d in jax.devices() if d.platform != "cpu"]
        if not devs:
            devs = jax.devices()
        return devs[self.device_id % len(devs)]


# Compatibility alias: reference code says CUDAPlace; on this stack the
# accelerator is a TPU.
CUDAPlace = TPUPlace


def core_devices():
    return jax.devices()


def is_tpu_available():
    return any(d.platform != "cpu" for d in jax.devices())
