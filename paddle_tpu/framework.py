"""Program IR: Program / Block / Operator / Variable.

TPU-native re-design of the reference Fluid IR
(``paddle/fluid/framework/framework.proto:20-176`` and the Python mirror
``python/paddle/fluid/framework.py``).  The IR is the user-facing contract:
Python layer calls append ``Operator``s to ``Block``s of a ``Program``; the
Executor later lowers a whole block to ONE compiled XLA computation (rather
than interpreting op-by-op as ``paddle/fluid/framework/executor.cc:334`` does).

Differences from the reference, driven by the TPU/XLA compilation model:
  * No protobuf round-trip on the hot path; the IR is plain Python objects
    with a stable ``to_dict``/``from_dict`` serialization (used by save/load
    of inference models).
  * Variables carry a ``lod_level`` like the reference's ``VarDesc`` but the
    runtime ragged representation is row-splits + padded/segment-id form
    (see ``paddle_tpu.lod``), not nested offset vectors on the tensor.
"""

from __future__ import annotations

import collections
import contextlib
import os
import sys
import threading

import numpy as np

__all__ = [
    "Variable",
    "Operator",
    "Block",
    "Program",
    "default_main_program",
    "default_startup_program",
    "program_guard",
    "switch_main_program",
    "switch_startup_program",
    "unique_name",
    "grad_var_name",
    "convert_np_dtype",
    "Parameter",
]

# ---------------------------------------------------------------------------
# dtype handling
# ---------------------------------------------------------------------------

_DTYPE_ALIASES = {
    "float32": "float32", "fp32": "float32", "float": "float32",
    "float64": "float64", "fp64": "float64", "double": "float64",
    "float16": "float16", "fp16": "float16", "half": "float16",
    "bfloat16": "bfloat16", "bf16": "bfloat16",
    "int8": "int8", "uint8": "uint8",
    "int16": "int16", "int32": "int32", "int64": "int64",
    "bool": "bool",
}


def convert_np_dtype(dtype):
    """Normalize a dtype-ish value to a canonical string name."""
    if isinstance(dtype, str):
        key = dtype.lower()
        if key in _DTYPE_ALIASES:
            return _DTYPE_ALIASES[key]
        raise ValueError(f"unsupported dtype string: {dtype!r}")
    # jnp.bfloat16 / np dtypes / python types
    name = np.dtype(dtype).name if not _is_bfloat16(dtype) else "bfloat16"
    return convert_np_dtype(name)


def _is_bfloat16(dtype):
    try:
        return "bfloat16" in str(dtype)
    except Exception:  # pragma: no cover
        return False


# ---------------------------------------------------------------------------
# unique names
# ---------------------------------------------------------------------------

class _UniqueNameGenerator:
    def __init__(self):
        self._ids = collections.defaultdict(int)
        self._lock = threading.Lock()

    def __call__(self, key):
        with self._lock:
            idx = self._ids[key]
            self._ids[key] += 1
        return f"{key}_{idx}"


_name_generator = _UniqueNameGenerator()


def unique_name(key):
    return _name_generator(key)


@contextlib.contextmanager
def unique_name_scope(prefix):
    """Deterministic name scope: inside the guard, generated names restart
    from zero under ``prefix`` — so re-running the same layer-building code
    in the guard reproduces IDENTICAL parameter names, which is how
    unrolled decode loops (legacy ``beam_search``) share weights across
    timesteps.  Distinct prefixes keep scopes from colliding with the
    outer program's names."""
    global _name_generator
    saved = _name_generator
    fresh = _UniqueNameGenerator()
    _name_generator = lambda key: fresh(f"{prefix}{key}")
    try:
        yield
    finally:
        _name_generator = saved


GRAD_SUFFIX = "@GRAD"


def grad_var_name(name):
    return name + GRAD_SUFFIX


# ---------------------------------------------------------------------------
# Variable
# ---------------------------------------------------------------------------

class Variable:
    """A named tensor in a Block (reference: ``VarDesc`` + python ``Variable``,
    ``python/paddle/fluid/framework.py:117``).

    ``shape`` may contain -1 for dimensions unknown until feed time (batch).
    ``persistable`` variables live across executor runs (parameters, optimizer
    state); everything else is scratch within one lowered computation.
    """

    def __init__(self, block, name=None, shape=None, dtype="float32",
                 lod_level=0, persistable=False, stop_gradient=False,
                 is_data=False, initializer=None, trainable=True,
                 type="lod_tensor"):
        self.block = block
        if name is None:
            name = unique_name("_generated_var")
        self.name = name
        self.shape = tuple(int(d) for d in shape) if shape is not None else None
        self.dtype = convert_np_dtype(dtype)
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.initializer = initializer
        self.trainable = trainable
        self.type = type  # lod_tensor | selected_rows | tensor_array | reader

    # -- program topology helpers -----------------------------------------
    @property
    def op(self):
        """The op that (last) outputs this variable, or None."""
        for op in reversed(self.block.ops):
            if self.name in op.output_arg_names:
                return op
        return None

    def to_dict(self):
        d = {
            "name": self.name,
            "shape": list(self.shape) if self.shape is not None else None,
            "dtype": self.dtype,
            "lod_level": self.lod_level,
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "is_data": self.is_data,
            "trainable": self.trainable,
            "type": self.type,
        }
        if isinstance(self, Parameter):
            d["is_parameter"] = True
            d["optimize_attr"] = dict(self.optimize_attr or {})
        return d

    @staticmethod
    def from_dict(block, d):
        if d.get("is_parameter"):
            v = Parameter(block, d["shape"], d["dtype"], name=d["name"],
                          lod_level=d.get("lod_level", 0),
                          trainable=d.get("trainable", True))
            v.optimize_attr = d.get("optimize_attr",
                                    {"learning_rate": 1.0})
            v.stop_gradient = d.get("stop_gradient", False)
            v.is_data = d.get("is_data", False)
            return v
        v = Variable(block, name=d["name"],
                     shape=d["shape"], dtype=d["dtype"],
                     lod_level=d.get("lod_level", 0),
                     persistable=d.get("persistable", False),
                     stop_gradient=d.get("stop_gradient", False),
                     is_data=d.get("is_data", False),
                     trainable=d.get("trainable", True),
                     type=d.get("type", "lod_tensor"))
        return v

    def __repr__(self):
        return (f"Variable(name={self.name}, shape={self.shape}, "
                f"dtype={self.dtype}, persistable={self.persistable})")

    # numpy-style convenience mirrored from math_op_patch (monkey-patched in
    # paddle_tpu.layers.math_op_patch to avoid a circular import).


class Parameter(Variable):
    """A trainable persistable variable (reference ``framework.py:Parameter``)."""

    def __init__(self, block, shape, dtype, **kwargs):
        kwargs.setdefault("persistable", True)
        kwargs.setdefault("trainable", True)
        self.regularizer = kwargs.pop("regularizer", None)
        self.gradient_clip_attr = kwargs.pop("gradient_clip_attr", None)
        self.optimize_attr = kwargs.pop("optimize_attr", {"learning_rate": 1.0})
        self.do_model_average = kwargs.pop("do_model_average", False)
        super().__init__(block, shape=shape, dtype=dtype, **kwargs)


# ---------------------------------------------------------------------------
# Operator
# ---------------------------------------------------------------------------

_PKG_DIR = os.path.dirname(os.path.abspath(__file__)) + os.sep


def _creation_site():
    """(filename, lineno) of the first stack frame OUTSIDE paddle_tpu —
    the user code that (transitively) appended this op.  The static
    analyzer (``paddle_tpu.analysis``) points its diagnostics here, so
    "shape mismatch in op #12" becomes "…at model.py:42".  A plain
    frame walk (no traceback object) keeps this ~1us per op, paid once
    at program build time."""
    f = sys._getframe(2)
    while f is not None:
        if not f.f_code.co_filename.startswith(_PKG_DIR):
            return (f.f_code.co_filename, f.f_lineno)
        f = f.f_back
    return None


class Operator:
    """One node of the IR (reference ``OpDesc``, ``framework.proto:157``).

    inputs / outputs: dict of slot name -> list of variable names.
    attrs: plain-python attribute dict; a sub-block is referenced by storing
    the Block object itself under the attr (serialized as block index).
    """

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        self.inputs = {k: list(v) if isinstance(v, (list, tuple)) else [v]
                       for k, v in (inputs or {}).items()}
        self.outputs = {k: list(v) if isinstance(v, (list, tuple)) else [v]
                        for k, v in (outputs or {}).items()}
        # normalize Variable objects to names
        for d in (self.inputs, self.outputs):
            for k, vs in d.items():
                d[k] = [v.name if isinstance(v, Variable) else v for v in vs]
        self.attrs = dict(attrs or {})
        self.creation_site = _creation_site()

    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self):
        return [n for vs in self.inputs.values() for n in vs]

    @property
    def output_arg_names(self):
        return [n for vs in self.outputs.values() for n in vs]

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def has_attr(self, name):
        return name in self.attrs

    def to_dict(self):
        attrs = {}
        for k, v in self.attrs.items():
            if isinstance(v, Block):
                attrs[k] = {"__block__": v.idx}
            elif isinstance(v, np.ndarray):
                attrs[k] = {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
            else:
                attrs[k] = v
        out = {"type": self.type, "inputs": self.inputs,
               "outputs": self.outputs, "attrs": attrs}
        # keep the diagnostic pointer across save/load round-trips:
        # replay --localize names an op of a DESERIALIZED program, and
        # without the site the report can only say "op #12"
        if self.creation_site is not None:
            out["creation_site"] = list(self.creation_site)
        return out

    @staticmethod
    def from_dict(block, d, program):
        attrs = {}
        for k, v in d["attrs"].items():
            if isinstance(v, dict) and "__block__" in v:
                attrs[k] = program.block(v["__block__"])
            elif isinstance(v, dict) and "__ndarray__" in v:
                attrs[k] = np.asarray(v["__ndarray__"], dtype=v["dtype"])
            else:
                attrs[k] = v
        op = Operator(block, d["type"], d["inputs"], d["outputs"], attrs)
        site = d.get("creation_site")
        if site:
            op.creation_site = (site[0], site[1])
        return op

    def __repr__(self):
        ins = {k: v for k, v in self.inputs.items()}
        outs = {k: v for k, v in self.outputs.items()}
        return f"Op(type={self.type}, inputs={ins}, outputs={outs})"


# ---------------------------------------------------------------------------
# Block
# ---------------------------------------------------------------------------

class Block:
    """An ordered list of ops plus its variable symbol table
    (reference ``BlockDesc``, ``framework.proto:163``)."""

    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars = collections.OrderedDict()  # name -> Variable
        self.ops = []

    @property
    def parent_block(self):
        return None if self.parent_idx < 0 else self.program.block(self.parent_idx)

    # -- variables ---------------------------------------------------------
    def create_var(self, **kwargs):
        name = kwargs.get("name")
        if name is not None and name in self.vars:
            return self.vars[name]
        v = Variable(self, **kwargs)
        self.vars[v.name] = v
        return v

    def create_parameter(self, shape, dtype, **kwargs):
        p = Parameter(self, shape, dtype, **kwargs)
        # parameters always live in the root (global) block, like the reference
        gblock = self.program.global_block()
        p.block = gblock
        gblock.vars[p.name] = p
        return p

    def var(self, name):
        """Find a variable by name, searching ancestor blocks."""
        block = self
        while block is not None:
            if name in block.vars:
                return block.vars[name]
            block = block.parent_block
        raise KeyError(f"variable {name!r} not found in block {self.idx} "
                       f"or its ancestors")

    def has_var(self, name):
        try:
            self.var(name)
            return True
        except KeyError:
            return False

    def has_var_local(self, name):
        return name in self.vars

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # -- ops ---------------------------------------------------------------
    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        self._infer_shape(op)
        self.program.bump_version()
        return op

    def prepend_op(self, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        self._infer_shape(op)
        self.program.bump_version()
        return op

    def insert_op(self, index, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(index, op)
        self._infer_shape(op)
        self.program.bump_version()
        return op

    def remove_op(self, index):
        self.ops.pop(index)
        self.program.bump_version()

    def _infer_shape(self, op):
        # late import to avoid cycle; infer_shape is best-effort at build time
        from paddle_tpu.ops import registry
        # declare any still-undeclared outputs (grad vars, temporaries)
        for n in op.output_arg_names:
            if n and not self.has_var(n):
                v = Variable(self, name=n)
                v.shape = None
                self.vars[n] = v
        opdef = registry.lookup(op.type)
        if opdef is not None and opdef.infer_shape is not None:
            try:
                opdef.infer_shape(op, self)
            except (registry.ShapeInferenceSkip, KeyError, TypeError):
                pass

    def to_dict(self):
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "vars": [v.to_dict() for v in self.vars.values()],
            "ops": [op.to_dict() for op in self.ops],
        }

    def __repr__(self):
        return f"Block(idx={self.idx}, ops={len(self.ops)}, vars={len(self.vars)})"


# ---------------------------------------------------------------------------
# Program
# ---------------------------------------------------------------------------

class Program:
    """A whole computation: list of blocks, block 0 is global
    (reference ``ProgramDesc``, ``framework.proto:176``)."""

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self._current_block_idx = 0
        self._version = 0  # bumped on mutation; part of the jit cache key
        self.random_seed = 0
        # parity with reference Program attributes
        self._is_inference = False
        # mixed precision (bf16 compute, f32 master weights).  None = defer
        # to the PADDLE_TPU_AMP env var; True/False = explicit per-program.
        self.amp = None
        # programs that deliberately carry host ops (metrics, decoding,
        # persistence) set this to suppress the host-op-cliff warning —
        # it stays on for programs that hit the cliff unexpectedly
        self.expect_host_ops = False

    # -- blocks ------------------------------------------------------------
    def global_block(self):
        return self.blocks[0]

    def block(self, idx):
        return self.blocks[idx]

    def current_block(self):
        return self.blocks[self._current_block_idx]

    def create_block(self, parent_idx=None):
        parent = self._current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent_idx=parent)
        self.blocks.append(b)
        self._current_block_idx = b.idx
        return b

    def rollback(self):
        self._current_block_idx = self.current_block().parent_idx

    @property
    def num_blocks(self):
        return len(self.blocks)

    def bump_version(self):
        self._version += 1

    # -- cloning / pruning -------------------------------------------------
    def clone(self, for_test=False):
        """Deep-copy the program.  With for_test=True, ops flip their
        ``is_test`` attr (dropout/batch_norm behave in inference mode),
        mirroring reference ``Program.clone`` semantics."""
        p = Program.from_dict(self.to_dict())
        self._copy_param_attrs_to(p)
        if for_test:
            for blk in p.blocks:
                for op in blk.ops:
                    if "is_test" in op.attrs or op.type in ("dropout", "batch_norm"):
                        op.attrs["is_test"] = True
        return p

    def _copy_param_attrs_to(self, other):
        """Carry non-serializable Parameter attrs (regularizer, clip) onto
        a program reconstructed via from_dict."""
        src = {v.name: v for v in self.global_block().vars.values()
               if isinstance(v, Parameter)}
        for v in other.global_block().vars.values():
            if isinstance(v, Parameter) and v.name in src:
                s = src[v.name]
                v.regularizer = s.regularizer
                v.gradient_clip_attr = s.gradient_clip_attr
                v.do_model_average = s.do_model_average

    def prune(self, targets):
        """Backward-slice the global block to the ops needed for ``targets``
        (reference ``framework/prune.cc``).  Control-flow ops keep their
        sub-blocks intact.  Returns a new Program."""
        target_names = set()
        for t in targets:
            target_names.add(t.name if isinstance(t, Variable) else t)
        src = self.global_block()
        needed = set(target_names)
        kept = []
        for op in reversed(src.ops):
            if any(o in needed for o in op.output_arg_names):
                kept.append(op)
                needed.update(op.input_arg_names)
        kept.reverse()

        pruned = Program()
        pruned.random_seed = self.random_seed
        pruned.amp = self.amp
        # copy sub-blocks wholesale (indices preserved) so block attrs resolve
        for b in self.blocks[1:]:
            nb = Block(pruned, len(pruned.blocks), parent_idx=b.parent_idx)
            pruned.blocks.append(nb)
            for v in b.vars.values():
                nb.vars[v.name] = Variable.from_dict(nb, v.to_dict())
            for op in b.ops:
                nb.ops.append(Operator.from_dict(nb, op.to_dict(), pruned))
        dst = pruned.global_block()
        for v in src.vars.values():
            dst.vars[v.name] = Variable.from_dict(dst, v.to_dict())
        for op in kept:
            dst.ops.append(Operator.from_dict(dst, op.to_dict(), pruned))
        self._copy_param_attrs_to(pruned)
        return pruned

    def inference_optimize(self):
        p = self.clone(for_test=True)
        p._is_inference = True
        return p

    # -- serialization -----------------------------------------------------
    def to_dict(self):
        return {"blocks": [b.to_dict() for b in self.blocks],
                "random_seed": self.random_seed,
                "amp": self.amp,
                "expect_host_ops": self.expect_host_ops}

    @staticmethod
    def from_dict(d):
        p = Program()
        p.random_seed = d.get("random_seed", 0)
        p.amp = d.get("amp")
        p.expect_host_ops = d.get("expect_host_ops", False)
        # create all blocks first so sub-block attrs can resolve
        for bd in d["blocks"][1:]:
            b = Block(p, bd["idx"], parent_idx=bd["parent_idx"])
            p.blocks.append(b)
        for b, bd in zip(p.blocks, d["blocks"]):
            for vd in bd["vars"]:
                b.vars[vd["name"]] = Variable.from_dict(b, vd)
            for od in bd["ops"]:
                b.ops.append(Operator.from_dict(b, od, p))
        return p

    def to_string(self, throw_on_error=False):
        lines = []
        for b in self.blocks:
            lines.append(f"-- block {b.idx} (parent {b.parent_idx}) --")
            for v in b.vars.values():
                lines.append(f"  var {v.name}: shape={v.shape} dtype={v.dtype}"
                             + (" persistable" if v.persistable else ""))
            for op in b.ops:
                lines.append(f"  {op!r}")
        return "\n".join(lines)

    __str__ = to_string

    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()


# ---------------------------------------------------------------------------
# default programs / guards (reference framework.py:1235,1277)
# ---------------------------------------------------------------------------

_main_program = Program()
_startup_program = Program()


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


def switch_main_program(program):
    global _main_program
    prev, _main_program = _main_program, program
    return prev


def switch_startup_program(program):
    global _startup_program
    prev, _startup_program = _startup_program, program
    return prev


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    prev_main = switch_main_program(main_program)
    prev_startup = None
    if startup_program is not None:
        prev_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(prev_main)
        if prev_startup is not None:
            switch_startup_program(prev_startup)
