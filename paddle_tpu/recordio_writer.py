"""recordio: Python API over the native chunked record format
(reference ``paddle/fluid/recordio/`` + ``python/paddle/fluid/
recordio_writer.py``).  Pure-Python fallback keeps the same on-disk
layout when no C++ toolchain is present."""

from __future__ import annotations

import ctypes
import contextlib
import struct
import zlib

import numpy as np

from paddle_tpu import native

__all__ = ["RecordIOWriter", "RecordIOScanner", "RecordIOLoader",
           "convert_reader_to_recordio_file"]

_MAGIC = 0x0DEA11ED
_RAW, _ZLIB = 0, 1
_HDR = struct.Struct("<6I")


class RecordIOWriter:
    def __init__(self, path, compressor=_ZLIB, max_num_records=1000):
        self._lib = native.load()
        self.path = path
        if self._lib:
            self._w = self._lib.recio_writer_open(
                path.encode(), compressor, max_num_records)
            if not self._w:
                raise IOError(f"cannot open {path!r}")
        else:  # pure-python fallback
            self._f = open(path, "wb")
            self._compressor = compressor
            self._max = max_num_records
            self._buf = []
            self._n = 0

    def write(self, data: bytes):
        if isinstance(data, str):
            data = data.encode()
        if self._lib:
            rc = self._lib.recio_writer_write(self._w, data, len(data))
            if rc != 0:
                raise IOError("recordio write failed")
        else:
            self._buf.append(struct.pack("<I", len(data)) + data)
            self._n += 1
            if self._n >= self._max:
                self._flush()

    def _flush(self):
        if not self._n:
            return
        raw = b"".join(self._buf)
        payload = zlib.compress(raw) if self._compressor == _ZLIB else raw
        self._f.write(_HDR.pack(_MAGIC, self._compressor, self._n,
                                len(payload), len(raw),
                                zlib.crc32(payload) & 0xFFFFFFFF))
        self._f.write(payload)
        self._buf, self._n = [], 0

    def close(self):
        if self._lib:
            self._lib.recio_writer_close(self._w)
        else:
            self._flush()
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class RecordIOScanner:
    """Sequential record iterator (native when available)."""

    def __init__(self, path):
        self._lib = native.load()
        self.path = path

    def __iter__(self):
        if self._lib:
            s = self._lib.recio_scanner_open(self.path.encode())
            if not s:
                raise IOError(f"cannot open {self.path!r}")
            try:
                ptr = ctypes.POINTER(ctypes.c_uint8)()
                ln = ctypes.c_uint32()
                while True:
                    rc = self._lib.recio_scanner_next(
                        s, ctypes.byref(ptr), ctypes.byref(ln))
                    if rc == 0:
                        return
                    if rc < 0:
                        raise IOError("corrupt recordio chunk")
                    yield ctypes.string_at(ptr, ln.value)
            finally:
                self._lib.recio_scanner_close(s)
        else:
            with open(self.path, "rb") as f:
                while True:
                    hdr = f.read(_HDR.size)
                    if len(hdr) < _HDR.size:
                        return
                    magic, comp, n, plen, rlen, crc = _HDR.unpack(hdr)
                    if magic != _MAGIC:
                        raise IOError("bad recordio magic")
                    payload = f.read(plen)
                    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                        raise IOError("recordio crc mismatch")
                    raw = zlib.decompress(payload) if comp == _ZLIB \
                        else payload
                    pos = 0
                    for _ in range(n):
                        (ln,) = struct.unpack_from("<I", raw, pos)
                        pos += 4
                        yield raw[pos:pos + ln]
                        pos += ln


class RecordIOLoader:
    """Multi-file threaded prefetch loader (native reader threads; the
    analog of the reference's open_files + double-buffer reader ops)."""

    def __init__(self, paths, n_threads=2, capacity=256):
        lib = native.load()
        if lib is None:
            raise RuntimeError("native loader requires a C++ toolchain")
        self._lib = lib
        arr = (ctypes.c_char_p * len(paths))(
            *[p.encode() for p in paths])
        self._l = lib.recio_loader_open(arr, len(paths), n_threads,
                                        capacity)

    def __iter__(self):
        ptr = ctypes.POINTER(ctypes.c_uint8)()
        ln = ctypes.c_uint32()
        while True:
            rc = self._lib.recio_loader_next(self._l, ctypes.byref(ptr),
                                             ctypes.byref(ln))
            if rc == 0:
                return
            yield ctypes.string_at(ptr, ln.value)

    def close(self):
        if self._l:
            self._lib.recio_loader_close(self._l)
            self._l = None


def convert_reader_to_recordio_file(filename, reader_creator, feeder=None,
                                    compressor=_ZLIB,
                                    max_num_records=1000):
    """Serialize a python reader's samples (numpy-pickled) into a recordio
    file (reference ``recordio_writer.py:22``)."""
    import pickle
    count = 0
    with RecordIOWriter(filename, compressor, max_num_records) as w:
        for sample in reader_creator():
            w.write(pickle.dumps(sample, protocol=4))
            count += 1
    return count
